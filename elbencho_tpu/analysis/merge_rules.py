"""merge-rules: every wire counter has exactly one declared merge rule.

Tree-merge == flat-merge is the control plane's provable-by-schema
property (docs/control-plane.md): the service wire, ``RemoteWorker``
ingest, the ``--svcfanout`` subtree merge, the flight recorder, and the
``/metrics`` fleet aggregation all merge counters by the SAME two
tables — ``PATH_AUDIT_COUNTERS`` + ``PATH_AUDIT_MAX_KEYS`` and
``CONTROL_AUDIT_COUNTERS``. This rule makes the cross-checks machine-
enforced:

- no duplicate wire keys / context attrs / ingest attrs across the two
  schemas (a duplicate silently double-merges);
- ``PATH_AUDIT_MAX_KEYS`` / ``PATH_AUDIT_WORKER_ATTRS`` /
  ``PATH_AUDIT_POOL_ATTRS`` contain no stale names (a typo there turns
  a MAX counter into a sum without any test noticing);
- every ``CONTROL_AUDIT_COUNTERS`` mode is ``sum`` or ``max``;
- ``stream.MERGE_MAX_KEYS`` equals exactly the union of the schemas'
  MAX keys (the subtree merge can never diverge from the flat merge);
- ``flightrec.counter_schema()`` carries every schema key with the
  matching mode;
- merge/aggregation modules never hardcode a wire-key string literal —
  they must derive from the schema tables, so appending a counter to
  the table plumbs it everywhere (the invariant ROADMAP item 3's
  binary wire codec will lean on).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from .core import Finding, LintError, rule
from .schema_rules import extract_counter_keys

DEVICE_FILE = "elbencho_tpu/tpu/device.py"
CONTROL_FILE = "elbencho_tpu/service/fault_tolerance.py"

#: modules that MERGE or re-serialize counters: hardcoding a wire key
#: here (instead of iterating the schema tables) is how tree-merge and
#: flat-merge drift apart. Consumers that only *read* merged results
#: (doctor verdicts, chart lanes, summarize columns) are not listed —
#: naming a specific counter is their whole job.
MERGE_SITE_FILES = (
    "elbencho_tpu/service/stream.py",
    "elbencho_tpu/service/remote_worker.py",
    "elbencho_tpu/telemetry/flightrec.py",
    "elbencho_tpu/telemetry/registry.py",
    "elbencho_tpu/telemetry/exporter.py",
    "elbencho_tpu/stats/statistics.py",
)


@dataclass
class MergeSchema:
    """Everything the pure checker needs, with file anchors so findings
    point at the declaring table (tests feed synthetic instances)."""

    path_entries: "list[tuple[str, str, str]]"   # (attr, key, ingest)
    path_file: str
    path_line: int
    max_keys: "set[str]"
    max_keys_line: int
    worker_attrs: "set[str]"
    worker_attrs_line: int
    pool_attrs: "set[str]"
    pool_attrs_line: int
    control_entries: "list[tuple[str, str, str]]"  # (attr, key, mode)
    control_file: str
    control_line: int
    # None = not extracted (fixture trees); cross-checks skip
    stream_max_keys: "set[str] | None" = None
    stream_file: str = "elbencho_tpu/service/stream.py"
    stream_line: int = 1
    flightrec_schema: "dict[str, str] | None" = None
    flightrec_file: str = "elbencho_tpu/telemetry/flightrec.py"
    histo_keys: "set[str]" = field(default_factory=set)

    @property
    def path_keys(self) -> "list[str]":
        return [k for _a, k, _i in self.path_entries]

    @property
    def control_keys(self) -> "list[str]":
        return [k for _a, k, _m in self.control_entries]

    def all_keys(self) -> "set[str]":
        return set(self.path_keys) | set(self.control_keys)

    def declared_max(self) -> "set[str]":
        return self.max_keys | {k for _a, k, m in self.control_entries
                                if m == "max"}


def _assign_line(tree: ast.AST, name: str, default: int = 1) -> int:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            return node.lineno
    return default


def _extract_entries(src: str, name: str, width: int) \
        -> "list[tuple] | None":
    """Rows of a ``NAME = ((a, b, c), ...)`` literal table, as tuples of
    the first ``width`` constant elements."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or not any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            continue
        if not isinstance(node.value, (ast.Tuple, ast.List)):
            return None
        rows = []
        for elt in node.value.elts:
            if not isinstance(elt, (ast.Tuple, ast.List)) \
                    or len(elt.elts) < width:
                return None
            vals = []
            for sub in elt.elts[:width]:
                if not isinstance(sub, ast.Constant):
                    return None
                vals.append(sub.value)
            rows.append(tuple(vals))
        return rows
    return None


def _extract_frozenset(src: str, name: str) -> "set[str] | None":
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or not any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            continue
        call = node.value
        if isinstance(call, ast.Call) and len(call.args) == 1:
            call = call.args[0]
        if not isinstance(call, (ast.Set, ast.Tuple, ast.List)):
            return None
        out = set()
        for elt in call.elts:
            if not isinstance(elt, ast.Constant):
                return None
            out.add(elt.value)
        return out
    return None


def _is_real_repo(project) -> bool:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.abspath(project.root) == here


def extract_merge_schema(project) -> MergeSchema:
    """The live schema tables, AST-extracted (so fixture trees work) —
    plus the two *computed* derivations (stream merge keys, flightrec
    schema) via runtime import when linting the real repo."""
    dev_src = project.source(DEVICE_FILE)
    ctl_src = project.source(CONTROL_FILE)
    if dev_src is None or ctl_src is None:
        raise LintError("merge-rules: schema files missing "
                        f"({DEVICE_FILE}, {CONTROL_FILE})")
    path_entries = _extract_entries(dev_src, "PATH_AUDIT_COUNTERS", 3)
    control_entries = _extract_entries(ctl_src,
                                       "CONTROL_AUDIT_COUNTERS", 3)
    max_keys = _extract_frozenset(dev_src, "PATH_AUDIT_MAX_KEYS")
    worker_attrs = _extract_frozenset(dev_src, "PATH_AUDIT_WORKER_ATTRS")
    pool_attrs = _extract_frozenset(dev_src, "PATH_AUDIT_POOL_ATTRS")
    if None in (path_entries, control_entries, max_keys, worker_attrs,
                pool_attrs):
        raise LintError(
            "merge-rules: cannot extract the audit schema tables — a "
            "schema moved/renamed; update analysis/merge_rules.py with "
            "it (that is part of the merge-rule contract)")
    dev_tree, ctl_tree = ast.parse(dev_src), ast.parse(ctl_src)
    ms = MergeSchema(
        path_entries=path_entries, path_file=DEVICE_FILE,
        path_line=_assign_line(dev_tree, "PATH_AUDIT_COUNTERS"),
        max_keys=max_keys,
        max_keys_line=_assign_line(dev_tree, "PATH_AUDIT_MAX_KEYS"),
        worker_attrs=worker_attrs,
        worker_attrs_line=_assign_line(dev_tree,
                                       "PATH_AUDIT_WORKER_ATTRS"),
        pool_attrs=pool_attrs,
        pool_attrs_line=_assign_line(dev_tree, "PATH_AUDIT_POOL_ATTRS"),
        control_entries=control_entries, control_file=CONTROL_FILE,
        control_line=_assign_line(ctl_tree, "CONTROL_AUDIT_COUNTERS"),
    )
    if _is_real_repo(project):
        from ..service import stream
        from ..telemetry import flightrec
        ms.stream_max_keys = set(stream.MERGE_MAX_KEYS)
        ms.stream_line = _assign_line(
            ast.parse(project.source(ms.stream_file) or ""),
            "MERGE_MAX_KEYS")
        ms.flightrec_schema = dict(flightrec.counter_schema())
        ms.histo_keys = set(stream.MERGE_HISTO_KEYS)
    return ms


def check_merge_schema(ms: MergeSchema) -> "list[Finding]":
    """Pure checker over an extracted MergeSchema (unit-testable with
    synthetic violations)."""
    out: "list[Finding]" = []
    R = "merge-rules"

    def dup_names(seq):
        seen, dups = set(), []
        for name in seq:
            if name in seen:
                dups.append(name)
            seen.add(name)
        return dups

    for key in dup_names(ms.path_keys):
        out.append(Finding(R, ms.path_file, ms.path_line,
                           f"dup-key:{key}",
                           f"wire key {key!r} appears more than once in "
                           f"PATH_AUDIT_COUNTERS — it would be merged "
                           f"twice into every record"))
    for key in dup_names(ms.control_keys):
        out.append(Finding(R, ms.control_file, ms.control_line,
                           f"dup-key:{key}",
                           f"wire key {key!r} appears more than once in "
                           f"CONTROL_AUDIT_COUNTERS"))
    for key in sorted(set(ms.path_keys) & set(ms.control_keys)):
        out.append(Finding(R, ms.control_file, ms.control_line,
                           f"cross-dup-key:{key}",
                           f"wire key {key!r} is declared by BOTH "
                           f"PATH_AUDIT_COUNTERS and "
                           f"CONTROL_AUDIT_COUNTERS — exactly one table "
                           f"may own a counter's merge rule"))
    for attr in dup_names(a for a, _k, _i in ms.path_entries):
        out.append(Finding(R, ms.path_file, ms.path_line,
                           f"dup-attr:{attr}",
                           f"context attribute {attr!r} appears twice in "
                           f"PATH_AUDIT_COUNTERS"))
    for ing in dup_names(i for _a, _k, i in ms.path_entries):
        out.append(Finding(R, ms.path_file, ms.path_line,
                           f"dup-ingest:{ing}",
                           f"RemoteWorker ingest attribute {ing!r} "
                           f"appears twice in PATH_AUDIT_COUNTERS — two "
                           f"wire keys would overwrite one mirror"))
    path_keys = set(ms.path_keys)
    for key in sorted(ms.max_keys - path_keys):
        out.append(Finding(R, ms.path_file, ms.max_keys_line,
                           f"stale-max:{key}",
                           f"PATH_AUDIT_MAX_KEYS names {key!r} which is "
                           f"not a PATH_AUDIT_COUNTERS wire key — a "
                           f"renamed counter would silently fall back "
                           f"to sum-merge"))
    path_attrs = {a for a, _k, _i in ms.path_entries}
    for attr in sorted(ms.worker_attrs - path_attrs):
        out.append(Finding(R, ms.path_file, ms.worker_attrs_line,
                           f"stale-worker-attr:{attr}",
                           f"PATH_AUDIT_WORKER_ATTRS names {attr!r} "
                           f"which is not a PATH_AUDIT_COUNTERS "
                           f"attribute"))
    for attr in sorted(ms.pool_attrs - path_attrs):
        out.append(Finding(R, ms.path_file, ms.pool_attrs_line,
                           f"stale-pool-attr:{attr}",
                           f"PATH_AUDIT_POOL_ATTRS names {attr!r} which "
                           f"is not a PATH_AUDIT_COUNTERS attribute"))
    for attr, key, mode in ms.control_entries:
        if mode not in ("sum", "max"):
            out.append(Finding(R, ms.control_file, ms.control_line,
                               f"bad-mode:{key}",
                               f"CONTROL_AUDIT_COUNTERS entry {key!r} "
                               f"declares merge mode {mode!r} — only "
                               f"'sum' and 'max' exist on the wire"))
    declared_max = ms.declared_max()
    if ms.stream_max_keys is not None \
            and ms.stream_max_keys != declared_max:
        extra = sorted(ms.stream_max_keys - declared_max)
        missing = sorted(declared_max - ms.stream_max_keys)
        out.append(Finding(
            R, ms.stream_file, ms.stream_line, "stream-max-drift",
            f"stream.MERGE_MAX_KEYS diverged from the schemas' MAX "
            f"keys (extra: {extra or '-'}, missing: {missing or '-'}) "
            f"— the --svcfanout subtree merge would disagree with the "
            f"flat merge"))
    if ms.flightrec_schema is not None:
        for key in sorted(ms.all_keys()):
            want = "max" if key in declared_max else "sum"
            got = ms.flightrec_schema.get(key)
            if got is None:
                out.append(Finding(
                    R, ms.flightrec_file, 1, f"flightrec-missing:{key}",
                    f"flightrec.counter_schema() does not record "
                    f"{key!r} — the flight recorder would silently "
                    f"drop the counter from every recording"))
            elif got != want:
                out.append(Finding(
                    R, ms.flightrec_file, 1, f"flightrec-mode:{key}",
                    f"flightrec.counter_schema() merges {key!r} as "
                    f"{got!r} but the wire schema says {want!r}"))
    for key in sorted(ms.histo_keys & ms.all_keys()):
        out.append(Finding(R, ms.stream_file, ms.stream_line,
                           f"histo-collision:{key}",
                           f"{key!r} is both a histogram merge key and "
                           f"a counter wire key"))
    return out


def scan_hardcoded_keys(project, wire_keys: "set[str]",
                        files=MERGE_SITE_FILES) -> "list[Finding]":
    """String literals equal to a wire key inside merge/aggregation
    modules: those modules must iterate the schema tables instead, or
    an appended counter stops short of their site."""
    out: "list[Finding]" = []
    for rel in files:
        tree = project.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and node.value in wire_keys:
                out.append(Finding(
                    "merge-rules", rel, node.lineno,
                    f"literal:{rel}:{node.value}",
                    f"merge site hardcodes wire key {node.value!r} — "
                    f"derive from PATH_AUDIT_COUNTERS / "
                    f"CONTROL_AUDIT_COUNTERS so an appended counter "
                    f"plumbs through this site automatically"))
    return out


@rule("merge-rules",
      "every counter reachable over the wire has exactly one declared "
      "sum/MAX merge rule, consistent across the service wire, the "
      "subtree merge, flightrec, and /metrics")
def check(project) -> "list[Finding]":
    ms = extract_merge_schema(project)
    findings = check_merge_schema(ms)
    findings.extend(scan_hardcoded_keys(project, ms.all_keys()))
    return findings
