"""Statistics: live stats + first-done/last-done phase results + CSV/JSON.

Reference: source/Statistics.{h,cpp} (3.5 kLoC) — live render paths
(fullscreen/single-line/no-console, :182-1249), live CSV/JSON streams
(:3000-3292), and the two-column result model: **first done** (the moment
the fastest worker finished = stonewall snapshots of everyone at that
instant) vs **last done** (all workers finished)
(docs/result-columns-explanation.md; generatePhaseResults :1695).

TPU extension: per-chip HBM ingest bandwidth rows when ``--tpuids`` staging
is active (BASELINE.json north-star metric).
"""

from __future__ import annotations

import json
import os
import sys
import time

from ..phases import BenchMode, BenchPhase, phase_entry_type, phase_name
from ..tpu.device import PATH_AUDIT_COUNTERS, sum_path_audit_counters
from .latency_histogram import LatencyHistogram


def sum_tpu_transfer_totals(workers) -> "tuple[int, int, int]":
    """(bytes, dma_usec, dispatch_usec) summed over a worker list — the
    live-wire and /metrics aggregation of the dispatch-vs-DMA split
    (one definition so the two exports can never diverge)."""
    tpu_bytes = tpu_usec = tpu_dispatch_usec = 0
    for w in workers:
        tpu_bytes += w.tpu_transfer_bytes
        tpu_usec += w.tpu_transfer_usec
        tpu_dispatch_usec += w.tpu_dispatch_usec
    return tpu_bytes, tpu_usec, tpu_dispatch_usec


def merge_live_latency_histos(workers) -> "tuple[LatencyHistogram, ...]":
    """(io, entries) histograms merged over a worker list for the live
    telemetry views (rwmix reads fold into io — a live scrape wants one
    op-latency distribution, not the result table's split)."""
    io_histo = LatencyHistogram()
    ent_histo = LatencyHistogram()
    for w in workers:
        io_histo.merge(w.iops_latency_histo)
        io_histo.merge(w.iops_latency_histo_rwmix)
        ent_histo.merge(w.entries_latency_histo)
    return io_histo, ent_histo


def _fmt_elapsed_usec(usec: int) -> str:
    secs = usec / 1_000_000
    if secs >= 60:
        m, s = divmod(secs, 60)
        return f"{int(m)}m{s:.1f}s"
    if secs >= 1:
        return f"{secs:.3f}s"
    return f"{usec / 1000:.2f}ms"


class PhaseResults:
    """Aggregated first-done/last-done numbers for one finished phase."""

    def __init__(self):
        self.phase: BenchPhase = BenchPhase.IDLE
        self.phase_name = ""
        self.entry_type = "files"
        self.first_done_usec = 0
        self.last_done_usec = 0
        self.stonewall = {}     # first-done totals dict
        self.final = {}         # last-done totals dict
        self.stonewall_rwmix = {}
        self.final_rwmix = {}
        self.iops_histo = LatencyHistogram()
        self.entries_histo = LatencyHistogram()
        self.iops_histo_rwmix = LatencyHistogram()
        self.cpu_stonewall = 0.0
        self.cpu_last_done = 0.0
        self.elapsed_usec_vec: "list[int]" = []
        self.tpu_bytes = 0
        self.tpu_usec = 0           # DMA wall time (submit -> ready)
        self.tpu_dispatch_usec = 0  # host-side submit cost of the pipeline
        self.tpu_per_chip: "dict[int, tuple[int, int]]" = {}
        # --tpudirect H2D/D2H path audit, keyed by wire/JSON name
        # (schema: tpu.device.PATH_AUDIT_COUNTERS)
        self.tpu_path_counters: "dict[str, int]" = {
            key: 0 for _attr, key, _ingest in PATH_AUDIT_COUNTERS}
        self.num_workers = 0
        # per-service-host CPU util at phase end (telemetry satellite;
        # JSON-only result key HostCPUUtil)
        self.host_cpu_util: "dict[str, float]" = {}
        # --svctolerant: hosts lost mid-run (results exclude them)
        self.degraded_hosts: "list[str]" = []
        # control-plane audit (fault_tolerance.CONTROL_AUDIT_COUNTERS)
        self.control_counters: "dict[str, int]" = {}
        # --flightrec: the run doctor's verdict for this phase
        # (telemetry/doctor.py; JSON-only "Analysis" block)
        self.analysis: "dict | None" = None
        # --slowops: the fleet-merged tail forensics block
        # (telemetry/slowops.py; JSON-only "TailAnalysis" block)
        self.tail_analysis: "dict | None" = None


class Statistics:
    def __init__(self, cfg, worker_manager):
        self.cfg = cfg
        self.manager = worker_manager
        self._header_printed = False
        self._live_csv_fh = None
        self._live_json_fh = None
        self._live_rows = 0      # data rows written to the live streams
        self._live_started = 0.0
        self._fullscreen_active = False
        # --telemetry: BenchTelemetry bound by the coordinator; the live
        # loop samples it at its cadence so scrapes between intervals
        # read a warm snapshot
        self.telemetry = None
        # --flightrec: FlightRecorder bound by the coordinator; None =
        # recording off, every hook is a single `is None` test
        self.flightrec = None
        # dedicated CPU meter for /status replies (primed, rate-limited;
        # see SampledCPUUtil for why the shared phase meter is off limits)
        from .cpu_util import SampledCPUUtil
        self._status_cpu = SampledCPUUtil()

    # ------------------------------------------------------------------
    # live statistics (reference: printLiveStats, Statistics.cpp:1337)
    # ------------------------------------------------------------------

    def _sum_live_ops(self) -> "tuple[int, int, int, int]":
        entries = num_bytes = iops = 0
        for w in self.manager.workers:
            entries += (w.live_ops.num_entries_done
                        + w.live_ops_rwmix_read.num_entries_done)
            num_bytes += (w.live_ops.num_bytes_done
                          + w.live_ops_rwmix_read.num_bytes_done)
            iops += (w.live_ops.num_iops_done
                     + w.live_ops_rwmix_read.num_iops_done)
        done = self.manager.shared.num_workers_done \
            + self.manager.shared.num_workers_done_with_error
        return entries, num_bytes, iops, done

    def live_stats_loop(self, phase: BenchPhase,
                        phase_start: "float | None" = None) -> None:
        """Poll worker counters until the phase completes; render according
        to the configured live mode. Runs on the coordinator thread."""
        cfg = self.cfg
        interval = max(cfg.live_stats_interval_ms, 50) / 1000.0
        use_line = not cfg.disable_live_stats
        is_tty = sys.stdout.isatty()
        if self.flightrec is not None:
            self.flightrec.phase_start(
                phase_name(phase, cfg.bench_mode == BenchMode.S3))
        self._live_started = time.monotonic()
        last_bytes = last_iops = 0
        last_t = self._live_started
        next_render = self._live_started + interval
        while not self.manager.all_workers_done():
            time.sleep(0.02)  # fine-grained poll so short phases don't stall
            if phase_start is not None:
                self.manager.check_phase_time_limit(phase_start)
            self.manager.check_fail_fast_interrupt()
            if time.monotonic() < next_render:
                continue
            next_render = time.monotonic() + interval
            entries, num_bytes, iops, done = self._sum_live_ops()
            now = time.monotonic()
            dt = max(now - last_t, 1e-9)
            bps = (num_bytes - last_bytes) / dt
            ops_per_s = (iops - last_iops) / dt
            last_bytes, last_iops, last_t = num_bytes, iops, now
            elapsed = int(now - self._live_started)
            # live CSV/JSON files are written even when console live stats
            # are off (--nolive / service mode)
            self._write_live_files(phase, entries, num_bytes, iops, elapsed)
            if self.telemetry is not None:
                self.telemetry.sample()  # live-stats-cadence sampling
            if self.flightrec is not None:
                self.flightrec.sample(self)  # same cadence, same counters
            if not use_line:
                continue
            unit, div = ("MB", 1000 ** 2) if cfg.use_base10_units \
                else ("MiB", 1 << 20)
            fullscreen = (is_tty and not cfg.use_single_line_live_stats
                          and not cfg.single_line_live_stats_no_erase)
            if fullscreen:
                self._render_fullscreen(phase, elapsed, bps / div,
                                        ops_per_s, unit, div, done)
                continue
            line = (f"{phase_name(phase, cfg.bench_mode == BenchMode.S3)}: "
                    f"{elapsed}s; {bps / div:,.0f} {unit}/s; "
                    f"{ops_per_s:,.0f} IOPS; {entries} entries; "
                    f"{num_bytes / div:,.0f} {unit} total; "
                    f"{done}/{len(self.manager.workers)} done")
            if cfg.show_cpu_util:
                line += f"; CPU {self.manager.shared.cpu_util.update():.0f}%"
            if is_tty and not cfg.single_line_live_stats_no_erase:
                print("\r\x1b[2K" + line, end="", flush=True)
            else:
                print(line, flush=True)
        if use_line and is_tty:
            if self._fullscreen_active:
                print("\x1b[2J\x1b[H", end="", flush=True)
                self._fullscreen_active = False
                self._exit_fullscreen_keys()
            elif not cfg.single_line_live_stats_no_erase:
                print("\r\x1b[2K", end="", flush=True)

    #: fallback worker rows per fullscreen frame when no tty size known
    _FS_ROWS = 40
    #: header/footer lines around the worker table
    _FS_CHROME_LINES = 6
    #: per-frame snapshot of the terminal-derived row count
    _fs_rows = _FS_ROWS

    def _term_fs_rows(self) -> int:
        """Worker rows that fit the current terminal (reference:
        TerminalTk console size; read once per frame to follow resizes)."""
        import shutil
        lines = shutil.get_terminal_size(fallback=(80, 0)).lines
        return max(lines - self._FS_CHROME_LINES, 4) if lines \
            else self._FS_ROWS

    def _render_fullscreen(self, phase, elapsed, rate, ops_per_s, unit,
                           div, done) -> None:
        """Fullscreen per-worker live table (ANSI, dependency-free analogue
        of the reference's ftxui screen, Statistics.cpp:716-1249). Arrow /
        PgUp / PgDn / Home keys scroll the worker rows."""
        cfg = self.cfg
        shared = self.manager.shared
        workers = self.manager.workers
        self._fs_rows = self._term_fs_rows()  # one consistent size/frame
        self._poll_fullscreen_keys(len(workers))
        scroll = getattr(self, "_fs_scroll", 0)
        lines = []
        s3 = cfg.bench_mode == BenchMode.S3
        lines.append(
            f"Phase: {phase_name(phase, s3)}   Elapsed: {elapsed}s   "
            f"Done: {done}/{len(workers)}")
        lines.append(f"Total: {rate:,.0f} {unit}/s  {ops_per_s:,.0f} IOPS"
                     + (f"  CPU: {shared.cpu_util.update():.0f}%"
                        if cfg.show_cpu_util else ""))
        if cfg.show_svc_ping and cfg.hosts:
            # --svcping: control-plane /status RTT per service
            pings = [f"{w.host}={w.last_ping_usec / 1000:.1f}ms"
                     for w in workers if hasattr(w, "last_ping_usec")]
            if pings:
                lines.append("Service ping: " + "  ".join(pings))
        lines.append("")
        lines.append(f"{'Rank':>6} {'Entries':>10} {unit:>10} {'IOPS':>12} "
                     f"{'State':>8}")
        window = workers[scroll:scroll + self._fs_rows]
        for w in window:
            state = "done" if w.phase_finished else "run"
            lines.append(
                f"{w.rank:>6} {w.live_ops.num_entries_done:>10} "
                f"{w.live_ops.num_bytes_done / div:>10,.0f} "
                f"{w.live_ops.num_iops_done:>12,} {state:>8}")
        hidden = len(workers) - len(window)
        if hidden > 0:
            lines.append(f"... showing {scroll}..{scroll + len(window) - 1} "
                         f"of {len(workers)} workers (arrow keys / PgUp / "
                         f"PgDn scroll)")
        # footer: running tail percentiles (bucket-walk over the live
        # histograms the wire already carries — tails are visible
        # MID-RUN, not only post-mortem; slow-op forensics satellite).
        # Entry-granular phases (mkdirs/stat/delete) move no blocks, so
        # their entry latencies ARE the per-op distribution shown.
        # gate on BUCKET content, not num_values: master-mode sum-only
        # mirrors (no --telemetry bucket view on the wire) carry counts
        # and sums with empty buckets — percentile() would answer 0
        io_histo, ent_histo = merge_live_latency_histos(workers)
        tail_histo, tail_label = ((io_histo, "IO")
                                  if any(io_histo.buckets)
                                  else (ent_histo, "Entry"))
        if any(tail_histo.buckets):
            lines.append(
                f"{tail_label} lat us: "
                f"p50={tail_histo.percentile(50):,.0f}  "
                f"p99={tail_histo.percentile(99):,.0f}  "
                f"p99.9={tail_histo.percentile(99.9):,.0f}  "
                f"max={tail_histo.max_micro:,}")
        # per-service-host CPU util sampled from the /status polls
        # (telemetry satellite; RemoteWorker.cpu_util_pct live ingest)
        host_cpus = [(w.host, w.cpu_util_pct) for w in workers
                     if getattr(w, "host", None) is not None
                     and hasattr(w, "cpu_util_pct")]
        if host_cpus:
            lines.append("Host CPU%: " + "  ".join(
                f"{h}={p:.0f}" for h, p in host_cpus))
        frame = "\x1b[H" + "\x1b[2K" + "\n\x1b[2K".join(lines) + "\x1b[J"
        if not self._fullscreen_active:
            print("\x1b[2J", end="")
            self._fullscreen_active = True
            self._enter_fullscreen_keys()
        print(frame, end="", flush=True)

    # -- fullscreen keyboard navigation (reference: ftxui arrow-key rows) ----

    def _enter_fullscreen_keys(self) -> None:
        """Put stdin into cbreak so arrow keys arrive without Enter; restored
        by close()/_exit_fullscreen_keys."""
        self._fs_scroll = 0
        self._fs_old_termios = None
        try:
            import termios
            import tty
            if sys.stdin.isatty():
                fd = sys.stdin.fileno()
                self._fs_old_termios = (fd, termios.tcgetattr(fd))
                tty.setcbreak(fd)
        except (ImportError, OSError):
            pass

    def _exit_fullscreen_keys(self) -> None:
        old = getattr(self, "_fs_old_termios", None)
        if old is not None:
            try:
                import termios
                termios.tcsetattr(old[0], termios.TCSADRAIN, old[1])
            except (ImportError, OSError):
                pass
            self._fs_old_termios = None

    def _poll_fullscreen_keys(self, num_workers: int) -> None:
        """Non-blocking read of pending key escape sequences; updates the
        scroll offset window over the per-worker rows."""
        if getattr(self, "_fs_old_termios", None) is None:
            return
        import select
        scroll = getattr(self, "_fs_scroll", 0)
        max_scroll = max(num_workers - self._fs_rows, 0)
        buf = b""
        try:
            while select.select([sys.stdin], [], [], 0)[0]:
                chunk = os.read(sys.stdin.fileno(), 64)
                if not chunk:
                    break
                buf += chunk
        except OSError:
            pass
        # parse sequence-by-sequence: auto-repeat delivers several escape
        # sequences per read, so the buffer must be consumed incrementally
        i = 0
        while i < len(buf):
            seq, step = self._match_key_seq(buf[i:])
            i += step
            if seq in ("up", "k"):
                scroll -= 1
            elif seq in ("down", "j"):
                scroll += 1
            elif seq in ("pgup", "\x02"):
                scroll -= self._fs_rows
            elif seq in ("pgdn", "\x06"):
                scroll += self._fs_rows
            elif seq in ("home", "g"):
                scroll = 0
            elif seq in ("end", "G"):
                scroll = max_scroll
        self._fs_scroll = min(max(scroll, 0), max_scroll)

    _ESC_SEQS = {b"\x1b[A": "up", b"\x1b[B": "down", b"\x1b[5~": "pgup",
                 b"\x1b[6~": "pgdn", b"\x1b[H": "home", b"\x1b[F": "end"}

    @classmethod
    def _match_key_seq(cls, buf: bytes) -> "tuple[str, int]":
        """Match one key at the front of buf -> (name, bytes_consumed)."""
        if buf[:1] == b"\x1b":
            for seq, name in cls._ESC_SEQS.items():
                if buf.startswith(seq):
                    return name, len(seq)
            return "", 1  # unknown escape: skip the ESC byte
        return chr(buf[0]), 1

    def _write_live_files(self, phase, entries, num_bytes, iops,
                          elapsed) -> None:
        cfg = self.cfg
        if cfg.live_csv_file_path:
            if self._live_csv_fh is None:
                self._live_csv_fh = (sys.stdout
                                     if cfg.live_csv_file_path == "stdout"
                                     else open(cfg.live_csv_file_path, "a"))
                print("ISODate,Label,Phase,Seconds,Entries,Bytes,IOPS",
                      file=self._live_csv_fh, flush=True)
            print(f"{time.strftime('%Y-%m-%dT%H:%M:%S')},"
                  f"{cfg.bench_label},{phase_name(phase)},{elapsed},"
                  f"{entries},{num_bytes},{iops}",
                  file=self._live_csv_fh, flush=True)
        if cfg.live_json_file_path:
            if self._live_json_fh is None:
                self._live_json_fh = (sys.stdout
                                      if cfg.live_json_file_path == "stdout"
                                      else open(cfg.live_json_file_path, "a"))
            rec = {"ISODate": time.strftime("%Y-%m-%dT%H:%M:%S"),
                   "Label": cfg.bench_label, "Phase": phase_name(phase),
                   "Seconds": elapsed, "Entries": entries,
                   "Bytes": num_bytes, "IOPS": iops}
            if cfg.live_json_extended or cfg.live_csv_extended:
                rec["Workers"] = [
                    {"Rank": w.rank, **w.live_ops.as_dict()}
                    for w in self.manager.workers]
            print(json.dumps(rec), file=self._live_json_fh, flush=True)
        if cfg.live_csv_file_path or cfg.live_json_file_path:
            self._live_rows += 1
        self._flush_live_files()

    def _flush_live_files(self) -> None:
        """Push the live streams all the way to stable storage every
        interval: flush() alone leaves rows in the OS page cache, where a
        tailer/scraper on another host (network filesystem) only sees
        them on buffer-boundary writeback — fsync is best effort (stdout
        and pipes have no fsync)."""
        for fh in (self._live_csv_fh, self._live_json_fh):
            if fh is None:
                continue
            try:
                fh.flush()
                os.fsync(fh.fileno())
            except (OSError, ValueError):
                pass

    # ------------------------------------------------------------------
    # phase results (reference: printPhaseResults :1619 /
    # generatePhaseResults :1695)
    # ------------------------------------------------------------------

    def generate_phase_results(self, phase: BenchPhase) -> PhaseResults:
        cfg = self.cfg
        res = PhaseResults()
        res.phase = phase
        s3 = cfg.bench_mode == BenchMode.S3
        res.phase_name = phase_name(phase, s3)
        res.entry_type = phase_entry_type(phase, s3)
        res.cpu_stonewall = self.manager.shared.cpu_util_stonewall
        res.cpu_last_done = self.manager.shared.cpu_util_last_done

        stonewall_totals = {"entries": 0, "bytes": 0, "iops": 0}
        final_totals = {"entries": 0, "bytes": 0, "iops": 0}
        stonewall_rwmix = {"entries": 0, "bytes": 0, "iops": 0}
        final_rwmix = {"entries": 0, "bytes": 0, "iops": 0}
        workers = [w for w in self.manager.workers if w.got_phase_work]
        res.num_workers = len(workers)
        for w in workers:
            res.elapsed_usec_vec.extend(w.elapsed_usec_vec)
            stonewall_totals["entries"] += w.stonewall_ops.num_entries_done
            stonewall_totals["bytes"] += w.stonewall_ops.num_bytes_done
            stonewall_totals["iops"] += w.stonewall_ops.num_iops_done
            final_totals["entries"] += w.live_ops.num_entries_done
            final_totals["bytes"] += w.live_ops.num_bytes_done
            final_totals["iops"] += w.live_ops.num_iops_done
            stonewall_rwmix["entries"] += \
                w.stonewall_ops_rwmix_read.num_entries_done
            stonewall_rwmix["bytes"] += \
                w.stonewall_ops_rwmix_read.num_bytes_done
            stonewall_rwmix["iops"] += \
                w.stonewall_ops_rwmix_read.num_iops_done
            final_rwmix["entries"] += w.live_ops_rwmix_read.num_entries_done
            final_rwmix["bytes"] += w.live_ops_rwmix_read.num_bytes_done
            final_rwmix["iops"] += w.live_ops_rwmix_read.num_iops_done
            res.iops_histo.merge(w.iops_latency_histo)
            res.entries_histo.merge(w.entries_latency_histo)
            res.iops_histo_rwmix.merge(w.iops_latency_histo_rwmix)
            res.tpu_bytes += w.tpu_transfer_bytes
            res.tpu_usec += w.tpu_transfer_usec
            res.tpu_dispatch_usec += w.tpu_dispatch_usec
            if getattr(w, "_tpu", None) is not None:
                chip = w._tpu.chip_id
                b, u = res.tpu_per_chip.get(chip, (0, 0))
                res.tpu_per_chip[chip] = (b + w.tpu_transfer_bytes,
                                          u + w.tpu_transfer_usec)
            else:  # RemoteWorker: per-chip map ingested from service JSON
                for chip, (b2, u2) in getattr(w, "tpu_per_chip",
                                              {}).items():
                    b, u = res.tpu_per_chip.get(chip, (0, 0))
                    res.tpu_per_chip[chip] = (b + b2, u + u2)
        res.tpu_path_counters = sum_path_audit_counters(workers)
        # fleet straggler attribution (fleet tracing / run doctor): the
        # per-host finish spread behind the phase barrier, computed here
        # — after the barrier, before the control-counter merge — so
        # StragglerSkewUsec (MAX merge = the straggler's lag behind the
        # FIRST finisher) and BarrierWaitUSec (sum = worker-time the
        # fleet idled waiting for the LAST finisher) ride the existing
        # CONTROL_AUDIT_COUNTERS plumbing into JSON//metrics/flightrec
        self._compute_barrier_skew()
        # per-host CPU util (last /status ingest of each RemoteWorker)
        res.host_cpu_util = {
            w.host: round(getattr(w, "cpu_util_pct", 0.0), 1)
            for w in self.manager.workers
            if getattr(w, "host", None) is not None}
        from ..service.fault_tolerance import merge_control_audit_counters
        res.control_counters = merge_control_audit_counters(
            self.manager.workers)
        res.degraded_hosts = list(self.manager.shared.degraded_hosts)
        stonewall_elapsed = [w.stonewall_elapsed_usec for w in workers
                             if w.stonewall_taken]
        res.first_done_usec = min(res.elapsed_usec_vec, default=0)
        if stonewall_elapsed:
            res.first_done_usec = min(stonewall_elapsed)
        res.last_done_usec = max(res.elapsed_usec_vec, default=0)
        res.stonewall = stonewall_totals
        res.final = final_totals
        res.stonewall_rwmix = stonewall_rwmix
        res.final_rwmix = final_rwmix
        if getattr(cfg, "slow_ops_k", 0):
            res.tail_analysis = self._build_tail_analysis(res)
        return res

    def _build_tail_analysis(self, res: PhaseResults) -> "dict | None":
        """Fleet-merge every worker's slow-op capture (local recorders
        directly, RemoteWorkers' shipped snapshots) into the phase's
        TailAnalysis block. The exact percentiles come from the merged
        io histogram (rwmix reads folded in, like the live view); the
        captures add the WHO/WHERE attribution."""
        from ..telemetry.slowops import build_tail_analysis
        parts: "list[tuple[str, dict | None]]" = []
        for w in self.manager.workers:
            if getattr(w, "_slowops", None) is not None:
                parts.append(("", w._slowops.snapshot()))
            elif getattr(w, "host", None) is not None:
                parts.append((w.host, getattr(w, "slowops_shipped",
                                              None)))
        if not any(((snap or {}).get("OpsSeen", 0)
                    or (snap or {}).get("Records"))
                   for _host, snap in parts):
            return None  # nothing captured (e.g. a pure mkdir phase)
        io_histo = LatencyHistogram()
        io_histo.merge(res.iops_histo)
        io_histo.merge(res.iops_histo_rwmix)
        if not io_histo.num_values:
            # entry-granular phase (stat/delete): the entry latencies
            # ARE the per-op distribution the captures attribute
            io_histo.merge(res.entries_histo)
        if not io_histo.num_values:
            return None  # no latencies recorded this phase (e.g. mkdir)
        return build_tail_analysis(
            parts, io_histo, getattr(self.cfg, "slow_ops_k", 0),
            getattr(self.cfg, "op_sample_rate", 1.0))

    def _compute_barrier_skew(self) -> None:
        """Per-host barrier decomposition from the finish stamps each
        RemoteWorker takes when its host's /benchresult lands: skew =
        lag behind the first host to finish, barrier wait = idle wait
        for the last. Meaningful only with >= 2 finishing hosts; local
        runs and single-host fleets keep both counters at zero."""
        finishes = [(w, w.phase_done_monotonic)
                    for w in self.manager.workers
                    if getattr(w, "host", None) is not None
                    and getattr(w, "phase_done_monotonic", 0.0)]
        if len(finishes) < 2:
            return
        first = min(t for _w, t in finishes)
        last = max(t for _w, t in finishes)
        for w, t in finishes:
            w.straggler_skew_usec = int((t - first) * 1e6)
            w.barrier_wait_usec = int((last - t) * 1e6)

    def per_host_barrier_stats(self) -> "dict[str, dict]":
        """{host: {...}} snapshot of the barrier decomposition plus each
        host's clock-offset estimate — the flight recorder stores it in
        phase_end rows and the doctor names the straggler from it."""
        out: "dict[str, dict]" = {}
        for w in self.manager.workers:
            host = getattr(w, "host", None)
            if host is None:
                continue
            entry = {
                "StragglerSkewUsec": getattr(w, "straggler_skew_usec", 0),
                "BarrierWaitUSec": getattr(w, "barrier_wait_usec", 0),
                # how coarse the master's done observation was for this
                # host (poll-interval / stream-tick quantization) — the
                # doctor's straggler floor scales with it so sampling
                # noise can't fabricate a verdict
                "ObsQuantumUsec": getattr(w, "done_obs_quantum_usec", 0),
            }
            estimate = getattr(w, "_host_clock_estimate", None)
            if estimate is not None:
                off, unc, known = estimate()
                if known:
                    entry["ClockOffsetUsec"] = off
                    entry["ClockUncUsec"] = unc
            out[host] = entry
        return out

    # -- rendering ----------------------------------------------------------

    def print_phase_results_table_header(self) -> None:
        line = (f"{'OPERATION':<12}{'RESULT TYPE':<20}"
                f"{'FIRST DONE':>14}{'LAST DONE':>14}")
        print(line)
        print(f"{'=' * 11:<12}{'=' * 18:<20}{'=' * 12:>14}{'=' * 12:>14}")
        self._print_to_res_file(line)

    def print_phase_results(self, phase: BenchPhase) -> PhaseResults:
        res = self.generate_phase_results(phase)
        if self.flightrec is not None:
            # run doctor: final sample + phase_end row + bottleneck
            # verdict — computed AFTER the barrier (RemoteWorkers have
            # ingested their final /benchresult, so totals are exact)
            # and BEFORE rendering so the text/JSON outputs carry it
            res.analysis = self.flightrec.finish_phase(self, res)
        self._render_result_rows(res)
        if self.cfg.csv_file_path:
            self._write_csv(res)
        if self.cfg.json_file_path:
            self._write_json(res)
        return res

    def _row(self, op: str, rtype: str, first, last) -> str:
        return f"{op:<12}{rtype + ' :':<20}{first:>14}{last:>14}"

    def _render_result_rows(self, res: PhaseResults) -> None:
        cfg = self.cfg
        unit, div = ("MB", 1000 ** 2) if cfg.use_base10_units \
            else ("MiB", 1 << 20)
        rows = []
        first_s = res.first_done_usec / 1e6 or 1e-9
        last_s = res.last_done_usec / 1e6 or 1e-9
        op = res.phase_name
        rows.append(self._row(op, "Elapsed time",
                              _fmt_elapsed_usec(res.first_done_usec),
                              _fmt_elapsed_usec(res.last_done_usec)))
        if res.final["entries"]:
            rows.append(self._row(
                "", f"{res.entry_type}/s",
                f"{res.stonewall['entries'] / first_s:,.0f}",
                f"{res.final['entries'] / last_s:,.0f}"))
            rows.append(self._row(
                "", f"{res.entry_type} total",
                f"{res.stonewall['entries']}", f"{res.final['entries']}"))
        if res.final["iops"]:
            rows.append(self._row(
                "", "IOPS", f"{res.stonewall['iops'] / first_s:,.0f}",
                f"{res.final['iops'] / last_s:,.0f}"))
        if res.final["bytes"]:
            rows.append(self._row(
                "", f"Throughput {unit}/s",
                f"{res.stonewall['bytes'] / first_s / div:,.0f}",
                f"{res.final['bytes'] / last_s / div:,.0f}"))
            rows.append(self._row(
                "", f"Total {unit}",
                f"{res.stonewall['bytes'] / div:,.0f}",
                f"{res.final['bytes'] / div:,.0f}"))
        if res.final_rwmix["iops"]:
            rows.append(self._row(
                "", "Read IOPS (rwmix)",
                f"{res.stonewall_rwmix['iops'] / first_s:,.0f}",
                f"{res.final_rwmix['iops'] / last_s:,.0f}"))
            rows.append(self._row(
                "", f"Read {unit}/s (rwmix)",
                f"{res.stonewall_rwmix['bytes'] / first_s / div:,.0f}",
                f"{res.final_rwmix['bytes'] / last_s / div:,.0f}"))
        if res.tpu_bytes:
            # HBM ingest rows: the TPU-native headline metric
            rows.append(self._row(
                "", f"HBM ingest {unit}/s", "-",
                f"{res.tpu_bytes / last_s / div:,.0f}"))
            for chip, (b, u) in sorted(res.tpu_per_chip.items()):
                rows.append(self._row(
                    "", f"  chip {chip} {unit}/s", "-",
                    f"{b / last_s / div:,.0f}"))
            # dispatch-vs-DMA split (TransferPipeline accounting): the
            # host-side submit overhead --tpubudget bounds vs the DMA
            # wall time the pipeline overlaps
            tpu_ops = sum(res.tpu_path_counters.get(k, 0) for k in (
                "TpuH2dDirectOps", "TpuH2dStagedOps",
                "TpuD2hDirectOps", "TpuD2hStagedOps"))
            if tpu_ops and (res.tpu_dispatch_usec or res.tpu_usec):
                rows.append(self._row(
                    "", "HBM dispatch us/op", "-",
                    f"{res.tpu_dispatch_usec / tpu_ops:,.1f}"))
                rows.append(self._row(
                    "", "HBM DMA us/op", "-",
                    f"{res.tpu_usec / tpu_ops:,.1f}"))
        if cfg.show_cpu_util:
            rows.append(self._row("", "CPU util %",
                                  f"{res.cpu_stonewall:.0f}",
                                  f"{res.cpu_last_done:.0f}"))
        if cfg.show_latency and res.iops_histo.num_values:
            h = res.iops_histo
            rows.append(f"{'':12}{'IO latency us :':<20}"
                        f"min={h.min_micro} avg={h.avg_micro:.0f} "
                        f"max={h.max_micro}")
        if cfg.show_latency and res.entries_histo.num_values:
            h = res.entries_histo
            rows.append(f"{'':12}{'Ent latency us :':<20}"
                        f"min={h.min_micro} avg={h.avg_micro:.0f} "
                        f"max={h.max_micro}")
        if cfg.show_latency_percentiles and res.iops_histo.num_values:
            nines = res.iops_histo.percentiles_nines(
                cfg.num_latency_percentile_9s)
            txt = " ".join(f"{k}={v:.0f}" for k, v in nines.items())
            rows.append(f"{'':12}{'IO lat pcts :':<20}{txt}")
        if cfg.show_latency_histogram and res.iops_histo.num_values:
            rows.append(f"{'':12}IO lat histogram : "
                        f"{res.iops_histo.histogram_str()}")
        if cfg.show_all_elapsed:
            txt = ", ".join(_fmt_elapsed_usec(u)
                            for u in sorted(res.elapsed_usec_vec))
            rows.append(f"{'':12}Worker elapsed   : {txt}")
        if cfg.show_svc_elapsed and cfg.hosts:
            # per-service last-done elapsed (--svcelapsed)
            parts = []
            for w in self.manager.workers:
                if getattr(w, "host", None) and w.elapsed_usec_vec:
                    parts.append(f"{w.host}="
                                 f"{_fmt_elapsed_usec(max(w.elapsed_usec_vec))}")
            if parts:
                rows.append(f"{'':12}Service elapsed  : {', '.join(parts)}")
        if res.tail_analysis is not None:
            # --slowops tail forensics: how heavy the tail is and who
            # owns it (full detail in the JSON TailAnalysis block)
            tail = res.tail_analysis
            hosts = tail["Owners"]["ByHost"]
            owner = max(hosts, key=hosts.get) if hosts else ""
            line = (f"p50={tail['P50Usec']} p99={tail['P99Usec']} "
                    f"p99.9={tail['P999Usec']} max={tail['MaxUsec']} "
                    f"({tail['TailRatio']:g}x p50")
            if owner:
                line += (f"; {hosts[owner]:.0%} of captured tail time "
                         f"on {owner}")
            rows.append(f"{'':12}{'Tail lat us :':<20}{line})")
        if res.analysis is not None:
            # --flightrec run doctor: where the wall time went + the
            # named bottleneck, right under the numbers it explains
            ana = res.analysis
            busy = "  ".join(
                f"{name}={pct:g}%" for name, pct in ana["StagePct"].items()
                if pct)
            if busy:
                rows.append(f"{'':12}{'Stage time % :':<20}{busy}")
            first = f" ({ana['Evidence'][0]})" if ana["Evidence"] else ""
            rows.append(f"{'':12}{'Bottleneck :':<20}"
                        f"{ana['Verdict']}{first}")
        if res.degraded_hosts:
            # loud, unmissable: these numbers exclude lost hosts and must
            # never be read as a clean run (--svctolerant)
            rows.append(
                f"{'':12}{'DEGRADED hosts :':<20}"
                f"{', '.join(res.degraded_hosts)} "
                f"(lost mid-run; results cover survivors only)")
        if not cfg.ignore_0usec_errors and res.num_workers \
                and res.first_done_usec == 0:
            # reference semantics (Statistics.cpp:2186): warn when the
            # fastest worker finished in 0 microseconds — the whole phase
            # was too short to measure
            rows.append(
                f"{'':12}WARNING: phase completed in 0 microseconds; "
                f"results may be bogus (too little work?). --no0usecerr "
                f"silences this.")
        for row in rows:
            print(row)
            self._print_to_res_file(row)

    def _print_to_res_file(self, line: str) -> None:
        if self.cfg.res_file_path:
            with open(self.cfg.res_file_path, "a") as f:
                f.write(line + "\n")

    # -- CSV / JSON output (reference: Statistics.cpp:2485-2783 + csv-docs) --

    def _result_record(self, res: PhaseResults) -> dict:
        first_s = res.first_done_usec / 1e6 or 1e-9
        last_s = res.last_done_usec / 1e6 or 1e-9
        rec = {
            "ISODate": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "Label": self.cfg.bench_label,
            "Phase": res.phase_name,
            "EntryType": res.entry_type,
            "NumWorkers": res.num_workers,
            "ElapsedUSecFirst": res.first_done_usec,
            "ElapsedUSecLast": res.last_done_usec,
            "EntriesFirst": res.stonewall["entries"],
            "EntriesLast": res.final["entries"],
            "EntriesPerSecFirst": round(res.stonewall["entries"] / first_s, 2),
            "EntriesPerSecLast": round(res.final["entries"] / last_s, 2),
            "IOPSFirst": round(res.stonewall["iops"] / first_s, 2),
            "IOPSLast": round(res.final["iops"] / last_s, 2),
            "BytesFirst": res.stonewall["bytes"],
            "BytesLast": res.final["bytes"],
            "MiBPerSecFirst": round(
                res.stonewall["bytes"] / first_s / (1 << 20), 2),
            "MiBPerSecLast": round(
                res.final["bytes"] / last_s / (1 << 20), 2),
            "CPUUtilStoneWall": round(res.cpu_stonewall, 1),
            "CPUUtil": round(res.cpu_last_done, 1),
            "IOLatUSecMin": res.iops_histo.min_micro,
            "IOLatUSecAvg": round(res.iops_histo.avg_micro, 1),
            "IOLatUSecMax": res.iops_histo.max_micro,
            "IOLatUSecP99": round(res.iops_histo.percentile(99), 1),
            "EntLatUSecMin": res.entries_histo.min_micro,
            "EntLatUSecAvg": round(res.entries_histo.avg_micro, 1),
            "EntLatUSecMax": res.entries_histo.max_micro,
            "TpuHbmBytes": res.tpu_bytes,
            "TpuHbmMiBPerSec": round(
                res.tpu_bytes / last_s / (1 << 20), 2) if res.tpu_bytes else 0,
            # dispatch-vs-DMA split of the transfer pipeline: host-side
            # submit cost vs per-transfer DMA wall time (overlapping
            # windows — divide bytes by PHASE time for bandwidth)
            "TpuDispatchUSec": res.tpu_dispatch_usec,
            "TpuTransferUSec": res.tpu_usec,
            "TpuPerChip": {str(k): {"Bytes": b, "USec": u}
                           for k, (b, u) in res.tpu_per_chip.items()},
            # H2D/D2H path audit, keyed by PATH_AUDIT_COUNTERS
            **res.tpu_path_counters,
            # --svctolerant: hosts lost mid-run (count in CSV; the host
            # list + control-plane audit counters are JSON-only)
            "NumHostsDegraded": len(res.degraded_hosts),
            "DegradedHosts": list(res.degraded_hosts),
            **res.control_counters,
            # telemetry (JSON-only): per-host CPU view, /metrics scrapes
            # served this run, spans recorded by the --tracefile ring
            "HostCPUUtil": dict(res.host_cpu_util),
            "TelemetryScrapes": (self.telemetry.registry.scrapes
                                 if self.telemetry is not None else 0),
            "TraceEvents": (self.manager.shared.tracer.num_recorded
                            if self.manager.shared.tracer is not None
                            else 0),
            # spans the --tracefile ring LOST (sampled out by
            # --tracesample + overwritten before a write) — so a sampled
            # trace is honest about what it dropped (JSON-only)
            "TraceDropped": (self.manager.shared.tracer.num_dropped
                             if self.manager.shared.tracer is not None
                             else 0),
            # crash-safe run lifecycle (JSON-only): number of finished
            # phases a --resume run skipped per its journal — non-zero
            # marks every record of a resumed run so the summarize tool
            # can banner it (0 = fresh run)
            "Resumed": getattr(self.cfg, "resumed_skipped_phases", 0),
        }
        # unconditional so CSV rows keep a fixed column count
        rec["RWMixReadIOPSLast"] = round(res.final_rwmix["iops"] / last_s, 2)
        rec["RWMixReadMiBPerSecLast"] = round(
            res.final_rwmix["bytes"] / last_s / (1 << 20), 2)
        # scenario identity (--scenario; docs/scenarios.md): every record
        # of a scenario run carries the scenario + step tag so the whole
        # JSON/CSV/summarize/chart pipeline works unchanged; EpochRateMiBs
        # is the per-epoch data rate on epoch-type legs (0 elsewhere) —
        # the coldwarm/epochs comparison column. Appended, never
        # reordered (make check-schema).
        rec["Scenario"] = getattr(self.cfg, "scenario", "")
        rec["ScenarioStep"] = getattr(self.cfg, "scenario_step_label", "")
        rec["EpochRateMiBs"] = rec["MiBPerSecLast"] \
            if getattr(self.cfg, "scenario_epoch", 0) else 0
        # the epoch number itself is JSON-only (popped for CSV)
        rec["ScenarioEpoch"] = getattr(self.cfg, "scenario_epoch", 0)
        # --autotune (JSON-only): whether this phase ran at a tuned
        # point and the search's measured gain over the defaults — the
        # summarize tool's Tuned/Gain% columns; the full Autotune block
        # (trajectory, chosen config, doctor diff) is its own terminal
        # AUTOTUNE record (docs/autotuning.md)
        tuned = getattr(self.cfg, "autotune_applied", None)
        rec["AutotuneTuned"] = bool(tuned)
        rec["AutotuneGainPct"] = tuned["gain_pct"] if tuned else 0
        return rec

    #: fixed result columns of the CSV schema (docs/result-columns.md);
    #: TpuPerChip and the TpuH2d*/TpuD2h* path-audit counters are JSON-only
    CSV_RESULT_COLUMNS = (
        "ISODate", "Label", "Phase", "EntryType", "NumWorkers",
        "ElapsedUSecFirst", "ElapsedUSecLast", "EntriesFirst", "EntriesLast",
        "EntriesPerSecFirst", "EntriesPerSecLast", "IOPSFirst", "IOPSLast",
        "BytesFirst", "BytesLast", "MiBPerSecFirst", "MiBPerSecLast",
        "CPUUtilStoneWall", "CPUUtil", "IOLatUSecMin", "IOLatUSecAvg",
        "IOLatUSecMax", "IOLatUSecP99", "EntLatUSecMin", "EntLatUSecAvg",
        "EntLatUSecMax", "TpuHbmBytes", "TpuHbmMiBPerSec",
        "TpuDispatchUSec", "TpuTransferUSec", "NumHostsDegraded",
        "RWMixReadIOPSLast", "RWMixReadMiBPerSecLast",
        "Scenario", "ScenarioStep", "EpochRateMiBs")

    @classmethod
    def check_csv_file_compatibility(cls, cfg) -> None:
        """Appending to an existing CSV requires a matching column count
        (reference: checkCSVFileCompatibility, ProgArgs.cpp:4303 — catches
        files written by a different version/config before any phase
        runs). Raises ValueError on mismatch."""
        path = cfg.csv_file_path
        if not path or not os.path.exists(path) \
                or os.path.getsize(path) == 0:
            return
        with open(path) as f:
            first_line = f.readline().rstrip("\n")
        found = first_line.count(",")
        labels = 0 if cfg.no_csv_labels else len(cfg.config_labels())
        expected = len(cls.CSV_RESULT_COLUMNS) + labels - 1
        if found == expected:
            return
        if getattr(cfg, "_defaulted_csv", False):
            # implicit default file (user never asked for CSV): rotate to
            # a fresh suffixed name instead of failing the run — a new
            # release adding flags would otherwise break every run until
            # the stale default file is deleted by hand
            base, ext = os.path.splitext(path)
            for n in range(2, 1000):
                candidate = f"{base}_{n}{ext}"
                if not os.path.exists(candidate) \
                        or os.path.getsize(candidate) == 0 \
                        or cls._csv_columns_match(candidate, expected):
                    from ..toolkits.logger import log
                    log(0, f"NOTE: default CSV result file {path} has an "
                           f"incompatible column count (old version?); "
                           f"writing to {candidate} instead")
                    cfg.csv_file_path = candidate
                    return
        raise ValueError(
            f"CSV output file exists and the column compatibility "
            f"check failed (was it written by a different version or "
            f"with different label settings?). Found commas: {found}; "
            f"expected: {expected}; file: {path}")

    @staticmethod
    def _csv_columns_match(path: str, expected: int) -> bool:
        with open(path) as f:
            return f.readline().rstrip("\n").count(",") == expected

    def _write_csv(self, res: PhaseResults) -> None:
        from ..service.fault_tolerance import CONTROL_AUDIT_COUNTERS
        rec = self._result_record(res)
        rec.pop("TpuPerChip")
        for _attr, key, _ingest in PATH_AUDIT_COUNTERS:  # JSON-only keys
            rec.pop(key)
        rec.pop("DegradedHosts")  # list is JSON-only; the count stays CSV
        for _attr, key, _mode in CONTROL_AUDIT_COUNTERS:  # JSON-only keys
            rec.pop(key)
        for key in ("HostCPUUtil", "TelemetryScrapes", "TraceEvents",
                    "TraceDropped", "Resumed", "ScenarioEpoch",
                    "AutotuneTuned", "AutotuneGainPct"):
            rec.pop(key)  # telemetry + lifecycle keys are JSON-only
        assert tuple(rec) == self.CSV_RESULT_COLUMNS, "CSV schema drift"
        labels = {} if self.cfg.no_csv_labels else self.cfg.config_labels()
        path = self.cfg.csv_file_path
        new_file = not os.path.exists(path) or os.path.getsize(path) == 0
        with open(path, "a") as f:
            if new_file:
                f.write(",".join(list(rec) + list(labels)) + "\n")
            # comma-escape EVERY value (Label is user-supplied) so the
            # fixed column count the compatibility check relies on holds
            vals = [str(v).replace(",", ";")
                    for v in list(rec.values()) + list(labels.values())]
            f.write(",".join(vals) + "\n")

    def _write_json(self, res: PhaseResults) -> None:
        """JSONL: one JSON object per phase result (consumed by
        tools/elbencho-tpu-summarize-json)."""
        rec = self._result_record(res)
        rec["Config"] = self.cfg.config_labels()
        rec["ElapsedUSecList"] = res.elapsed_usec_vec
        rec["IOLatHisto"] = res.iops_histo.to_dict()
        rec["EntLatHisto"] = res.entries_histo.to_dict()
        if res.analysis is not None:
            # --flightrec run doctor: stage decomposition + bottleneck
            # verdict (docs/result-columns.md Analysis block); absent
            # without --flightrec so the off path stays byte-identical
            rec["Analysis"] = res.analysis
        if res.tail_analysis is not None:
            # --slowops tail forensics (docs/result-columns.md
            # TailAnalysis block); absent without --slowops so the off
            # path stays byte-identical
            rec["TailAnalysis"] = res.tail_analysis
        with open(self.cfg.json_file_path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    # -- service protocol views (used by HTTP /status & /benchresult) --------

    def get_live_stats_dict(self) -> dict:
        entries, num_bytes, iops, done = self._sum_live_ops()
        shared = self.manager.shared
        workers = self.manager.workers
        lat_sums = {"NumIOLatUSec": 0, "SumIOLatUSec": 0,
                    "NumEntLatUSec": 0, "SumEntLatUSec": 0}
        for w in workers:
            # rwmix reads fold into the io sums, matching the live
            # bucket view (merge_live_latency_histos) — the master's
            # flight-recorder IoBusyUSec must not undercount rwmix runs
            lat_sums["NumIOLatUSec"] += w.iops_latency_histo.num_values \
                + w.iops_latency_histo_rwmix.num_values
            lat_sums["SumIOLatUSec"] += w.iops_latency_histo.sum_micro \
                + w.iops_latency_histo_rwmix.sum_micro
            lat_sums["NumEntLatUSec"] += w.entries_latency_histo.num_values
            lat_sums["SumEntLatUSec"] += w.entries_latency_histo.sum_micro
        tpu_bytes, tpu_usec, tpu_dispatch_usec = \
            sum_tpu_transfer_totals(workers)
        stats = {
            "BenchID": shared.bench_uuid,
            "PhaseCode": int(shared.current_phase),
            "PhaseName": phase_name(shared.current_phase),
            "NumWorkersDone": shared.num_workers_done,
            "NumWorkersDoneWithError": shared.num_workers_done_with_error,
            "NumEntriesDone": entries,
            "NumBytesDone": num_bytes,
            "NumIOPSDone": iops,
            "CPUUtil": round(self._status_cpu.sample(), 1),
            **lat_sums,
            # live telemetry harvest: the master mirrors these into its
            # RemoteWorker's ingest attributes on every /status poll so
            # its /metrics fleet view aggregates mid-run (same wire keys
            # and merge rules as the phase-end /benchresult payload)
            "TpuHbmBytes": tpu_bytes,
            "TpuHbmUSec": tpu_usec,
            "TpuHbmDispatchUSec": tpu_dispatch_usec,
            **sum_path_audit_counters(workers),
        }
        if getattr(self.cfg, "telemetry", False):
            # bucket-level latency for the master's /metrics histogram;
            # only shipped when the master asked for telemetry (the flag
            # travels the config wire) to keep the common poll lean
            io_histo, ent_histo = merge_live_latency_histos(workers)
            stats["IOLatHisto"] = io_histo.to_dict()
            stats["EntLatHisto"] = ent_histo.to_dict()
        return stats

    def get_bench_result_dict(self) -> dict:
        """Final per-phase result for the master (per-worker elapsed vec +
        mergeable histograms, reference: getBenchResultAsPropertyTreeForService
        Statistics.cpp:2784)."""
        shared = self.manager.shared
        elapsed_vec = []
        tpu_bytes = tpu_usec = tpu_dispatch_usec = 0
        tpu_per_chip = {}
        for w in self.manager.workers:
            if w.got_phase_work:
                elapsed_vec.extend(w.elapsed_usec_vec)
            tpu_bytes += w.tpu_transfer_bytes
            tpu_usec += w.tpu_transfer_usec
            tpu_dispatch_usec += w.tpu_dispatch_usec
            if getattr(w, "_tpu", None) is not None:
                chip = w._tpu.chip_id
                b, u = tpu_per_chip.get(chip, (0, 0))
                tpu_per_chip[chip] = (b + w.tpu_transfer_bytes,
                                      u + w.tpu_transfer_usec)
            else:  # RemoteWorker: per-chip map ingested from service JSON
                for chip, (b2, u2) in getattr(w, "tpu_per_chip",
                                              {}).items():
                    b, u = tpu_per_chip.get(chip, (0, 0))
                    tpu_per_chip[chip] = (b + b2, u + u2)
        iops_histo = LatencyHistogram()
        entries_histo = LatencyHistogram()
        iops_histo_rwmix = LatencyHistogram()
        final = {"entries": 0, "bytes": 0, "iops": 0}
        stonewall = {"entries": 0, "bytes": 0, "iops": 0}
        final_rwmix = {"entries": 0, "bytes": 0, "iops": 0}
        stonewall_rwmix = {"entries": 0, "bytes": 0, "iops": 0}
        for w in self.manager.workers:
            if not w.got_phase_work:
                continue
            iops_histo.merge(w.iops_latency_histo)
            entries_histo.merge(w.entries_latency_histo)
            iops_histo_rwmix.merge(w.iops_latency_histo_rwmix)
            final["entries"] += w.live_ops.num_entries_done
            final["bytes"] += w.live_ops.num_bytes_done
            final["iops"] += w.live_ops.num_iops_done
            stonewall["entries"] += w.stonewall_ops.num_entries_done
            stonewall["bytes"] += w.stonewall_ops.num_bytes_done
            stonewall["iops"] += w.stonewall_ops.num_iops_done
            final_rwmix["entries"] += w.live_ops_rwmix_read.num_entries_done
            final_rwmix["bytes"] += w.live_ops_rwmix_read.num_bytes_done
            final_rwmix["iops"] += w.live_ops_rwmix_read.num_iops_done
            stonewall_rwmix["bytes"] += \
                w.stonewall_ops_rwmix_read.num_bytes_done
            stonewall_rwmix["iops"] += \
                w.stonewall_ops_rwmix_read.num_iops_done
            stonewall_rwmix["entries"] += \
                w.stonewall_ops_rwmix_read.num_entries_done
        stonewall_elapsed = [w.stonewall_elapsed_usec
                             for w in self.manager.workers
                             if w.got_phase_work and w.stonewall_taken]
        return {
            "BenchID": shared.bench_uuid,
            "PhaseCode": int(shared.current_phase),
            "NumWorkersDone": shared.num_workers_done,
            "NumWorkersDoneWithError": shared.num_workers_done_with_error,
            "ElapsedUSecList": elapsed_vec,
            "StoneWallUSec": min(stonewall_elapsed, default=0),
            "Final": final,
            "StoneWall": stonewall,
            "FinalRWMixRead": final_rwmix,
            "StoneWallRWMixRead": stonewall_rwmix,
            "IOLatHisto": iops_histo.to_dict(),
            "EntLatHisto": entries_histo.to_dict(),
            "IOLatHistoRWMixRead": iops_histo_rwmix.to_dict(),
            "CPUUtilStoneWall": round(shared.cpu_util_stonewall, 1),
            "CPUUtil": round(shared.cpu_util_last_done, 1),
            "TpuHbmBytes": tpu_bytes,
            "TpuHbmUSec": tpu_usec,
            # host-side submit cost of the transfer pipeline, shipped
            # separately so the master's dispatch-vs-DMA split survives
            # distribution (RemoteWorker ingests it as tpu_dispatch_usec)
            "TpuHbmDispatchUSec": tpu_dispatch_usec,
            # per-chip breakdown travels the wire so the master's merged
            # record can attribute bytes to chips across services
            "TpuPerChip": {str(k): {"Bytes": b, "USec": u}
                           for k, (b, u) in tpu_per_chip.items()},
            # H2D/D2H path audit, keyed by PATH_AUDIT_COUNTERS
            **sum_path_audit_counters(self.manager.workers),
        }

    def close(self) -> None:
        self._exit_fullscreen_keys()
        for fh in (self._live_csv_fh, self._live_json_fh):
            if fh is not None and fh is not sys.stdout:
                fh.close()
        self._live_csv_fh = self._live_json_fh = None

    def abort_cleanup(self) -> None:
        """Master-side abort hygiene: close the live streams and remove
        live-stats files this run opened but never wrote a data row to —
        a back-to-back run must not inherit a stale header-only artifact
        (run lifecycle satellite, docs/fault-tolerance.md)."""
        self.close()
        if self._live_rows:
            return  # real data: keep the files
        for path in (self.cfg.live_csv_file_path,
                     self.cfg.live_json_file_path):
            if not path or path == "stdout":
                continue
            try:
                # the streams open in append mode: an earlier run's rows
                # may live in the same file — remove only empty or
                # header-only leftovers
                with open(path) as f:
                    head = f.readline()
                    more = f.readline()
                if more or (head and not head.startswith("ISODate")):
                    continue
                os.unlink(path)
            except OSError:
                pass
