"""Latency histogram with log2 buckets at quarter-log2 resolution.

Reference: source/LatencyHistogram.{h,cpp} — 112 buckets covering 1 us to
2^28 us (LatencyHistogram.h:14-18); min/avg/max; percentiles including
configurable "number of nines" (``--latpercent9s``); mergeable across
workers (operator+= :185); serializable for the service protocol (:35-37).

Bucket index for a value v (microseconds): floor(4 * log2(v)) for v >= 1,
bucket 0 for v < 1; clamped to the last bucket. This gives 4 buckets per
power of two => ~19% bucket width, matching the reference's quarter-log2
resolution.
"""

from __future__ import annotations

import math

NUM_BUCKETS = 112  # 4 per log2 step, 28 log2 steps
_LOG2_QUARTERS = 4


def bucket_index(micro_secs: float) -> int:
    if micro_secs < 1:
        return 0
    idx = int(_LOG2_QUARTERS * math.log2(micro_secs))
    return min(idx, NUM_BUCKETS - 1)


def bucket_lower_bound(idx: int) -> float:
    """Smallest microsecond value landing in bucket idx."""
    return 2 ** (idx / _LOG2_QUARTERS)


class LatencyHistogram:
    __slots__ = ("buckets", "num_values", "sum_micro", "min_micro",
                 "max_micro")

    def __init__(self):
        self.buckets = [0] * NUM_BUCKETS
        self.num_values = 0
        self.sum_micro = 0
        self.min_micro = 0
        self.max_micro = 0

    def add_latency(self, micro_secs: float) -> None:
        micro_secs = int(micro_secs)
        self.buckets[bucket_index(micro_secs)] += 1
        if not self.num_values or micro_secs < self.min_micro:
            self.min_micro = micro_secs
        if micro_secs > self.max_micro:
            self.max_micro = micro_secs
        self.num_values += 1
        self.sum_micro += micro_secs

    def add_latencies_array(self, micro_secs) -> None:
        """Vectorized bulk insert of a uint64 numpy array (the native
        engine returns per-block latencies in bulk; per-value Python
        add_latency would dominate small-block hot paths)."""
        import numpy as np
        n = len(micro_secs)
        if not n:
            return
        vals = np.asarray(micro_secs, dtype=np.uint64)
        lo = int(vals.min())
        if not self.num_values or lo < self.min_micro:
            self.min_micro = lo
        hi = int(vals.max())
        if hi > self.max_micro:
            self.max_micro = hi
        self.num_values += n
        self.sum_micro += int(vals.sum())
        # bucket = floor(4*log2(v)) for v >= 1 (bucket_index, vectorized)
        clipped = np.maximum(vals, 1).astype(np.float64)
        idx = np.minimum((_LOG2_QUARTERS * np.log2(clipped)).astype(np.int64),
                         NUM_BUCKETS - 1)
        counts = np.bincount(idx, minlength=NUM_BUCKETS)
        for i in np.nonzero(counts)[0]:
            self.buckets[int(i)] += int(counts[i])

    # -- aggregation --------------------------------------------------------

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """In-place merge (reference operator+=, LatencyHistogram.h:185)."""
        if other.num_values:
            if not self.num_values or other.min_micro < self.min_micro:
                self.min_micro = other.min_micro
            if other.max_micro > self.max_micro:
                self.max_micro = other.max_micro
        self.num_values += other.num_values
        self.sum_micro += other.sum_micro
        for i, count in enumerate(other.buckets):
            self.buckets[i] += count
        return self

    def reset(self) -> None:
        self.__init__()

    # -- queries ------------------------------------------------------------

    @property
    def avg_micro(self) -> float:
        return self.sum_micro / self.num_values if self.num_values else 0.0

    def percentile(self, pct: float) -> float:
        """Latency (us) below which pct% of samples fall (bucket lower bound,
        like the reference's bucket-walk percentile)."""
        if not self.num_values:
            return 0.0
        target = self.num_values * (pct / 100.0)
        running = 0
        for idx, count in enumerate(self.buckets):
            running += count
            if running >= target and count:
                return bucket_lower_bound(idx)
        return float(self.max_micro)

    def percentiles_nines(self, num_nines: int = 2) -> "dict[str, float]":
        """p50/p75/p99 plus p99.9... up to num_nines total nines
        (reference: --latpercent9s)."""
        out = {"p50": self.percentile(50), "p75": self.percentile(75),
               "p99": self.percentile(99)}
        pct = 99.0
        frac = 0.9
        for _ in range(3, num_nines + 1):  # p99 already covers two nines
            pct = pct + frac
            frac /= 10
            out[f"p{pct:g}"] = self.percentile(pct)
        return out

    # -- serialization (service protocol) -----------------------------------

    def to_dict(self, include_buckets: bool = True) -> dict:
        d = {
            "LatMicroSecTotal": self.sum_micro,
            "LatNumValues": self.num_values,
            "LatMinMicroSec": self.min_micro,
            "LatMaxMicroSec": self.max_micro,
        }
        if include_buckets:
            # sparse encoding: only non-zero buckets
            d["LatHistoList"] = {str(i): c for i, c in enumerate(self.buckets) if c}
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "LatencyHistogram":
        histo = cls()
        histo.sum_micro = int(d.get("LatMicroSecTotal", 0))
        histo.num_values = int(d.get("LatNumValues", 0))
        histo.min_micro = int(d.get("LatMinMicroSec", 0))
        histo.max_micro = int(d.get("LatMaxMicroSec", 0))
        for idx_str, count in d.get("LatHistoList", {}).items():
            histo.buckets[int(idx_str)] = int(count)
        return histo

    def to_prometheus_buckets(self) -> "list[tuple[float, int]]":
        """Cumulative (upper_bound_usec, count) pairs over the log2
        buckets for Prometheus histogram exposition (telemetry/registry):
        a value in bucket i is < bucket_lower_bound(i + 1), so that upper
        edge is the bucket's ``le`` bound. Always ends with (+Inf,
        num_values); counts are monotonically non-decreasing by
        construction. Only buckets up to the last non-empty one are
        emitted (the tail would repeat num_values 100+ times)."""
        out: "list[tuple[float, int]]" = []
        running = 0
        last_nonzero = -1
        for idx in range(NUM_BUCKETS - 1, -1, -1):
            if self.buckets[idx]:
                last_nonzero = idx
                break
        for idx in range(last_nonzero + 1):
            running += self.buckets[idx]
            le = bucket_lower_bound(idx + 1)
            if idx == NUM_BUCKETS - 1 and self.max_micro >= le:
                # the top bucket CLAMPS outliers beyond its bound
                # (bucket_index); reporting them under a finite `le`
                # they exceed would cap every derived quantile there —
                # fold the clamp bucket into +Inf instead
                break
            out.append((le, running))
        out.append((float("inf"), self.num_values))
        return out

    def histogram_str(self) -> str:
        """Compact "bucketLowerBound=count" dump for --lathisto."""
        parts = [f"{bucket_lower_bound(i):.0f}us={c}"
                 for i, c in enumerate(self.buckets) if c]
        return ", ".join(parts)
