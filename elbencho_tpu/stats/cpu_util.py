"""CPU utilization from /proc/stat deltas between update() calls.

Reference: source/CPUUtil.{h,cpp} (CPUUtil.h:14-46). Used to bracket each
benchmark phase (stonewall + last-done snapshots, WorkersSharedData.h:57-58)
and for live ``--cpu`` display.
"""

from __future__ import annotations


class CPUUtil:
    def __init__(self):
        self._last_busy = 0
        self._last_total = 0
        self._current_pct = 0.0

    @staticmethod
    def _read_proc_stat() -> "tuple[int, int]":
        try:
            with open("/proc/stat", "r") as f:
                fields = f.readline().split()[1:]
            vals = [int(v) for v in fields]
        except (OSError, ValueError, IndexError):
            return (0, 0)
        idle = vals[3] + (vals[4] if len(vals) > 4 else 0)  # idle + iowait
        total = sum(vals)
        return (total - idle, total)

    def update(self) -> float:
        """Refresh utilization percentage from the delta since last update."""
        busy, total = self._read_proc_stat()
        d_busy = busy - self._last_busy
        d_total = total - self._last_total
        self._last_busy, self._last_total = busy, total
        self._current_pct = (100.0 * d_busy / d_total) if d_total > 0 else 0.0
        return self._current_pct

    @property
    def percent(self) -> float:
        return self._current_pct


class SampledCPUUtil(CPUUtil):
    """CPUUtil for on-demand samplers (/status replies, /metrics
    scrapes) that must not touch the benchmark's shared phase meter —
    updating that one would reset its /proc/stat baseline out from under
    the stonewall/last-done snapshots. Baseline-primed at construction
    (a first unprimed delta would report the since-boot average), and
    rate-limited so a fast poller can't shrink the measurement window
    into jiffy noise."""

    def __init__(self, min_interval_secs: float = 1.0):
        import time
        super().__init__()
        self._min_interval = min_interval_secs
        self.update()  # prime the baseline; percent stays 0 until due
        self._last_sample = time.monotonic()  # window starts at priming

    def sample(self) -> float:
        """update() if the window elapsed, else the last value."""
        import time
        now = time.monotonic()
        if now - self._last_sample >= self._min_interval:
            self._last_sample = now
            return self.update()
        return self._current_pct
