"""NUMA zone binding (reference: source/toolkits/NumaTk.h via libnuma).

Pure-Python equivalent: bind the calling thread's CPU affinity to the CPUs
of the given NUMA node (from sysfs), which is what the reference's
``--zones`` round-robin binding achieves for worker threads.
"""

from __future__ import annotations

import os

from ..toolkits import logger


def _node_cpus(zone: int) -> "set[int]":
    path = f"/sys/devices/system/node/node{zone}/cpulist"
    try:
        with open(path) as f:
            spec = f.read().strip()
    except OSError:
        return set()
    cpus: "set[int]" = set()
    for part in spec.split(","):
        if "-" in part:
            lo, hi = part.split("-")
            cpus.update(range(int(lo), int(hi) + 1))
        elif part:
            cpus.add(int(part))
    return cpus


def bind_to_numa_zone(zone: int) -> bool:
    cpus = _node_cpus(zone)
    if not cpus:
        logger.log_error(f"NUMA zone {zone} not found or empty; "
                         "skipping binding")
        return False
    try:
        os.sched_setaffinity(0, cpus)
        return True
    except OSError as err:
        logger.log_error(f"NUMA binding to zone {zone} failed: {err}")
        return False


def numa_is_available() -> bool:
    return os.path.isdir("/sys/devices/system/node/node0")
