"""NUMA zone binding (reference: source/toolkits/NumaTk.h:22-320 via
libnuma — bindToNumaZones / setMemPolicy).

Two halves, matching the reference's split:

- CPU affinity: bind the calling thread to the CPUs of a NUMA node
  (sysfs cpulist + sched_setaffinity) — the ``--zones`` round-robin
  worker binding.
- MEMORY policy: libnuma isn't a baked-in dependency, so the
  set_mempolicy/mbind/get_mempolicy syscalls are invoked directly via
  ctypes. ``bind_to_numa_zone`` applies MPOL_BIND for the thread (all
  its future page faults allocate on the zone), and ``mbind_buffer``
  pins an already-mmap'd I/O buffer to the zone (MPOL_MF_MOVE migrates
  any pages that faulted elsewhere first) — the staging buffers a
  worker DMAs through should live next to the core driving them.
"""

from __future__ import annotations

import ctypes
import os
import platform

from ..toolkits import logger

# mode constants (linux/mempolicy.h)
MPOL_DEFAULT = 0
MPOL_PREFERRED = 1
MPOL_BIND = 2
MPOL_INTERLEAVE = 3
# mbind flags
MPOL_MF_MOVE = 1 << 1
# get_mempolicy flags
MPOL_F_NODE = 1 << 0
MPOL_F_ADDR = 1 << 1

#: syscall numbers differ per arch (no libc wrappers outside libnuma)
_SYSCALLS = {
    "x86_64": {"mbind": 237, "set_mempolicy": 238, "get_mempolicy": 239},
    "aarch64": {"mbind": 235, "set_mempolicy": 237, "get_mempolicy": 236},
}

_MAXNODE = 64  # one u64 nodemask covers every machine this targets


def _syscall_table() -> "dict[str, int] | None":
    return _SYSCALLS.get(platform.machine())


def _libc():
    return ctypes.CDLL(None, use_errno=True)


def _nodemask(zone: int) -> ctypes.c_uint64:
    return ctypes.c_uint64(1 << zone)


def set_thread_mempolicy_bind(zone: int) -> bool:
    """MPOL_BIND the calling thread's allocations to one node
    (reference: NumaTk setMemPolicy / numa_set_membind)."""
    table = _syscall_table()
    if table is None:
        return False
    mask = _nodemask(zone)
    res = _libc().syscall(table["set_mempolicy"], MPOL_BIND,
                          ctypes.byref(mask), _MAXNODE)
    if res != 0:
        logger.log_error(
            f"set_mempolicy(MPOL_BIND, node {zone}) failed: "
            f"{os.strerror(ctypes.get_errno())}")
        return False
    return True


def reset_thread_mempolicy() -> bool:
    """Back to MPOL_DEFAULT (tests; and phase teardown symmetry)."""
    table = _syscall_table()
    if table is None:
        return False
    return _libc().syscall(table["set_mempolicy"], MPOL_DEFAULT,
                           None, _MAXNODE) == 0


def get_thread_mempolicy() -> "tuple[int, int] | None":
    """(mode, nodemask) of the calling thread, or None when
    unsupported — lets tests assert the policy actually landed."""
    table = _syscall_table()
    if table is None:
        return None
    mode = ctypes.c_int(0)
    mask = ctypes.c_uint64(0)
    res = _libc().syscall(table["get_mempolicy"], ctypes.byref(mode),
                          ctypes.byref(mask), _MAXNODE, None, 0)
    if res != 0:
        return None
    return mode.value, mask.value


def mbind_buffer(addr: int, length: int, zone: int) -> bool:
    """MPOL_BIND one mmap'd region to a node, migrating already-faulted
    pages (reference: NumaTk.h mbind of the GPU staging buffers). addr
    must be page-aligned — true for mmap allocations."""
    table = _syscall_table()
    if table is None:
        return False
    mask = _nodemask(zone)
    res = _libc().syscall(table["mbind"], ctypes.c_void_p(addr),
                          ctypes.c_ulong(length), MPOL_BIND,
                          ctypes.byref(mask), _MAXNODE, MPOL_MF_MOVE)
    if res != 0:
        logger.log_error(
            f"mbind(node {zone}, {length} bytes) failed: "
            f"{os.strerror(ctypes.get_errno())}")
        return False
    return True


def get_buffer_policy(addr: int) -> "tuple[int, int] | None":
    """(mode, nodemask) governing an address (MPOL_F_ADDR), or None."""
    table = _syscall_table()
    if table is None:
        return None
    mode = ctypes.c_int(0)
    mask = ctypes.c_uint64(0)
    res = _libc().syscall(table["get_mempolicy"], ctypes.byref(mode),
                          ctypes.byref(mask), _MAXNODE,
                          ctypes.c_void_p(addr), MPOL_F_ADDR)
    if res != 0:
        return None
    return mode.value, mask.value


def _node_cpus(zone: int) -> "set[int]":
    path = f"/sys/devices/system/node/node{zone}/cpulist"
    try:
        with open(path) as f:
            spec = f.read().strip()
    except OSError:
        return set()
    cpus: "set[int]" = set()
    for part in spec.split(","):
        if "-" in part:
            lo, hi = part.split("-")
            cpus.update(range(int(lo), int(hi) + 1))
        elif part:
            cpus.add(int(part))
    return cpus


def bind_to_numa_zone(zone: int, bind_memory: bool = True) -> bool:
    """Bind the calling thread's CPU affinity AND (by default) its memory
    policy to one NUMA zone — the reference binds both
    (NumaTk.h:22-320: numa_run_on_node + set_mempolicy)."""
    cpus = _node_cpus(zone)
    if not cpus:
        logger.log_error(f"NUMA zone {zone} not found or empty; "
                         "skipping binding")
        return False
    try:
        os.sched_setaffinity(0, cpus)
    except OSError as err:
        logger.log_error(f"NUMA binding to zone {zone} failed: {err}")
        return False
    if bind_memory:
        # a failed memory bind degrades to CPU-only binding with the
        # error logged (same behavior as the reference's soft fallback)
        set_thread_mempolicy_bind(zone)
    return True


def numa_is_available() -> bool:
    return os.path.isdir("/sys/devices/system/node/node0")
