"""Unified registered-buffer staging allocator (ROADMAP open item 5).

One per-worker pool owns the WHOLE staging-buffer lifecycle that used to
be scattered across three bespoke implementations:

  - the worker's per-iodepth ``mmap`` I/O buffers (local_worker.py
    ``_alloc_io_buffer`` + the ``gc.collect()``-guarded teardown dance),
  - ``TpuWorkerContext``'s page-aligned aggregation mmaps (--tpubatch),
  - the plain Python buffers of the S3/GCS multipart and HDFS paths.

The pool allocates ONE slab, right, once:

  - hugepage-backed where available: ``MAP_HUGETLB`` first (real
    reserved hugepages — TLB-cheap and unswappable for DMA), graceful
    fallback to a normal anonymous mapping with ``MADV_HUGEPAGE``
    honoring the existing ``--madvise hugepage``/``nohugepage`` idiom;
  - O_DIRECT-safe: every slot starts on a 4 KiB boundary (64-byte
    alignment for the dlpack export of --tpudirect falls out of that);
  - NUMA-bound: the slab is ``mbind``-pinned to the worker's ``--zones``
    zone via the existing mempolicy plumbing (utils/numa.py), so DMA
    source/target pages live next to the core driving them;
  - registered ONCE: the slab becomes the fixed-buffer table of a
    persistent io_uring (csrc ABI 11 ``ioengine_pool_*``) shared by the
    classic block loop and the streaming ring — no per-call
    ``get_user_pages`` pin/unpin ever again — optionally with an SQPOLL
    submission thread (``--iosqpoll``) that takes ``io_uring_enter``
    off the submit path entirely.

Every capability degrades LOUDLY down a fallback ladder mirroring the
engine's uring -> AIO -> Python chain:

  hugetlb slab  -> THP-advised slab  -> plain slab
  SQPOLL ring   -> enter-based ring  -> no pool ring (per-call paths)

Audit counters (``pool_buf_reuses``/``pool_occupancy_hwm``/
``pool_registered_ops``/``pool_sqpoll_ops``) flow through
``PATH_AUDIT_COUNTERS`` into the service wire, JSON, ``/metrics`` and
trace spans like every prior counter.
"""

from __future__ import annotations

import ctypes
import mmap
import os

from ..toolkits import logger

#: O_DIRECT-safe slot stride (matches csrc kAlign; 64B-alignment for the
#: --tpudirect dlpack export is implied)
SLOT_ALIGN = 4096

#: hugetlb mappings must be multiples of the huge page size
HUGE_PAGE_BYTES = 2 << 20

_MAP_HUGETLB = getattr(mmap, "MAP_HUGETLB", 0x40000)
_MADV_HUGEPAGE = getattr(mmap, "MADV_HUGEPAGE", 14)
_MADV_NOHUGEPAGE = getattr(mmap, "MADV_NOHUGEPAGE", 15)

#: slabs deliberately kept alive for the life of the process after a
#: ring drain failed with kernel-owned ops still in flight — dropping
#: the references would munmap memory a late DMA completion lands in
#: (the pool-owned successor of local_worker._LEAKED_STREAM_BUFFERS)
_LEAKED_SLABS: "list" = []


class StagingPoolExhausted(RuntimeError):
    """acquire() found no free slot (checkout API; the rotation-based
    hot loops never hit this — their slot count IS the pool size)."""


def _align_up(n: int, align: int) -> int:
    return (n + align - 1) // align * align


class StagingPool:
    """Per-worker staging allocator; see the module docstring.

    The hot loops address slots by rotation index (``views[i]`` /
    ``slot_addrs[i]``, the worker's existing ``% n_slots`` discipline);
    ``acquire``/``release`` is the checkout API for auxiliary users and
    tests. Both feed the same occupancy/reuse audit counters.
    """

    def __init__(self, n_slots: int, slot_size: int, *,
                 numa_zone: "int | None" = None, fill_algo=None,
                 madvise_flags: str = "", register: bool = True,
                 want_sqpoll: bool = False, sqpoll_idle_ms: int = 2000,
                 native=None, log_rank: "int | None" = 0):
        self.n_slots = max(n_slots, 1)
        self.slot_size = max(slot_size, 1)
        self.stride = _align_up(self.slot_size, SLOT_ALIGN)
        self.numa_zone = numa_zone
        self._madvise = {f.strip() for f in madvise_flags.split(",")
                         if f.strip()}
        self._log = log_rank == 0  # one worker logs for the host
        self.broken = False       # a ring drain failed: pool unusable
        self._leaked = False
        self._aux_slabs: "list" = []    # (mmap, views) of alloc_aux
        self._free: "list[int]" = []    # checkout API free list
        self._checked_out: "set[int]" = set()
        # -- audit counters (PATH_AUDIT_POOL_ATTRS schema names) --------
        self.pool_buf_reuses = 0       # slot hand-outs beyond first use
        self.pool_occupancy_hwm = 0    # max slots simultaneously in use
        self.pool_registered_ops = 0   # ops run against fixed buffers
        self.pool_sqpoll_ops = 0       # ops submitted with no enter
        self._first_uses_left = self.n_slots
        # -- the slab ---------------------------------------------------
        slab_bytes = self.n_slots * self.stride
        self._slab, self.hugepage_backed = self._map_slab(slab_bytes)
        base = ctypes.addressof(ctypes.c_char.from_buffer(self._slab))
        if numa_zone is not None:
            # pin the slab's pages to the worker's zone (MPOL_MF_MOVE
            # migrates anything the fill below would otherwise fault on
            # a foreign node) — the existing mempolicy plumbing
            from .numa import mbind_buffer
            mbind_buffer(base, len(self._slab), numa_zone)
        whole = memoryview(self._slab)
        self.views = [whole[i * self.stride:
                            i * self.stride + self.slot_size]
                      for i in range(self.n_slots)]
        self.slot_addrs = [base + i * self.stride
                           for i in range(self.n_slots)]
        self._free = list(range(self.n_slots))
        if fill_algo is not None:
            # pre-fill with random data so writes aren't trivially
            # compressible (same contract as the old _alloc_io_buffer)
            for mv in self.views:
                mv[:] = fill_algo.fill_buffer(self.slot_size)
        # -- the one-time registration / SQPOLL ladder ------------------
        self.native_pool = None
        self.registered = False
        self.sqpoll_active = False
        self.fallback_reason = ""
        if register:
            self._open_native_pool(native, want_sqpoll, sqpoll_idle_ms)
        elif want_sqpoll:
            self._note("NOTE: --iosqpoll ignored: pool registration is "
                       "disabled for this run")

    # ------------------------------------------------------------------
    # slab mapping ladder: hugetlb -> (THP-advised) normal mapping
    # ------------------------------------------------------------------

    def _map_slab(self, nbytes: int) -> "tuple[mmap.mmap, bool]":
        want_thp = "hugepage" in self._madvise
        no_huge = "nohugepage" in self._madvise
        if not no_huge:
            try:
                m = mmap.mmap(-1, _align_up(nbytes, HUGE_PAGE_BYTES),
                              flags=(mmap.MAP_PRIVATE | mmap.MAP_ANONYMOUS
                                     | _MAP_HUGETLB))
                return m, True
            except (OSError, ValueError):
                # no reserved hugepages (vm.nr_hugepages=0 is the common
                # case) or no MAP_HUGETLB support: normal mapping below
                pass
        m = mmap.mmap(-1, nbytes)
        try:
            if no_huge:
                m.madvise(_MADV_NOHUGEPAGE)
            elif want_thp:
                # --madvise hugepage routed to the staging slab too, not
                # just --mmap file mappings (transparent huge pages)
                m.madvise(_MADV_HUGEPAGE)
        except OSError:
            pass  # advice is advisory; an old kernel refusing it is fine
        return m, False

    # ------------------------------------------------------------------
    # native registration ladder: SQPOLL ring -> plain ring -> no ring
    # ------------------------------------------------------------------

    def _open_native_pool(self, native, want_sqpoll: bool,
                          sqpoll_idle_ms: int) -> None:
        if native is None:
            from .native import get_native_engine
            native = get_native_engine()
        if native is None:
            self.fallback_reason = "native ioengine unavailable"
            if want_sqpoll:
                self._note("NOTE: --iosqpoll requires the native "
                           "ioengine; staging buffers stay unregistered")
            return
        if want_sqpoll and not native.sqpoll_supported():
            # loud capability fallback BEFORE the open so the log names
            # the reason — and don't ask the open for SQPOLL at all (its
            # internal retry exists for races, not as the normal path)
            self._note("NOTE: --iosqpoll requested but this kernel/"
                       "process cannot get an SQPOLL ring (needs "
                       "io_uring with kernel 5.11+); falling back to "
                       "enter-based submission")
            want_sqpoll = False
        from .native import NativePoolError
        try:
            self.native_pool = native.open_pool(
                self.slot_addrs, self.stride, want_sqpoll=want_sqpoll,
                sqpoll_idle_ms=sqpoll_idle_ms)
        except NativePoolError as err:
            # kernel without io_uring (CI's 4.4 included): the loud tail
            # of the fallback ladder — everything keeps working on the
            # per-call registration paths
            self.fallback_reason = str(err)
            self._note(f"NOTE: staging-pool buffer registration "
                       f"unavailable ({err}); block loops and streams "
                       f"keep their per-call buffer paths")
            return
        self.registered = self.native_pool.fixed_buffers
        self.sqpoll_active = self.native_pool.sqpoll_active
        if want_sqpoll and not self.sqpoll_active:
            self._note("NOTE: --iosqpoll: SQPOLL ring refused at open; "
                       "running the pool ring with enter-based "
                       "submission instead")
        if not self.registered:
            self._note("NOTE: staging-pool fixed-buffer registration "
                       "refused (RLIMIT_MEMLOCK?); pool ring runs with "
                       "unregistered opcodes")
        elif self._log:
            mode = "sqpoll" if self.sqpoll_active else "enter"
            self._note(f"staging pool: {self.n_slots} x "
                       f"{self.slot_size} B slots registered once as "
                       f"io_uring fixed buffers (submit={mode}, "
                       f"hugepages={'on' if self.hugepage_backed else 'off'})")

    def _note(self, msg: str) -> None:
        if self._log:
            logger.log(logger.LOG_NORMAL, msg)

    # ------------------------------------------------------------------
    # slot access: rotation (hot loops) + checkout (aux users, tests)
    # ------------------------------------------------------------------

    def slot(self, i: int) -> memoryview:
        return self.views[i % self.n_slots]

    def acquire(self) -> int:
        """Check a slot out; raises StagingPoolExhausted when every slot
        is taken (the caller sized the pool — silent overcommit would
        alias in-flight DMA buffers)."""
        if not self._free:
            raise StagingPoolExhausted(
                f"all {self.n_slots} staging slots checked out")
        idx = self._free.pop()
        self._checked_out.add(idx)
        if self._first_uses_left > 0:
            self._first_uses_left -= 1
        else:
            self.pool_buf_reuses += 1
        self.note_occupancy(len(self._checked_out))
        return idx

    def release(self, idx: int) -> None:
        if idx in self._checked_out:
            self._checked_out.remove(idx)
            self._free.append(idx)

    def note_occupancy(self, in_use: int) -> None:
        if in_use > self.pool_occupancy_hwm:
            self.pool_occupancy_hwm = min(in_use, self.n_slots)

    def account_ops(self, n: int) -> None:
        """Rotation-path reuse accounting: n ops each consumed one slot
        hand-out; hand-outs beyond the slab's first full rotation are
        reuses (called from the shared _account_chunk seam and the
        per-op Python loops)."""
        if n <= 0:
            return
        first = min(n, self._first_uses_left)
        self._first_uses_left -= first
        self.pool_buf_reuses += n - first

    def book_engine_stats(self, fixed_ops: int, sqpoll_ops: int,
                          drain_failed: bool) -> None:
        """Ingest one native chunk's pool-engine stats
        (ioengine_run_block_loop5 out_pool_stats)."""
        self.pool_registered_ops += fixed_ops
        self.pool_sqpoll_ops += sqpoll_ops
        if drain_failed:
            # kernel-owned ops may still target the slab: stop using the
            # ring and keep the memory mapped for the life of the process
            self.broken = True
            logger.log_error(
                "staging pool: ring drain failed; keeping the slab "
                "mapped until process exit")
            self.leak()

    def account_stream_events(self, stream, n_events: int) -> None:
        """Registration/SQPOLL audit for n reaped streaming ops (the
        fused loop calls this per reap batch)."""
        if n_events <= 0:
            return
        if getattr(stream, "fixed_buffers", False):
            self.pool_registered_ops += n_events
        if getattr(stream, "sqpoll", False):
            self.pool_sqpoll_ops += n_events

    def reset_counters(self) -> None:
        """Per-phase counter reset. The pool itself persists across
        phases — that is its whole point — so _first_uses_left carries
        over: ops of a later phase on an already-rotated slab all count
        as reuses, which is exactly the cross-phase reuse the counter
        exists to prove."""
        self.pool_buf_reuses = 0
        self.pool_occupancy_hwm = 0
        self.pool_registered_ops = 0
        self.pool_sqpoll_ops = 0

    # ------------------------------------------------------------------
    # auxiliary allocations: same policy, same lifecycle, one owner
    # ------------------------------------------------------------------

    def alloc_aux(self, count: int, nbytes: int) -> "list[memoryview]":
        """Carve `count` page-aligned buffers of `nbytes` with the
        pool's allocation policy (hugepage attempt, NUMA bind) — the
        TpuWorkerContext aggregation slots; freed by pool close()."""
        m, _huge = self._map_slab(_align_up(nbytes, SLOT_ALIGN) * count)
        base = ctypes.addressof(ctypes.c_char.from_buffer(m))
        if self.numa_zone is not None:
            from .numa import mbind_buffer
            mbind_buffer(base, len(m), self.numa_zone)
        stride = _align_up(nbytes, SLOT_ALIGN)
        whole = memoryview(m)
        views = [whole[i * stride: i * stride + nbytes]
                 for i in range(count)]
        self._aux_slabs.append((m, whole, views))
        return views

    # ------------------------------------------------------------------
    # teardown: ONE lifecycle for every staging buffer
    # ------------------------------------------------------------------

    def leak(self) -> None:
        """Park the slab(s) in the module leak list: called when kernel
        DMA may still target them after a failed ring drain — unmapping
        would hand late completions unmapped address space."""
        if not self._leaked:
            self._leaked = True
            _LEAKED_SLABS.append((self._slab, self.views,
                                  list(self._aux_slabs)))
        self.broken = True

    def close(self) -> None:
        """Close the native ring and unmap every buffer the pool ever
        handed out. Replaces three bespoke teardown paths (including the
        gc.collect()-guarded mmap dance); a view exported to jax/numpy
        that outlives us leaves its mapping to process teardown via the
        BufferError guard — never a crash, never a use-after-free."""
        if self.native_pool is not None:
            if self.native_pool.close() != 0:
                # a pooled stream never released the ring (failed drain):
                # kernel DMA may still target the slab
                self.leak()
            self.native_pool = None
        if self._leaked:
            return
        for mv in self.views:
            _release_quietly(mv)
        self.views = []
        for m, whole, views in self._aux_slabs:
            for mv in views:
                _release_quietly(mv)
            _release_quietly(whole)
            try:
                m.close()
            except BufferError:
                pass  # an exported view outlived us; OS reclaims at exit
        self._aux_slabs = []
        try:
            self._slab.close()
        except BufferError:
            pass


def _release_quietly(mv: memoryview) -> None:
    """release() raises BufferError while an export (numpy/jax view) is
    still alive — the mapping then stays with the exporter and the OS
    reclaims it at process exit, same contract as the mmap close guard."""
    try:
        mv.release()
    except BufferError:
        pass
