"""ctypes loader for the native C++ ioengine (csrc/libioengine.so).

The reference's hot I/O loops are native C++ (rwBlockSized
LocalWorker.cpp:1702, aioBlockSized :1828 via libaio); this framework keeps
that property: the block loop runs in C++ when available and falls back to
the pure-Python loop otherwise (tests, unsupported workload features).

Build: ``make -C csrc`` (g++; no external deps beyond libaio if present).
"""

from __future__ import annotations

import ctypes
import os
import threading

_lock = threading.Lock()
_engine = None
_engine_checked = False

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# repo checkout layout (csrc/ beside the package) — buildable via make
_SO_PATH = os.path.join(os.path.dirname(_PKG_DIR), "csrc", "libioengine.so")
# installed layout (deb/rpm/wheel ship the prebuilt .so inside the package)
_SO_PATH_INSTALLED = os.path.join(_PKG_DIR, "_native", "libioengine.so")


# engine selector values (must match csrc/ioengine.cpp)
ENGINE_CODES = {"auto": 0, "sync": 1, "aio": 2, "uring": 3}


class _NativeEngine:
    """Thin wrapper over libioengine.so. See csrc/ioengine.cpp for the ABI."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.ioengine_run_block_loop2.restype = ctypes.c_int
        lib.ioengine_run_block_loop2.argtypes = [
            ctypes.c_int,                     # fd
            ctypes.POINTER(ctypes.c_uint64),  # offsets
            ctypes.POINTER(ctypes.c_uint64),  # lengths
            ctypes.c_uint64,                  # num_blocks
            ctypes.c_int,                     # is_write
            ctypes.c_void_p,                  # buffer
            ctypes.c_uint64,                  # buffer size
            ctypes.c_int,                     # iodepth
            ctypes.POINTER(ctypes.c_uint64),  # out: latencies (usec/block)
            ctypes.POINTER(ctypes.c_uint64),  # out: bytes done
            ctypes.POINTER(ctypes.c_int),     # interrupt flag
            ctypes.c_int,                     # engine (ENGINE_CODES)
        ]
        lib.ioengine_uring_supported.restype = ctypes.c_int
        lib.ioengine_uring_supported.argtypes = []

    def uring_supported(self) -> bool:
        return bool(self._lib.ioengine_uring_supported())

    def run_block_loop(self, fd: int, offsets, lengths, is_write: bool,
                       buf_addr: int, iodepth: int, worker,
                       interrupt_flag=None, engine: str = "auto") -> bool:
        n = len(offsets)
        off_arr = (ctypes.c_uint64 * n)(*offsets)
        len_arr = (ctypes.c_uint64 * n)(*lengths)
        lat_arr = (ctypes.c_uint64 * n)()
        bytes_done = ctypes.c_uint64(0)
        interrupt = (interrupt_flag if interrupt_flag is not None
                     else ctypes.c_int(0))  # c_int(0) is falsy: no `or`!
        buf_size = max(lengths)
        ret = self._lib.ioengine_run_block_loop2(
            fd, off_arr, len_arr, n, 1 if is_write else 0,
            ctypes.c_void_p(buf_addr), buf_size, iodepth,
            lat_arr, ctypes.byref(bytes_done), ctypes.byref(interrupt),
            ENGINE_CODES[engine])
        if ret < 0:
            raise OSError(-ret, os.strerror(-ret))
        total_bytes = sum(lengths)
        if bytes_done.value == total_bytes:
            for i in range(n):
                worker.iops_latency_histo.add_latency(lat_arr[i])
            worker.live_ops.num_iops_done += n
        else:
            # interrupted chunk: AIO completes out of order, so per-block
            # latencies can't be attributed reliably — count bytes/ops only
            # (the phase is being aborted; its results are partial anyway)
            avg_len = max(total_bytes // n, 1)
            worker.live_ops.num_iops_done += \
                min(n, bytes_done.value // avg_len)
        worker.live_ops.num_bytes_done += bytes_done.value
        worker.create_stonewall_stats_if_triggered()
        return True


def get_native_engine() -> "_NativeEngine | None":
    """Lazily load the native engine; None if not built or disabled via
    ELBENCHO_TPU_NO_NATIVE=1."""
    global _engine, _engine_checked
    if _engine_checked:
        return _engine
    with _lock:
        if _engine_checked:
            return _engine
        if os.environ.get("ELBENCHO_TPU_NO_NATIVE") != "1":
            if not os.path.exists(_SO_PATH) \
                    and not os.path.exists(_SO_PATH_INSTALLED):
                _try_build()
            for so in (_SO_PATH, _SO_PATH_INSTALLED):
                if os.path.exists(so):
                    try:
                        _engine = _NativeEngine(ctypes.CDLL(so))
                        break
                    except (OSError, AttributeError):
                        _engine = None
        _engine_checked = True
        return _engine


def _try_build() -> None:
    """One-shot best-effort build of the engine (g++ is in the image)."""
    import subprocess
    csrc = os.path.dirname(_SO_PATH)
    try:
        subprocess.run(["make", "-C", csrc], capture_output=True,
                       timeout=120, check=False)
    except (OSError, subprocess.TimeoutExpired):
        pass


def reset_native_engine_cache() -> None:
    global _engine, _engine_checked
    with _lock:
        _engine = None
        _engine_checked = False
