"""ctypes loader for the native C++ ioengine (csrc/libioengine.so).

The reference's hot I/O loops are native C++ (rwBlockSized
LocalWorker.cpp:1702, aioBlockSized :1828 via libaio); this framework keeps
that property: the block loop runs in C++ when available and falls back to
the pure-Python loop otherwise (tests, unsupported workload features).

Build: ``make -C csrc`` (g++; no external deps beyond libaio if present).
"""

from __future__ import annotations

import ctypes
import errno as errno_mod
import os
import threading

_lock = threading.Lock()
_engine = None
_engine_checked = False

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# repo checkout layout (csrc/ beside the package) — buildable via make
_SO_PATH = os.path.join(os.path.dirname(_PKG_DIR), "csrc", "libioengine.so")
# installed layout (deb/rpm/wheel ship the prebuilt .so inside the package)
_SO_PATH_INSTALLED = os.path.join(_PKG_DIR, "_native", "libioengine.so")


# engine selector values (must match csrc/ioengine.cpp)
ENGINE_CODES = {"auto": 0, "sync": 1, "aio": 2, "uring": 3}
#: reverse map for logs/diagnostics (single owner of the naming)
ENGINE_NAMES = {code: name for name, code in ENGINE_CODES.items()}

# ABI generation expected from the .so; ioengine_version() reports
# "elbencho-tpu ioengine <N> (...)". A mismatch means a stale binary
# (e.g. installed prebuilt vs newer source) — refuse it rather than run
# benchmarks against outdated native code.
EXPECTED_ABI = 11

#: ioengine_pool_features bits (csrc POOL_FEAT_*)
POOL_FEAT_URING = 1
POOL_FEAT_FIXED_BUFFERS = 2
POOL_FEAT_SQPOLL = 4

#: ioengine_stream_set_fault kinds (csrc STREAM_FAULT_*; TEST ONLY —
#: config validation rejects the env knob outside a test harness)
STREAM_FAULT_KINDS = {"eio": 1, "short": 2, "hang": 3}


def parse_fault_spec(spec: str) -> "tuple[int, int, int]":
    """Parse the ELBENCHO_TPU_IO_FAULT test knob: "kind:every_n[:seed]"
    (kind in eio|short|hang) -> (seed, every_n, kind_code). Raises
    ValueError on a malformed spec so a typo fails loudly in the harness
    instead of silently injecting nothing."""
    parts = spec.split(":")
    if len(parts) not in (2, 3) or parts[0] not in STREAM_FAULT_KINDS:
        raise ValueError(
            f"malformed ELBENCHO_TPU_IO_FAULT {spec!r} (want "
            f"'eio|short|hang:EVERY_N[:SEED]')")
    every_n = int(parts[1])
    seed = int(parts[2]) if len(parts) == 3 else 0
    if every_n <= 0:
        raise ValueError("ELBENCHO_TPU_IO_FAULT every_n must be > 0")
    return seed, every_n, STREAM_FAULT_KINDS[parts[0]]

_EILSEQ = errno_mod.EILSEQ  # engine's verify-mismatch return code


class NativeVerifyError(Exception):
    """In-loop data integrity check failed (ioengine -EILSEQ). Carries the
    exact mismatch location so the caller can report the file offset the
    way postReadIntegrityCheckVerifyBuf does (LocalWorker.cpp:2170)."""

    def __init__(self, block_idx: int, word_idx: int, want: int, got: int):
        self.block_idx = block_idx
        self.word_idx = word_idx
        self.want = want
        self.got = got
        super().__init__(f"integrity check failed at block {block_idx} "
                         f"word {word_idx}")


def _as_ptr(values, n, np_dtype_name, c_type):
    """ctypes view of a numpy array (zero-copy) or python list."""
    import numpy as np
    if isinstance(values, np.ndarray):
        arr = np.ascontiguousarray(values, dtype=np.dtype(np_dtype_name))
        ptr = arr.ctypes.data_as(ctypes.POINTER(c_type))
        ptr._keepalive = arr  # the view must outlive the native call
        return ptr
    return (c_type * n)(*values)


def _as_u64_ptr(values, n):
    return _as_ptr(values, n, "uint64", ctypes.c_uint64)


def _account_chunk(worker, lat_arr, lengths_np, n: int, bytes_done: int,
                   total_bytes: int, op_is_read) -> None:
    """Post-chunk accounting shared by the block and mmap loops: on a
    complete chunk, latencies and counters are attributed exactly (split
    into the rwmix-read counters when per-op flags are present); on an
    interrupted chunk, completions can be out of order (AIO), so only the
    done-prefix estimate is booked and latencies are skipped — with flags
    the prefix split keeps the read/write ratio roughly right (exact for
    the in-order sync/mmap paths)."""
    import numpy as np
    if bytes_done == total_bytes:
        lat = np.frombuffer(lat_arr, dtype=np.uint64)
        if op_is_read is not None and op_is_read.any():
            rd = op_is_read.astype(bool)
            worker.iops_latency_histo_rwmix.add_latencies_array(lat[rd])
            worker.iops_latency_histo.add_latencies_array(lat[~rd])
            n_read = int(rd.sum())
            read_bytes = int(lengths_np[rd].sum())
            worker.live_ops_rwmix_read.num_iops_done += n_read
            worker.live_ops_rwmix_read.num_bytes_done += read_bytes
            worker.live_ops.num_iops_done += n - n_read
            worker.live_ops.num_bytes_done += total_bytes - read_bytes
        else:
            worker.iops_latency_histo.add_latencies_array(lat)
            worker.live_ops.num_iops_done += n
            worker.live_ops.num_bytes_done += bytes_done
    else:
        avg_len = max(total_bytes // n, 1)
        done = min(n, bytes_done // avg_len)
        if op_is_read is not None and done:
            rd = op_is_read[:done].astype(bool)
            n_read = int(rd.sum())
            read_bytes = int(lengths_np[:done][rd].sum())
            worker.live_ops_rwmix_read.num_iops_done += n_read
            worker.live_ops_rwmix_read.num_bytes_done += read_bytes
            worker.live_ops.num_iops_done += done - n_read
            worker.live_ops.num_bytes_done += \
                max(bytes_done - read_bytes, 0)
        else:
            worker.live_ops.num_iops_done += done
            worker.live_ops.num_bytes_done += bytes_done
    worker._num_iops_submitted += n
    pool = getattr(worker, "_staging_pool", None)
    if pool is not None:
        # staging-slot reuse accounting at the one seam every array
        # path (native block/mmap loops, fused stream) flows through
        pool.account_ops(n)
    worker.create_stonewall_stats_if_triggered()


class NativeStreamError(OSError):
    """Stream open/submit/reap failed inside the engine (-errno)."""

    def __init__(self, errno_val: int, what: str):
        super().__init__(errno_val, f"{os.strerror(errno_val)} ({what})")


class NativePoolError(OSError):
    """Pool ring open failed inside the engine (-errno) — the caller's
    cue to log the loud per-call-registration fallback."""

    def __init__(self, errno_val: int, what: str):
        super().__init__(errno_val, f"{os.strerror(errno_val)} ({what})")


class NativePool:
    """Persistent registered-buffer pool ring (ioengine_pool_*; ABI 11):
    the staging pool's slab registered ONCE as io_uring fixed buffers,
    shared by the classic block loop (run_block_loop(pool=...)) and the
    streaming producer mode (open_stream(pool=...)). Optionally SQPOLL —
    kernel submission-queue polling, no io_uring_enter on the submit
    path. The slot buffers belong to the caller (utils/staging_pool.py)
    and must stay mapped until close() returned."""

    def __init__(self, lib: ctypes.CDLL, slot_addrs, slot_size: int,
                 want_sqpoll: bool = False, sqpoll_idle_ms: int = 2000):
        self._lib = lib
        self._handle = None
        n_slots = len(slot_addrs)
        self.n_slots = n_slots
        self.slot_size = slot_size
        addr_arr = (ctypes.c_uint64 * n_slots)(*slot_addrs)
        err = ctypes.c_int(0)
        handle = lib.ioengine_pool_open(
            addr_arr, n_slots, slot_size, 1 if want_sqpoll else 0,
            max(sqpoll_idle_ms, 0), ctypes.byref(err))
        if not handle:
            raise NativePoolError(-err.value or errno_mod.EINVAL,
                                  "pool open")
        self._handle = handle
        feats = int(lib.ioengine_pool_features(handle))
        self.fixed_buffers = bool(feats & POOL_FEAT_FIXED_BUFFERS)
        self.sqpoll_active = bool(feats & POOL_FEAT_SQPOLL)

    @property
    def handle(self):
        return self._handle

    def close(self) -> int:
        """0, or -EBUSY while a pooled stream still owns the ring (the
        stream's close drains kernel DMA out of the slab first)."""
        ret = 0
        if self._handle is not None:
            ret = self._lib.ioengine_pool_close(self._handle)
            if ret == 0:
                self._handle = None
        return ret

    def __del__(self):  # belt-and-braces: never leak a kernel ring
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


class NativeStream:
    """Submission/completion ring over registered staging slots
    (ioengine_stream_*): up to len(slot_addrs) io_uring reads/writes in
    flight with the GIL released, reaped slot-by-slot so the caller can
    overlap storage I/O with TPU HBM transfers (the fused loop of
    workers/local_worker.py). One in-flight op per slot — the engine
    returns -EBUSY on a violation of the slot-reuse discipline.

    With ``pool`` (a NativePool), the stream borrows the pool's
    PERSISTENT ring instead of building its own: no ring setup and no
    buffer registration on this open — the slab was registered once at
    pool open (slot i of the stream is pool slot i). Falls back to an
    owned ring when the pool ring is unavailable/busy."""

    #: reap batch bound (cq depth can reach 2x sq entries)
    _MAX_EVENTS = 64

    def __init__(self, lib: ctypes.CDLL, fds, slot_addrs, slot_size: int,
                 pool: "NativePool | None" = None):
        self._lib = lib
        self._handle = None
        n_slots = len(slot_addrs)
        self.n_slots = n_slots
        fds_arr = (ctypes.c_int * len(fds))(*fds)
        handle = None
        self.pooled = False
        if pool is not None and pool.handle is not None \
                and pool.n_slots == n_slots:
            err = ctypes.c_int(0)
            handle = lib.ioengine_stream_open_pooled(
                pool.handle, fds_arr, len(fds), ctypes.byref(err))
            self.pooled = bool(handle)
        if not handle:
            addr_arr = (ctypes.c_uint64 * n_slots)(*slot_addrs)
            err = ctypes.c_int(0)
            handle = lib.ioengine_stream_open(
                fds_arr, len(fds), addr_arr, n_slots, slot_size,
                ctypes.byref(err))
        if not handle:
            raise NativeStreamError(-err.value or errno_mod.EINVAL,
                                    "stream open")
        self._handle = handle
        #: registration/SQPOLL audit hooks (pool counters ride on these)
        self.fixed_buffers = bool(
            lib.ioengine_stream_fixed_buffers(handle))
        self.sqpoll = bool(lib.ioengine_stream_sqpoll(handle))
        #: ENGINE_CODES value of the backend THIS ring runs on (the open
        #: may fall back from uring to AIO; pins/logs must use this)
        self.backend = int(lib.ioengine_stream_backend_of(handle))
        self.backend_name = ENGINE_NAMES.get(self.backend, "none")
        max_ev = max(self._MAX_EVENTS, 2 * n_slots)
        self._out_slots = (ctypes.c_uint32 * max_ev)()
        self._out_lat = (ctypes.c_uint64 * max_ev)()
        self._out_res = (ctypes.c_int64 * max_ev)()
        self._max_events = max_ev
        # --tracefile stream-reap sub-spans (telemetry/tracer.py); None
        # keeps reap() free of any per-call trace work
        self.tracer = None
        self.trace_rank = 0

    def submit(self, slot: int, fd_idx: int, offset: int, length: int,
               is_write: bool) -> None:
        ret = self._lib.ioengine_stream_submit(
            self._handle, slot, fd_idx, offset, length,
            1 if is_write else 0)
        if ret < 0:
            raise NativeStreamError(-ret, f"stream submit slot {slot}")

    def reap(self, min_complete: int = 1, timeout_msecs: int = 1000,
             interrupt_flag=None) -> "list[tuple[int, int, int]]":
        """Blocking (bounded, interruptible) harvest; returns
        [(slot, lat_usec, res), ...] — res is the raw per-op result
        (bytes moved, or -errno), checked by the caller so a short read
        mid-stream surfaces with its slot context."""
        interrupt = (interrupt_flag if interrupt_flag is not None
                     else ctypes.c_int(0))
        tracer = self.tracer
        t0 = tracer.now_ns() if tracer is not None else 0
        got = self._lib.ioengine_stream_reap(
            self._handle, min_complete, timeout_msecs, self._out_slots,
            self._out_lat, self._out_res, self._max_events,
            ctypes.byref(interrupt))
        if got < 0:
            raise NativeStreamError(-got, "stream reap")
        if tracer is not None:
            # reap sub-span: how long the worker sat in the engine's
            # completion wait, and how many storage ops it harvested
            tracer.record("stream_reap", "stream", t0,
                          (tracer.now_ns() - t0) // 1000,
                          rank=self.trace_rank, sampled=True,
                          events=got, min_complete=min_complete)
        return [(self._out_slots[i], self._out_lat[i], self._out_res[i])
                for i in range(got)]

    def inflight(self) -> int:
        return self._lib.ioengine_stream_inflight(self._handle)

    def set_timeout(self, timeout_usec: int) -> None:
        """--iotimeout: per-op deadline. Ops older than this at reap time
        are cancelled and surface as res == -ETIMEDOUT with their slot
        re-armed (0 disarms)."""
        ret = self._lib.ioengine_stream_set_timeout(self._handle,
                                                    max(timeout_usec, 0))
        if ret < 0:
            raise NativeStreamError(-ret, "stream set_timeout")

    def set_fault(self, seed: int, every_n: int, kind: int) -> None:
        """Deterministic fault injection (TEST ONLY; STREAM_FAULT_KINDS).
        Op k (by submit order) is faulted when (k+seed) % every_n == 0."""
        ret = self._lib.ioengine_stream_set_fault(self._handle, seed,
                                                  every_n, kind)
        if ret < 0:
            raise NativeStreamError(-ret, "stream set_fault")

    def set_fault_from_spec(self, spec: str) -> None:
        seed, every_n, kind = parse_fault_spec(spec)
        self.set_fault(seed, every_n, kind)

    def cancel(self, slot: int) -> None:
        """Request cancellation of the slot's in-flight op; its completion
        surfaces via reap (-ECANCELED, or the real result if the op beat
        the cancel). -ENOENT (no in-flight op) is not an error here."""
        ret = self._lib.ioengine_stream_cancel(self._handle, slot)
        if ret < 0 and ret != -errno_mod.ENOENT:
            raise NativeStreamError(-ret, f"stream cancel slot {slot}")

    def oldest_age_usec(self) -> int:
        """Age of the oldest in-flight op (op-age tracking; 0 = idle)."""
        return int(self._lib.ioengine_stream_oldest_age_usec(self._handle))

    def close(self) -> int:
        """Drains outstanding kernel DMA before the ring is torn down;
        idempotent. Returns 0, or -errno when the drain had to be
        aborted with ops still kernel-owned — the caller must then keep
        the slot buffers mapped for the life of the process (a late
        completion DMAs into them)."""
        ret = 0
        if self._handle is not None:
            ret = self._lib.ioengine_stream_close(self._handle)
            self._handle = None
        return ret

    def __enter__(self) -> "NativeStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # belt-and-braces: never leak a kernel ring
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


class _NativeEngine:
    """Thin wrapper over libioengine.so. See csrc/ioengine.cpp for the ABI."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.ioengine_run_block_loop4.restype = ctypes.c_int
        lib.ioengine_run_block_loop4.argtypes = [
            ctypes.POINTER(ctypes.c_int),     # fds
            ctypes.POINTER(ctypes.c_uint32),  # per-block fd index (or None)
            ctypes.POINTER(ctypes.c_uint64),  # offsets
            ctypes.POINTER(ctypes.c_uint64),  # lengths
            ctypes.c_uint64,                  # num_blocks
            ctypes.c_int,                     # is_write
            ctypes.c_void_p,                  # buffer
            ctypes.c_uint64,                  # buffer size
            ctypes.c_int,                     # iodepth
            ctypes.POINTER(ctypes.c_uint64),  # out: latencies (usec/block)
            ctypes.POINTER(ctypes.c_uint64),  # out: bytes done
            ctypes.POINTER(ctypes.c_int),     # interrupt flag
            ctypes.c_int,                     # engine (ENGINE_CODES)
            ctypes.POINTER(ctypes.c_ubyte),   # rwmix per-op read flags
            ctypes.c_uint64,                  # verify salt
            ctypes.c_int,                     # do_verify
            ctypes.c_int,                     # block variance pct
            ctypes.c_uint64,                  # block variance seed
            ctypes.POINTER(ctypes.c_uint64),  # out: verify mismatch info[4]
            ctypes.c_uint64,                  # read rate limit (bytes/s)
            ctypes.c_uint64,                  # write rate limit (bytes/s)
            ctypes.POINTER(ctypes.c_uint64),  # in/out rate windows [4]
            ctypes.c_int,                     # inline readback (sync only)
            ctypes.c_int,                     # flock mode 0|1=range|2=full
            ctypes.c_int,                     # opslog fd (-1 = off)
            ctypes.c_int,                     # opslog flock
            ctypes.c_int,                     # worker rank (for records)
        ]
        lib.ioengine_uring_supported.restype = ctypes.c_int
        lib.ioengine_uring_supported.argtypes = []
        # resolved here so a stale .so missing any symbol downgrades to
        # the pure-Python fallback via get_native_engine's AttributeError
        # catch instead of crashing at call time
        lib.ioengine_version.restype = ctypes.c_char_p
        lib.ioengine_version.argtypes = []
        lib.ioengine_run_mmap_loop3.restype = ctypes.c_int
        lib.ioengine_run_mmap_loop3.argtypes = [
            ctypes.c_void_p,                  # mapping base address
            ctypes.POINTER(ctypes.c_uint64),  # offsets
            ctypes.POINTER(ctypes.c_uint64),  # lengths
            ctypes.c_uint64,                  # num blocks
            ctypes.c_int,                     # is_write
            ctypes.c_void_p,                  # io buffer
            ctypes.POINTER(ctypes.c_uint64),  # out: latencies
            ctypes.POINTER(ctypes.c_uint64),  # out: bytes done
            ctypes.POINTER(ctypes.c_int),     # interrupt flag
            ctypes.POINTER(ctypes.c_ubyte),   # rwmix per-op read flags
            ctypes.c_uint64,                  # verify salt
            ctypes.c_int,                     # do_verify
            ctypes.c_int,                     # block variance pct
            ctypes.c_uint64,                  # block variance seed
            ctypes.POINTER(ctypes.c_uint64),  # out: verify mismatch info[4]
            ctypes.c_uint64,                  # read rate limit (bytes/s)
            ctypes.c_uint64,                  # write rate limit (bytes/s)
            ctypes.POINTER(ctypes.c_uint64),  # in/out rate windows [4]
        ]
        lib.ioengine_net_client_loop.restype = ctypes.c_int
        lib.ioengine_net_client_loop.argtypes = [
            ctypes.c_int,                     # connected socket fd
            ctypes.c_void_p,                  # request payload
            ctypes.c_uint64,                  # block (request) size
            ctypes.c_uint64,                  # response size
            ctypes.c_uint64,                  # number of round trips
            ctypes.POINTER(ctypes.c_uint64),  # out: latencies
            ctypes.POINTER(ctypes.c_uint64),  # out: bytes moved
            ctypes.POINTER(ctypes.c_int),     # interrupt flag
        ]
        lib.ioengine_net_server_loop.restype = ctypes.c_int
        lib.ioengine_net_server_loop.argtypes = [
            ctypes.POINTER(ctypes.c_int),     # connection fds
            ctypes.c_uint64,                  # number of connections
            ctypes.POINTER(ctypes.c_uint64),  # in/out per-conn state
            ctypes.c_uint64,                  # block size
            ctypes.c_uint64,                  # response size
            ctypes.c_void_p,                  # response payload
            ctypes.c_uint64,                  # max responses this slice
            ctypes.c_uint64,                  # slice duration msecs
            ctypes.POINTER(ctypes.c_uint64),  # out: latencies
            ctypes.POINTER(ctypes.c_uint64),  # out: bytes moved
            ctypes.POINTER(ctypes.c_uint64),  # out: responses sent
            ctypes.POINTER(ctypes.c_uint64),  # out: open connections left
            ctypes.POINTER(ctypes.c_int),     # interrupt flag
        ]
        lib.ioengine_stream_open.restype = ctypes.c_void_p
        lib.ioengine_stream_open.argtypes = [
            ctypes.POINTER(ctypes.c_int),     # fds
            ctypes.c_uint32,                  # num fds
            ctypes.POINTER(ctypes.c_uint64),  # slot base addresses
            ctypes.c_uint64,                  # num slots
            ctypes.c_uint64,                  # slot size (bytes)
            ctypes.POINTER(ctypes.c_int),     # out: -errno on failure
        ]
        lib.ioengine_stream_submit.restype = ctypes.c_int
        lib.ioengine_stream_submit.argtypes = [
            ctypes.c_void_p,                  # stream handle
            ctypes.c_uint32,                  # slot index
            ctypes.c_uint32,                  # fd index
            ctypes.c_uint64,                  # file offset
            ctypes.c_uint64,                  # length
            ctypes.c_int,                     # is_write
        ]
        lib.ioengine_stream_reap.restype = ctypes.c_int
        lib.ioengine_stream_reap.argtypes = [
            ctypes.c_void_p,                  # stream handle
            ctypes.c_int,                     # min completions to wait for
            ctypes.c_int,                     # timeout msecs
            ctypes.POINTER(ctypes.c_uint32),  # out: completed slot indices
            ctypes.POINTER(ctypes.c_uint64),  # out: latencies (usec)
            ctypes.POINTER(ctypes.c_int64),   # out: raw cqe results
            ctypes.c_int,                     # max events
            ctypes.POINTER(ctypes.c_int),     # interrupt flag
        ]
        lib.ioengine_stream_inflight.restype = ctypes.c_int
        lib.ioengine_stream_inflight.argtypes = [ctypes.c_void_p]
        lib.ioengine_stream_close.restype = ctypes.c_int
        lib.ioengine_stream_close.argtypes = [ctypes.c_void_p]
        # ABI 10: per-op deadlines, cancellation, fault injection
        lib.ioengine_stream_set_timeout.restype = ctypes.c_int
        lib.ioengine_stream_set_timeout.argtypes = [ctypes.c_void_p,
                                                    ctypes.c_uint64]
        lib.ioengine_stream_set_fault.restype = ctypes.c_int
        lib.ioengine_stream_set_fault.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int]
        lib.ioengine_stream_cancel.restype = ctypes.c_int
        lib.ioengine_stream_cancel.argtypes = [ctypes.c_void_p,
                                               ctypes.c_uint32]
        lib.ioengine_stream_oldest_age_usec.restype = ctypes.c_int64
        lib.ioengine_stream_oldest_age_usec.argtypes = [ctypes.c_void_p]
        lib.ioengine_stream_backend.restype = ctypes.c_int
        lib.ioengine_stream_backend.argtypes = []
        lib.ioengine_stream_backend_of.restype = ctypes.c_int
        lib.ioengine_stream_backend_of.argtypes = [ctypes.c_void_p]
        # ABI 11: registered-buffer staging pool + SQPOLL
        lib.ioengine_pool_open.restype = ctypes.c_void_p
        lib.ioengine_pool_open.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),  # slot base addresses
            ctypes.c_uint64,                  # num slots
            ctypes.c_uint64,                  # slot size (bytes)
            ctypes.c_int,                     # want SQPOLL
            ctypes.c_uint32,                  # SQPOLL idle timeout (ms)
            ctypes.POINTER(ctypes.c_int),     # out: -errno on failure
        ]
        lib.ioengine_pool_features.restype = ctypes.c_int
        lib.ioengine_pool_features.argtypes = [ctypes.c_void_p]
        lib.ioengine_pool_close.restype = ctypes.c_int
        lib.ioengine_pool_close.argtypes = [ctypes.c_void_p]
        lib.ioengine_sqpoll_supported.restype = ctypes.c_int
        lib.ioengine_sqpoll_supported.argtypes = []
        lib.ioengine_stream_open_pooled.restype = ctypes.c_void_p
        lib.ioengine_stream_open_pooled.argtypes = [
            ctypes.c_void_p,                  # pool handle
            ctypes.POINTER(ctypes.c_int),     # fds
            ctypes.c_uint32,                  # num fds
            ctypes.POINTER(ctypes.c_int),     # out: -errno on failure
        ]
        lib.ioengine_stream_fixed_buffers.restype = ctypes.c_int
        lib.ioengine_stream_fixed_buffers.argtypes = [ctypes.c_void_p]
        lib.ioengine_stream_sqpoll.restype = ctypes.c_int
        lib.ioengine_stream_sqpoll.argtypes = [ctypes.c_void_p]
        lib.ioengine_run_block_loop5.restype = ctypes.c_int
        lib.ioengine_run_block_loop5.argtypes = \
            [ctypes.c_void_p] + list(lib.ioengine_run_block_loop4.argtypes) \
            + [ctypes.POINTER(ctypes.c_uint64)]  # out: pool stats[3]
        self._stream_backend = None  # kernel capability, probed once
        lib.ioengine_run_file_loop3.restype = ctypes.c_int
        lib.ioengine_run_file_loop3.argtypes = [
            ctypes.c_char_p,                  # NUL-separated paths blob
            ctypes.POINTER(ctypes.c_uint32),  # per-path blob offsets
            ctypes.c_uint64,                  # num files
            ctypes.c_int,                     # op (FILE_OPS)
            ctypes.c_int,                     # open flags
            ctypes.c_uint64,                  # file size
            ctypes.c_uint64,                  # block size
            ctypes.c_void_p,                  # io buffer
            ctypes.POINTER(ctypes.c_uint64),  # per-file range starts (opt)
            ctypes.POINTER(ctypes.c_uint64),  # per-file range lengths (opt)
            ctypes.c_int,                     # ignore delete errors
            ctypes.POINTER(ctypes.c_uint64),  # out: entry latencies
            ctypes.POINTER(ctypes.c_uint64),  # out: block latencies
            ctypes.POINTER(ctypes.c_uint64),  # out: bytes done
            ctypes.POINTER(ctypes.c_uint64),  # out: entries done
            ctypes.POINTER(ctypes.c_uint64),  # out: failing file index
            ctypes.POINTER(ctypes.c_int),     # interrupt flag
            ctypes.c_uint64,                  # verify salt
            ctypes.c_int,                     # do_verify
            ctypes.c_int,                     # block variance pct
            ctypes.c_uint64,                  # block variance seed
            ctypes.c_int,                     # rwmix read pct (write op)
            ctypes.c_uint64,                  # rwmix base (rank+submitted)
            ctypes.POINTER(ctypes.c_uint64),  # out: verify mismatch info[4]
            ctypes.POINTER(ctypes.c_uint64),  # out: rwmix {blocks, bytes}
            ctypes.c_uint64,                  # read rate limit (bytes/s)
            ctypes.c_uint64,                  # write rate limit (bytes/s)
            ctypes.POINTER(ctypes.c_uint64),  # in/out rate windows [4]
            ctypes.c_int,                     # inline readback (write op)
            ctypes.c_int,                     # flock mode 0|1=range|2=full
        ]

    def uring_supported(self) -> bool:
        return bool(self._lib.ioengine_uring_supported())

    def sqpoll_supported(self) -> bool:
        """--iosqpoll capability probe: can this process get an SQPOLL
        ring (kernel 5.11+ for unprivileged; policy may refuse)."""
        return bool(self._lib.ioengine_sqpoll_supported())

    def open_pool(self, slot_addrs, slot_size: int,
                  want_sqpoll: bool = False,
                  sqpoll_idle_ms: int = 2000) -> NativePool:
        """Open the persistent registered-buffer pool ring over the
        staging slab (see NativePool); raises NativePoolError when the
        kernel cannot provide a ring — callers log the loud fallback to
        the per-call registration paths."""
        return NativePool(self._lib, slot_addrs, slot_size,
                          want_sqpoll=want_sqpoll,
                          sqpoll_idle_ms=sqpoll_idle_ms)

    def stream_supported(self) -> bool:
        """Streaming producer mode: io_uring primary, kernel-AIO tier."""
        return self.stream_backend() != 0

    def stream_backend(self) -> int:
        """ENGINE_CODES value of the backend a stream would PREDICTABLY
        use on this kernel: 3 = io_uring, 2 = kernel AIO, 0 =
        unavailable. Probed once (it creates and destroys a ring); the
        kernel capability cannot change mid-run. A live stream reports
        its ACTUAL backend via NativeStream.backend — the open can still
        fall back to AIO (e.g. ENOMEM on the ring mmaps at a large slot
        count), so engine pins must check the stream, not this."""
        if self._stream_backend is None:
            self._stream_backend = int(self._lib.ioengine_stream_backend())
        return self._stream_backend

    def stream_backend_name(self) -> str:
        return ENGINE_NAMES.get(self.stream_backend(), "none")

    def open_stream(self, fds, slot_addrs, slot_size: int,
                    pool: "NativePool | None" = None) -> NativeStream:
        """Open a submission/completion ring over the given staging slots
        (see NativeStream); raises NativeStreamError when the kernel
        cannot provide one (callers fall back to the Python loop). With
        ``pool``, the stream borrows the pool's persistent ring and its
        once-registered fixed buffers instead of building its own."""
        return NativeStream(self._lib, fds, slot_addrs, slot_size,
                            pool=pool)

    def version(self) -> str:
        return self._lib.ioengine_version().decode()

    def abi_version(self) -> int:
        # "elbencho-tpu ioengine <N> (...)" -> N; 0 if unparseable
        parts = self.version().split()
        try:
            return int(parts[2])
        except (IndexError, ValueError):
            return 0

    #: op codes of ioengine_run_file_loop (csrc/ioengine.cpp FILE_OP_*)
    FILE_OPS = {"write": 0, "read": 1, "stat": 2, "unlink": 3}

    def run_file_loop(self, paths: "list[str]", op: str, open_flags: int,
                      file_size: int, block_size: int, buf_addr: int,
                      ignore_delete_errors: bool, worker,
                      interrupt_flag=None, ranges=None,
                      verify_salt: int = 0, block_var_pct: int = 0,
                      block_var_seed: int = 0,
                      rwmix_pct: int = 0, limit_read_bps: int = 0,
                      limit_write_bps: int = 0, rl_state=None,
                      inline_readback: bool = False,
                      flock_mode: int = 0) -> None:
        """Dir-mode LOSF hot path: open->blocks->close (or stat/unlink)
        per file, entirely in C++. Counters/histograms update after the
        call; partial (interrupted) chunks attribute only completed
        files. ranges: optional (starts, lens) uint64 arrays for
        custom-tree per-file byte slices (default: [0, file_size)).
        verify/rwmix/variance run inside the loop (FileLoopMod); a
        verify mismatch raises NativeVerifyError with the global block
        index."""
        import numpy as np
        n = len(paths)
        encoded = [os.fsencode(p) for p in paths]
        blob = b"\0".join(encoded) + b"\0"
        offs = (ctypes.c_uint32 * n)()
        pos = 0
        for i, e in enumerate(encoded):
            offs[i] = pos
            pos += len(e) + 1
        io_op = op in ("write", "read") and block_size
        if ranges is not None:
            starts_arr = _as_u64_ptr(ranges[0], n)
            lens_arr = _as_u64_ptr(ranges[1], n)
            per_file_blocks = (
                (np.asarray(ranges[1], dtype=np.uint64)
                 + np.uint64(block_size - 1)) // np.uint64(block_size)
            ).astype(np.int64) if io_op else None
            total_blocks = int(per_file_blocks.sum()) if io_op else 0
        else:
            starts_arr = lens_arr = per_file_blocks = None
            bpf = (file_size + block_size - 1) // block_size \
                if io_op and file_size else 0
            total_blocks = n * bpf
        entry_lat = (ctypes.c_uint64 * n)()
        block_lat = (ctypes.c_uint64 * max(total_blocks, 1))()
        bytes_done = ctypes.c_uint64(0)
        entries_done = ctypes.c_uint64(0)
        fail_idx = ctypes.c_uint64(0)
        verify_info = (ctypes.c_uint64 * 4)()
        rwmix_out = (ctypes.c_uint64 * 2)()
        rwmix_base = worker.rank + worker._num_iops_submitted
        interrupt = (interrupt_flag if interrupt_flag is not None
                     else ctypes.c_int(0))
        ret = self._lib.ioengine_run_file_loop3(
            blob, offs, n, self.FILE_OPS[op], open_flags, file_size,
            block_size, ctypes.c_void_p(buf_addr), starts_arr, lens_arr,
            1 if ignore_delete_errors else 0, entry_lat, block_lat,
            ctypes.byref(bytes_done), ctypes.byref(entries_done),
            ctypes.byref(fail_idx), ctypes.byref(interrupt),
            verify_salt, 1 if verify_salt else 0, block_var_pct,
            block_var_seed, rwmix_pct, rwmix_base, verify_info, rwmix_out,
            limit_read_bps, limit_write_bps, rl_state,
            1 if inline_readback else 0, flock_mode)
        if ret == -_EILSEQ:
            raise NativeVerifyError(int(verify_info[0]),
                                    int(verify_info[1]),
                                    int(verify_info[2]),
                                    int(verify_info[3]))
        if ret < 0:
            failed = paths[min(fail_idx.value, n - 1)]
            raise OSError(-ret, f"{os.strerror(-ret)} "
                                f"({op}: {failed})", failed)
        done = entries_done.value
        if done:
            worker.entries_latency_histo.add_latencies_array(
                np.frombuffer(entry_lat, dtype=np.uint64)[:done])
        if per_file_blocks is not None:
            num_blocks = int(per_file_blocks[:done].sum())
        else:
            num_blocks = done * (total_blocks // n if n else 0)
        rwmix_blocks, rwmix_bytes = rwmix_out[0], rwmix_out[1]
        if num_blocks:
            lat = np.frombuffer(block_lat, dtype=np.uint64)[:num_blocks]
            if rwmix_pct and op == "write" and rwmix_blocks:
                # same in-loop modulo as the engine: flags are exact
                rd = (((np.uint64(rwmix_base)
                        + np.arange(num_blocks, dtype=np.uint64))
                       % np.uint64(100)) < np.uint64(rwmix_pct))
                worker.iops_latency_histo_rwmix.add_latencies_array(lat[rd])
                worker.iops_latency_histo.add_latencies_array(lat[~rd])
            else:
                worker.iops_latency_histo.add_latencies_array(lat)
        worker.live_ops.num_entries_done += done
        worker.live_ops.num_iops_done += num_blocks - rwmix_blocks
        worker.live_ops.num_bytes_done += bytes_done.value - rwmix_bytes
        worker.live_ops_rwmix_read.num_iops_done += rwmix_blocks
        worker.live_ops_rwmix_read.num_bytes_done += rwmix_bytes
        worker._num_iops_submitted += num_blocks
        worker.create_stonewall_stats_if_triggered()

    def run_net_client_loop(self, fd: int, payload: bytes, resp_size: int,
                            n_ops: int, worker,
                            interrupt_flag=None) -> None:
        """n_ops netbench round trips (send payload, await resp_size)."""
        import numpy as np
        lat_arr = (ctypes.c_uint64 * n_ops)()
        bytes_done = ctypes.c_uint64(0)
        interrupt = (interrupt_flag if interrupt_flag is not None
                     else ctypes.c_int(0))
        ret = self._lib.ioengine_net_client_loop(
            fd, payload, len(payload), resp_size, n_ops, lat_arr,
            ctypes.byref(bytes_done), ctypes.byref(interrupt))
        if ret < 0:
            raise OSError(-ret, os.strerror(-ret))
        per_op = len(payload) + resp_size
        done_ops = bytes_done.value // per_op if per_op else 0
        worker.iops_latency_histo.add_latencies_array(
            np.frombuffer(lat_arr, dtype=np.uint64)[:done_ops])
        worker.live_ops.num_iops_done += done_ops
        worker.live_ops.num_bytes_done += bytes_done.value
        worker.create_stonewall_stats_if_triggered()

    def run_net_server_slice(self, fds, conn_state, block_size: int,
                             resp_payload: bytes, worker,
                             max_responses: int = 4096,
                             slice_msecs: int = 500,
                             interrupt_flag=None) -> int:
        """One polling slice of the netbench server loop; returns the
        number of still-open connections (conn_state mutated in place)."""
        import numpy as np
        n = len(fds)
        fds_arr = (ctypes.c_int * n)(*fds)
        lat_arr = (ctypes.c_uint64 * max_responses)()
        bytes_done = ctypes.c_uint64(0)
        responses = ctypes.c_uint64(0)
        open_conns = ctypes.c_uint64(0)
        interrupt = (interrupt_flag if interrupt_flag is not None
                     else ctypes.c_int(0))
        ret = self._lib.ioengine_net_server_loop(
            fds_arr, n, conn_state, block_size, len(resp_payload),
            resp_payload, max_responses, slice_msecs, lat_arr,
            ctypes.byref(bytes_done), ctypes.byref(responses),
            ctypes.byref(open_conns), ctypes.byref(interrupt))
        if ret < 0:
            raise OSError(-ret, os.strerror(-ret))
        worker.iops_latency_histo.add_latencies_array(
            np.frombuffer(lat_arr, dtype=np.uint64)[:responses.value])
        worker.live_ops.num_iops_done += responses.value
        worker.live_ops.num_bytes_done += bytes_done.value
        worker.create_stonewall_stats_if_triggered()
        return open_conns.value

    def run_mmap_loop(self, map_addr: int, offsets, lengths,
                      is_write: bool, buf_addr: int, worker,
                      interrupt_flag=None, op_is_read=None,
                      verify_salt: int = 0, block_var_pct: int = 0,
                      block_var_seed: int = 0, limit_read_bps: int = 0,
                      limit_write_bps: int = 0, rl_state=None) -> None:
        """--mmap hot loop: memcpy between the mapping and the io buffer
        entirely in C++ (same accounting and block modifiers as
        run_block_loop)."""
        import numpy as np
        n = len(offsets)
        lat_arr = (ctypes.c_uint64 * n)()
        bytes_done = ctypes.c_uint64(0)
        verify_info = (ctypes.c_uint64 * 4)()
        interrupt = (interrupt_flag if interrupt_flag is not None
                     else ctypes.c_int(0))
        flags_arr = None
        if op_is_read is not None:
            flags_arr = _as_ptr(op_is_read, n, "uint8", ctypes.c_ubyte)
        ret = self._lib.ioengine_run_mmap_loop3(
            ctypes.c_void_p(map_addr), _as_u64_ptr(offsets, n),
            _as_u64_ptr(lengths, n), n, 1 if is_write else 0,
            ctypes.c_void_p(buf_addr), lat_arr, ctypes.byref(bytes_done),
            ctypes.byref(interrupt), flags_arr, verify_salt,
            1 if verify_salt else 0, block_var_pct, block_var_seed,
            verify_info, limit_read_bps, limit_write_bps, rl_state)
        if ret == -_EILSEQ:
            raise NativeVerifyError(int(verify_info[0]),
                                    int(verify_info[1]),
                                    int(verify_info[2]),
                                    int(verify_info[3]))
        if ret < 0:
            raise OSError(-ret, os.strerror(-ret))
        lengths_np = (lengths if isinstance(lengths, np.ndarray)
                      else np.asarray(lengths, dtype=np.uint64))
        _account_chunk(worker, lat_arr, lengths_np, n, bytes_done.value,
                       int(lengths_np.sum()), op_is_read)

    def run_block_loop(self, fd: int, offsets, lengths, is_write: bool,
                       buf_addr: int, iodepth: int, worker,
                       interrupt_flag=None, engine: str = "auto",
                       fds: "list[int] | None" = None,
                       fd_idx: "list[int] | None" = None,
                       op_is_read=None, verify_salt: int = 0,
                       block_var_pct: int = 0,
                       block_var_seed: int = 0,
                       limit_read_bps: int = 0,
                       limit_write_bps: int = 0,
                       rl_state=None, inline_readback: bool = False,
                       flock_mode: int = 0, ops_fd: int = -1,
                       ops_lock: bool = False,
                       worker_rank: int = 0,
                       pool: "NativePool | None" = None,
                       pool_stats=None) -> bool:
        """fds/fd_idx: striped multi-file mode — fd_idx[i] selects the
        file of block i (reference: calcFileIdxAndOffsetStriped). offsets/
        lengths/fd_idx may be numpy uint64/uint32 arrays, passed zero-copy
        (the vectorized offset-generator path).

        In-loop block modifiers (reference LocalWorker.cpp:1741,2124,2242):
        op_is_read — uint8 array, rwmix per-op read flags for a write
        phase (accounting is split into the worker's rwmix-read counters);
        verify_salt — --verify fill-on-write/check-on-read, raising
        NativeVerifyError with the exact mismatch location;
        block_var_pct/seed — --blockvarpct refill of each write block.

        pool: a NativePool — the uring engine then runs this chunk over
        the pool's persistent ring with its once-registered fixed
        buffers (ioengine_run_block_loop5); the caller's staging buffers
        MUST be the pool's slots. pool_stats: the StagingPool whose
        registration/SQPOLL audit counters the chunk's engine stats are
        booked into."""
        import numpy as np
        n = len(offsets)
        off_arr = _as_u64_ptr(offsets, n)
        len_arr = _as_u64_ptr(lengths, n)
        lat_arr = (ctypes.c_uint64 * n)()
        bytes_done = ctypes.c_uint64(0)
        verify_info = (ctypes.c_uint64 * 4)()
        interrupt = (interrupt_flag if interrupt_flag is not None
                     else ctypes.c_int(0))  # c_int(0) is falsy: no `or`!
        buf_size = int(lengths.max() if isinstance(lengths, np.ndarray)
                       else max(lengths))
        if fds is None:
            fds_arr = (ctypes.c_int * 1)(fd)
            idx_arr = None
        else:
            fds_arr = (ctypes.c_int * len(fds))(*fds)
            idx_arr = _as_ptr(fd_idx, n, "uint32", ctypes.c_uint32)
        flags_arr = None
        if op_is_read is not None:
            flags_arr = _as_ptr(op_is_read, n, "uint8", ctypes.c_ubyte)
        loop4_args = (
            fds_arr, idx_arr, off_arr, len_arr, n, 1 if is_write else 0,
            ctypes.c_void_p(buf_addr), buf_size, iodepth,
            lat_arr, ctypes.byref(bytes_done), ctypes.byref(interrupt),
            ENGINE_CODES[engine], flags_arr, verify_salt,
            1 if verify_salt else 0, block_var_pct, block_var_seed,
            verify_info, limit_read_bps, limit_write_bps, rl_state,
            1 if inline_readback else 0, flock_mode, ops_fd,
            1 if ops_lock else 0, worker_rank)
        if pool is not None and pool.handle is not None:
            engine_stats = (ctypes.c_uint64 * 3)()
            ret = self._lib.ioengine_run_block_loop5(
                pool.handle, *loop4_args, engine_stats)
            if pool_stats is not None:
                pool_stats.book_engine_stats(int(engine_stats[0]),
                                             int(engine_stats[1]),
                                             bool(engine_stats[2]))
        else:
            ret = self._lib.ioengine_run_block_loop4(*loop4_args)
        if ret == -_EILSEQ:
            raise NativeVerifyError(int(verify_info[0]),
                                    int(verify_info[1]),
                                    int(verify_info[2]),
                                    int(verify_info[3]))
        if ret < 0:
            raise OSError(-ret, os.strerror(-ret))
        lengths_np = (lengths if isinstance(lengths, np.ndarray)
                      else np.asarray(lengths, dtype=np.uint64))
        _account_chunk(worker, lat_arr, lengths_np, n, bytes_done.value,
                       int(lengths_np.sum()), op_is_read)
        return True


def get_native_engine(try_build: bool = True) -> "_NativeEngine | None":
    """Lazily load the native engine; None if not built or disabled via
    ELBENCHO_TPU_NO_NATIVE=1. try_build=False only loads an existing .so
    (diagnostics paths like --version must not kick off a compile)."""
    global _engine, _engine_checked
    if _engine_checked:
        return _engine
    with _lock:
        if _engine_checked:
            return _engine
        if os.environ.get("ELBENCHO_TPU_NO_NATIVE") != "1":
            # always invoke make in the checkout layout: it is an mtime
            # no-op when the .so is fresh, and it prevents silently
            # benchmarking a stale binary after an ioengine.cpp edit
            if try_build and os.path.exists(
                    os.path.join(os.path.dirname(_SO_PATH), "ioengine.cpp")):
                _try_build()
            for so in (_SO_PATH, _SO_PATH_INSTALLED):
                if os.path.exists(so):
                    try:
                        candidate = _NativeEngine(ctypes.CDLL(so))
                        if candidate.abi_version() != EXPECTED_ABI:
                            # visible refusal: otherwise the silent
                            # pure-Python fallback looks like a storage
                            # slowdown to the user
                            from ..toolkits.logger import log_error
                            log_error(
                                f"ignoring stale native ioengine {so} "
                                f"(ABI {candidate.abi_version()}, expected "
                                f"{EXPECTED_ABI}); falling back to the "
                                f"pure-Python I/O loop unless another "
                                f"build is found")
                            continue
                        _engine = candidate
                        break
                    except (OSError, AttributeError):
                        _engine = None
        # a build-skipping probe must not cache "unavailable" — a later
        # real run still gets its chance to compile the engine
        if _engine is not None or try_build:
            _engine_checked = True
        return _engine


def _try_build() -> None:
    """One-shot best-effort build of the engine (g++ is in the image)."""
    import subprocess
    csrc = os.path.dirname(_SO_PATH)
    try:
        subprocess.run(["make", "-C", csrc], capture_output=True,
                       timeout=120, check=False)
    except (OSError, subprocess.TimeoutExpired):
        pass


def reset_native_engine_cache() -> None:
    global _engine, _engine_checked
    with _lock:
        _engine = None
        _engine_checked = False
