"""Control-plane fault tolerance: transient-error classifier + retry policy.

The S3 data plane already classifies and retries transient failures
(`toolkits/s3_tk.py` `_RETRY_STATUSES` + interruptible linear backoff); this
module gives the master->service HTTP control plane the same idiom so one
flaky `/status` poll can no longer abort a whole multi-host run ("RPC
Considered Harmful", PAPERS.md: naive request/reply fabrics become the
reliability bottleneck of distributed accelerator workloads).

Semantics (docs/fault-tolerance.md):

- **Idempotent** requests (`/status`, `/benchresult`, `/protocolversion`,
  `/preparefile` — re-upload overwrites) retry freely on any transient
  error: connection failures, malformed/truncated replies, 5xx/429.
- **Non-idempotent** requests (`/preparephase`, `/startphase`) retry only
  on *connect-level* failures, where the request provably never reached
  the service.
- Every retry sleeps a jittered exponential backoff and draws from a
  per-phase time budget (`--svcretrybudget`) so a dying host converges to
  an error instead of retrying forever.
"""

from __future__ import annotations

import http.client
import random
from dataclasses import dataclass

#: HTTP statuses the control plane treats as transient, mirroring
#: s3_tk.S3Client._RETRY_STATUSES (+504 for intermediary timeouts)
TRANSIENT_HTTP_STATUSES = (500, 502, 503, 504, 429)

#: exception types a control-plane exchange may raise transiently: every
#: socket-level failure is an OSError (incl. ConnectionError/timeout);
#: http.client.HTTPException covers half-closed sockets returning a
#: malformed status line (BadStatusLine), truncated bodies
#: (IncompleteRead), and over-long/garbage header replies
TRANSIENT_EXCEPTIONS = (OSError, http.client.HTTPException)


class ConnectFailedError(ConnectionError):
    """TCP connect to the service failed — the request was never sent, so
    retrying is safe even for non-idempotent requests."""


class GarbageReplyError(http.client.HTTPException):
    """A 200 reply whose body was not the expected JSON (fault injection:
    bit rot / truncation behind a proxy). Safe to retry idempotently."""


def is_transient_error(err: BaseException) -> bool:
    """Shared classifier: would a retry plausibly succeed?"""
    return isinstance(err, TRANSIENT_EXCEPTIONS)


def is_connect_level_error(err: BaseException) -> bool:
    """True when the failure happened before the request was sent (or the
    peer provably refused it), making a retry safe for non-idempotent
    requests too."""
    return isinstance(err, (ConnectFailedError, ConnectionRefusedError))


@dataclass(frozen=True)
class RetryPolicy:
    """Per-request retry shape (--svcretries / --svcretrybudget)."""

    num_retries: int = 3         # retries per request on top of attempt 1
    budget_secs: float = 30.0    # per-phase backoff-sleep budget per host
    base_delay_secs: float = 0.05
    max_delay_secs: float = 2.0

    @classmethod
    def from_config(cls, cfg) -> "RetryPolicy":
        return cls(num_retries=max(cfg.svc_num_retries, 0),
                   budget_secs=max(cfg.svc_retry_budget_secs, 0))

    def backoff_delay(self, attempt: int, rng: random.Random) -> float:
        """Jittered exponential backoff: 2^attempt growth, 0.5x-1.5x
        jitter so a fleet of masters doesn't thundering-herd a recovering
        service."""
        base = min(self.base_delay_secs * (2 ** attempt),
                   self.max_delay_secs)
        return base * (0.5 + rng.random())


class RetryBudget:
    """Per-phase backoff-time account. Retries across ALL requests of one
    phase draw from it, so many individually-cheap retries against a dead
    host still converge to an error within --svcretrybudget seconds."""

    def __init__(self, budget_secs: float):
        self.budget_secs = budget_secs
        self.spent_secs = 0.0

    def reset(self) -> None:
        self.spent_secs = 0.0

    def try_spend(self, delay_secs: float) -> bool:
        if self.spent_secs + delay_secs > self.budget_secs:
            return False
        self.spent_secs += delay_secs
        return True


# ---------------------------------------------------------------------------
# control-plane audit counters (per-host; master side)
# ---------------------------------------------------------------------------

#: (RemoteWorker attribute, wire/JSON key, merge mode) — the control-plane
#: analogue of tpu.device.PATH_AUDIT_COUNTERS. "max" entries merge across
#: hosts like the existing TpuPipeInflightHwm MAX-merge: a high-water mark
#: summed over hosts would report an age/streak no single host ever saw.
#: JSON-only result keys (docs/result-columns.md).
CONTROL_AUDIT_COUNTERS = (
    ("svc_retries", "SvcRetries", "sum"),
    ("svc_consec_retries_hwm", "SvcConsecRetriesHwm", "max"),
    ("svc_heartbeat_age_hwm_usec", "SvcHeartbeatAgeHwmUsec", "max"),
    # master liveness lease (--svcleasesecs): observed SERVICE-side and
    # shipped back over the wire (http_service lease counters ingested
    # by RemoteWorker) — service-lifetime values, so a master that
    # returns after a crash sees how often its predecessors orphaned
    # the host. Appended entries, never reordered (wire/JSON schema).
    ("svc_lease_expiries", "SvcLeaseExpiries", "sum"),
    ("svc_lease_age_hwm_usec", "SvcLeaseAgeHwmUsec", "max"),
    # streaming control plane (--svcstream/--svcfanout), MASTER-observed:
    # the polling-vs-streaming A/B evidence. SvcRequests counts every
    # HTTP request the master sent a host this phase (poll mode: O(ticks)
    # per host; stream mode: the per-phase setup handful); SvcCtlBytes is
    # every control-plane payload byte the master received (poll replies
    # + stream frames); the Svc{StreamFrames,StreamBytes,DeltaSavedBytes}
    # trio measures the stream itself (DeltaSaved = full-snapshot size
    # minus delta size, summed — what delta encoding kept off the wire);
    # SvcAggDepthHwm is the deepest aggregation tree observed in frames
    # (flat stream = 1, polling = 0); SvcConnHwm samples the master's
    # open control-plane sockets (streams + keep-alive request conns) —
    # the O(fanout)-connections proof. Appended entries, never reordered.
    ("svc_requests", "SvcRequests", "sum"),
    ("svc_ctl_bytes", "SvcCtlBytes", "sum"),
    ("svc_stream_frames", "SvcStreamFrames", "sum"),
    ("svc_stream_bytes", "SvcStreamBytes", "sum"),
    ("svc_delta_saved_bytes", "SvcDeltaSavedBytes", "sum"),
    ("svc_agg_depth_hwm", "SvcAggDepthHwm", "max"),
    ("svc_conn_hwm", "SvcConnHwm", "max"),
    # fleet straggler attribution (docs/telemetry.md "Fleet tracing"),
    # MASTER-computed after the phase barrier from per-host finish
    # times: StragglerSkewUsec is each host's finish lag behind the
    # FIRST host to finish (MAX-merge = the straggler's skew — the
    # per-host phase start/finish spread a pod-scale barrier pays);
    # BarrierWaitUSec is each host's idle wait for the LAST finisher
    # (sum = fleet worker-seconds lost to the barrier; the doctor turns
    # it into a barrier-wait share + straggler verdict). Both are zero
    # for local runs and single-host fleets. Appended, never reordered.
    ("straggler_skew_usec", "StragglerSkewUsec", "max"),
    ("barrier_wait_usec", "BarrierWaitUSec", "sum"),
    # master failover (--svcadoptsecs / --resume --adopt; docs/
    # fault-tolerance.md "Master failover"): MasterTakeovers is
    # MASTER-observed (1 per host claimed via /adopt on the takeover
    # phase); SvcAdoptions / SvcAdoptWaitUsec are observed SERVICE-side
    # and shipped back like the lease counters — service-lifetime
    # values (adoptions survived + the longest awaiting-adoption wait
    # any grace window saw). Appended entries, never reordered.
    ("master_takeovers", "MasterTakeovers", "sum"),
    ("svc_adoptions", "SvcAdoptions", "sum"),
    ("svc_adopt_wait_usec", "SvcAdoptWaitUsec", "max"),
)


def merge_control_audit_counters(workers) -> dict:
    """Merge the per-host control-plane counters over a worker list
    (local workers contribute 0), keyed by wire/JSON name."""
    totals = {key: 0 for _attr, key, _mode in CONTROL_AUDIT_COUNTERS}
    for w in workers:
        for attr, key, mode in CONTROL_AUDIT_COUNTERS:
            val = getattr(w, attr, 0)
            if mode == "max":
                totals[key] = max(totals[key], val)
            else:
                totals[key] += val
    return totals
