"""Master<->service wire protocol constants.

Reference: source/Common.h:229-298 — HTTP paths, GET/JSON parameter keys,
and the strict exact-match protocol version handshake (HTTP_PROTOCOLVERSION,
Common.h:91). The wire format here is HTTP/1.1 + JSON (the reference uses
boost property-tree JSON; same idea, plain json module)."""

from __future__ import annotations

from .. import HTTP_PROTOCOL_VERSION  # noqa: F401 (re-export)

# http service paths (reference: HTTPCLIENTPATH_*, Common.h:229-246)
PATH_INFO = "/info"
PATH_PROTOCOL_VERSION = "/protocolversion"
PATH_STATUS = "/status"
PATH_BENCH_RESULT = "/benchresult"
PATH_PREPARE_FILE = "/preparefile"
PATH_PREPARE_PHASE = "/preparephase"
PATH_START_PHASE = "/startphase"
PATH_INTERRUPT_PHASE = "/interruptphase"
# telemetry extension (ours; no reference equivalent): Prometheus
# text-format metrics piggybacked onto the service route table
PATH_METRICS = "/metrics"
# streaming control plane (ours; no reference equivalent): server-push
# live-stats stream of delta-encoded ndjson frames (--svcstream), also
# the parent->child attachment point of the --svcfanout aggregation tree
PATH_LIVE_STREAM = "/livestream"
# master failover (ours; docs/fault-tolerance.md "Master failover"): a
# replacement master claims an awaiting-adoption host — validated by
# bench UUID + journal fingerprint + takeover token under route_lock
PATH_ADOPT = "/adopt"

# transferred parameter keys (reference: XFER_*, Common.h:251-298)
KEY_PROTOCOL_VERSION = "ProtocolVersion"
KEY_BENCH_ID = "BenchID"
KEY_PHASE_CODE = "PhaseCode"
KEY_PHASE_NAME = "PhaseName"
KEY_NUM_WORKERS_DONE = "NumWorkersDone"
KEY_NUM_WORKERS_DONE_WITH_ERROR = "NumWorkersDoneWithError"
KEY_NUM_ENTRIES_DONE = "NumEntriesDone"
KEY_NUM_BYTES_DONE = "NumBytesDone"
KEY_NUM_IOPS_DONE = "NumIOPSDone"
KEY_ELAPSED_USEC_LIST = "ElapsedUSecList"
KEY_ERROR_HISTORY = "ErrorHistory"
KEY_BENCH_PATH_TYPE = "BenchPathType"
KEY_NUM_BENCH_PATHS = "NumBenchPaths"
KEY_FILE_NAME = "FileName"
KEY_AUTHORIZATION = "PwHash"
KEY_INTERRUPT_QUIT = "quit"
# master liveness lease (ours; no reference equivalent): /preparephase
# reply echoes the armed lease so the master can log/verify it, and the
# service-observed lease counters ride /status + /benchresult
KEY_SVC_LEASE_SECS = "SvcLeaseSecs"
KEY_SVC_LEASE_EXPIRIES = "SvcLeaseExpiries"
KEY_SVC_LEASE_AGE_HWM = "SvcLeaseAgeHwmUsec"
# streaming control plane (--svcstream/--svcfanout): /livestream query
# params — desired push cadence, tree fanout, the comma-separated host
# subtree this node aggregates, and the resync marker a consumer sets
# when it reconnects after a missed/garbled frame (the first frame of
# any stream is a full snapshot; Resync makes the intent auditable).
# /interruptphase reuses Subtree/Fanout for O(fanout) teardown fan-out.
KEY_STREAM_INTERVAL_MS = "IntervalMs"
KEY_STREAM_FANOUT = "Fanout"
KEY_STREAM_SUBTREE = "Subtree"
KEY_STREAM_RESYNC = "Resync"
# fleet tracing (ours; docs/telemetry.md "Fleet tracing"): the master
# stamps the run's trace id + a per-request parent span (flow) id onto
# /preparephase, /startphase, /benchresult and the /livestream open so
# services can tag their handling spans and emit the matching Chrome
# flow-finish events; ShipTrace on /benchresult asks the service to
# attach its bounded span ring (size-capped by --traceshipcap — a
# refusal is LOUD, never fatal); SvcClockUsec is the service wall-clock
# stamp on /status + /benchresult replies (and the X-Svc-Clock-Usec
# /livestream response header) feeding the master's NTP-style
# clock-offset estimator — always present, so arming fleet tracing
# never changes per-tick wire traffic
KEY_TRACE_ID = "TraceId"
KEY_PARENT_SPAN = "ParentSpan"
KEY_SHIP_TRACE = "ShipTrace"
KEY_SVC_CLOCK = "SvcClockUsec"
KEY_TRACE_RING = "TraceRing"
KEY_TRACE_RING_REFUSED = "TraceRingRefused"
HDR_SVC_CLOCK = "X-Svc-Clock-Usec"
# slow-op forensics (--slowops; docs/telemetry.md "Tail forensics"):
# ShipSlowOps on /benchresult asks the service to attach its merged
# worker slow-op capture (K-slowest heaps + density samples) to the
# reply — same piggyback discipline as ShipTrace: size-capped by
# --traceshipcap, refusal LOUD never fatal, zero extra requests
KEY_SHIP_SLOWOPS = "ShipSlowOps"
KEY_SLOWOPS = "SlowOps"
KEY_SLOWOPS_REFUSED = "SlowOpsRefused"
# master failover (--svcadoptsecs / --resume --adopt; docs/
# fault-tolerance.md "Master failover"): the takeover token + journal
# fingerprint ride /preparephase (stashed by the service as the /adopt
# credentials) and /adopt (presented by the claiming master);
# AwaitingAdoption appears in /status ONLY while a host is in the
# adoption grace window, and the service-observed adoption counters
# ride /status + /benchresult ONLY when nonzero — flags-off wire
# traffic stays byte-identical
KEY_TAKEOVER_TOKEN = "TakeoverToken"
KEY_JOURNAL_FINGERPRINT = "JournalFingerprint"
KEY_SVC_ADOPT_SECS = "SvcAdoptSecs"
KEY_AWAITING_ADOPTION = "AwaitingAdoption"
KEY_SVC_ADOPTIONS = "SvcAdoptions"
KEY_SVC_ADOPT_WAIT = "SvcAdoptWaitUsec"


def make_pw_hash(secret: str) -> str:
    """Shared-secret hash for --svcpwfile (reference: HashTk + ProgArgs
    :3003; sha256 here — the protocol is ours)."""
    import hashlib
    return hashlib.sha256(secret.encode()).hexdigest()


def read_pw_file(path: str) -> str:
    with open(path) as f:
        return make_pw_hash(f.read().strip())
