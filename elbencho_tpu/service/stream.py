"""Streaming control plane: server-push live stats, aggregation tree, deltas.

ROADMAP item 3 / ISSUE 8 tentpole. The master used to drive every service
with per-request HTTP — a fresh TCP connection and a full /status JSON
serialization per host per live-stats tick, so control-plane cost grew
O(hosts) and capped fleet size long before the data path did. PAPERS.md
"RPC Considered Harmful" (arXiv 1805.08430) is the blueprint: the
per-request RPC idiom, not the network, is the bottleneck. Three layers,
all opt-in via ``--svcstream`` (default off = per-request polling parity):

1. **Persistent server-push stream** (`/livestream`): one chunked-HTTP
   connection per attached host carrying newline-delimited JSON frames at
   the ``--svcupint`` cadence, pushed early whenever a completion-relevant
   value changes (worker done/error counts, phase identity) so
   end-of-phase detection is no slower than the 25ms poll ramp.
2. **Hierarchical aggregation** (``--svcfanout N``): the master attaches
   only N root services; each root re-streams its assigned subtree
   (heap-shaped, passed down via the ``Subtree`` query param) after
   merging child frames with the existing wire merge rules (sum, except
   the documented MAX-merged high-water marks). Per-host detail survives
   in the frame's ``Hosts`` map. A failed child drops its whole
   sub-subtree into ``Unreach``; the master then re-attaches those hosts
   directly (stream -> poll fallback ladder, logged LOUDLY).
3. **Delta encoding**: frames carry only the keys that changed since the
   previously sent frame, with a periodic full snapshot (every
   ``FULL_FRAME_EVERY`` frames), a mandatory full first frame, and
   sequence numbers so a consumer that misses a frame reconnects with
   ``Resync=1`` instead of applying a delta to the wrong base.

Lease semantics (docs/fault-tolerance.md, --svcleasesecs) carry over
route-aware: a stream opened WITH the run's bench UUID renews the
service's master-liveness lease on every pushed frame; observer streams
(no/stale UUID) never do, and a stream that dies mid-phase stops
renewing, so orphan recovery still fires.
"""

from __future__ import annotations

import json
import threading
import time

from ..stats.latency_histogram import LatencyHistogram
from ..toolkits import logger
from ..tpu.device import PATH_AUDIT_MAX_KEYS
from . import protocol as proto
from .fault_tolerance import CONTROL_AUDIT_COUNTERS

#: content type of the frame stream (newline-delimited JSON objects)
NDJSON_CONTENT_TYPE = "application/x-ndjson"

#: a full snapshot replaces the delta every Nth frame — belt-and-braces
#: against silent state drift (a MISSED frame is caught immediately by
#: the sequence check and answered with a resync reconnect)
FULL_FRAME_EVERY = 64

#: server-side change-detection granularity between pushes; mirrors the
#: 25ms fast-poll floor of the polling ladder (POLL_MIN_SECS) so phase
#: completion is detected just as promptly without per-request cost
TICK_SECS = 0.025
MIN_INTERVAL_MS = 25

#: a push into a dead/stalled peer must not hang the session thread
SEND_TIMEOUT_SECS = 10.0

#: per-node cap on how long an interrupt fan-out waits for its forwards
#: (each node replies within this no matter what lives below it)
FORWARD_JOIN_SECS = 5.0


def stream_read_timeout(interval_ms: int) -> float:
    """Consumer-side no-frame timeout: generous multiples of the push
    cadence — frames heartbeat every interval, but a loaded aggregation
    node may clump pushes, and a spurious timeout costs a resync (and on
    its second strike, the whole stream falls back to polling)."""
    return max(interval_ms / 1000.0 * 8, 5.0)

#: a single frame larger than this is line noise, not a frame
MAX_FRAME_BYTES = 16 << 20

# frame meta keys (everything else is the live-stats dict schema)
KEY_SEQ = "Seq"
KEY_FULL = "Full"
KEY_HOSTS = "Hosts"
KEY_AGG_DEPTH = "AggDepth"
KEY_UNREACH = "Unreach"

#: a service does not know the host label its parent addresses it by;
#: it files its own entry under this sentinel and the parent rewrites it
SELF_LABEL = ""

# per-host entry keys inside the Hosts map (short on purpose: with
# thousands of hosts these names dominate frame size)
HOST_DONE = "D"          # NumWorkersDone of that host
HOST_ERR = "E"           # NumWorkersDoneWithError of that host
HOST_ENTRIES = "Ent"     # live entries done
HOST_BYTES = "B"         # live bytes done
HOST_IOPS = "I"          # live iops done
HOST_CPU = "C"           # CPU util percent
HOST_RTT = "Rtt"         # stream-open round trip usec (measured upstream)
HOST_HIJACKED = "Hij"    # bench UUID mismatch AFTER a first match
# fleet tracing: per-host clock offset/uncertainty (usec) relative to
# THIS frame's sender, estimated from the parent->child stream-open
# ping and CHAINED down the aggregation tree (each node adds its own
# measured child offset to the entries it forwards) — the master adds
# its root measurement on top, giving master-relative offsets for every
# host without one extra request (telemetry/tracefleet.py)
HOST_CLOCK_OFF = "Co"
HOST_CLOCK_UNC = "Cu"

#: top-level keys excluded from the numeric subtree merge: identity and
#: frame plumbing stay the aggregating node's own
MERGE_EXCLUDED_KEYS = frozenset({
    KEY_SEQ, KEY_FULL, KEY_HOSTS, KEY_AGG_DEPTH, KEY_UNREACH,
    proto.KEY_BENCH_ID, proto.KEY_PHASE_CODE, proto.KEY_PHASE_NAME,
    "CPUUtil",
})

#: keys that MAX-merge across a subtree instead of summing — exactly the
#: wire protocol's documented high-water marks, derived from the same
#: schemas so the tree can never diverge from the flat merge
MERGE_MAX_KEYS = PATH_AUDIT_MAX_KEYS | {
    key for _attr, key, mode in CONTROL_AUDIT_COUNTERS if mode == "max"}

#: mergeable latency histograms (bucket-wise sum via LatencyHistogram)
MERGE_HISTO_KEYS = frozenset({"IOLatHisto", "EntLatHisto"})


class StreamProtocolError(Exception):
    """A frame violated the stream contract (sequence gap, delta without
    a base, undecodable line). The consumer reconnects with Resync=1."""


class StreamDetachedError(Exception):
    """This host can no longer be served by the streaming plane for the
    current phase; the caller falls back one rung (stream -> poll)."""


# ---------------------------------------------------------------------------
# delta codec
# ---------------------------------------------------------------------------

def encode_delta(prev: dict, cur: dict) -> dict:
    """Frame carrying only the keys of ``cur`` that differ from ``prev``.
    The ``Hosts`` map deltas per host entry (an unchanged host is simply
    absent). Keys never disappear mid-stream; the periodic full snapshot
    covers any drift."""
    out: dict = {}
    for key, val in cur.items():
        if key == KEY_HOSTS:
            prev_hosts = prev.get(KEY_HOSTS, {})
            changed = {h: e for h, e in val.items()
                       if prev_hosts.get(h) != e}
            if changed:
                out[KEY_HOSTS] = changed
        elif prev.get(key, _MISSING) != val:
            out[key] = val
    return out


_MISSING = object()


def apply_delta(state: dict, frame: dict) -> dict:
    """New state dict from ``state`` + a delta (or full) frame. Pure —
    re-applying the same frame is idempotent. Frame meta keys (Seq/Full)
    are dropped from the result."""
    new = dict(state)
    for key, val in frame.items():
        if key in (KEY_SEQ, KEY_FULL):
            continue
        if key == KEY_HOSTS:
            hosts = dict(new.get(KEY_HOSTS, {}))
            hosts.update(val)
            new[KEY_HOSTS] = hosts
        else:
            new[key] = val
    return new


def check_seq(last_seq: int, frame: dict) -> int:
    """Enforce the gap-free sequence contract; returns the new last_seq.
    A full frame re-anchors the sequence (that is its whole point)."""
    seq = frame.get(KEY_SEQ, 0)
    if not isinstance(seq, int) or seq <= 0:
        raise StreamProtocolError(f"bad frame sequence number {seq!r}")
    if frame.get(KEY_FULL):
        return seq
    if last_seq and seq != last_seq + 1:
        raise StreamProtocolError(
            f"frame sequence gap ({last_seq} -> {seq}); resync required")
    if not last_seq:
        raise StreamProtocolError("delta frame before any full snapshot")
    return seq


# ---------------------------------------------------------------------------
# aggregation-tree planning
# ---------------------------------------------------------------------------

def plan_subtree(hosts: "list[str]", fanout: int
                 ) -> "list[tuple[str, list[str]]]":
    """Split a host list into ``(child, sub_subtree)`` pairs: the first
    ``fanout`` hosts become direct children, the remainder is dealt
    round-robin so depth stays balanced (heap-shaped N-ary forest)."""
    if not hosts:
        return []
    if fanout <= 0:
        fanout = len(hosts)
    children = hosts[:fanout]
    rest = hosts[fanout:]
    return [(child, rest[i::fanout]) for i, child in enumerate(children)]


def plan_tree(hosts: "list[str]", fanout: int
              ) -> "list[tuple[str, list[str]]]":
    """The master's attachment plan: with ``--svcfanout 0`` every host is
    a root with an empty subtree (flat streaming); otherwise the first
    ``fanout`` hosts are roots, each aggregating its assigned subtree."""
    if fanout <= 0:
        return [(h, []) for h in hosts]
    return plan_subtree(hosts, fanout)


def tree_depth(num_hosts: int, fanout: int) -> int:
    """Expected AggDepth for a clean tree (used by tests/sizing docs)."""
    depth, layer, covered = 0, fanout if fanout > 0 else num_hosts, 0
    while covered < num_hosts:
        depth += 1
        covered += layer
        layer *= fanout if fanout > 0 else 1
    return max(depth, 1)


# ---------------------------------------------------------------------------
# subtree merge (service side)
# ---------------------------------------------------------------------------

def merge_subtree_frame(dst: dict, src: dict) -> dict:
    """Merge a child's applied frame state into ``dst`` with the wire
    merge rules: numeric keys sum, the documented high-water marks MAX,
    latency histograms merge bucket-wise, identity/meta keys stay own."""
    for key, val in src.items():
        if key in MERGE_EXCLUDED_KEYS:
            continue
        if key in MERGE_HISTO_KEYS:
            if isinstance(val, dict):
                merged = LatencyHistogram.from_dict(dst.get(key) or {})
                merged.merge(LatencyHistogram.from_dict(val))
                dst[key] = merged.to_dict()
        elif isinstance(val, bool):
            continue
        elif isinstance(val, (int, float)):
            if key in MERGE_MAX_KEYS:
                dst[key] = max(dst.get(key, 0), val)
            else:
                dst[key] = dst.get(key, 0) + val
    return dst


def live_host_entry(stats: dict) -> dict:
    """A node's own per-host entry for the frame's Hosts map, derived
    from its live-stats dict (statistics.get_live_stats_dict schema)."""
    return {
        HOST_DONE: stats.get(proto.KEY_NUM_WORKERS_DONE, 0),
        HOST_ERR: stats.get(proto.KEY_NUM_WORKERS_DONE_WITH_ERROR, 0),
        HOST_ENTRIES: stats.get(proto.KEY_NUM_ENTRIES_DONE, 0),
        HOST_BYTES: stats.get(proto.KEY_NUM_BYTES_DONE, 0),
        HOST_IOPS: stats.get(proto.KEY_NUM_IOPS_DONE, 0),
        HOST_CPU: stats.get("CPUUtil", 0),
    }


# ---------------------------------------------------------------------------
# consumer-side stream handle (shared by master and interior aggregators)
# ---------------------------------------------------------------------------

class StreamHandle:
    """One open /livestream response: reads ndjson frames incrementally.
    ``rtt_usec`` is the open round trip (connect -> response headers) —
    the streaming replacement for the --svcping /status RTT."""

    def __init__(self, conn, resp, rtt_usec: int, label: str,
                 on_close=None, clock_t0_usec: int = 0,
                 clock_t1_usec: int = 0, svc_clock_usec: int = 0):
        self._conn = conn
        self._resp = resp
        self._on_close = on_close
        self.rtt_usec = rtt_usec
        self.label = label
        self.last_frame_bytes = 0
        self._closed = False
        # fleet tracing: the open round trip bracketed in LOCAL wall
        # clock + the peer's X-Svc-Clock-Usec stamp — one ready-made
        # clock-offset sample (0s when the peer predates the header)
        self.clock_t0_usec = clock_t0_usec
        self.clock_t1_usec = clock_t1_usec
        self.svc_clock_usec = svc_clock_usec

    def read_frame(self) -> dict:
        """Next frame dict. Raises OSError on EOF/timeout (the socket
        state is unreliable after either — reconnect, never resume) and
        StreamProtocolError on an undecodable or truncated line."""
        line = self._resp.readline(MAX_FRAME_BYTES)
        if not line:
            raise OSError(f"live stream from {self.label} ended")
        if not line.endswith(b"\n"):
            raise StreamProtocolError(
                f"oversized/truncated frame from {self.label}")
        self.last_frame_bytes = len(line)
        try:
            frame = json.loads(line)
        except json.JSONDecodeError as err:
            raise StreamProtocolError(
                f"undecodable frame from {self.label}: {err}") from err
        if not isinstance(frame, dict):
            raise StreamProtocolError(f"non-object frame from {self.label}")
        return frame

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._conn.close()
        except OSError:
            pass
        if self._on_close is not None:
            self._on_close()


# ---------------------------------------------------------------------------
# interior node: child aggregation (service side)
# ---------------------------------------------------------------------------

class ChildAggregator:
    """Parent side of one child's stream: a daemon thread that keeps the
    child's latest applied frame state, reconnecting with backoff. A
    ``None`` snapshot means the child (and therefore its whole assigned
    sub-subtree) is currently unreachable."""

    RECONNECT_MIN_SECS = 0.2
    RECONNECT_MAX_SECS = 5.0

    def __init__(self, label: str, subtree: "list[str]", bench_id: str,
                 interval_ms: int, fanout: int, pw_hash: str,
                 default_port: int):
        self.label = label
        self.subtree = list(subtree)
        self.bench_id = bench_id
        self.interval_ms = interval_ms
        self.fanout = fanout
        self.pw_hash = pw_hash
        self.default_port = default_port
        self.rtt_usec = 0
        self.hijacked = False
        # child clock offset relative to THIS node (fleet tracing),
        # min-RTT filtered over the reconnect history
        from ..telemetry.tracefleet import ClockSyncEstimator
        self.clock = ClockSyncEstimator()
        self._matched = False
        self._state: "dict | None" = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._handle: "StreamHandle | None" = None
        self._thread: "threading.Thread | None" = None
        self._last_logged_err = ""
        # a child is only REPORTED unreachable after this long without a
        # frame: the aggregator thread needs a moment to connect at
        # session start, a blip must ride out one reconnect-backoff
        # cycle, and premature reporting is costly — the master's
        # detachment is one-way for the phase
        self.unreach_grace_secs = max(6.0, interval_ms / 1000.0 * 8)
        self._down_since: "float | None" = None
        # cheap completion signal for the parent's tick loop: recomputed
        # per APPLIED frame (not per tick), so idle ticks cost nothing
        self.done_err_sig: tuple = ()

    def start(self) -> None:
        self._down_since = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name=f"svc-agg-{self.label}", daemon=True)
        self._thread.start()

    def down_for_secs(self) -> float:
        """Seconds this child has been without an applied frame (0 while
        it is streaming)."""
        down_since = self._down_since
        return 0.0 if down_since is None \
            else time.monotonic() - down_since

    def stop(self) -> None:
        """Tear the child stream down; once the parent stream is gone the
        child must stop seeing lease renewals (orphan recovery depends on
        the whole chain dying together)."""
        self._stop.set()
        handle = self._handle
        if handle is not None:
            handle.close()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def snapshot(self) -> "dict | None":
        with self._lock:
            return self._state

    def _check_hijack(self, state: dict) -> None:
        """Grace-then-strict UUID tracking: frames sent before the child
        processed /startphase legitimately carry a stale/empty UUID; only
        a DIFFERENT non-empty UUID after a first match is a hijack."""
        if not self.bench_id:
            return
        frame_id = state.get(proto.KEY_BENCH_ID, "")
        if frame_id == self.bench_id:
            self._matched = True
        elif self._matched and frame_id:
            self.hijacked = True

    def _run(self) -> None:
        from .remote_worker import ServiceClient  # lazy: no import cycle
        backoff = self.RECONNECT_MIN_SECS
        read_timeout = stream_read_timeout(self.interval_ms)
        while not self._stop.is_set():
            client = ServiceClient(self.label, self.default_port,
                                   self.pw_hash, gauge=False)
            handle = None
            try:
                handle = client.open_stream(
                    self.bench_id, self.interval_ms, fanout=self.fanout,
                    subtree=self.subtree, read_timeout=read_timeout,
                    resync=True)
                self._handle = handle
                self.rtt_usec = handle.rtt_usec
                if handle.svc_clock_usec:
                    # the stream-open ping doubles as a clock-offset
                    # sample (X-Svc-Clock-Usec response header)
                    self.clock.add_sample(handle.clock_t0_usec,
                                          handle.clock_t1_usec,
                                          handle.svc_clock_usec)
                backoff = self.RECONNECT_MIN_SECS
                last_seq = 0
                state: dict = {}
                while not self._stop.is_set():
                    frame = handle.read_frame()
                    last_seq = check_seq(last_seq, frame)
                    state = apply_delta(
                        {} if frame.get(KEY_FULL) else state, frame)
                    self._check_hijack(state)
                    if self.bench_id and not self._matched:
                        # never merge frames that haven't matched this
                        # run's UUID: a child serving ANOTHER master's
                        # run must not feed its done counts/byte totals
                        # into our aggregate — it stays "warming" until
                        # the grace expires and Unreach hands it to the
                        # master's direct-attachment ladder (where the
                        # polling rung raises the hijack properly)
                        continue
                    sig = tuple(sorted(
                        (h, e.get(HOST_DONE, 0), e.get(HOST_ERR, 0),
                         e.get(HOST_HIJACKED, 0))
                        for h, e in state.get(KEY_HOSTS, {}).items()))
                    with self._lock:
                        self._state = state
                        self._down_since = None
                        self.done_err_sig = sig
                        self._last_logged_err = ""
            except Exception as err:  # noqa: BLE001 - failure=unreachable
                # LOUD fallback contract: a child that cannot be
                # aggregated must be diagnosable HERE (e.g. an HTTP 401
                # from a password mismatch), not only as the master's
                # generic tree-no-longer-covers fallback. Logged once
                # per distinct cause, not per reconnect attempt.
                msg = f"{type(err).__name__}: {err}"
                if self._stop.is_set():
                    pass  # deliberate teardown closed the stream
                elif msg != self._last_logged_err:
                    self._last_logged_err = msg
                    logger.log_error(
                        f"subtree aggregator: stream from child "
                        f"{self.label} failed: {msg} (reconnecting; the "
                        f"child falls to Unreach after "
                        f"{self.unreach_grace_secs:.0f}s)")
            finally:
                self._handle = None
                if handle is not None:
                    handle.close()
                client.close()
            with self._lock:
                self._state = None
                if self._down_since is None:
                    self._down_since = time.monotonic()
            if self._stop.wait(backoff):
                return
            backoff = min(backoff * 2, self.RECONNECT_MAX_SECS)


# ---------------------------------------------------------------------------
# server-side stream session
# ---------------------------------------------------------------------------

class StreamSession:
    """One /livestream connection: builds merged frames from this node's
    own live stats plus its child aggregators, delta-encodes, and pushes
    chunked ndjson until the peer goes away or the service shuts down.

    Push policy: a frame goes out when the configured interval elapsed
    (heartbeat — an empty delta still carries Seq, which doubles as the
    consumer's liveness signal) or IMMEDIATELY when a completion-relevant
    value changes (per-host done/error counts, phase identity, subtree
    reachability), checked every TICK_SECS."""

    def __init__(self, state, handler, params: dict, default_port: int):
        self.state = state
        self.handler = handler
        self.bench_id = params.get(proto.KEY_BENCH_ID, "")
        try:
            interval_ms = int(params.get(proto.KEY_STREAM_INTERVAL_MS,
                                         500) or 500)
        except ValueError:
            interval_ms = 500
        self.interval_ms = max(interval_ms, MIN_INTERVAL_MS)
        try:
            self.fanout = max(int(params.get(proto.KEY_STREAM_FANOUT, 0)
                                  or 0), 0)
        except ValueError:
            self.fanout = 0
        subtree = [h for h in
                   (params.get(proto.KEY_STREAM_SUBTREE, "") or "")
                   .split(",") if h]
        self.default_port = default_port
        self.params = params
        self.aggs = [
            ChildAggregator(child, chunk, self.bench_id, self.interval_ms,
                            self.fanout, state.pw_hash, default_port)
            for child, chunk in plan_subtree(subtree, self.fanout)]

    def _record_open_span(self) -> None:
        """Fleet tracing: a /livestream open stamped with a ParentSpan
        flow id gets its handling span + flow-finish like any request
        route (the open is the stream plane's one RPC)."""
        from ..telemetry.tracefleet import record_handle_span
        record_handle_span(self.state.manager, proto.PATH_LIVE_STREAM,
                           self.params, time.perf_counter_ns())

    def build_frame(self) -> dict:
        """Current merged state: own live stats + every reachable child's
        subtree state, per-host detail in Hosts, unreachable sub-subtrees
        listed in Unreach for the master's direct-attachment fallback."""
        stats = self.state.status()
        merged = dict(stats)
        hosts = {SELF_LABEL: live_host_entry(stats)}
        unreach: "list[str]" = []
        depth = 1
        for agg in self.aggs:
            snap = agg.snapshot()
            if snap is None:
                if agg.down_for_secs() >= agg.unreach_grace_secs:
                    # past the warm-up/blip grace: the child and its
                    # whole assigned sub-subtree fall to the master's
                    # direct-attachment ladder
                    unreach.append(agg.label)
                    unreach.extend(agg.subtree)
                continue
            depth = max(depth, 1 + snap.get(KEY_AGG_DEPTH, 1))
            merge_subtree_frame(merged, snap)
            # fleet tracing: chain clock offsets down the tree — every
            # entry below this child is (child-relative offset) + (our
            # measured offset TO the child); uncertainty bounds add
            child_off = agg.clock.offset_usec
            child_unc = agg.clock.uncertainty_usec
            has_clock = agg.clock.has_estimate
            for hlabel, entry in snap.get(KEY_HOSTS, {}).items():
                if hlabel == SELF_LABEL:
                    entry = dict(entry)
                    entry[HOST_RTT] = agg.rtt_usec
                    if agg.hijacked:
                        entry[HOST_HIJACKED] = 1
                    if has_clock:
                        entry[HOST_CLOCK_OFF] = child_off
                        entry[HOST_CLOCK_UNC] = child_unc
                    hosts[agg.label] = entry
                else:
                    if has_clock and HOST_CLOCK_OFF in entry:
                        entry = dict(entry)
                        entry[HOST_CLOCK_OFF] += child_off
                        entry[HOST_CLOCK_UNC] = \
                            entry.get(HOST_CLOCK_UNC, 0) + child_unc
                    hosts[hlabel] = entry
            unreach.extend(snap.get(KEY_UNREACH, []))
        merged[KEY_HOSTS] = hosts
        merged[KEY_AGG_DEPTH] = depth
        merged[KEY_UNREACH] = sorted(set(unreach))
        return merged

    def _tick_signature(self) -> tuple:
        """Cheap completion-relevant signal computed WITHOUT building a
        frame: the node's own phase/done/error state, each child's
        per-applied-frame done/err signature, and each child's
        reachability verdict. A change here pushes immediately; full
        frame builds otherwise happen only at the interval cadence —
        idle 25ms ticks must stay near-free (dozens of sessions tick
        concurrently on an interior node)."""
        return (
            self.state.cheap_live_signature(),
            tuple(agg.done_err_sig for agg in self.aggs),
            tuple(agg.snapshot() is None
                  and agg.down_for_secs() >= agg.unreach_grace_secs
                  for agg in self.aggs),
        )

    def serve(self) -> None:
        from ..telemetry.tracefleet import svc_wall_clock_usec
        h = self.handler
        h.send_response(200)
        h.send_header("Content-Type", NDJSON_CONTENT_TYPE)
        h.send_header("Transfer-Encoding", "chunked")
        # clock stamp for the consumer's skew estimator: the stream-open
        # round trip is a ready-made NTP-style sample (fleet tracing) —
        # a header, not a frame key, so frames never carry (or subtree-
        # sum) a per-tick clock value
        h.send_header(proto.HDR_SVC_CLOCK,
                      str(svc_wall_clock_usec(self.default_port)))
        h.end_headers()
        h.close_connection = True
        self._record_open_span()
        try:
            h.connection.settimeout(SEND_TIMEOUT_SECS)
        except OSError:
            pass
        for agg in self.aggs:
            agg.start()
        interval = self.interval_ms / 1000.0
        prev: dict = {}
        seq = 0
        last_push = 0.0
        last_sig = None
        try:
            while not self.state.stream_shutdown.is_set():
                sig = self._tick_signature()
                now = time.monotonic()
                if seq and sig == last_sig and now - last_push < interval:
                    time.sleep(TICK_SECS)
                    continue
                cur = self.build_frame()
                seq += 1
                full = seq == 1 or seq % FULL_FRAME_EVERY == 0
                payload = dict(cur) if full else encode_delta(prev, cur)
                payload[KEY_SEQ] = seq
                if full:
                    payload[KEY_FULL] = 1
                data = (json.dumps(payload, separators=(",", ":"))
                        + "\n").encode()
                h.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
                prev = cur
                last_push = now
                last_sig = sig
                # route-aware lease renewal: only a stream carrying the
                # run's CURRENT bench UUID proves the owning master
                # alive — and only when the peer is actually DRAINING
                # the stream: a black-holed master (partition, preempted
                # VM) leaves our small frames piling up in the kernel
                # send queue, and counting those buffered writes as
                # liveness would delay orphan recovery far past
                # --svcleasesecs
                if self._send_queue_drained(h.connection):
                    self.state.stream_pushed(self.bench_id)
                time.sleep(TICK_SECS)
        except (OSError, ValueError):
            pass  # peer went away; the session dies with it
        finally:
            for agg in self.aggs:
                agg.stop()
            try:
                h.wfile.write(b"0\r\n\r\n")
            except (OSError, ValueError):
                pass

    #: unsent bytes allowed in the peer's direction before a push stops
    #: counting as a lease renewal (a few frames of slack for a busy but
    #: alive master)
    SEND_QUEUE_SLACK_BYTES = 8192

    @staticmethod
    def _send_queue_drained(sock) -> bool:
        """True when the connection's kernel send queue holds (nearly)
        nothing — i.e. the peer has been ACKing what we push. Falls back
        to True where the TIOCOUTQ ioctl is unavailable (non-Linux):
        renewal then degrades to write-success semantics."""
        try:
            import fcntl as _fcntl
            import struct
            import termios
            buf = _fcntl.ioctl(sock.fileno(), termios.TIOCOUTQ,
                               struct.pack("i", 0))
            return struct.unpack("i", buf)[0] \
                <= StreamSession.SEND_QUEUE_SLACK_BYTES
        except (ImportError, OSError, AttributeError):
            return True


# ---------------------------------------------------------------------------
# interrupt fan-out along the tree (teardown is O(fanout) too)
# ---------------------------------------------------------------------------

def forward_interrupt(state, params: dict) -> None:
    """/interruptphase carrying a Subtree param: forward the interrupt to
    this node's direct children (each with ITS sub-subtree) concurrently,
    best-effort and bounded — a dead child must not stall teardown."""
    subtree = [h for h in (params.get(proto.KEY_STREAM_SUBTREE, "") or "")
               .split(",") if h]
    if not subtree:
        return
    try:
        fanout = max(int(params.get(proto.KEY_STREAM_FANOUT, 0) or 0), 0)
    except ValueError:
        fanout = 0
    quit_param = proto.KEY_INTERRUPT_QUIT in params
    from .remote_worker import ServiceClient

    # every node bounds its OWN forwards by this join deadline, so a
    # child always replies within ~FORWARD_JOIN_SECS no matter how deep
    # (or dead) the tree below it is — which is why the per-request read
    # timeout must EXCEED it, or a parent would declare a healthy child
    # unreachable merely for waiting on ITS dead descendants
    forward_timeout = FORWARD_JOIN_SECS + 3

    def send_one(target: str, chunk: "list[str]") -> None:
        client = ServiceClient(target, state.base_cfg.service_port,
                               state.pw_hash, gauge=False)
        fwd_params = {}
        if quit_param:
            fwd_params[proto.KEY_INTERRUPT_QUIT] = "1"
        if chunk:
            fwd_params[proto.KEY_STREAM_SUBTREE] = ",".join(chunk)
            fwd_params[proto.KEY_STREAM_FANOUT] = fanout
        try:
            client._request("GET", proto.PATH_INTERRUPT_PHASE, fwd_params,
                            timeout=forward_timeout)
        except Exception:  # noqa: BLE001 - best effort, like teardown
            logger.log_error(f"interrupt forward to {target} failed"
                             + (f"; sending directly to its {len(chunk)} "
                                f"sub-subtree host(s)" if chunk else ""))
            # a dead child must not strand its sub-subtree with workers
            # still running: degrade to direct sends (the teardown
            # analogue of the Unreach -> direct-attachment ladder)
            for sub_host in chunk:
                send_one(sub_host, [])
        finally:
            client.close()

    threads = [threading.Thread(target=send_one, args=(child, chunk),
                                daemon=True,
                                name=f"svc-int-fwd-{child}")
               for child, chunk in plan_subtree(subtree, fanout)]
    for t in threads:
        t.start()
    # one shared deadline for ALL forwards: do_GET calls this BEFORE
    # taking route_lock (holding the route lock across child RPCs is
    # the stall testing/lockgraph.py bans), but the handler thread is
    # still pinned here — a row of dead children must not hold it for
    # fanout x timeout
    deadline = time.monotonic() + FORWARD_JOIN_SECS
    for t in threads:
        t.join(timeout=max(deadline - time.monotonic(), 0))


# ---------------------------------------------------------------------------
# master side: per-run streaming state
# ---------------------------------------------------------------------------

class HostStreamState:
    """Per-host live view fed by root stream readers; waited on by the
    host's RemoteWorker under StreamControl.cond."""

    __slots__ = ("done", "err", "entries", "bytes", "iops", "cpu", "rtt",
                 "hijacked", "unreachable", "attached", "last_change",
                 "clock_off", "clock_unc", "has_clock")

    def __init__(self):
        self.reset(time.monotonic())

    def reset(self, now: float) -> None:
        self.done = 0
        self.err = 0
        self.entries = 0
        self.bytes = 0
        self.iops = 0
        self.cpu = 0.0
        self.rtt = 0
        self.hijacked = False
        self.unreachable = False
        self.attached = True
        self.last_change = now
        # fleet tracing: tree-chained clock offset of this host relative
        # to its ROOT (the master adds its own root measurement on top);
        # reset with the phase and repopulated by the next frame
        self.clock_off = 0
        self.clock_unc = 0
        self.has_clock = False


class StreamControl:
    """Master-side streaming bookkeeping for one run: the attachment plan
    (roots + their subtrees), per-host live state distributed from root
    stream frames, and the detach logic that keeps the invariant: a
    host's live contribution reaches the master EITHER via the tree
    (attached) OR via its own /status polling (detached), never both."""

    def __init__(self, cfg, hosts: "list[str]"):
        self.cfg = cfg
        self.fanout = max(getattr(cfg, "svc_fanout", 0), 0)
        self.hosts = list(hosts)
        self.plan = dict(plan_tree(self.hosts, self.fanout))
        self.cond = threading.Condition()
        self.states = {h: HostStreamState() for h in self.hosts}
        self.workers_by_host: dict = {}
        self._phase_uuid: "str | None" = None
        self._entered = 0  # workers past /startphase, into live-waiting
        # reverse tree map: which root's stream serves each host (a root
        # serves itself) — waiters consult it to notice a root whose
        # WORKER is gone (degraded in an earlier phase) and can
        # therefore never stream nor detach them
        self.root_of: "dict[str, str]" = {}
        for root, subtree in self.plan.items():
            self.root_of[root] = root
            for member in subtree:
                self.root_of[member] = root

    def register_workers(self, workers) -> None:
        self.workers_by_host = {
            w.host: w for w in workers if getattr(w, "host", None)}

    def subtree_of(self, host: str) -> "list[str] | None":
        """The subtree a root host aggregates; None for non-root hosts."""
        return self.plan.get(host)

    def ensure_phase(self, bench_id: str) -> None:
        """First worker entering a new phase resets the per-host states
        (idempotent for the others — keyed by the phase's bench UUID)."""
        with self.cond:
            if self._phase_uuid == bench_id:
                return
            self._phase_uuid = bench_id
            self._entered = 0
            now = time.monotonic()
            for st in self.states.values():
                st.reset(now)

    def state_of(self, host: str) -> HostStreamState:
        return self.states[host]

    def note_entered(self) -> None:
        """A worker finished /startphase and is now live-waiting; once
        ALL active workers are past that point the master's steady-state
        connection census (SvcConnHwm) becomes meaningful — during the
        start burst, per-host request connections are legitimately still
        open."""
        with self.cond:
            self._entered += 1

    def all_entered(self) -> bool:
        active = sum(1 for w in self.workers_by_host.values()
                     if not getattr(w, "degraded", False))
        with self.cond:
            return self._entered >= active > 0

    def detach_host(self, host: str) -> None:
        """The host leaves the streaming plane for this phase (its worker
        falls back to direct polling); later tree frames must no longer
        mirror into its worker, or its contribution would double."""
        with self.cond:
            st = self.states.get(host)
            if st is not None:
                st.attached = False
            self.cond.notify_all()

    def detach_subtree(self, root_host: str) -> None:
        """Root stream died: every still-attached, still-waiting host of
        its subtree becomes unreachable so the waiters fall back too."""
        with self.cond:
            for label in (root_host, *self.plan.get(root_host, ())):
                st = self.states.get(label)
                if st is not None and st.attached:
                    st.unreachable = True
            self.cond.notify_all()

    def ingest_frame(self, root_host: str, state: dict) -> None:
        """Distribute a root frame's per-host entries into the host
        states and the per-host RemoteWorker mirrors (live_ops for the
        master's live display, CPU gauge, stream-open RTT as the
        --svcping value)."""
        with self.cond:
            now = time.monotonic()
            for label, entry in state.get(KEY_HOSTS, {}).items():
                if label == SELF_LABEL:
                    label = root_host
                st = self.states.get(label)
                if st is None or not st.attached:
                    continue
                prog = (entry.get(HOST_ENTRIES, 0),
                        entry.get(HOST_BYTES, 0),
                        entry.get(HOST_IOPS, 0),
                        entry.get(HOST_DONE, 0))
                if prog != (st.entries, st.bytes, st.iops, st.done):
                    st.last_change = now
                st.entries, st.bytes, st.iops, st.done = prog
                st.err = entry.get(HOST_ERR, 0)
                st.cpu = entry.get(HOST_CPU, 0.0)
                st.rtt = entry.get(HOST_RTT, st.rtt)
                if HOST_CLOCK_OFF in entry:
                    # tree-chained clock offset relative to the ROOT;
                    # a root's own entry carries none (offset 0 to
                    # itself) — has_clock then stays False and the
                    # master's direct root estimate stands alone
                    st.clock_off = entry[HOST_CLOCK_OFF]
                    st.clock_unc = entry.get(HOST_CLOCK_UNC, 0)
                    st.has_clock = True
                if entry.get(HOST_HIJACKED):
                    st.hijacked = True
                worker = self.workers_by_host.get(label)
                if worker is not None:
                    worker.live_ops.num_entries_done = st.entries
                    worker.live_ops.num_bytes_done = st.bytes
                    worker.live_ops.num_iops_done = st.iops
                    worker.cpu_util_pct = st.cpu
                    if st.rtt:
                        worker.last_ping_usec = st.rtt
            for label in state.get(KEY_UNREACH, ()):
                st = self.states.get(label)
                if st is not None:
                    st.unreachable = True
            self.cond.notify_all()

    def root_worker_lost(self, host: str) -> bool:
        """True when the worker that would stream for this host no
        longer exists or was degraded out of the run (--svctolerant):
        it can never open the subtree stream NOR run the detach in
        _run_root_stream's finally, so its waiters must detach
        themselves instead of holding the phase barrier forever."""
        root_worker = self.workers_by_host.get(
            self.root_of.get(host, host))
        return root_worker is None \
            or getattr(root_worker, "degraded", False)

    def subtree_fully_attached(self, root_host: str) -> bool:
        """True while every host of the root's subtree is still served by
        the tree. The moment ANY member detaches to polling, the root
        must stop ingesting the subtree-aggregated telemetry: the
        interior aggregator keeps retrying the lost child forever, so a
        recovered child would re-enter the aggregate while its own
        polling worker also reports it — the one way a host could count
        twice. Detachment is one-way per phase, so this latches False."""
        with self.cond:
            return all(self.states[label].attached
                       for label in (root_host,
                                     *self.plan.get(root_host, ())))

    def subtree_satisfied(self, root_host: str, num_threads: int) -> bool:
        """True when no attached subtree host (incl. the root itself) is
        still mid-phase: each is done, errored, hijacked, unreachable, or
        already detached to polling."""
        with self.cond:
            for label in (root_host, *self.plan.get(root_host, ())):
                st = self.states[label]
                if st.attached and not st.unreachable and not st.hijacked \
                        and not st.err and st.done < num_threads:
                    return False
            return True
