"""Service role: HTTP server exposing the control-plane endpoints.

Reference: source/HTTPServiceSWS.{h,cpp} + HTTPService.{h,cpp} — a
deliberately **single-threaded** HTTP server (invariant documented at
HTTPServiceSWS.cpp:130-136: no concurrent mutation of the worker pool),
with endpoints /info /protocolversion /status /benchresult /preparefile
/preparephase /startphase /interruptphase (defineServerResources :137),
daemonization with logfile + instance lock (HTTPService.cpp:32-110),
duplicate /startphase idempotency via bench-UUID compare (:543-554), and
strict protocol-version handshake (:280-293).

Streaming control plane (ours; docs/control-plane.md): the server is a
ThreadingHTTPServer so the long-lived `/livestream` push connections
(--svcstream) and keep-alive request connections cannot block each
other — but every OTHER route still runs under one route_lock, which
preserves the reference's no-concurrent-pool-mutation invariant exactly
(requests serialize as if single-threaded; only the read-only stream
sessions run beside them).

The control plane rides DCN between TPU-VM hosts; benchmark traffic never
crosses it (SURVEY.md section 2.3).
"""

from __future__ import annotations

import fcntl
import getpass
import json
import os
import shutil
import sys
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import HTTP_PROTOCOL_VERSION, __version__
from ..config.args import BenchConfig, ConfigError
from ..phases import BenchPhase
from ..stats.statistics import Statistics
from ..toolkits import logger
from ..workers.manager import WorkerManager
from . import protocol as proto

SVC_TMP_DIR = "/var/tmp"


class ServiceState:
    """Mutable service-side state: current config + worker pool + stats.
    Rebuilt on every /preparephase (reference: :376-498 kills and respawns
    the pool so stale workers never leak into the next run)."""

    def __init__(self, base_cfg: BenchConfig):
        self.base_cfg = base_cfg
        self.cfg: "BenchConfig | None" = None
        self.manager: "WorkerManager | None" = None
        self.statistics: "Statistics | None" = None
        self.phase_start_monotonic = 0.0
        self.pw_hash = ""
        if base_cfg.svc_password_file:
            self.pw_hash = proto.read_pw_file(base_cfg.svc_password_file)
        # worker-pool mutation guard: request handling is serialized by
        # route_lock, but the lease watchdog thread (--svcleasesecs) may
        # tear down the pool concurrently with an HTTP request — RLock so
        # teardown can nest under prepare/orphan recovery (single-shot
        # semantics live in teardown_workers itself)
        self._teardown_lock = threading.RLock()
        # route serialization: the server is threaded (so /livestream
        # push sessions and parked keep-alive connections cannot block
        # the control plane), but all stateful routes run one at a time
        # under this lock — the reference's single-threaded invariant,
        # kept by construction
        self.route_lock = threading.Lock()
        # streaming control plane: session shutdown signal (stream
        # sessions are read-only and run OUTSIDE route_lock)
        self.stream_shutdown = threading.Event()
        # master liveness lease (--svcleasesecs): armed per /preparephase,
        # renewed by every authorized master request, watched by a daemon
        # thread. Counters are SERVICE-lifetime (they survive pool
        # rebuilds) and ship over the wire as SvcLeaseExpiries (sum) /
        # SvcLeaseAgeHwmUsec (MAX) — fault_tolerance.CONTROL_AUDIT_COUNTERS
        self._lease_secs = 0
        self._lease_last_contact = time.monotonic()
        self._lease_stop = threading.Event()
        self._lease_thread: "threading.Thread | None" = None
        self.lease_expiries = 0
        self.lease_age_hwm_usec = 0
        # master failover (--svcadoptsecs + /adopt): takeover credentials
        # stashed at /preparephase (token + journal fingerprint ride the
        # config wire as protocol extras — absent unless the master armed
        # them), the awaiting-adoption grace state the lease watchdog
        # enters instead of orphan recovery, and the SERVICE-lifetime
        # adoption counters (ship like the lease counters, but only when
        # nonzero — flags-off wire traffic stays byte-identical)
        self._adopt_token = ""
        self._adopt_fingerprint = ""
        self._adopt_grace_secs = 0
        self._awaiting_adoption = False
        self._adopt_wait_started = 0.0
        self.svc_adoptions = 0
        self.svc_adopt_wait_usec = 0
        # per-host --tracefile paths this service wrote (fleet tracing):
        # scrubbed together with the upload temp dir on quit/orphan so
        # service hosts don't accumulate stale trace rings — but ONLY
        # once a master provably holds the ring: attaching it to a
        # /benchresult reply makes it PENDING, and the master's NEXT
        # contact (it would not proceed without having processed the
        # result) promotes pending -> shipped. A refused-over-cap ring,
        # a master that crashed mid-response, or spans recorded after
        # the last collection (a new /startphase clears both marks)
        # leave the local file as the ONLY copy — the scrub spares it.
        self._trace_files: "set[str]" = set()
        self._trace_shipped: "set[str]" = set()
        self._trace_ship_pending = ""
        # /metrics piggyback (telemetry subsystem): one sampler for the
        # service lifetime; the provider indirection follows the worker
        # pool across /preparephase rebuilds
        from ..telemetry.registry import BenchTelemetry
        self._telemetry = BenchTelemetry(
            base_cfg, lambda: (self.statistics, self.manager),
            role="service", extra_control=self.lease_counters)

    def teardown_workers(self) -> None:
        """Single-shot + concurrency-safe: the HTTP handler (interrupt
        with quit, /preparephase rebuild) and the lease watchdog may both
        reach here; whoever swaps the manager out first tears it down,
        everyone else sees None and returns."""
        with self._teardown_lock:
            manager, self.manager = self.manager, None
            self.statistics = None
            if manager is None:
                return
            manager.interrupt_and_notify_workers()
            try:
                manager.join_all_threads()
            except Exception:  # noqa: BLE001 - teardown is best effort
                pass

    def prepare_phase(self, cfg_dict: dict) -> dict:
        """Kill+rebuild the worker pool from the master's config JSON;
        reply with bench path info + error history."""
        with self._teardown_lock:
            return self._prepare_phase_locked(cfg_dict)

    def _prepare_phase_locked(self, cfg_dict: dict) -> dict:
        self.teardown_workers()
        logger.clear_error_history()
        version = cfg_dict.get(proto.KEY_PROTOCOL_VERSION)
        if version != HTTP_PROTOCOL_VERSION:
            raise ConfigError(
                f"protocol version mismatch: master={version!r} "
                f"service={HTTP_PROTOCOL_VERSION!r}")
        # master-failover credentials: protocol extras on the config
        # wire, present ONLY when the master armed --svcadoptsecs with a
        # journal (popped before config parsing — they are not fields)
        adopt_token = cfg_dict.pop(proto.KEY_TAKEOVER_TOKEN, "")
        adopt_fingerprint = cfg_dict.pop(proto.KEY_JOURNAL_FINGERPRINT, "")
        # overrides are applied BEFORE derive(): deriving first would
        # probe (open, size-check) the MASTER's paths on this host even
        # when a pinned --path means they are never used here
        cfg = BenchConfig.from_service_dict(cfg_dict, derive=False)
        cfg.run_as_service = True
        cfg.disable_live_stats = True
        # keep OUR listen port, not the master's --port: netbench derives
        # its data port (svc port + 1000) from it
        cfg.service_port = self.base_cfg.service_port
        # service-side overrides: pinned bench paths / TPU ids
        # (reference: ProgArgs.cpp:1366-1382)
        if self.base_cfg.paths:
            cfg.paths = list(self.base_cfg.paths)
        if self.base_cfg.tpu_ids_str:
            cfg.tpu_ids_str = self.base_cfg.tpu_ids_str  # derive() parses
        if cfg.tree_file_path:
            cfg.tree_file_path = self._uploaded_file_path(
                os.path.basename(cfg.tree_file_path))
        if cfg.trace_file_path:
            # one trace file per service host: suffix with the master's
            # rank offset so a shared filesystem can't clobber files
            base, ext = os.path.splitext(cfg.trace_file_path)
            cfg.trace_file_path = f"{base}.r{cfg.rank_offset}{ext}"
            # remember it for the quit/orphan scrub: per-host trace
            # files must not accumulate forever on service hosts
            # (docs/telemetry.md "Fleet tracing" retention note)
            self._trace_files.add(cfg.trace_file_path)
        cfg.derive()
        cfg.check()
        self.cfg = cfg
        self.manager = WorkerManager(cfg)
        self.statistics = Statistics(cfg, self.manager)
        self.manager.prepare_threads()
        # arm the master liveness lease: the master's flag arrived on the
        # config wire (its /preparephase IS the lease advertisement); a
        # service started with its own --svcleasesecs uses that as the
        # default for masters that don't set one
        lease_secs = cfg.svc_lease_secs or self.base_cfg.svc_lease_secs
        self._arm_lease(lease_secs)
        # a fresh /preparephase supersedes any earlier adoption state;
        # grace arms only when the master advertised a takeover token
        # (a service-side --svcadoptsecs default without credentials
        # would leave a host no master could ever claim)
        self._adopt_token = adopt_token
        self._adopt_fingerprint = adopt_fingerprint
        self._awaiting_adoption = False
        grace_secs = cfg.svc_adopt_secs or self.base_cfg.svc_adopt_secs
        self._adopt_grace_secs = grace_secs if adopt_token else 0
        reply = {
            proto.KEY_BENCH_PATH_TYPE: int(cfg.bench_path_type),
            proto.KEY_NUM_BENCH_PATHS: len(cfg.paths),
            "FileSize": cfg.file_size,
            "BlockSize": cfg.block_size,
            "RandomAmount": cfg.random_amount,
            proto.KEY_ERROR_HISTORY: logger.get_error_history(),
        }
        if lease_secs:
            reply[proto.KEY_SVC_LEASE_SECS] = lease_secs
        if self._adopt_grace_secs:
            reply[proto.KEY_SVC_ADOPT_SECS] = self._adopt_grace_secs
        return reply

    # -- master liveness lease (--svcleasesecs) -----------------------------

    def lease_counters(self) -> dict:
        counters = {"SvcLeaseExpiries": self.lease_expiries,
                    "SvcLeaseAgeHwmUsec": self.lease_age_hwm_usec}
        # adoption counters ship ONLY when nonzero: a run without the
        # failover flags keeps byte-identical wire replies
        if self.svc_adoptions:
            counters[proto.KEY_SVC_ADOPTIONS] = self.svc_adoptions
        if self.svc_adopt_wait_usec:
            counters[proto.KEY_SVC_ADOPT_WAIT] = self.svc_adopt_wait_usec
        return counters

    def note_master_contact(self) -> None:
        """A master request arriving AFTER a /benchresult that attached
        the span ring proves that reply was received and processed —
        promote the pending ship so the quit/orphan scrub may treat the
        local ring file as a duplicate."""
        if self._trace_ship_pending:
            self._trace_shipped.add(self._trace_ship_pending)
            self._trace_ship_pending = ""

    def touch_lease(self) -> None:
        """Every authorized master request renews the lease (the /status
        poll cadence is the natural heartbeat). Also tracks the largest
        gap between contacts as a high-water mark, so a lease that came
        CLOSE to expiring is visible even without an expiry."""
        now = time.monotonic()
        if self._lease_secs:
            age_usec = int((now - self._lease_last_contact) * 1e6)
            if age_usec > self.lease_age_hwm_usec:
                self.lease_age_hwm_usec = age_usec
        self._lease_last_contact = now

    def release_lease(self) -> None:
        """Disarm without orphan recovery: the master deliberately let go
        (/interruptphase at run end / teardown), which must not count as
        a crashed master."""
        self._lease_secs = 0

    def adopt(self, params: dict) -> "tuple[int, dict]":
        """Master-failover takeover handshake (/adopt): a new master
        claims this host's in-flight run. Validated against the
        credentials the DEAD master advertised at /preparephase — bench
        UUID, takeover token, and journal fingerprint all come from its
        journal, so only a master resuming the very same journal can
        adopt. Runs under route_lock (handler) plus the teardown lock
        (the lease watchdog contends for the awaiting state). Legal
        even before lease expiry: a warm standby may beat the grace
        window."""
        with self._teardown_lock:
            manager = self.manager
            if manager is None:
                return (409, {"Error": "nothing to adopt: no worker pool"
                                       " (orphan recovery already ran?)"})
            if not self._adopt_token:
                return (403, {"Error": "host holds no takeover "
                                       "credentials (--svcadoptsecs was "
                                       "not armed at /preparephase)"})
            if params.get(proto.KEY_TAKEOVER_TOKEN, "") != \
                    self._adopt_token:
                return (403, {"Error": "takeover token mismatch (stale "
                                       "token from an older run?)"})
            fingerprint = params.get(proto.KEY_JOURNAL_FINGERPRINT, "")
            if self._adopt_fingerprint \
                    and fingerprint != self._adopt_fingerprint:
                return (403, {"Error": "journal fingerprint mismatch: "
                                       "the adopter resumed a different "
                                       "journal than the dead master's"})
            shared = manager.shared
            bench_id = params.get(proto.KEY_BENCH_ID, "")
            if shared.bench_uuid and bench_id != shared.bench_uuid:
                return (409, {"Error": "bench UUID mismatch: this host "
                                       "runs a different phase than the "
                                       "adopter's journal describes"})
            self.svc_adoptions += 1
            if self._awaiting_adoption:
                wait_usec = int(
                    (time.monotonic() - self._adopt_wait_started) * 1e6)
                if wait_usec > self.svc_adopt_wait_usec:
                    self.svc_adopt_wait_usec = wait_usec
                self._awaiting_adoption = False
            # any pending span-ring ship went to the DEAD master: drop
            # the mark WITHOUT promoting it, so the scrub keeps treating
            # the local ring file as the only copy
            self._trace_ship_pending = ""
            cfg = self.cfg
            lease_secs = cfg.svc_lease_secs or self.base_cfg.svc_lease_secs
            self._arm_lease(lease_secs)
            reply = {
                proto.KEY_BENCH_PATH_TYPE: int(cfg.bench_path_type),
                proto.KEY_NUM_BENCH_PATHS: len(cfg.paths),
                "FileSize": cfg.file_size,
                "BlockSize": cfg.block_size,
                "RandomAmount": cfg.random_amount,
                proto.KEY_BENCH_ID: shared.bench_uuid,
                proto.KEY_PHASE_CODE: int(shared.current_phase),
                proto.KEY_NUM_WORKERS_DONE: shared.num_workers_done,
                proto.KEY_ERROR_HISTORY: logger.get_error_history(),
            }
            if lease_secs:
                reply[proto.KEY_SVC_LEASE_SECS] = lease_secs
            if self._adopt_grace_secs:
                reply[proto.KEY_SVC_ADOPT_SECS] = self._adopt_grace_secs
            return (200, reply)

    def cheap_live_signature(self) -> tuple:
        """Completion-relevant snapshot for the stream session's tick
        loop: plain attribute reads (GIL-safe like every live counter),
        no stats walk, no JSON — cheap enough for dozens of concurrent
        25ms tickers."""
        manager = self.manager
        if manager is None:
            return (None,)
        shared = manager.shared
        return (shared.bench_uuid, int(shared.current_phase),
                shared.num_workers_done,
                shared.num_workers_done_with_error)

    def stream_pushed(self, bench_id: str) -> None:
        """Route-aware lease renewal for the streaming plane: a pushed
        frame renews the lease ONLY when the stream was opened with the
        run's CURRENT bench UUID — the stream analogue of the /status
        rule (observer streams can never keep an orphaned service alive,
        and a stream that dies mid-phase stops renewing, so orphan
        recovery still fires)."""
        manager = self.manager
        uuid = manager.shared.bench_uuid if manager is not None else ""
        if bench_id and uuid and bench_id == uuid:
            self.touch_lease()

    def _arm_lease(self, lease_secs: int) -> None:
        self._lease_last_contact = time.monotonic()
        self._lease_secs = max(lease_secs, 0)
        if not self._lease_secs:
            return
        if self._lease_thread is None or not self._lease_thread.is_alive():
            self._lease_stop.clear()
            self._lease_thread = threading.Thread(
                target=self._lease_watch_loop, name="svc-lease-watchdog",
                daemon=True)
            self._lease_thread.start()

    def _lease_watch_loop(self) -> None:
        while not self._lease_stop.wait(0.2):
            with self._teardown_lock:
                secs = self._lease_secs
                if not secs or self.manager is None:
                    continue
                if self._awaiting_adoption:
                    # adoption grace (--svcadoptsecs): workers stay
                    # alive and nothing is scrubbed — a takeover
                    # master's /adopt clears this state; expiry falls
                    # through to the unchanged orphan recovery
                    wait = time.monotonic() - self._adopt_wait_started
                    if wait < self._adopt_grace_secs:
                        continue
                    self._awaiting_adoption = False
                    wait_usec = int(wait * 1e6)
                    if wait_usec > self.svc_adopt_wait_usec:
                        self.svc_adopt_wait_usec = wait_usec
                    logger.log_error(
                        f"adoption grace expired: no master adopted "
                        f"this host within --svcadoptsecs "
                        f"{self._adopt_grace_secs}s; falling back to "
                        f"orphan recovery")
                    self._orphan_recover(
                        time.monotonic() - self._lease_last_contact, secs)
                    continue
                # the expiry clock runs only while a phase is ACTIVE on
                # this host: once our workers finished (or before the
                # first /startphase) the master legitimately goes silent
                # here — it is polling the straggler hosts, sleeping
                # --phasedelay, or printing results — and an idle-at-
                # barrier pool is not the storage-hammering hazard the
                # lease exists to stop (a new master's /preparephase
                # rebuilds it anyway)
                shared = self.manager.shared
                busy = shared.current_phase not in (
                    BenchPhase.IDLE, BenchPhase.TERMINATE) \
                    and not self.manager.all_workers_done()
                if not busy:
                    self._lease_last_contact = time.monotonic()
                    continue
                age = time.monotonic() - self._lease_last_contact
                if age < secs:
                    continue
                if self._adopt_grace_secs and self._adopt_token:
                    self._awaiting_adoption = True
                    self._adopt_wait_started = time.monotonic()
                    logger.log_error(
                        f"AWAITING ADOPTION — master lease expired (no "
                        f"master contact for {age:.1f}s, --svcleasesecs "
                        f"{secs}); keeping workers and run state alive "
                        f"for --svcadoptsecs {self._adopt_grace_secs}s "
                        f"so a takeover master may /adopt this host")
                    continue
                self._orphan_recover(age, secs)

    def _orphan_recover(self, age: float, secs: int) -> None:
        """Lease expired with a worker pool alive: the master is gone.
        Interrupt the workers, drop the pool, clear the bench UUID, and
        return to idle — the host is immediately reusable instead of
        hammering storage until someone notices. Called under the
        teardown lock (watchdog thread)."""
        self.lease_expiries += 1
        age_usec = int(age * 1e6)
        if age_usec > self.lease_age_hwm_usec:
            self.lease_age_hwm_usec = age_usec
        self._lease_secs = 0  # disarm until the next /preparephase
        logger.log_error(
            f"ORPHANED — master lease expired: no master contact for "
            f"{age:.1f}s (--svcleasesecs {secs}); interrupting workers "
            f"and returning to idle")
        shared = self.manager.shared
        self.interrupt()
        self.teardown_workers()
        shared.clear_bench_uuid()
        self._cleanup_run_temp_files()

    def _cleanup_run_temp_files(self) -> None:
        """Drop this service's per-run upload dir (treefiles etc.) AND
        the per-host ``.r<rankoffset>`` trace files it wrote, so an
        orphaned/quit service leaves no stale per-host temp state behind;
        the next master re-uploads its prep files at /preparefile (and
        re-arms tracing per /preparephase). The master's COLLECTED
        copies — the fleet-trace inputs — live on the master and are
        untouched by this."""
        if self._awaiting_adoption:
            # a takeover master may still claim this run: its uploaded
            # prep files, per-host trace rings, and slow-op state must
            # survive the grace window (the scrub re-runs on grace
            # expiry via orphan recovery, or at the adopted run's end)
            return
        d = os.path.join(SVC_TMP_DIR,
                         f"elbencho_tpu_{getpass.getuser()}"
                         f"_p{self.base_cfg.service_port}")
        shutil.rmtree(d, ignore_errors=True)
        trace_files, self._trace_files = self._trace_files, set()
        shipped, self._trace_shipped = self._trace_shipped, set()
        for path in trace_files & shipped:
            # the master holds a collected copy — the local ring is a
            # duplicate and must not accumulate. Never-shipped files
            # (ring refused over --traceshipcap, master crashed before
            # collection, --tracefleet off) are the only copy and stay.
            try:
                os.unlink(path)
            except OSError:
                pass  # never written (tracing armed but no phase ran)

    def close(self) -> None:
        """Service shutdown: stop the lease watchdog, end every live
        stream session, drop the pool."""
        self.stream_shutdown.set()
        self._lease_stop.set()
        if self._lease_thread is not None:
            self._lease_thread.join(timeout=5)
            self._lease_thread = None
        self.teardown_workers()

    def _uploaded_file_path(self, name: str) -> str:
        d = os.path.join(SVC_TMP_DIR,
                         f"elbencho_tpu_{getpass.getuser()}"
                         f"_p{self.base_cfg.service_port}")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, name)

    def start_phase(self, phase_code: int, bench_id: str) -> "tuple[int, str]":
        """(http_status, message). Duplicate BenchID is idempotent success
        (reference: :534-578)."""
        if self.manager is None:
            return (400, "no /preparephase received yet")
        shared = self.manager.shared
        if bench_id and shared.bench_uuid == bench_id:
            return (200, "phase already running (duplicate start)")
        if not self.manager.all_workers_done() and \
                shared.current_phase not in (BenchPhase.IDLE,
                                             BenchPhase.TERMINATE):
            return (409, "workers still busy with another phase")
        # a new phase records new spans the last collection cannot have
        # covered: the local ring file is no longer a duplicate
        self._trace_shipped.clear()
        self._trace_ship_pending = ""
        phase = BenchPhase(phase_code)
        self.phase_start_monotonic = time.monotonic()
        self.manager.start_next_phase(phase)
        if bench_id:
            shared.adopt_bench_uuid(bench_id)  # master's UUID wins
        return (200, "phase started")

    def status(self) -> dict:
        # snapshot once: the lease watchdog may null these concurrently
        statistics, manager, cfg = self.statistics, self.manager, self.cfg
        if statistics is None:
            return {proto.KEY_PHASE_CODE: int(BenchPhase.IDLE),
                    proto.KEY_NUM_WORKERS_DONE: 0,
                    **self.lease_counters()}
        if manager is not None and cfg is not None:
            manager.check_phase_time_limit(self.phase_start_monotonic)
        stats = statistics.get_live_stats_dict()
        stats.update(self.lease_counters())
        if self._awaiting_adoption:
            # present ONLY during the grace window — the standby's (and
            # any observer's) takeover trigger; absent otherwise so
            # flags-off status replies stay byte-identical
            stats[proto.KEY_AWAITING_ADOPTION] = 1
        return stats

    def bench_result(self, params: "dict | None" = None) -> dict:
        from ..telemetry.tracefleet import svc_wall_clock_usec
        params = params or {}
        statistics, manager = self.statistics, self.manager
        if statistics is None:
            reply = self.lease_counters()
            reply[proto.KEY_SVC_CLOCK] = svc_wall_clock_usec(
                self.base_cfg.service_port)
            return reply
        result = statistics.get_bench_result_dict()
        result[proto.KEY_ERROR_HISTORY] = logger.get_error_history()
        result.update(self.lease_counters())
        result[proto.KEY_SVC_CLOCK] = svc_wall_clock_usec(
            self.base_cfg.service_port)
        tracer = manager.shared.tracer if manager else None
        if tracer is not None:
            try:  # phase is over: persist the span ring for Perfetto
                tracer.write()
            except OSError as err:
                logger.log_error(f"--tracefile write failed: {err}")
        if tracer is not None and params.get(proto.KEY_SHIP_TRACE):
            self._attach_trace_ring(result, tracer)
        if params.get(proto.KEY_SHIP_SLOWOPS) and manager is not None:
            self._attach_slowops(result, manager)
        return result

    #: reply key carrying the PRE-SERIALIZED span ring from bench_result
    #: to the handler, which splices it into the reply body — the ring
    #: (up to --traceshipcap MiB) is serialized exactly once, and never
    #: a second time inside the reply's own json.dumps under route_lock
    TRACE_RING_JSON_KEY = "_TraceRingJson"

    def _attach_trace_ring(self, result: dict, tracer) -> None:
        """Fleet tracing: attach this host's span ring to the
        /benchresult reply so the master can merge it — unless it
        exceeds --traceshipcap, in which case the refusal is LOUD on
        both ends but never fails the result exchange (the run's
        numbers outrank its telemetry)."""
        import json as json_mod
        cap_mib = getattr(self.cfg, "trace_ship_cap_mib", 16)
        ring = {
            "traceEvents": tracer.snapshot_events(),
            "otherData": {
                "rankOffset": tracer.rank_offset,
                "wallAnchorUsec": tracer.wall_anchor_usec,
                "sample": tracer.sample,
                "numRecorded": tracer.num_recorded,
                "numDropped": tracer.num_dropped,
                **tracer.extra_other_data,
            },
        }
        ring_json = json_mod.dumps(ring, separators=(",", ":"))
        if len(ring_json) > cap_mib << 20:
            logger.log_error(
                f"fleet trace: NOT shipping this host's span ring — "
                f"{len(ring_json) >> 20} MiB serialized exceeds "
                f"--traceshipcap {cap_mib} MiB; the local file "
                f"{getattr(self.cfg, 'trace_file_path', '')!r} keeps "
                f"the spans, the merged fleet trace will miss this lane "
                f"(raise --traceshipcap or lower --tracesample)")
            result[proto.KEY_TRACE_RING_REFUSED] = {
                "Events": len(ring["traceEvents"]),
                "Bytes": len(ring_json), "CapMiB": cap_mib}
            return
        result[self.TRACE_RING_JSON_KEY] = ring_json
        # PENDING until the master's next contact proves the reply
        # landed (note_master_contact); a master that dies mid-response
        # must not cost the only copy of these spans
        self._trace_ship_pending = getattr(self.cfg,
                                           "trace_file_path", "")

    #: reply key carrying the PRE-SERIALIZED slow-op capture, spliced
    #: into the reply body like the span ring (serialized exactly once)
    SLOWOPS_JSON_KEY = "_SlowOpsJson"

    def _attach_slowops(self, result: dict, manager) -> None:
        """Slow-op forensics: merge this host's per-worker captures and
        attach them to the /benchresult reply. The density sample is
        thinned to the merged-lane cap BEFORE shipping (the master
        decimates each host's lane to MERGED_LANE_CAP anyway, so extra
        points would only be serialized to be discarded on arrival) and
        still enforced against --traceshipcap like the span ring — an
        over-cap capture is refused LOUDLY on both ends, never fatally
        (the run's numbers outrank its telemetry)."""
        import json as json_mod
        from ..telemetry.slowops import merge_snapshots, thin_points
        parts = [w._slowops.snapshot() for w in manager.workers
                 if getattr(w, "_slowops", None) is not None]
        if not parts:
            return
        merged = merge_snapshots(parts,
                                 getattr(self.cfg, "slow_ops_k", 0))
        merged["Sample"] = thin_points(merged["Sample"])
        cap_mib = getattr(self.cfg, "trace_ship_cap_mib", 16)
        merged_json = json_mod.dumps(merged, separators=(",", ":"))
        if len(merged_json) > cap_mib << 20:
            logger.log_error(
                f"slow-op forensics: NOT shipping this host's capture — "
                f"{len(merged_json) >> 20} MiB serialized exceeds "
                f"--traceshipcap {cap_mib} MiB; lower "
                f"--slowops/--opsample or raise the cap (the merged "
                f"TailAnalysis will miss this host)")
            result[proto.KEY_SLOWOPS_REFUSED] = {
                "Records": len(merged.get("Records", [])),
                "Bytes": len(merged_json), "CapMiB": cap_mib}
            return
        result[self.SLOWOPS_JSON_KEY] = merged_json

    def metrics(self) -> str:
        """Prometheus text rendering of this service's live state."""
        return self._telemetry.render()

    def interrupt(self) -> None:
        """Concurrency-safe with the lease watchdog's teardown: reads the
        manager once under the lock; the manager calls themselves are
        flag-sets + notifies, safe against a concurrent join."""
        with self._teardown_lock:
            manager = self.manager
        if manager is not None:
            manager.shared.request_interrupt()
            manager.interrupt_and_notify_workers()


def _make_handler(state: ServiceState, server_holder: dict):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # the server is single-threaded by design (no concurrent worker-
        # pool mutation); a keep-alive client that parks its connection
        # between requests (Prometheus scrapers on /metrics do) would
        # otherwise block the whole control plane inside readline() —
        # time the idle connection out instead (handle_one_request turns
        # socket.timeout into close_connection)
        timeout = 5

        def log_message(self, fmt, *args):  # quiet by default
            logger.log(logger.LOG_DEBUG, "HTTP " + fmt % args)

        # -- helpers -------------------------------------------------------

        def _reply(self, code: int, body, content_type="application/json"):
            data = (json.dumps(body) if not isinstance(body, (bytes, str))
                    else body)
            if isinstance(data, str):
                data = data.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _params(self) -> dict:
            query = urllib.parse.urlparse(self.path).query
            return {k: v[0] for k, v in
                    urllib.parse.parse_qs(query).items()}

        def _check_auth(self, params: dict) -> bool:
            if not state.pw_hash:
                return True
            if params.get(proto.KEY_AUTHORIZATION) == state.pw_hash:
                return True
            self._reply(401, {"Error": "authorization required"})
            return False

        #: routes whose mere use proves the owning master is alive;
        #: /status needs the run's bench UUID (observers don't have it)
        #: and /metrics + info/version probes never renew
        _LEASE_RENEWING_ROUTES = frozenset({
            proto.PATH_PREPARE_PHASE, proto.PATH_PREPARE_FILE,
            proto.PATH_START_PHASE, proto.PATH_BENCH_RESULT,
        })

        def _touch_lease_for(self, route: str, params: dict) -> None:
            """Master-liveness lease renewal (--svcleasesecs), route-aware:
            an observer polling /status (dashboard, readiness probe) must
            NOT keep an orphaned service alive — only the owning master's
            polls, marked with the current bench UUID, count."""
            if route in self._LEASE_RENEWING_ROUTES:
                state.touch_lease()
                # ...and proves any pending /benchresult reply (the one
                # carrying the span ring) was received: promote the ship
                state.note_master_contact()
                return
            if route == proto.PATH_STATUS:
                bench_id = params.get(proto.KEY_BENCH_ID, "")
                manager = state.manager
                uuid = manager.shared.bench_uuid \
                    if manager is not None else ""
                if bench_id and uuid and bench_id == uuid:
                    state.touch_lease()
                    state.note_master_contact()

        # -- GET endpoints ---------------------------------------------------

        def do_GET(self):  # noqa: N802 (http.server API)
            params = self._params()
            route = urllib.parse.urlparse(self.path).path
            if not self._check_auth(params):
                return
            if route == proto.PATH_LIVE_STREAM:
                # the server-push stream session (--svcstream) blocks for
                # the connection's lifetime and only READS benchmark
                # state — it runs beside the lock-serialized routes; its
                # lease renewal is per-push (ServiceState.stream_pushed)
                from .stream import StreamSession
                try:
                    StreamSession(state, self, params,
                                  state.base_cfg.service_port).serve()
                except Exception as err:  # noqa: BLE001 - log, drop conn
                    logger.log_error(f"live stream session failed: {err}")
                return
            if route == proto.PATH_INTERRUPT_PHASE:
                # O(fanout) teardown: forward to this node's subtree
                # children FIRST (bounded, best-effort, read-only on
                # state) so a --quit that shuts us down cannot strand
                # the tree below us — and BEFORE taking route_lock:
                # holding the route lock across outbound child requests
                # would stall every control route for up to the forward
                # join deadline (the lock-order detector's
                # route_lock-across-RPC rule, testing/lockgraph.py)
                from .stream import forward_interrupt
                forward_interrupt(state, params)
            with state.route_lock:
                self._do_get_locked(route, params)

        def _record_handle_span(self, route, params, t0_ns) -> None:
            # fleet tracing: handling span + flow-finish for a request
            # stamped with a ParentSpan flow id (shared helper, also
            # used by the /livestream open)
            from ..telemetry.tracefleet import record_handle_span
            record_handle_span(state.manager, route, params, t0_ns)

        def _do_get_locked(self, route, params):
            self._touch_lease_for(route, params)
            t0_ns = time.perf_counter_ns()
            recorded_early = False
            if route == proto.PATH_BENCH_RESULT:
                # record the handling span BEFORE bench_result snapshots
                # and ships the span ring, or the /benchresult
                # flow-finish would land strictly after the shipped
                # snapshot and the master's rpc:/benchresult arrow would
                # dangle in every merged fleet trace (the span is a
                # handling-start marker, not a duration)
                self._record_handle_span(route, params, t0_ns)
                recorded_early = True
            try:
                if route == proto.PATH_INFO:
                    self._reply(200, {
                        "Service": "elbencho-tpu", "Version": __version__,
                        proto.KEY_PROTOCOL_VERSION: HTTP_PROTOCOL_VERSION})
                elif route == proto.PATH_PROTOCOL_VERSION:
                    self._reply(200, HTTP_PROTOCOL_VERSION,
                                content_type="text/plain")
                elif route == proto.PATH_STATUS:
                    from ..telemetry.tracefleet import svc_wall_clock_usec
                    stats = state.status()
                    # clock stamp for the master's skew estimator — at
                    # the handler layer, NOT in status(): stream frames
                    # reuse status() and must not carry (or worse,
                    # subtree-sum) a per-tick clock value
                    stats[proto.KEY_SVC_CLOCK] = svc_wall_clock_usec(
                        state.base_cfg.service_port)
                    self._reply(200, stats)
                elif route == proto.PATH_METRICS:
                    from ..telemetry.registry import PROMETHEUS_CONTENT_TYPE
                    self._reply(200, state.metrics(),
                                content_type=PROMETHEUS_CONTENT_TYPE)
                elif route == proto.PATH_BENCH_RESULT:
                    result = state.bench_result(params)
                    # splice the pre-serialized payloads in, so the
                    # multi-MiB span ring / slow-op capture are never
                    # dumps'd a second time under route_lock
                    splices = []
                    ring_json = result.pop(
                        ServiceState.TRACE_RING_JSON_KEY, None)
                    if ring_json is not None:
                        splices.append(
                            f'"{proto.KEY_TRACE_RING}":' + ring_json)
                    slowops_json = result.pop(
                        ServiceState.SLOWOPS_JSON_KEY, None)
                    if slowops_json is not None:
                        splices.append(
                            f'"{proto.KEY_SLOWOPS}":' + slowops_json)
                    if not splices:
                        self._reply(200, result)
                    else:
                        body = json.dumps(result)
                        body = (body[:-1] + "," if body != "{}"
                                else "{") + ",".join(splices) + "}"
                        self._reply(200, body)
                elif route == proto.PATH_ADOPT:
                    code, reply = state.adopt(params)
                    self._reply(code, reply)
                elif route == proto.PATH_START_PHASE:
                    code, msg = state.start_phase(
                        int(params.get(proto.KEY_PHASE_CODE, 0)),
                        params.get(proto.KEY_BENCH_ID, ""))
                    self._reply(code, {"Message": msg})
                elif route == proto.PATH_INTERRUPT_PHASE:
                    # (subtree forwarding already happened in do_GET,
                    # outside route_lock)
                    # a deliberate interrupt is the master LETTING GO —
                    # never an expiry, so disarm before the workers stop
                    # (and it proves the master processed the last
                    # /benchresult, ring included)
                    state.note_master_contact()
                    state.release_lease()
                    state.interrupt()
                    quit_requested = proto.KEY_INTERRUPT_QUIT in params
                    self._reply(200, {"Message": "interrupted"})
                    if quit_requested:
                        state.teardown_workers()
                        state._cleanup_run_temp_files()
                        server_holder["shutdown"] = True
                else:
                    self._reply(404, {"Error": f"unknown path {route}"})
            except Exception as err:  # noqa: BLE001 - reply errors over HTTP
                logger.log_error(f"service request failed: {err}")
                self._reply(500, {"Error": str(err)})
            if not recorded_early:
                self._record_handle_span(route, params, t0_ns)

        # -- POST endpoints --------------------------------------------------

        def do_POST(self):  # noqa: N802
            params = self._params()
            route = urllib.parse.urlparse(self.path).path
            if not self._check_auth(params):
                return
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length else b""
            with state.route_lock:
                self._do_post_locked(route, params, body)

        def _do_post_locked(self, route, params, body):
            self._touch_lease_for(route, params)
            t0_ns = time.perf_counter_ns()
            try:
                if route == proto.PATH_PREPARE_PHASE:
                    reply = state.prepare_phase(json.loads(body))
                    self._reply(200, reply)
                elif route == proto.PATH_PREPARE_FILE:
                    name = os.path.basename(
                        params.get(proto.KEY_FILE_NAME, "upload"))
                    dst = state._uploaded_file_path(name)
                    with open(dst, "wb") as f:
                        f.write(body)
                    self._reply(200, {"Message": f"stored {name}"})
                else:
                    self._reply(404, {"Error": f"unknown path {route}"})
            except (ConfigError, ValueError) as err:
                logger.log_error(f"prepare failed: {err}")
                self._reply(400, {
                    "Error": str(err),
                    proto.KEY_ERROR_HISTORY: logger.get_error_history()})
            except Exception as err:  # noqa: BLE001
                logger.log_error(f"service request failed: {err}")
                self._reply(500, {
                    "Error": str(err),
                    proto.KEY_ERROR_HISTORY: logger.get_error_history()})
            self._record_handle_span(route, params, t0_ns)

    return Handler


def create_service_server(cfg: BenchConfig, bind_host: str = "0.0.0.0"
                          ) -> "tuple[ThreadingHTTPServer, ServiceState, dict]":
    """Build the (server, state, shutdown-holder) triple one service
    instance runs on. Shared by HTTPService.start and the in-process
    fleet harness (testing/service_harness.in_process_services) that the
    scale suite spins 64+ of inside one test process. Threaded so stream
    sessions cannot block the request routes; daemon threads so a live
    stream can never hang shutdown."""
    state = ServiceState(cfg)
    holder = {"shutdown": False}
    handler = _make_handler(state, holder)
    server = ThreadingHTTPServer((bind_host, cfg.service_port), handler)
    server.daemon_threads = True
    server.timeout = 0.5
    return server, state, holder


class HTTPService:
    """Service-role entry (reference: Coordinator::main :42-62 +
    HTTPService::startServer)."""

    def __init__(self, cfg: BenchConfig):
        self.cfg = cfg

    def start(self) -> int:
        cfg = self.cfg
        logger.enable_error_history(True)
        if not cfg.run_service_in_foreground:
            self._daemonize()
        try:
            server, state, holder = create_service_server(cfg)
        except OSError as err:
            print(f"ERROR: cannot bind service port {cfg.service_port}: "
                  f"{err}", file=sys.stderr)
            return 1
        self._install_signal_handlers(state, holder)
        logger.log(0, f"elbencho-tpu service listening on port "
                      f"{cfg.service_port}")
        try:
            while not holder["shutdown"]:
                server.handle_request()  # single-threaded by design
        except KeyboardInterrupt:
            pass
        finally:
            # deliberate exit: release the lease (never an expiry) and
            # scrub temp state like --quit does — the scrub itself
            # spares a host parked in the awaiting-adoption state
            state.release_lease()
            state.close()  # lease watchdog + worker pool
            state._cleanup_run_temp_files()
            server.server_close()
        return 0

    @staticmethod
    def _install_signal_handlers(state: ServiceState, holder: dict) -> None:
        """Two-stage SIGTERM/SIGINT for the service role (the service
        analogue of the coordinator's master-side handler): the FIRST
        signal requests a graceful exit — finish the in-flight request,
        release the lease deliberately so the shutdown never counts as a
        crashed master, scrub temp state, exit 0. A SECOND signal
        restores the default disposition and re-raises it, so a wedged
        teardown can always be killed the hard way."""
        import signal

        def _handle(signum, _frame):
            if holder.get("signal_seen"):
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)
                return
            holder["signal_seen"] = True
            holder["shutdown"] = True
            state.release_lease()
            logger.log(0, "service: shutdown signal received — finishing "
                          "in-flight request, then exiting (signal again "
                          "to force-kill)")

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, _handle)
            except ValueError:
                pass  # not the main thread (embedded/test harness use)

    def _daemonize(self) -> None:
        """Double-fork daemonization with logfile + single-instance lock
        (reference: HTTPService::daemonize, HTTPService.cpp:32-110). The
        lock file doubles as a pidfile so a SIGKILL'd instance's leftover
        is detected and reclaimed instead of refusing to start."""
        log_path = os.path.join(
            SVC_TMP_DIR,
            f"elbencho_tpu_{getpass.getuser()}_p{self.cfg.service_port}.log")
        lock_path = log_path + ".lock"
        lock_fd = claim_instance_lock(lock_path)
        if os.fork() > 0:
            os._exit(0)
        os.setsid()
        if os.fork() > 0:
            os._exit(0)
        # record the daemon's FINAL pid (post-double-fork) so the next
        # start can tell a live instance from a dead leftover
        write_lock_pid(lock_fd)
        log_fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                         0o644)
        os.dup2(log_fd, 1)
        os.dup2(log_fd, 2)
        devnull = os.open(os.devnull, os.O_RDONLY)
        os.dup2(devnull, 0)


# ---------------------------------------------------------------------------
# single-instance lock with stale-pid reclaim (satellite of the crash-safe
# run lifecycle: a SIGKILL'd service must not brick its port's lock)
# ---------------------------------------------------------------------------

def pid_alive(pid: int) -> bool:
    """Is the pid a live process we could signal? EPERM means alive but
    foreign — treated as alive (never reclaim someone else's lock)."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def read_lock_pid(lock_fd: int) -> int:
    try:
        os.lseek(lock_fd, 0, os.SEEK_SET)
        data = os.read(lock_fd, 32)
        return int(data.decode().strip() or "0")
    except (OSError, ValueError):
        return 0


def write_lock_pid(lock_fd: int) -> None:
    try:
        os.ftruncate(lock_fd, 0)
        os.lseek(lock_fd, 0, os.SEEK_SET)
        os.write(lock_fd, f"{os.getpid()}\n".encode())
    except OSError:
        pass  # lock still held via flock; the pid is advisory detail


def claim_instance_lock(lock_path: str) -> int:
    """Acquire the single-instance lock, reclaiming a stale leftover.

    The flock is authoritative for liveness (the kernel releases it when
    the holder dies, however it dies); the pid recorded in the file tells
    apart the two ways an acquire can go:

    - flock HELD by someone: a live instance — refuse, naming its pid.
    - flock free but a pid is recorded: the previous instance was
      SIGKILL'd (a clean shutdown has no chance to run either) — log the
      reclaim and start up; refusing here would brick the port until an
      operator deletes the file by hand.
    """
    lock_fd = os.open(lock_path, os.O_RDWR | os.O_CREAT, 0o644)
    try:
        fcntl.flock(lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except BlockingIOError:
        holder = read_lock_pid(lock_fd)
        os.close(lock_fd)
        detail = f" (pid {holder})" if holder else ""
        print(f"ERROR: another service instance{detail} holds {lock_path}",
              file=sys.stderr)
        raise SystemExit(1) from None
    stale = read_lock_pid(lock_fd)
    if stale and stale != os.getpid():
        if pid_alive(stale):
            # flock free but the recorded pid lives: pid reuse after a
            # reboot, or an instance that closed its lock fd — the flock
            # is authoritative, so proceed, but say what happened
            logger.log(0, f"NOTE: service lock {lock_path} recorded live "
                          f"pid {stale} without holding the lock "
                          f"(pid reuse?); proceeding under flock")
        else:
            logger.log_error(
                f"reclaiming stale service lock {lock_path}: previous "
                f"instance (pid {stale}) is dead (SIGKILL'd?)")
    write_lock_pid(lock_fd)
    return lock_fd
