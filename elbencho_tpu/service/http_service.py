"""Service role: HTTP server exposing the 8 control-plane endpoints.

Reference: source/HTTPServiceSWS.{h,cpp} + HTTPService.{h,cpp} — a
deliberately **single-threaded** HTTP server (invariant documented at
HTTPServiceSWS.cpp:130-136: no concurrent mutation of the worker pool),
with endpoints /info /protocolversion /status /benchresult /preparefile
/preparephase /startphase /interruptphase (defineServerResources :137),
daemonization with logfile + instance lock (HTTPService.cpp:32-110),
duplicate /startphase idempotency via bench-UUID compare (:543-554), and
strict protocol-version handshake (:280-293).

The control plane rides DCN between TPU-VM hosts; benchmark traffic never
crosses it (SURVEY.md section 2.3).
"""

from __future__ import annotations

import fcntl
import getpass
import json
import os
import sys
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, HTTPServer

from .. import HTTP_PROTOCOL_VERSION, __version__
from ..config.args import BenchConfig, ConfigError
from ..phases import BenchPhase
from ..stats.statistics import Statistics
from ..toolkits import logger
from ..workers.manager import WorkerManager
from . import protocol as proto

SVC_TMP_DIR = "/var/tmp"


class ServiceState:
    """Mutable service-side state: current config + worker pool + stats.
    Rebuilt on every /preparephase (reference: :376-498 kills and respawns
    the pool so stale workers never leak into the next run)."""

    def __init__(self, base_cfg: BenchConfig):
        self.base_cfg = base_cfg
        self.cfg: "BenchConfig | None" = None
        self.manager: "WorkerManager | None" = None
        self.statistics: "Statistics | None" = None
        self.phase_start_monotonic = 0.0
        self.pw_hash = ""
        if base_cfg.svc_password_file:
            self.pw_hash = proto.read_pw_file(base_cfg.svc_password_file)
        # /metrics piggyback (telemetry subsystem): one sampler for the
        # service lifetime; the provider indirection follows the worker
        # pool across /preparephase rebuilds
        from ..telemetry.registry import BenchTelemetry
        self._telemetry = BenchTelemetry(
            base_cfg, lambda: (self.statistics, self.manager),
            role="service")

    def teardown_workers(self) -> None:
        if self.manager is not None:
            self.manager.interrupt_and_notify_workers()
            try:
                self.manager.join_all_threads()
            except Exception:  # noqa: BLE001 - teardown is best effort
                pass
            self.manager = None
            self.statistics = None

    def prepare_phase(self, cfg_dict: dict) -> dict:
        """Kill+rebuild the worker pool from the master's config JSON;
        reply with bench path info + error history."""
        self.teardown_workers()
        logger.clear_error_history()
        version = cfg_dict.get(proto.KEY_PROTOCOL_VERSION)
        if version != HTTP_PROTOCOL_VERSION:
            raise ConfigError(
                f"protocol version mismatch: master={version!r} "
                f"service={HTTP_PROTOCOL_VERSION!r}")
        # overrides are applied BEFORE derive(): deriving first would
        # probe (open, size-check) the MASTER's paths on this host even
        # when a pinned --path means they are never used here
        cfg = BenchConfig.from_service_dict(cfg_dict, derive=False)
        cfg.run_as_service = True
        cfg.disable_live_stats = True
        # keep OUR listen port, not the master's --port: netbench derives
        # its data port (svc port + 1000) from it
        cfg.service_port = self.base_cfg.service_port
        # service-side overrides: pinned bench paths / TPU ids
        # (reference: ProgArgs.cpp:1366-1382)
        if self.base_cfg.paths:
            cfg.paths = list(self.base_cfg.paths)
        if self.base_cfg.tpu_ids_str:
            cfg.tpu_ids_str = self.base_cfg.tpu_ids_str  # derive() parses
        if cfg.tree_file_path:
            cfg.tree_file_path = self._uploaded_file_path(
                os.path.basename(cfg.tree_file_path))
        if cfg.trace_file_path:
            # one trace file per service host: suffix with the master's
            # rank offset so a shared filesystem can't clobber files
            base, ext = os.path.splitext(cfg.trace_file_path)
            cfg.trace_file_path = f"{base}.r{cfg.rank_offset}{ext}"
        cfg.derive()
        cfg.check()
        self.cfg = cfg
        self.manager = WorkerManager(cfg)
        self.statistics = Statistics(cfg, self.manager)
        self.manager.prepare_threads()
        return {
            proto.KEY_BENCH_PATH_TYPE: int(cfg.bench_path_type),
            proto.KEY_NUM_BENCH_PATHS: len(cfg.paths),
            "FileSize": cfg.file_size,
            "BlockSize": cfg.block_size,
            "RandomAmount": cfg.random_amount,
            proto.KEY_ERROR_HISTORY: logger.get_error_history(),
        }

    def _uploaded_file_path(self, name: str) -> str:
        d = os.path.join(SVC_TMP_DIR,
                         f"elbencho_tpu_{getpass.getuser()}"
                         f"_p{self.base_cfg.service_port}")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, name)

    def start_phase(self, phase_code: int, bench_id: str) -> "tuple[int, str]":
        """(http_status, message). Duplicate BenchID is idempotent success
        (reference: :534-578)."""
        if self.manager is None:
            return (400, "no /preparephase received yet")
        shared = self.manager.shared
        if bench_id and shared.bench_uuid == bench_id:
            return (200, "phase already running (duplicate start)")
        if not self.manager.all_workers_done() and \
                shared.current_phase not in (BenchPhase.IDLE,
                                             BenchPhase.TERMINATE):
            return (409, "workers still busy with another phase")
        phase = BenchPhase(phase_code)
        self.phase_start_monotonic = time.monotonic()
        self.manager.start_next_phase(phase)
        if bench_id:
            shared.bench_uuid = bench_id  # master's UUID wins (hijack check)
        return (200, "phase started")

    def status(self) -> dict:
        if self.statistics is None:
            return {proto.KEY_PHASE_CODE: int(BenchPhase.IDLE),
                    proto.KEY_NUM_WORKERS_DONE: 0}
        if self.manager is not None and self.cfg is not None:
            self.manager.check_phase_time_limit(self.phase_start_monotonic)
        return self.statistics.get_live_stats_dict()

    def bench_result(self) -> dict:
        if self.statistics is None:
            return {}
        result = self.statistics.get_bench_result_dict()
        result[proto.KEY_ERROR_HISTORY] = logger.get_error_history()
        tracer = self.manager.shared.tracer if self.manager else None
        if tracer is not None:
            try:  # phase is over: persist the span ring for Perfetto
                tracer.write()
            except OSError as err:
                logger.log_error(f"--tracefile write failed: {err}")
        return result

    def metrics(self) -> str:
        """Prometheus text rendering of this service's live state."""
        return self._telemetry.render()

    def interrupt(self) -> None:
        if self.manager is not None:
            self.manager.shared.request_interrupt()
            self.manager.interrupt_and_notify_workers()


def _make_handler(state: ServiceState, server_holder: dict):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # the server is single-threaded by design (no concurrent worker-
        # pool mutation); a keep-alive client that parks its connection
        # between requests (Prometheus scrapers on /metrics do) would
        # otherwise block the whole control plane inside readline() —
        # time the idle connection out instead (handle_one_request turns
        # socket.timeout into close_connection)
        timeout = 5

        def log_message(self, fmt, *args):  # quiet by default
            logger.log(logger.LOG_DEBUG, "HTTP " + fmt % args)

        # -- helpers -------------------------------------------------------

        def _reply(self, code: int, body, content_type="application/json"):
            data = (json.dumps(body) if not isinstance(body, (bytes, str))
                    else body)
            if isinstance(data, str):
                data = data.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _params(self) -> dict:
            query = urllib.parse.urlparse(self.path).query
            return {k: v[0] for k, v in
                    urllib.parse.parse_qs(query).items()}

        def _check_auth(self, params: dict) -> bool:
            if not state.pw_hash:
                return True
            if params.get(proto.KEY_AUTHORIZATION) == state.pw_hash:
                return True
            self._reply(401, {"Error": "authorization required"})
            return False

        # -- GET endpoints ---------------------------------------------------

        def do_GET(self):  # noqa: N802 (http.server API)
            params = self._params()
            route = urllib.parse.urlparse(self.path).path
            if not self._check_auth(params):
                return
            try:
                if route == proto.PATH_INFO:
                    self._reply(200, {
                        "Service": "elbencho-tpu", "Version": __version__,
                        proto.KEY_PROTOCOL_VERSION: HTTP_PROTOCOL_VERSION})
                elif route == proto.PATH_PROTOCOL_VERSION:
                    self._reply(200, HTTP_PROTOCOL_VERSION,
                                content_type="text/plain")
                elif route == proto.PATH_STATUS:
                    self._reply(200, state.status())
                elif route == proto.PATH_METRICS:
                    from ..telemetry.registry import PROMETHEUS_CONTENT_TYPE
                    self._reply(200, state.metrics(),
                                content_type=PROMETHEUS_CONTENT_TYPE)
                elif route == proto.PATH_BENCH_RESULT:
                    self._reply(200, state.bench_result())
                elif route == proto.PATH_START_PHASE:
                    code, msg = state.start_phase(
                        int(params.get(proto.KEY_PHASE_CODE, 0)),
                        params.get(proto.KEY_BENCH_ID, ""))
                    self._reply(code, {"Message": msg})
                elif route == proto.PATH_INTERRUPT_PHASE:
                    state.interrupt()
                    quit_requested = proto.KEY_INTERRUPT_QUIT in params
                    self._reply(200, {"Message": "interrupted"})
                    if quit_requested:
                        state.teardown_workers()
                        server_holder["shutdown"] = True
                else:
                    self._reply(404, {"Error": f"unknown path {route}"})
            except Exception as err:  # noqa: BLE001 - reply errors over HTTP
                logger.log_error(f"service request failed: {err}")
                self._reply(500, {"Error": str(err)})

        # -- POST endpoints --------------------------------------------------

        def do_POST(self):  # noqa: N802
            params = self._params()
            route = urllib.parse.urlparse(self.path).path
            if not self._check_auth(params):
                return
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length else b""
            try:
                if route == proto.PATH_PREPARE_PHASE:
                    reply = state.prepare_phase(json.loads(body))
                    self._reply(200, reply)
                elif route == proto.PATH_PREPARE_FILE:
                    name = os.path.basename(
                        params.get(proto.KEY_FILE_NAME, "upload"))
                    dst = state._uploaded_file_path(name)
                    with open(dst, "wb") as f:
                        f.write(body)
                    self._reply(200, {"Message": f"stored {name}"})
                else:
                    self._reply(404, {"Error": f"unknown path {route}"})
            except (ConfigError, ValueError) as err:
                logger.log_error(f"prepare failed: {err}")
                self._reply(400, {
                    "Error": str(err),
                    proto.KEY_ERROR_HISTORY: logger.get_error_history()})
            except Exception as err:  # noqa: BLE001
                logger.log_error(f"service request failed: {err}")
                self._reply(500, {
                    "Error": str(err),
                    proto.KEY_ERROR_HISTORY: logger.get_error_history()})

    return Handler


class HTTPService:
    """Service-role entry (reference: Coordinator::main :42-62 +
    HTTPService::startServer)."""

    def __init__(self, cfg: BenchConfig):
        self.cfg = cfg

    def start(self) -> int:
        cfg = self.cfg
        logger.enable_error_history(True)
        if not cfg.run_service_in_foreground:
            self._daemonize()
        state = ServiceState(cfg)
        holder = {"shutdown": False}
        handler = _make_handler(state, holder)
        try:
            server = HTTPServer(("0.0.0.0", cfg.service_port), handler)
        except OSError as err:
            print(f"ERROR: cannot bind service port {cfg.service_port}: "
                  f"{err}", file=sys.stderr)
            return 1
        server.timeout = 0.5
        logger.log(0, f"elbencho-tpu service listening on port "
                      f"{cfg.service_port}")
        try:
            while not holder["shutdown"]:
                server.handle_request()  # single-threaded by design
        except KeyboardInterrupt:
            pass
        finally:
            state.teardown_workers()
            server.server_close()
        return 0

    def _daemonize(self) -> None:
        """Double-fork daemonization with logfile + single-instance flock
        (reference: HTTPService::daemonize, HTTPService.cpp:32-110)."""
        log_path = os.path.join(
            SVC_TMP_DIR,
            f"elbencho_tpu_{getpass.getuser()}_p{self.cfg.service_port}.log")
        lock_path = log_path + ".lock"
        lock_fd = os.open(lock_path, os.O_WRONLY | os.O_CREAT, 0o644)
        try:
            fcntl.flock(lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except BlockingIOError:
            print(f"ERROR: another service instance holds {lock_path}",
                  file=sys.stderr)
            raise SystemExit(1)
        if os.fork() > 0:
            os._exit(0)
        os.setsid()
        if os.fork() > 0:
            os._exit(0)
        log_fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                         0o644)
        os.dup2(log_fd, 1)
        os.dup2(log_fd, 2)
        devnull = os.open(os.devnull, os.O_RDONLY)
        os.dup2(devnull, 0)
