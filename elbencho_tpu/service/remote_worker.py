"""RemoteWorker: the master's per-host proxy thread.

Reference: source/workers/RemoteWorker.{h,cpp} — one per --hosts entry;
uploads prep files (:288-345), POSTs the serialized config
(preparePhase :354-407), GETs /startphase (:412), polls /status at an
adaptive cadence accumulating remote live ops into its own counters
(:447-560), fetches /benchresult and ingests per-thread elapsed vectors +
mergeable histograms (finishPhase :172-280), sends /interruptphase on
error/quit. Bench-UUID hijack detection: a /status reply with an unexpected
BenchID aborts the run (RemoteWorker.cpp:199-202).

Fault tolerance (service/fault_tolerance.py + docs/fault-tolerance.md):
transient control-plane failures retry with jittered backoff
(--svcretries/--svcretrybudget), a stalled-progress watchdog bounds how
long a silent host can hold a phase (--svcstalledsecs), and with
--svctolerant N the run completes degraded when up to N hosts are lost
mid-run instead of aborting.
"""

from __future__ import annotations

import http.client
import json
import os
import random
import threading
import time
import urllib.parse

from .. import HTTP_PROTOCOL_VERSION
from ..phases import BenchPhase
from ..stats.latency_histogram import LatencyHistogram
from ..toolkits import logger
from ..workers.base import Worker
from ..workers.shared import (WorkerHijackedException,
                              WorkerInterruptedException,
                              WorkerRemoteException,
                              WorkerStalledException)
from . import protocol as proto
from .fault_tolerance import (ConnectFailedError, GarbageReplyError,
                              RetryBudget, RetryPolicy,
                              TRANSIENT_EXCEPTIONS, TRANSIENT_HTTP_STATUSES,
                              is_connect_level_error, is_transient_error)
from .stream import StreamDetachedError, plan_tree

DEFAULT_PORT = 1611
CONNECT_TIMEOUT_SECS = 10
# best-effort /interruptphase sends (teardown path): short and retry-free
# so a dead host can't stall the shutdown of the survivors
INTERRUPT_TIMEOUT_SECS = 3
# adaptive /status cadence: start fast for short phases, back off to the
# configured --svcupint (reference: 25ms -> 500ms, RemoteWorker.cpp:447+)
POLL_MIN_SECS = 0.025
# done-observation granularity of the streaming plane: completion pushes
# ride the change-detection tick (stream.TICK_SECS, 25ms) plus frame
# transit — two ticks bounds it honestly
STREAM_DONE_OBS_QUANTUM_USEC = 50_000


def split_host_port(host: str, default_port: int = DEFAULT_PORT
                    ) -> "tuple[str, int]":
    if ":" in host:
        name, _, port = host.rpartition(":")
        return (name, int(port))
    return (host, default_port)


class ServiceClient:
    """HTTP/JSON client for one service host with transient-failure
    retries (shared idiom with the S3 data plane's retry strategy,
    s3_tk.S3Client.request).

    Streaming-control-plane core (docs/control-plane.md): ONE persistent
    keep-alive connection per host, reused across requests with a
    transparent one-shot reconnect when a parked connection turns out
    stale (the service times idle connections out) — per-request
    connection churn used to cost a TCP handshake per /status tick per
    host. `open_stream` opens the separate long-lived /livestream
    connection. The class-level `open_connections` gauge counts every
    control-plane socket this process believes open; the master samples
    it into the SvcConnHwm audit counter (the O(fanout) proof)."""

    #: open MASTER-side control-plane sockets process-wide (requests +
    #: streams). Interior-node clients (a service's child aggregators,
    #: interrupt forwarding) opt out via gauge=False: their sockets live
    #: on the service hosts and must not pollute the master's SvcConnHwm
    #: — which also keeps the in-process test fleet honest.
    open_connections = 0
    _conn_gauge_lock = threading.Lock()

    def __init__(self, host: str, default_port: int, pw_hash: str = "",
                 retry_policy: "RetryPolicy | None" = None,
                 interrupt_check=None, gauge: bool = True):
        self.hostname, self.port = split_host_port(host, default_port)
        self.pw_hash = pw_hash
        self.retry_policy = retry_policy or RetryPolicy(num_retries=0,
                                                        budget_secs=0.0)
        self.retry_budget = RetryBudget(self.retry_policy.budget_secs)
        self.interrupt_check = interrupt_check
        # deterministic per-host jitter stream (reproducible chaos runs)
        self._rng = random.Random(f"{self.hostname}:{self.port}")
        # the persistent keep-alive connection
        self._conn: "http.client.HTTPConnection | None" = None
        self._gauge = gauge
        # control-plane audit counters (fault_tolerance.py schema)
        self.total_retries = 0
        self.consec_retries = 0
        self.consec_retries_hwm = 0
        self.total_requests = 0  # SvcRequests: HTTP requests actually sent
        self.total_rx_bytes = 0  # SvcCtlBytes: response payload bytes

    def reset_phase_accounting(self) -> None:
        """New phase: fresh retry budget + per-phase counters."""
        self.retry_budget.reset()
        self.total_retries = 0
        self.consec_retries = 0
        self.consec_retries_hwm = 0
        self.total_requests = 0
        self.total_rx_bytes = 0

    def rebind(self, pw_hash: str, retry_policy: "RetryPolicy",
               interrupt_check) -> None:
        """Re-home an adopted client (e.g. one kept warm by the
        wait_for_services_ready probe) onto its RemoteWorker's policy."""
        self.pw_hash = pw_hash
        self.retry_policy = retry_policy
        self.retry_budget = RetryBudget(retry_policy.budget_secs)
        self.interrupt_check = interrupt_check
        self.reset_phase_accounting()

    def _host_label(self) -> str:
        return f"{self.hostname}:{self.port}"

    # -- connection lifecycle ----------------------------------------------

    def _conn_opened(self) -> None:
        if self._gauge:
            with ServiceClient._conn_gauge_lock:
                ServiceClient.open_connections += 1

    def _conn_closed(self) -> None:
        if self._gauge:
            with ServiceClient._conn_gauge_lock:
                ServiceClient.open_connections -= 1

    def _connect(self, timeout: float) -> "http.client.HTTPConnection":
        conn = http.client.HTTPConnection(self.hostname, self.port,
                                          timeout=timeout)
        try:
            conn.connect()
        except OSError as err:
            raise ConnectFailedError(
                f"connect to {self._host_label()} failed: {err}") from err
        self._conn_opened()
        return conn

    def drop_connection(self) -> None:
        """Close the persistent request connection (stream mode parks the
        master between phase-control bursts; holding an idle socket per
        host would defeat the O(fanout) steady state)."""
        conn, self._conn = self._conn, None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
            self._conn_closed()

    def close(self) -> None:
        self.drop_connection()

    def _request(self, method: str, path: str, params: "dict | None" = None,
                 body: "bytes | None" = None,
                 timeout: float = CONNECT_TIMEOUT_SECS,
                 allow_reuse: bool = True):
        """One raw exchange over the persistent connection. A failure to
        even reach the service raises ConnectFailedError so the retry
        layer knows the request was never sent (safe to retry
        non-idempotent requests). A failure on a REUSED connection is
        transparently retried once on a fresh one — the service closes
        idle keep-alive connections, and that stale-socket case must not
        surface as a spurious transient error. Non-idempotent callers
        pass allow_reuse=False: their request always rides a provably
        fresh connection, so the stale-retry ambiguity (was it
        processed?) cannot arise for them."""
        params = dict(params or {})
        if self.pw_hash:
            params[proto.KEY_AUTHORIZATION] = self.pw_hash
        if params:
            path = path + "?" + urllib.parse.urlencode(params)
        if not allow_reuse:
            self.drop_connection()
        for _attempt in (0, 1):
            conn = self._conn
            reused = conn is not None
            if conn is None:
                conn = self._connect(timeout)
                self._conn = conn
            try:
                if reused and conn.sock is not None:
                    # per-request timeout on the reused socket; EBADF
                    # here means the parked socket died — the stale-
                    # retry below handles it like any reuse failure
                    conn.sock.settimeout(timeout)
                conn.request(method, path, body=body)
                resp = conn.getresponse()
                data = resp.read()
            except TRANSIENT_EXCEPTIONS:
                self.drop_connection()
                if reused:
                    continue  # stale keep-alive socket: one fresh retry
                raise
            self.total_requests += 1
            self.total_rx_bytes += len(data)
            if resp.will_close:
                self.drop_connection()
            return resp.status, data
        raise AssertionError("unreachable")  # pragma: no cover

    def open_stream(self, bench_id: str, interval_ms: int, fanout: int = 0,
                    subtree: "list[str] | tuple" = (),
                    read_timeout: float = 10.0, resync: bool = False,
                    trace_params: "dict | None" = None):
        """Open the /livestream server-push connection (--svcstream);
        returns a stream.StreamHandle whose rtt_usec is the open round
        trip (the streaming --svcping source) and whose clock_* fields
        carry the fleet-tracing skew sample (the open ping bracketed in
        local wall clock + the service's X-Svc-Clock-Usec stamp). The
        stream rides its OWN connection — a chunked response would
        monopolize the request one."""
        from .stream import StreamHandle
        params = {proto.KEY_STREAM_INTERVAL_MS: int(interval_ms)}
        if bench_id:
            params[proto.KEY_BENCH_ID] = bench_id
        if fanout:
            params[proto.KEY_STREAM_FANOUT] = int(fanout)
        if subtree:
            params[proto.KEY_STREAM_SUBTREE] = ",".join(subtree)
        if resync:
            params[proto.KEY_STREAM_RESYNC] = 1
        if trace_params:
            params.update(trace_params)
        if self.pw_hash:
            params[proto.KEY_AUTHORIZATION] = self.pw_hash
        path = proto.PATH_LIVE_STREAM + "?" + urllib.parse.urlencode(params)
        t0 = time.monotonic()
        t0_wall = time.time_ns() // 1000
        conn = self._connect(CONNECT_TIMEOUT_SECS)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
        except TRANSIENT_EXCEPTIONS as err:
            conn.close()
            self._conn_closed()
            raise WorkerRemoteException(
                f"live stream open on {self._host_label()} failed: "
                f"{type(err).__name__}: {err}") from err
        rtt_usec = int((time.monotonic() - t0) * 1e6)
        t1_wall = time.time_ns() // 1000
        self.total_requests += 1
        if resp.status != 200:
            try:
                detail = resp.read(512).decode(errors="replace")
            except TRANSIENT_EXCEPTIONS:
                detail = ""
            conn.close()
            self._conn_closed()
            raise WorkerRemoteException(
                f"live stream open on {self._host_label()} failed "
                f"(HTTP {resp.status}): {detail}")
        try:
            svc_clock = int(resp.getheader(proto.HDR_SVC_CLOCK, "") or 0)
        except ValueError:
            svc_clock = 0
        if conn.sock is not None:
            conn.sock.settimeout(read_timeout)
        return StreamHandle(conn, resp, rtt_usec, self._host_label(),
                            on_close=self._conn_closed,
                            clock_t0_usec=t0_wall, clock_t1_usec=t1_wall,
                            svc_clock_usec=svc_clock)

    # -- retrying core ------------------------------------------------------

    def _exchange_retry(self, method: str, path: str,
                        params: "dict | None" = None,
                        body: "bytes | None" = None,
                        timeout: float = CONNECT_TIMEOUT_SECS,
                        idempotent: bool = True,
                        deadline: "float | None" = None,
                        parse_json: bool = True):
        """(status, payload) with transient-error retries.

        Idempotent requests retry on any transient failure including
        retryable HTTP statuses and garbage 200-replies; non-idempotent
        ones only on connect-level failures. Each retry sleeps a jittered
        exponential backoff drawn from the per-phase budget; an optional
        deadline (the stall watchdog) caps the whole exchange. On
        exhaustion the last transient status is returned for the caller's
        contextual error message, while transport errors raise
        WorkerRemoteException with host context.
        """
        policy = self.retry_policy
        attempt = 0
        while True:
            if self.interrupt_check is not None:
                self.interrupt_check()
            err: "BaseException | None" = None
            status, payload = 0, {}
            try:
                # non-idempotent requests always ride a provably fresh
                # connection (no stale-keep-alive ambiguity about whether
                # the service processed them)
                status, data = self._request(method, path, params, body,
                                             timeout=timeout,
                                             allow_reuse=idempotent)
                if parse_json:
                    try:
                        payload = json.loads(data) if data else {}
                    except json.JSONDecodeError:
                        payload = {"raw": data.decode(errors="replace")}
                        if status == 200:
                            # a mangled OK reply is indistinguishable from
                            # line noise — retryable, never trustable
                            err = GarbageReplyError(
                                f"undecodable JSON reply from "
                                f"{self._host_label()}")
                else:
                    payload = data
            except TRANSIENT_EXCEPTIONS as req_err:
                err = req_err
            if err is None and status in TRANSIENT_HTTP_STATUSES \
                    and idempotent:
                err = http.client.HTTPException(
                    f"transient HTTP {status} from {self._host_label()}")
                # keep last payload/status: returned on retry exhaustion
            if err is None:
                self.consec_retries = 0
                return status, payload
            retryable = is_transient_error(err) and (
                idempotent or is_connect_level_error(err))
            delay = policy.backoff_delay(attempt, self._rng)
            if (not retryable) or attempt >= policy.num_retries \
                    or (deadline is not None
                        and time.monotonic() + delay >= deadline) \
                    or not self.retry_budget.try_spend(delay):
                if status in TRANSIENT_HTTP_STATUSES:
                    # the service DID answer; hand the status back so the
                    # caller raises its own contextual error
                    return status, payload
                raise WorkerRemoteException(
                    f"service {self._host_label()}: {method} {path} "
                    f"failed: {type(err).__name__}: {err}") from err
            attempt += 1
            self.total_retries += 1
            self.consec_retries += 1
            self.consec_retries_hwm = max(self.consec_retries_hwm,
                                          self.consec_retries)
            logger.log(logger.LOG_VERBOSE,
                       f"retrying {method} {path} on "
                       f"{self._host_label()} in {delay * 1000:.0f}ms "
                       f"(attempt {attempt}/{policy.num_retries}: "
                       f"{type(err).__name__}: {err})")
            time.sleep(delay)

    # -- public request surface --------------------------------------------

    def get_json(self, path: str, params: "dict | None" = None,
                 timeout: float = CONNECT_TIMEOUT_SECS,
                 idempotent: bool = True,
                 deadline: "float | None" = None) -> "tuple[int, dict]":
        return self._exchange_retry("GET", path, params, timeout=timeout,
                                    idempotent=idempotent,
                                    deadline=deadline)

    def post_json(self, path: str, obj, params: "dict | None" = None,
                  timeout: float = 60.0,
                  idempotent: bool = False) -> "tuple[int, dict]":
        body = json.dumps(obj).encode()
        return self._exchange_retry("POST", path, params, body,
                                    timeout=timeout, idempotent=idempotent)

    def get_raw(self, path: str, params: "dict | None" = None,
                timeout: float = CONNECT_TIMEOUT_SECS
                ) -> "tuple[int, bytes]":
        return self._exchange_retry("GET", path, params, timeout=timeout,
                                    idempotent=True, parse_json=False)

    def post_raw(self, path: str, params: "dict | None", body: bytes,
                 timeout: float = 60.0, idempotent: bool = True
                 ) -> "tuple[int, bytes]":
        return self._exchange_retry("POST", path, params, body,
                                    timeout=timeout, idempotent=idempotent,
                                    parse_json=False)


class RemoteWorker(Worker):
    def __init__(self, shared, host_idx: int, host: str):
        super().__init__(shared, rank=host_idx)
        self.cfg = shared.config
        self.host = host
        self.host_idx = host_idx
        self.last_ping_usec = 0  # --svcping: last /status RTT
        self.cpu_util_pct = 0.0  # last /status CPUUtil (telemetry gauge)
        self.degraded = False    # --svctolerant: host lost mid-run
        # control-plane audit counters (CONTROL_AUDIT_COUNTERS schema);
        # the lease pair mirrors SERVICE-observed values (--svcleasesecs,
        # service-lifetime) ingested from /status + /benchresult
        self.svc_retries = 0
        self.svc_consec_retries_hwm = 0
        self.svc_heartbeat_age_hwm_usec = 0
        self.svc_lease_expiries = 0
        self.svc_lease_age_hwm_usec = 0
        # master failover (--resume --adopt; CONTROL_AUDIT_COUNTERS):
        # MasterTakeovers is master-observed (1 on the phase this worker
        # claimed its host via /adopt); the SvcAdopt pair mirrors
        # SERVICE-observed lifetime values ingested like the lease pair
        self.master_takeovers = 0
        self.svc_adoptions = 0
        self.svc_adopt_wait_usec = 0
        self._took_over = False       # this worker claimed its host
        self._takeover_counted = False
        # streaming control plane audit (--svcstream; master-observed,
        # CONTROL_AUDIT_COUNTERS schema — docs/control-plane.md)
        self.svc_requests = 0
        self.svc_ctl_bytes = 0
        self.svc_stream_frames = 0
        self.svc_stream_bytes = 0
        self.svc_delta_saved_bytes = 0
        self.svc_agg_depth_hwm = 0
        self.svc_conn_hwm = 0
        # fleet straggler attribution (CONTROL_AUDIT_COUNTERS schema):
        # computed by Statistics after the phase barrier from each
        # host's phase_done_monotonic finish stamp
        self.straggler_skew_usec = 0
        self.barrier_wait_usec = 0
        self.phase_done_monotonic = 0.0
        # how coarse the done observation was (usec): poll mode = the
        # poll interval at detection time (ramped, up to --svcupint),
        # stream mode = the push-on-change tick — the doctor scales its
        # straggler-bound floor by it so sampling noise can't fabricate
        # a verdict
        self.done_obs_quantum_usec = 0
        # fleet tracing: per-host clock-offset estimator fed by the
        # exchanges this worker performs anyway (/status polls, the
        # stream open, /benchresult)
        from ..telemetry.tracefleet import (ClockSyncEstimator,
                                            fleet_trace_enabled)
        self.clock_sync = ClockSyncEstimator()
        self._fleet_trace = fleet_trace_enabled(self.cfg)
        # slow-op forensics (--slowops): this proxy never records ops
        # itself — it ingests the snapshot its service ships at
        # /benchresult (the counters arrive via the PATH_AUDIT ingest)
        self._slowops = None
        self.slowops_shipped: "dict | None" = None
        pw_hash = ""
        if self.cfg.svc_password_file:
            pw_hash = proto.read_pw_file(self.cfg.svc_password_file)
        # adopt the persistent client the wait_for_services_ready probe
        # already holds an open connection on, instead of building a
        # throwaway one (duplicated --hosts entries: only the first
        # worker adopts; the rest get fresh clients)
        client = adopt_probed_client(*split_host_port(
            host, self.cfg.service_port))
        if client is not None:
            client.rebind(pw_hash, RetryPolicy.from_config(self.cfg),
                          self.check_interruption_flag_only)
        else:
            client = ServiceClient(
                host, self.cfg.service_port, pw_hash,
                retry_policy=RetryPolicy.from_config(self.cfg),
                interrupt_check=self.check_interruption_flag_only)
        self.client = client
        self.num_remote_threads = self.cfg.num_threads
        self._expected_bench_id = ""

    # ------------------------------------------------------------------

    def reset_stats(self) -> None:
        super().reset_stats()
        # zero EVERY live-ingest mirror, incl. the TPU-context path-audit
        # attrs _ingest_live_telemetry setattr'd last phase (base reset
        # only covers the worker-owned ones): a stale mirror would leak
        # the previous phase's totals into the next phase's first
        # /metrics view and flight-recorder tick
        from ..tpu.device import PATH_AUDIT_COUNTERS
        for _attr, _key, ingest_attr in PATH_AUDIT_COUNTERS:
            setattr(self, ingest_attr, 0)
        self.client.reset_phase_accounting()
        self.svc_retries = 0
        self.svc_consec_retries_hwm = 0
        self.svc_heartbeat_age_hwm_usec = 0
        self.svc_lease_expiries = 0
        self.svc_lease_age_hwm_usec = 0
        self.master_takeovers = 0
        self.svc_adoptions = 0
        self.svc_adopt_wait_usec = 0
        self.svc_requests = 0
        self.svc_ctl_bytes = 0
        self.svc_stream_frames = 0
        self.svc_stream_bytes = 0
        self.svc_delta_saved_bytes = 0
        self.svc_agg_depth_hwm = 0
        self.svc_conn_hwm = 0
        self.straggler_skew_usec = 0
        self.barrier_wait_usec = 0
        self.phase_done_monotonic = 0.0
        self.done_obs_quantum_usec = 0
        self.slowops_shipped = None
        if self.degraded:
            # a lost host stays excluded from all later phase results
            self.got_phase_work = False

    def _sync_control_counters(self) -> None:
        self.svc_retries = self.client.total_retries
        self.svc_consec_retries_hwm = self.client.consec_retries_hwm
        self.svc_requests = self.client.total_requests
        # SvcCtlBytes = every control-plane payload byte this phase:
        # request/poll replies plus live-stream frames
        self.svc_ctl_bytes = self.client.total_rx_bytes \
            + self.svc_stream_bytes

    def run(self) -> None:
        try:
            self._run_phases()
        finally:
            self.client.close()  # drop the persistent connection

    def _run_phases(self) -> None:
        self._check_protocol_version()
        if getattr(self.cfg, "adopt_run", False) \
                and getattr(self.cfg, "takeover_token", ""):
            # --resume --adopt: claim the dead master's live service via
            # /adopt — the pool-rebuilding /preparephase would kill the
            # very in-flight work the takeover exists to preserve
            self._adopt_remote_phase()
        else:
            self._prepare_remote_files()
            self._prepare_phase_remote()
        last_uuid = self.shared.bench_uuid
        self.shared.inc_num_workers_done()  # prep barrier
        while True:
            phase, last_uuid = self.shared.wait_for_phase_change(last_uuid)
            if phase == BenchPhase.TERMINATE:
                self._interrupt_remote(quit_service=False)
                return
            if phase == BenchPhase.IDLE:
                continue
            try:
                self._start_remote_phase(phase, last_uuid)
                self._live_until_done(phase)
                # straggler attribution: stamp when the live wait SAW
                # this host's workers done — before the /benchresult
                # fetch, whose duration (and, with fleet tracing, whose
                # shipped span ring) must not fabricate skew
                self.phase_done_monotonic = time.monotonic()
                self._finish_phase_remote()
                self._sync_control_counters()
                self.shared.inc_num_workers_done()
            except WorkerInterruptedException:
                self._interrupt_remote(quit_service=False)
                self._sync_control_counters()
                self.shared.inc_num_workers_done()
            except WorkerHijackedException as err:
                # bench-UUID hijack stays a hard abort: two masters on one
                # service corrupt BOTH runs, no degraded completion
                logger.log_error(f"Remote worker for {self.host} failed: "
                                 f"{err}")
                self._interrupt_remote(quit_service=False)
                self.shared.inc_num_workers_done_with_error(err)
            except Exception as err:  # noqa: BLE001
                logger.log_error(f"Remote worker for {self.host} failed: "
                                 f"{err}")
                self._interrupt_remote(quit_service=False)
                self._sync_control_counters()
                if self.shared.try_degrade_worker(self, err):
                    logger.log_error(
                        f"service {self.host} lost mid-run; completing "
                        f"phase with survivors (--svctolerant, results "
                        f"marked degraded)")
                    return  # host dropped for the rest of the run
                self.shared.inc_num_workers_done_with_error(err)

    # ------------------------------------------------------------------

    # -- fleet tracing: span-context propagation + clock-skew sampling ------

    def _trace_params(self) -> "tuple[dict | None, int]":
        """(extra request params, flow id) for one traced control-plane
        request: a fleet-unique flow id as ParentSpan plus the run's
        trace id. (None, 0) when fleet tracing is off — the wire stays
        byte-identical then."""
        tracer = self.shared.tracer
        if tracer is None or not self._fleet_trace:
            return None, 0
        from ..telemetry.tracer import next_flow_id
        flow_id = next_flow_id()
        params = {proto.KEY_PARENT_SPAN: flow_id}
        trace_id = tracer.extra_other_data.get("traceId", "")
        if trace_id:
            params[proto.KEY_TRACE_ID] = trace_id
        return params, flow_id

    def _record_rpc_span(self, path: str, flow_id: int, t0_ns: int) -> None:
        """Master half of an RPC edge: the rpc:<path> span (tid = this
        host's index, so each host's control traffic gets its own lane)
        plus the Chrome flow-start event the service's handling span
        finishes."""
        tracer = self.shared.tracer
        if tracer is None or not flow_id:
            return
        dur = max((tracer.now_ns() - t0_ns) // 1000, 1)
        tracer.record_rpc(f"rpc:{path}", t0_ns, dur, rank=self.host_idx,
                          flow_id=flow_id, side="out")

    def _feed_clock_sample(self, t0_wall_usec: int, reply: dict) -> None:
        """NTP-style offset sample from any reply carrying the service's
        SvcClockUsec stamp, bracketed by local wall-clock reads. Always
        fed when the key is present (the stamp is always on the wire) so
        the estimate is warm before anything needs it."""
        peer = reply.get(proto.KEY_SVC_CLOCK, 0) if isinstance(
            reply, dict) else 0
        if peer:
            self.clock_sync.add_sample(t0_wall_usec,
                                       time.time_ns() // 1000, peer)

    def _host_clock_estimate(self) -> "tuple[int, int, bool]":
        """(offset_usec, uncertainty_usec, known) of this host's clock
        relative to the master. Two candidate estimates — the direct
        estimator (for a fanout non-root host its only direct samples
        are /benchresult exchanges, whose RTT the shipped span ring
        inflates) and the aggregation-tree chain (master->root measured
        here, root->host carried in stream frames, built from tight
        stream-open pings) — and the one with the SMALLER uncertainty
        wins: uncertainty ~ rtt/2, so a ring-inflated sample can never
        displace a tight chained one."""
        best: "tuple[int, int] | None" = None
        if self.clock_sync.has_estimate:
            best = (self.clock_sync.offset_usec,
                    self.clock_sync.uncertainty_usec)
        sc = getattr(self.shared, "stream_control", None)
        if sc is not None:
            st = sc.states.get(self.host)
            root_worker = sc.workers_by_host.get(
                sc.root_of.get(self.host, self.host))
            if st is not None and st.has_clock \
                    and root_worker is not None \
                    and root_worker.clock_sync.has_estimate:
                from ..telemetry.tracefleet import chain_offsets
                chained = chain_offsets(
                    root_worker.clock_sync.offset_usec,
                    root_worker.clock_sync.uncertainty_usec,
                    st.clock_off, st.clock_unc)
                if best is None or chained[1] < best[1]:
                    best = chained
        if best is None:
            return 0, 0, False
        return best[0], best[1], True

    def _ingest_slowops(self, result: dict) -> None:
        """Slow-op forensics: keep the snapshot this host's /benchresult
        shipped for the master's TailAnalysis merge. A refusal (capture
        over --traceshipcap) is LOUD, never fatal — the merged block
        then names the missing host in its Refusals list."""
        refused = result.get(proto.KEY_SLOWOPS_REFUSED)
        if refused:
            logger.log_error(
                f"slow-op forensics: {self.host} refused to ship its "
                f"capture ({refused.get('Records', 0)} records, "
                f"{refused.get('Bytes', 0)} bytes > --traceshipcap "
                f"{refused.get('CapMiB', 0)} MiB) — TailAnalysis will "
                f"miss this host")
            self.slowops_shipped = None
            return
        shipped = result.get(proto.KEY_SLOWOPS)
        self.slowops_shipped = shipped if isinstance(shipped, dict) \
            else None

    def _collect_trace_ring(self, result: dict) -> None:
        """Fleet tracing: persist the span ring a /benchresult reply
        shipped as this host's per-host trace file next to the master's
        --tracefile, stamped with the estimated clock offset. A refusal
        (ring over --traceshipcap) and a write failure are LOUD, never
        fatal."""
        refused = result.get(proto.KEY_TRACE_RING_REFUSED)
        if refused:
            logger.log_error(
                f"fleet trace: {self.host} refused to ship its span "
                f"ring ({refused.get('Events', 0)} events, "
                f"{refused.get('Bytes', 0)} bytes > --traceshipcap "
                f"{refused.get('CapMiB', 0)} MiB) — its lane will be "
                f"missing from the merged fleet trace")
            return
        ring = result.get(proto.KEY_TRACE_RING)
        if not isinstance(ring, dict):
            return
        from ..telemetry.tracefleet import write_collected_ring
        tracer = self.shared.tracer
        trace_id = tracer.extra_other_data.get("traceId", "") \
            if tracer is not None else ""
        off, unc, _known = self._host_clock_estimate()
        rank_offset = ring.get("otherData", {}).get(
            "rankOffset",
            self.cfg.rank_offset + self.host_idx * self.cfg.num_threads)
        try:
            write_collected_ring(self.cfg.trace_file_path, rank_offset,
                                 ring, self.host, off, unc, trace_id)
        except OSError as err:
            logger.log_error(
                f"fleet trace: cannot write collected trace for "
                f"{self.host}: {err}")

    def _check_protocol_version(self) -> None:
        status, data = self.client.get_raw(proto.PATH_PROTOCOL_VERSION)
        remote = data.decode(errors="replace").strip().strip('"')
        if status != 200 or remote != HTTP_PROTOCOL_VERSION:
            raise WorkerRemoteException(
                f"service {self.host} protocol version mismatch: "
                f"{remote!r} != {HTTP_PROTOCOL_VERSION!r}")

    def _prepare_remote_files(self) -> None:
        """Upload treefile to the service (reference: :288-345).
        Idempotent: re-uploading simply overwrites the stored file."""
        if not self.cfg.tree_file_path:
            return
        with open(self.cfg.tree_file_path, "rb") as f:
            body = f.read()
        status, data = self.client.post_raw(
            proto.PATH_PREPARE_FILE, {
                proto.KEY_FILE_NAME:
                    os.path.basename(self.cfg.tree_file_path)}, body)
        if status != 200:
            raise WorkerRemoteException(
                f"file upload to {self.host} failed: {data!r}")

    def _prepare_phase_remote(self) -> None:
        """POST the full effective config with this host's rank offset
        (reference: preparePhase :354-407; rank offset = hostIdx * threads,
        ProgArgs.cpp:3921). Non-idempotent (rebuilds the remote worker
        pool): retried on connect-level failures only."""
        cfg_dict = self.cfg.to_service_dict(
            service_rank_offset=self.host_idx * self.cfg.num_threads)
        token = getattr(self.cfg, "takeover_token", "")
        if token:
            # master failover: the takeover credentials ride the config
            # wire as protocol extras, present ONLY when the coordinator
            # armed them (--svcadoptsecs > 0 with a journal) — without
            # them the POST body stays byte-identical
            cfg_dict[proto.KEY_TAKEOVER_TOKEN] = token
            cfg_dict[proto.KEY_JOURNAL_FINGERPRINT] = getattr(
                self.cfg, "journal_fingerprint", "")
        trace_params, flow_id = self._trace_params()
        tracer = self.shared.tracer
        t0_ns = tracer.now_ns() if tracer is not None else 0
        status, reply = self.client.post_json(proto.PATH_PREPARE_PHASE,
                                              cfg_dict,
                                              params=trace_params,
                                              timeout=300.0,
                                              idempotent=False)
        self._record_rpc_span(proto.PATH_PREPARE_PHASE, flow_id, t0_ns)
        self._replay_error_history(reply)
        if status != 200:
            raise WorkerRemoteException(
                f"preparation on {self.host} failed: "
                f"{reply.get('Error', reply)}")
        self.bench_path_info = reply

    def _adopt_remote_phase(self) -> None:
        """Takeover handshake (--resume --adopt): GET /adopt with the
        dead master's journaled credentials — bench UUID, takeover
        token, journal fingerprint — so the service re-arms its lease
        for THIS master and keeps its in-flight phase running. The reply
        doubles as the bench path info a /preparephase would have
        returned. Non-idempotent retry shape: a lost reply must not
        double-count the service's SvcAdoptions."""
        params = {
            proto.KEY_BENCH_ID: getattr(self.cfg, "adopt_bench_uuid", ""),
            proto.KEY_TAKEOVER_TOKEN: self.cfg.takeover_token,
            proto.KEY_JOURNAL_FINGERPRINT:
                getattr(self.cfg, "journal_fingerprint", ""),
        }
        status, reply = self.client.get_json(proto.PATH_ADOPT, params,
                                             idempotent=False)
        self._replay_error_history(reply)
        if status != 200:
            raise WorkerRemoteException(
                f"takeover of {self.host} failed ({status}): "
                f"{reply.get('Error', reply)}")
        self.bench_path_info = reply
        self._ingest_lease_counters(reply)
        self._took_over = True
        logger.log(0, f"adopted {self.host}: service accepted takeover "
                      f"(phase code {reply.get(proto.KEY_PHASE_CODE, 0)}, "
                      f"{reply.get(proto.KEY_NUM_WORKERS_DONE, 0)} "
                      f"worker(s) already done)")

    def _start_remote_phase(self, phase: BenchPhase, bench_id: str) -> None:
        self._expected_bench_id = bench_id
        params = {proto.KEY_PHASE_CODE: int(phase),
                  proto.KEY_BENCH_ID: bench_id}
        trace_params, flow_id = self._trace_params()
        if trace_params:
            params.update(trace_params)
        tracer = self.shared.tracer
        t0_ns = tracer.now_ns() if tracer is not None else 0
        status, reply = self.client.get_json(proto.PATH_START_PHASE,
                                             params, idempotent=False)
        self._record_rpc_span(proto.PATH_START_PHASE, flow_id, t0_ns)
        if status != 200:
            raise WorkerRemoteException(
                f"phase start on {self.host} failed: "
                f"{reply.get('Message', reply)}")
        if self._took_over and not self._takeover_counted:
            # lands exactly once, on the adopted phase: reset_stats
            # zeroed the counter before this worker woke for the phase
            self._takeover_counted = True
            self.master_takeovers = 1
        if getattr(self.shared, "stream_control", None) is not None:
            # streaming mode: live stats ride the stream connection; an
            # idle parked request socket per host would defeat the
            # O(fanout) steady state the tree buys
            self.client.drop_connection()

    # -- live-stats ingestion: streaming plane with polling fallback --------

    def _live_until_done(self, phase: BenchPhase) -> None:
        """Dispatch the phase's live-stats wait onto the streaming
        control plane (--svcstream) when it is active for this run,
        falling back LOUDLY one rung (stream -> poll) when the stream
        cannot serve this host — the control-plane analogue of the
        uring -> AIO -> Python ladder of the data path."""
        sc = getattr(self.shared, "stream_control", None)
        if sc is None:
            self._poll_until_done(phase)
            return
        sc.ensure_phase(self._expected_bench_id)
        sc.note_entered()
        try:
            subtree = sc.subtree_of(self.host)
            if subtree is not None:
                self._run_root_stream(phase, sc, subtree)
            else:
                self._wait_stream_host(phase, sc)
            return
        except StreamDetachedError as err:
            sc.detach_host(self.host)
            logger.log_error(
                f"SVCSTREAM FALLBACK: {self.host}: {err}; falling back "
                f"to /status polling for this phase (stream -> poll)")
        self._poll_until_done(phase)

    def _account_stream_frame(self, nbytes: int, state: dict,
                              is_full: bool, now: float,
                              last_frame: float) -> None:
        """Per-frame audit: frames/bytes received, bytes delta encoding
        kept off the wire, the deepest aggregation tree seen, and the
        inter-frame heartbeat gap.

        SvcDeltaSavedBytes is an estimate priced against the size of the
        most recent FULL frame on this stream (every stream starts with
        one) — re-serializing the merged state per frame just to price
        the delta would re-create a slice of the very per-tick cost the
        stream removes."""
        from .stream import KEY_AGG_DEPTH
        self.svc_stream_frames += 1
        self.svc_stream_bytes += nbytes
        if is_full:
            self._stream_full_frame_bytes = nbytes
        else:
            self.svc_delta_saved_bytes += max(
                getattr(self, "_stream_full_frame_bytes", 0) - nbytes, 0)
        self.svc_agg_depth_hwm = max(self.svc_agg_depth_hwm,
                                     int(state.get(KEY_AGG_DEPTH, 1)))
        self.svc_heartbeat_age_hwm_usec = max(
            self.svc_heartbeat_age_hwm_usec,
            int((now - last_frame) * 1e6))

    #: how long a root stream may deliver only non-matching frames after
    #: /startphase succeeded before the master stops waiting (persistent
    #: foreign UUID = hijack; persistent idle = fall to the polling rung)
    NO_MATCH_GRACE_SECS = 5.0

    def _run_root_stream(self, phase: BenchPhase, sc,
                         subtree: "list[str]") -> None:
        """Attached-root duty: own the subtree's /livestream, distribute
        per-host frame entries into the fleet's host states and worker
        mirrors, ingest the subtree-aggregated telemetry into THIS
        worker (the detach logic guarantees no host contributes twice),
        and stay on the wire until every subtree host is resolved."""
        from .stream import KEY_FULL, StreamProtocolError, apply_delta, \
            check_seq, stream_read_timeout
        interval_ms = max(self.cfg.svc_update_interval_ms, 25)
        read_timeout = stream_read_timeout(interval_ms)
        stalled_secs = max(self.cfg.svc_stalled_secs, 0)

        def reopen(resync: bool):
            trace_params, flow_id = self._trace_params()
            tracer = self.shared.tracer
            t0_ns = tracer.now_ns() if tracer is not None else 0
            try:
                handle = self.client.open_stream(
                    self._expected_bench_id, interval_ms,
                    fanout=sc.fanout, subtree=subtree,
                    read_timeout=read_timeout, resync=resync,
                    trace_params=trace_params)
            except (WorkerRemoteException, *TRANSIENT_EXCEPTIONS) as err:
                raise StreamDetachedError(
                    f"cannot open live stream: {err}") from err
            self._record_rpc_span(proto.PATH_LIVE_STREAM, flow_id, t0_ns)
            if handle.svc_clock_usec:
                # the stream-open ping doubles as a clock-offset sample
                self.clock_sync.add_sample(handle.clock_t0_usec,
                                           handle.clock_t1_usec,
                                           handle.svc_clock_usec)
            return handle

        handle = None
        state: dict = {}
        last_seq = 0
        matched = False
        resyncs = 0
        agg_zeroed = False
        no_match_since = time.monotonic()
        last_frame = time.monotonic()
        normal_exit = False
        try:
            handle = reopen(resync=False)
            self.last_ping_usec = handle.rtt_usec
            while True:
                self.check_interruption_request(force=True)
                try:
                    frame = handle.read_frame()
                    last_seq = check_seq(last_seq, frame)
                except (StreamProtocolError,
                        *TRANSIENT_EXCEPTIONS) as err:
                    # missed/garbled frame or a dead socket: ONE resync
                    # reconnect (the new stream's first frame is a full
                    # snapshot), then give the poll rung the phase
                    if resyncs >= 1:
                        raise StreamDetachedError(
                            f"live stream failed twice: {err}") from err
                    resyncs += 1
                    handle.close()
                    handle = reopen(resync=True)
                    last_seq = 0
                    state = {}
                    continue
                state = apply_delta(
                    {} if frame.get(KEY_FULL) else state, frame)
                frame_id = state.get(proto.KEY_BENCH_ID, "")
                if frame_id == self._expected_bench_id:
                    matched = True
                elif matched and frame_id:
                    self._raise_host_failure("hijacked")
                if not matched:
                    # stale pre-/startphase frames get a short grace; a
                    # stream that NEVER matches must not hang the phase
                    # on heartbeats — a persistent foreign UUID is a
                    # hijack (polling would raise on its first reply),
                    # persistent idle/empty falls to the polling rung
                    if time.monotonic() - no_match_since \
                            <= self.NO_MATCH_GRACE_SECS:
                        continue
                    if frame_id:
                        self._raise_host_failure("hijacked")
                    raise StreamDetachedError(
                        f"no frame matched this run's bench UUID within "
                        f"{self.NO_MATCH_GRACE_SECS:.0f}s")
                now = time.monotonic()
                self._account_stream_frame(handle.last_frame_bytes, state,
                                           bool(frame.get(KEY_FULL)),
                                           now, last_frame)
                last_frame = now
                # SvcConnHwm censuses STEADY-STATE connections: after
                # every worker is past its /startphase burst and before
                # the first finisher reopens for /benchresult — the
                # window where "master holds O(fanout) connections" is
                # the claim being audited
                if sc.all_entered() \
                        and not state.get(proto.KEY_NUM_WORKERS_DONE, 0):
                    self.svc_conn_hwm = max(self.svc_conn_hwm,
                                            ServiceClient.open_connections)
                sc.ingest_frame(self.host, state)
                # subtree-aggregated TPU/path-audit/lease telemetry lands
                # on the ROOT worker; the fleet sum/MAX over workers then
                # equals the flat merge (satellite: /metrics harvests
                # from stream frames — zero extra service requests).
                # Gated on the whole subtree still riding the tree: a
                # detached-then-recovered host would otherwise appear in
                # the aggregate AND in its own polling ingest. On the
                # first detach the already-ingested aggregate (which
                # baked in the lost host's pre-detach share) is zeroed —
                # mid-run /metrics under-counts the subtree rather than
                # double-counting; finals are exact either way
                # (/benchresult overwrites)
                if sc.subtree_fully_attached(self.host):
                    self._ingest_live_telemetry(state)
                elif not agg_zeroed:
                    agg_zeroed = True
                    self._reset_live_telemetry()
                st = sc.state_of(self.host)
                if st.hijacked:
                    self._raise_host_failure("hijacked")
                if st.err:
                    self._raise_host_failure("err")
                if stalled_secs and not self.shared.stonewall_triggered \
                        and now - st.last_change >= stalled_secs:
                    self._raise_host_failure("stalled", stalled_secs)
                if sc.subtree_satisfied(self.host,
                                        self.num_remote_threads):
                    self.done_obs_quantum_usec = \
                        STREAM_DONE_OBS_QUANTUM_USEC
                    normal_exit = True
                    return
        finally:
            if handle is not None:
                handle.close()
            if not normal_exit:
                # abnormal root exit: the subtree loses its aggregator;
                # still-waiting hosts detach and fall back to polling
                sc.detach_subtree(self.host)

    def _wait_stream_host(self, phase: BenchPhase, sc) -> None:
        """Non-root duty: wait on this host's stream-fed state until it
        is done — or raise the exact exception the polling loop would
        (error/hijack/stall), or detach when the tree stops covering this
        host (root died / subtree reported unreachable)."""
        st = sc.state_of(self.host)
        stalled_secs = max(self.cfg.svc_stalled_secs, 0)
        while True:
            self.check_interruption_request(force=True)
            action = None
            with sc.cond:
                if st.hijacked:
                    action = "hijacked"
                elif st.err:
                    action = "err"
                elif st.done >= self.num_remote_threads:
                    self.done_obs_quantum_usec = \
                        STREAM_DONE_OBS_QUANTUM_USEC
                    return
                elif st.unreachable or not st.attached \
                        or sc.root_worker_lost(self.host):
                    action = "detached"
                elif stalled_secs \
                        and not self.shared.stonewall_triggered \
                        and time.monotonic() - st.last_change \
                        >= stalled_secs:
                    action = "stalled"
                else:
                    sc.cond.wait(0.1)
                    continue
            if action in ("hijacked", "err", "stalled"):
                self._raise_host_failure(action, stalled_secs)
            raise StreamDetachedError(
                "aggregation tree no longer covers this host")

    def _poll_until_done(self, phase: BenchPhase) -> None:
        """Poll /status, mirroring remote live totals into this worker's
        counters so the master's live stats aggregate naturally
        (reference: waitForBenchPhaseCompletion :447-560).

        Stall watchdog (--svcstalledsecs): when the service's live
        counters stop advancing — or the service stops answering — for
        longer than the window, the host is declared stalled instead of
        holding the phase barrier forever."""
        interval = POLL_MIN_SECS
        max_interval = max(self.cfg.svc_update_interval_ms, 25) / 1000.0
        stalled_secs = max(self.cfg.svc_stalled_secs, 0)
        # bound the per-poll read block so a hung socket can't blow
        # through the stall window before the watchdog gets to look
        poll_timeout = min(CONNECT_TIMEOUT_SECS, stalled_secs) \
            if stalled_secs else CONNECT_TIMEOUT_SECS
        # two separate baselines: last_success (last answered /status)
        # drives the unreachable trip and the retry deadline, so a
        # legitimately idle host — e.g. a post-stonewall straggler whose
        # counters sit still — keeps its full retry window; last_progress
        # (last counter advance) drives only the static-counter trip
        last_progress = last_success = time.monotonic()
        last_counters = None
        while True:
            self.check_interruption_request(force=True)
            deadline = (last_success + stalled_secs) if stalled_secs \
                else None
            t0 = time.monotonic()
            t0_wall = time.time_ns() // 1000
            try:
                # the bench UUID marks this poll as the owning master's
                # heartbeat: the service's --svcleasesecs lease renews on
                # it, while observer /status polls (dashboards, probes)
                # deliberately cannot keep an orphaned service alive
                status, stats = self.client.get_json(
                    proto.PATH_STATUS,
                    {proto.KEY_BENCH_ID: self._expected_bench_id}
                    if self._expected_bench_id else None,
                    timeout=poll_timeout,
                    deadline=deadline)
            except WorkerRemoteException as err:
                if stalled_secs \
                        and time.monotonic() - last_success >= stalled_secs:
                    raise WorkerStalledException(
                        f"service {self.host} stalled: no reachable "
                        f"status for {stalled_secs}s "
                        f"(--svcstalledsecs)") from err
                raise
            now = time.monotonic()
            # --svcping: the /status round-trip IS the service ping
            # (reference fullscreen shows per-service latency, --svcping)
            self.last_ping_usec = int((now - t0) * 1e6)
            # fleet tracing: the same round trip is a clock-offset
            # sample (lease-renewal piggyback — zero extra requests)
            self._feed_clock_sample(t0_wall, stats)
            self.svc_conn_hwm = max(self.svc_conn_hwm,
                                    ServiceClient.open_connections)
            # heartbeat age: gap between successive successful polls
            self.svc_heartbeat_age_hwm_usec = max(
                self.svc_heartbeat_age_hwm_usec,
                int((now - last_success) * 1e6))
            last_success = now
            if status != 200:
                raise WorkerRemoteException(
                    f"status poll on {self.host} failed ({status})")
            got_id = stats.get(proto.KEY_BENCH_ID, "")
            if got_id and self._expected_bench_id \
                    and got_id != self._expected_bench_id:
                self._raise_host_failure("hijacked")  # reference: :199-202
            self.live_ops.num_entries_done = \
                stats.get(proto.KEY_NUM_ENTRIES_DONE, 0)
            self.live_ops.num_bytes_done = \
                stats.get(proto.KEY_NUM_BYTES_DONE, 0)
            self.live_ops.num_iops_done = \
                stats.get(proto.KEY_NUM_IOPS_DONE, 0)
            self._ingest_live_telemetry(stats)
            if stats.get(proto.KEY_NUM_WORKERS_DONE_WITH_ERROR, 0):
                self._raise_host_failure("err")
            done = stats.get(proto.KEY_NUM_WORKERS_DONE, 0)
            if done >= self.num_remote_threads:
                # the done observation is quantized by the CURRENT poll
                # interval (the host may have finished any time since
                # the previous poll)
                self.done_obs_quantum_usec = int(interval * 1e6)
                return
            counters = (self.live_ops.num_entries_done,
                        self.live_ops.num_bytes_done,
                        self.live_ops.num_iops_done, done)
            if counters != last_counters:
                last_counters = counters
                last_progress = now
            elif stalled_secs and not self.shared.stonewall_triggered \
                    and now - last_progress >= stalled_secs:
                # counters froze while the service still answers; with a
                # stonewall in effect straggler counters may legitimately
                # idle, so the static-counter trip is gated on it
                self._raise_host_failure("stalled", stalled_secs)
            time.sleep(interval)
            interval = min(interval * 2, max_interval)

    def _ingest_live_telemetry(self, stats: dict) -> None:
        """Mirror the per-host telemetry harvest of a /status reply into
        this worker's ingest attributes, so the master's /metrics fleet
        aggregation (sum_path_audit_counters + the MAX-merge rules) works
        MID-RUN exactly like the phase-end /benchresult ingest does. The
        final /benchresult ingest overwrites all of these."""
        from ..tpu.device import PATH_AUDIT_COUNTERS
        self.cpu_util_pct = stats.get("CPUUtil", 0.0)
        self._ingest_lease_counters(stats)
        if "TpuHbmBytes" not in stats:
            return  # pre-telemetry service replied (tests with old stubs)
        self.tpu_transfer_bytes = stats.get("TpuHbmBytes", 0)
        self.tpu_transfer_usec = stats.get("TpuHbmUSec", 0)
        self.tpu_dispatch_usec = stats.get("TpuHbmDispatchUSec", 0)
        for _attr, key, ingest_attr in PATH_AUDIT_COUNTERS:
            setattr(self, ingest_attr, stats.get(key, 0))
        if "IOLatHisto" in stats:  # --telemetry: bucket-level live view
            self.iops_latency_histo = LatencyHistogram.from_dict(
                stats["IOLatHisto"])
            self.entries_latency_histo = LatencyHistogram.from_dict(
                stats.get("EntLatHisto", {}))
        elif "SumIOLatUSec" in stats:
            # no bucket view on the wire, but every live reply/frame
            # carries the latency SUMS — mirror them so the flight
            # recorder's per-host IoBusyUSec (storage busy time) is live
            # mid-run; the final /benchresult ingest overwrites with the
            # full histograms
            io_histo = LatencyHistogram()
            io_histo.num_values = stats.get("NumIOLatUSec", 0)
            io_histo.sum_micro = stats.get("SumIOLatUSec", 0)
            self.iops_latency_histo = io_histo
            ent_histo = LatencyHistogram()
            ent_histo.num_values = stats.get("NumEntLatUSec", 0)
            ent_histo.sum_micro = stats.get("SumEntLatUSec", 0)
            self.entries_latency_histo = ent_histo

    def _reset_live_telemetry(self) -> None:
        """Zero every mirror _ingest_live_telemetry can set — incl. the
        conditionally-ingested histograms and lease counters. Lives next
        to the ingest so a new conditional key added there is visibly a
        key to reset here too (the stream plane zeroes a root's stale
        subtree aggregate when a member detaches to polling)."""
        self._ingest_live_telemetry({
            "TpuHbmBytes": 0, "IOLatHisto": {}, "EntLatHisto": {},
            proto.KEY_SVC_LEASE_EXPIRIES: 0,
            proto.KEY_SVC_LEASE_AGE_HWM: 0,
            proto.KEY_SVC_ADOPTIONS: 0,
            proto.KEY_SVC_ADOPT_WAIT: 0})

    def _raise_host_failure(self, kind: str, stalled_secs: int = 0):
        """The per-host failure exceptions, shared by the polling loop
        and both streaming waiters so the two planes can never drift in
        semantics or wording."""
        if kind == "hijacked":
            raise WorkerHijackedException(
                f"service {self.host} was hijacked by another master "
                f"(bench UUID mismatch)")  # reference: :199-202
        if kind == "err":
            raise WorkerRemoteException(
                f"worker error on service {self.host}"
                + self._fetch_remote_error_detail())
        raise WorkerStalledException(
            f"service {self.host} stalled: live counters static "
            f"for {stalled_secs}s (--svcstalledsecs)")

    def _ingest_lease_counters(self, reply: dict) -> None:
        """Mirror the service-observed lease counters (--svcleasesecs;
        service-lifetime values) so the fleet merge — SvcLeaseExpiries
        sums, SvcLeaseAgeHwmUsec MAXes across hosts — and the /metrics
        view pick them up like every CONTROL_AUDIT_COUNTERS entry."""
        if proto.KEY_SVC_LEASE_EXPIRIES in reply:
            self.svc_lease_expiries = reply[proto.KEY_SVC_LEASE_EXPIRIES]
            self.svc_lease_age_hwm_usec = reply.get(
                proto.KEY_SVC_LEASE_AGE_HWM, 0)
        # adoption counters are on the wire only when nonzero (master
        # failover); absent keys leave the mirrors untouched
        if proto.KEY_SVC_ADOPTIONS in reply:
            self.svc_adoptions = reply[proto.KEY_SVC_ADOPTIONS]
        if proto.KEY_SVC_ADOPT_WAIT in reply:
            self.svc_adopt_wait_usec = reply[proto.KEY_SVC_ADOPT_WAIT]

    def _replay_error_history(self, reply: dict) -> "list[str]":
        """Log the service's error-history lines under this host's prefix
        (reference: XFER_PREP_ERRORHISTORY replay)."""
        lines = reply.get(proto.KEY_ERROR_HISTORY, [])
        for line in lines:
            logger.log_error(f"[{self.host}] {line}")
        return lines

    @staticmethod
    def _strip_log_prefix(line: str) -> str:
        """'2026-.. ERROR: Worker 0 ...' -> 'Worker 0 ...' so an embedded
        root cause doesn't nest timestamps."""
        return line.split("ERROR: ", 1)[-1]

    def _fetch_remote_error_detail(self) -> str:
        """Pull the service's error history so the master shows the REAL
        failure, not just 'worker error' (reference: error history replay,
        Common.h XFER_PREP_ERRORHISTORY + finishPhase ingestion)."""
        try:
            status, result = self.client.get_json(proto.PATH_BENCH_RESULT,
                                                  timeout=15.0)
        except Exception:  # noqa: BLE001 - detail fetch must not mask
            return ""
        lines = self._replay_error_history(result) if status == 200 else []
        return f": {self._strip_log_prefix(lines[-1])}" if lines else ""

    def _finish_phase_remote(self) -> None:
        """GET /benchresult and ingest per-thread elapsed + histograms
        (reference: finishPhase :172-280). With fleet tracing armed the
        same request also asks the service to ship its span ring
        (ShipTrace) — collection piggybacks, zero extra requests."""
        params, flow_id = self._trace_params()
        if params is not None:
            params[proto.KEY_SHIP_TRACE] = 1
        if getattr(self.cfg, "slow_ops_k", 0):
            # slow-op forensics rides the SAME request (zero extra
            # requests; SvcRequests stays byte-identical)
            params = params or {}
            params[proto.KEY_SHIP_SLOWOPS] = 1
        tracer = self.shared.tracer
        t0_ns = tracer.now_ns() if tracer is not None else 0
        t0_wall = time.time_ns() // 1000
        status, result = self.client.get_json(proto.PATH_BENCH_RESULT,
                                              params=params, timeout=60.0)
        self._record_rpc_span(proto.PATH_BENCH_RESULT, flow_id, t0_ns)
        self._feed_clock_sample(t0_wall, result)
        if status != 200:
            raise WorkerRemoteException(
                f"result fetch from {self.host} failed ({status})")
        lines = self._replay_error_history(result)
        self._ingest_lease_counters(result)
        if result.get(proto.KEY_NUM_WORKERS_DONE_WITH_ERROR, 0):
            detail = f": {self._strip_log_prefix(lines[-1])}" if lines \
                else ""
            raise WorkerRemoteException(
                f"service {self.host} reported worker errors{detail}")
        final = result.get("Final", {})
        stonewall = result.get("StoneWall", {})
        self.live_ops.num_entries_done = final.get("entries", 0)
        self.live_ops.num_bytes_done = final.get("bytes", 0)
        self.live_ops.num_iops_done = final.get("iops", 0)
        self.stonewall_ops.num_entries_done = stonewall.get("entries", 0)
        self.stonewall_ops.num_bytes_done = stonewall.get("bytes", 0)
        self.stonewall_ops.num_iops_done = stonewall.get("iops", 0)
        final_rw = result.get("FinalRWMixRead", {})
        stone_rw = result.get("StoneWallRWMixRead", {})
        self.live_ops_rwmix_read.num_entries_done = final_rw.get("entries", 0)
        self.live_ops_rwmix_read.num_bytes_done = final_rw.get("bytes", 0)
        self.live_ops_rwmix_read.num_iops_done = final_rw.get("iops", 0)
        self.stonewall_ops_rwmix_read.num_entries_done = \
            stone_rw.get("entries", 0)
        self.stonewall_ops_rwmix_read.num_bytes_done = \
            stone_rw.get("bytes", 0)
        self.stonewall_ops_rwmix_read.num_iops_done = stone_rw.get("iops", 0)
        self.elapsed_usec_vec = list(
            result.get(proto.KEY_ELAPSED_USEC_LIST, []))
        self.stonewall_elapsed_usec = result.get("StoneWallUSec", 0)
        self.stonewall_taken = True
        self.phase_finished = True
        self.iops_latency_histo = LatencyHistogram.from_dict(
            result.get("IOLatHisto", {}))
        self.entries_latency_histo = LatencyHistogram.from_dict(
            result.get("EntLatHisto", {}))
        self.iops_latency_histo_rwmix = LatencyHistogram.from_dict(
            result.get("IOLatHistoRWMixRead", {}))
        self.tpu_transfer_bytes = result.get("TpuHbmBytes", 0)
        self.tpu_transfer_usec = result.get("TpuHbmUSec", 0)
        self.tpu_dispatch_usec = result.get("TpuHbmDispatchUSec", 0)
        # H2D/D2H path-audit counters, schema-driven so a counter added
        # to PATH_AUDIT_COUNTERS is ingested without touching this file
        from ..tpu.device import PATH_AUDIT_COUNTERS
        for _attr, key, ingest_attr in PATH_AUDIT_COUNTERS:
            setattr(self, ingest_attr, result.get(key, 0))
        # chip ids arrive as JSON string keys; normalize back to int so
        # the master's merge can't split one chip into "0" and 0 buckets
        self.tpu_per_chip = {
            int(chip): (v.get("Bytes", 0), v.get("USec", 0))
            for chip, v in result.get("TpuPerChip", {}).items()}
        self.got_phase_work = bool(self.elapsed_usec_vec)
        if getattr(self.cfg, "slow_ops_k", 0):
            self._ingest_slowops(result)
        if self._fleet_trace:
            self._collect_trace_ring(result)
        if getattr(self.shared, "stream_control", None) is not None:
            self.client.drop_connection()  # back to the parked steady state

    def _interrupt_remote(self, quit_service: bool) -> None:
        """Best effort, deliberately BELOW the retry layer: the service may
        already be gone, and burning --svcretries x timeout here serializes
        into teardown (error handler + TERMINATE both interrupt), stalling
        the whole run on a dead host. TRANSIENT_EXCEPTIONS is the shared
        classifier: a half-closed socket's malformed status line
        (HTTPException) must not escape and mask the original failure."""
        params = {proto.KEY_INTERRUPT_QUIT: "1"} if quit_service else {}
        try:
            self.client._request("GET", proto.PATH_INTERRUPT_PHASE, params,
                                 timeout=INTERRUPT_TIMEOUT_SECS)
        except TRANSIENT_EXCEPTIONS:
            pass  # service may already be gone (best effort)


# ---------------------------------------------------------------------------
# master-side helpers (reference: Coordinator::waitForServicesReady :165-227)
# ---------------------------------------------------------------------------

#: clients the ready-probe left with a warm persistent connection, keyed
#: by (hostname, port) for adoption by the host's RemoteWorker — the
#: probe used to build throwaway clients whose sockets were wasted
_probed_clients: "dict[tuple[str, int], ServiceClient]" = {}
_probed_clients_lock = threading.Lock()


def _register_probed_client(client: ServiceClient) -> None:
    key = (client.hostname, client.port)
    with _probed_clients_lock:
        old = _probed_clients.pop(key, None)
        _probed_clients[key] = client
    if old is not None:
        old.close()


def adopt_probed_client(hostname: str, port: int) -> "ServiceClient | None":
    with _probed_clients_lock:
        return _probed_clients.pop((hostname, port), None)


def wait_for_services_ready(hosts: "list[str]", default_port: int,
                            wait_secs: int) -> None:
    """Probe all hosts CONCURRENTLY against the shared --svcwait deadline
    (a slow first host used to eat the whole budget of the hosts after
    it) and report every unreachable host at once. Each successful
    probe's client — connection still open — is parked for adoption by
    that host's RemoteWorker (persistent-connection reuse instead of
    throwaway probe clients)."""
    deadline = time.monotonic() + max(wait_secs, 0)
    unreachable: "dict[str, str]" = {}
    lock = threading.Lock()

    def probe(host: str) -> None:
        client = ServiceClient(host, default_port)
        last_err = "no reply"
        while True:
            try:
                status, _ = client.get_json(proto.PATH_STATUS, timeout=3)
                if status in (200, 401):
                    _register_probed_client(client)
                    return
                last_err = f"HTTP {status}"
            except WorkerRemoteException as err:
                last_err = str(err)
            if time.monotonic() >= deadline:
                with lock:
                    unreachable[host] = last_err
                client.close()
                return
            time.sleep(1)

    threads = [threading.Thread(target=probe, args=(h,), daemon=True,
                                name=f"svc-probe-{h}") for h in hosts]
    for t in threads:
        t.start()
    for t in threads:
        # margin over the shared deadline: a probe returns right after its
        # own deadline check, so this only guards against pathological hangs
        t.join(timeout=max(deadline - time.monotonic(), 0) + 10)
    if unreachable:
        details = "; ".join(f"{h}: {e}" for h, e in unreachable.items())
        raise WorkerRemoteException(
            f"service(s) not reachable (--svcwait to extend the wait): "
            f"{details}")


def send_interrupt_to_hosts(hosts: "list[str]", default_port: int,
                            quit: bool = False, fanout: int = 0) -> None:
    """--interrupt / --quit handling (reference: Coordinator service
    control paths). With --svcfanout the interrupt walks the same
    aggregation tree the live stats ride: the master contacts only the
    roots, each root forwards to its children with their sub-subtrees
    (stream.forward_interrupt), so teardown is O(fanout) too."""
    verb = "quit" if quit else "interrupt"

    def send_one(host: str, subtree: "list[str]") -> None:
        client = ServiceClient(host, default_port)
        params = {proto.KEY_INTERRUPT_QUIT: "1"} if quit else {}
        if subtree:
            params[proto.KEY_STREAM_SUBTREE] = ",".join(subtree)
            params[proto.KEY_STREAM_FANOUT] = fanout
        try:
            client.get_json(proto.PATH_INTERRUPT_PHASE, params)
            via = f" (+{len(subtree)} host(s) via tree)" if subtree else ""
            logger.log(0, f"sent {verb} to {host}{via}")
        except (WorkerRemoteException, *TRANSIENT_EXCEPTIONS) as err:
            # OSError alone used to let a half-closed socket's malformed
            # status line (HTTPException) escape and mask the real failure
            logger.log_error(f"could not reach {host}: {err}")
            if subtree:
                # the same direct-attachment fallback the live-stats
                # plane has: a dead root must not strand its subtree
                # with workers still running
                logger.log_error(
                    f"{verb} fan-out: root {host} unreachable — sending "
                    f"directly to its {len(subtree)} subtree host(s)")
                for sub_host in subtree:
                    send_one(sub_host, [])
        finally:
            client.close()

    for host, subtree in plan_tree(hosts, max(fanout, 0)):
        send_one(host, subtree)
