"""RemoteWorker: the master's per-host proxy thread.

Reference: source/workers/RemoteWorker.{h,cpp} — one per --hosts entry;
uploads prep files (:288-345), POSTs the serialized config
(preparePhase :354-407), GETs /startphase (:412), polls /status at an
adaptive cadence accumulating remote live ops into its own counters
(:447-560), fetches /benchresult and ingests per-thread elapsed vectors +
mergeable histograms (finishPhase :172-280), sends /interruptphase on
error/quit. Bench-UUID hijack detection: a /status reply with an unexpected
BenchID aborts the run (RemoteWorker.cpp:199-202).
"""

from __future__ import annotations

import http.client
import json
import os
import time
import urllib.parse

from .. import HTTP_PROTOCOL_VERSION
from ..phases import BenchPhase
from ..stats.latency_histogram import LatencyHistogram
from ..toolkits import logger
from ..workers.base import Worker
from ..workers.shared import (WorkerInterruptedException,
                              WorkerRemoteException)
from . import protocol as proto

DEFAULT_PORT = 1611
CONNECT_TIMEOUT_SECS = 10
# adaptive /status cadence: start fast for short phases, back off to the
# configured --svcupint (reference: 25ms -> 500ms, RemoteWorker.cpp:447+)
POLL_MIN_SECS = 0.025


def split_host_port(host: str, default_port: int = DEFAULT_PORT
                    ) -> "tuple[str, int]":
    if ":" in host:
        name, _, port = host.rpartition(":")
        return (name, int(port))
    return (host, default_port)


class ServiceClient:
    """Minimal HTTP/JSON client for one service host."""

    def __init__(self, host: str, default_port: int, pw_hash: str = ""):
        self.hostname, self.port = split_host_port(host, default_port)
        self.pw_hash = pw_hash

    def _request(self, method: str, path: str, params: "dict | None" = None,
                 body: "bytes | None" = None,
                 timeout: float = CONNECT_TIMEOUT_SECS):
        params = dict(params or {})
        if self.pw_hash:
            params[proto.KEY_AUTHORIZATION] = self.pw_hash
        if params:
            path = path + "?" + urllib.parse.urlencode(params)
        conn = http.client.HTTPConnection(self.hostname, self.port,
                                          timeout=timeout)
        try:
            conn.request(method, path, body=body)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, data
        finally:
            conn.close()

    def get_json(self, path: str, params: "dict | None" = None,
                 timeout: float = CONNECT_TIMEOUT_SECS) -> "tuple[int, dict]":
        status, data = self._request("GET", path, params, timeout=timeout)
        try:
            return status, (json.loads(data) if data else {})
        except json.JSONDecodeError:
            return status, {"raw": data.decode(errors="replace")}

    def post_json(self, path: str, obj, params: "dict | None" = None,
                  timeout: float = 60.0) -> "tuple[int, dict]":
        body = json.dumps(obj).encode()
        status, data = self._request("POST", path, params, body=body,
                                     timeout=timeout)
        try:
            return status, (json.loads(data) if data else {})
        except json.JSONDecodeError:
            return status, {"raw": data.decode(errors="replace")}


class RemoteWorker(Worker):
    def __init__(self, shared, host_idx: int, host: str):
        super().__init__(shared, rank=host_idx)
        self.cfg = shared.config
        self.host = host
        self.host_idx = host_idx
        self.last_ping_usec = 0  # --svcping: last /status RTT
        pw_hash = ""
        if self.cfg.svc_password_file:
            pw_hash = proto.read_pw_file(self.cfg.svc_password_file)
        self.client = ServiceClient(host, self.cfg.service_port, pw_hash)
        self.num_remote_threads = self.cfg.num_threads
        self._expected_bench_id = ""

    # ------------------------------------------------------------------

    def run(self) -> None:
        self._check_protocol_version()
        self._prepare_remote_files()
        self._prepare_phase_remote()
        last_uuid = self.shared.bench_uuid
        self.shared.inc_num_workers_done()  # prep barrier
        while True:
            phase, last_uuid = self.shared.wait_for_phase_change(last_uuid)
            if phase == BenchPhase.TERMINATE:
                self._interrupt_remote(quit_service=False)
                return
            if phase == BenchPhase.IDLE:
                continue
            try:
                self._start_remote_phase(phase, last_uuid)
                self._poll_until_done(phase)
                self._finish_phase_remote()
                self.shared.inc_num_workers_done()
            except WorkerInterruptedException:
                self._interrupt_remote(quit_service=False)
                self.shared.inc_num_workers_done()
            except Exception as err:  # noqa: BLE001
                logger.log_error(f"Remote worker for {self.host} failed: "
                                 f"{err}")
                self._interrupt_remote(quit_service=False)
                self.shared.inc_num_workers_done_with_error(err)

    # ------------------------------------------------------------------

    def _check_protocol_version(self) -> None:
        status, data = self.client._request("GET",
                                            proto.PATH_PROTOCOL_VERSION)
        remote = data.decode().strip().strip('"')
        if status != 200 or remote != HTTP_PROTOCOL_VERSION:
            raise WorkerRemoteException(
                f"service {self.host} protocol version mismatch: "
                f"{remote!r} != {HTTP_PROTOCOL_VERSION!r}")

    def _prepare_remote_files(self) -> None:
        """Upload treefile to the service (reference: :288-345)."""
        if not self.cfg.tree_file_path:
            return
        with open(self.cfg.tree_file_path, "rb") as f:
            body = f.read()
        status, data = self.client._request(
            "POST", proto.PATH_PREPARE_FILE, {
                proto.KEY_FILE_NAME:
                    os.path.basename(self.cfg.tree_file_path)}, body)
        if status != 200:
            raise WorkerRemoteException(
                f"file upload to {self.host} failed: {data!r}")

    def _prepare_phase_remote(self) -> None:
        """POST the full effective config with this host's rank offset
        (reference: preparePhase :354-407; rank offset = hostIdx * threads,
        ProgArgs.cpp:3921)."""
        cfg_dict = self.cfg.to_service_dict(
            service_rank_offset=self.host_idx * self.cfg.num_threads)
        status, reply = self.client.post_json(proto.PATH_PREPARE_PHASE,
                                              cfg_dict, timeout=300.0)
        self._replay_error_history(reply)
        if status != 200:
            raise WorkerRemoteException(
                f"preparation on {self.host} failed: "
                f"{reply.get('Error', reply)}")
        self.bench_path_info = reply

    def _start_remote_phase(self, phase: BenchPhase, bench_id: str) -> None:
        self._expected_bench_id = bench_id
        status, reply = self.client.get_json(proto.PATH_START_PHASE, {
            proto.KEY_PHASE_CODE: int(phase),
            proto.KEY_BENCH_ID: bench_id})
        if status != 200:
            raise WorkerRemoteException(
                f"phase start on {self.host} failed: "
                f"{reply.get('Message', reply)}")

    def _poll_until_done(self, phase: BenchPhase) -> None:
        """Poll /status, mirroring remote live totals into this worker's
        counters so the master's live stats aggregate naturally
        (reference: waitForBenchPhaseCompletion :447-560)."""
        interval = POLL_MIN_SECS
        max_interval = max(self.cfg.svc_update_interval_ms, 25) / 1000.0
        while True:
            self.check_interruption_request(force=True)
            t0 = time.monotonic()
            status, stats = self.client.get_json(proto.PATH_STATUS)
            # --svcping: the /status round-trip IS the service ping
            # (reference fullscreen shows per-service latency, --svcping)
            self.last_ping_usec = int((time.monotonic() - t0) * 1e6)
            if status != 200:
                raise WorkerRemoteException(
                    f"status poll on {self.host} failed ({status})")
            got_id = stats.get(proto.KEY_BENCH_ID, "")
            if got_id and self._expected_bench_id \
                    and got_id != self._expected_bench_id:
                raise WorkerRemoteException(
                    f"service {self.host} was hijacked by another master "
                    f"(bench UUID mismatch)")  # reference: :199-202
            self.live_ops.num_entries_done = \
                stats.get(proto.KEY_NUM_ENTRIES_DONE, 0)
            self.live_ops.num_bytes_done = \
                stats.get(proto.KEY_NUM_BYTES_DONE, 0)
            self.live_ops.num_iops_done = \
                stats.get(proto.KEY_NUM_IOPS_DONE, 0)
            if stats.get(proto.KEY_NUM_WORKERS_DONE_WITH_ERROR, 0):
                raise WorkerRemoteException(
                    f"worker error on service {self.host}"
                    + self._fetch_remote_error_detail())
            done = stats.get(proto.KEY_NUM_WORKERS_DONE, 0)
            if done >= self.num_remote_threads:
                return
            time.sleep(interval)
            interval = min(interval * 2, max_interval)

    def _replay_error_history(self, reply: dict) -> "list[str]":
        """Log the service's error-history lines under this host's prefix
        (reference: XFER_PREP_ERRORHISTORY replay)."""
        lines = reply.get(proto.KEY_ERROR_HISTORY, [])
        for line in lines:
            logger.log_error(f"[{self.host}] {line}")
        return lines

    @staticmethod
    def _strip_log_prefix(line: str) -> str:
        """'2026-.. ERROR: Worker 0 ...' -> 'Worker 0 ...' so an embedded
        root cause doesn't nest timestamps."""
        return line.split("ERROR: ", 1)[-1]

    def _fetch_remote_error_detail(self) -> str:
        """Pull the service's error history so the master shows the REAL
        failure, not just 'worker error' (reference: error history replay,
        Common.h XFER_PREP_ERRORHISTORY + finishPhase ingestion)."""
        try:
            status, result = self.client.get_json(proto.PATH_BENCH_RESULT,
                                                  timeout=15.0)
        except Exception:  # noqa: BLE001 - detail fetch must not mask
            return ""
        lines = self._replay_error_history(result) if status == 200 else []
        return f": {self._strip_log_prefix(lines[-1])}" if lines else ""

    def _finish_phase_remote(self) -> None:
        """GET /benchresult and ingest per-thread elapsed + histograms
        (reference: finishPhase :172-280)."""
        status, result = self.client.get_json(proto.PATH_BENCH_RESULT,
                                              timeout=60.0)
        if status != 200:
            raise WorkerRemoteException(
                f"result fetch from {self.host} failed ({status})")
        lines = self._replay_error_history(result)
        if result.get(proto.KEY_NUM_WORKERS_DONE_WITH_ERROR, 0):
            detail = f": {self._strip_log_prefix(lines[-1])}" if lines \
                else ""
            raise WorkerRemoteException(
                f"service {self.host} reported worker errors{detail}")
        final = result.get("Final", {})
        stonewall = result.get("StoneWall", {})
        self.live_ops.num_entries_done = final.get("entries", 0)
        self.live_ops.num_bytes_done = final.get("bytes", 0)
        self.live_ops.num_iops_done = final.get("iops", 0)
        self.stonewall_ops.num_entries_done = stonewall.get("entries", 0)
        self.stonewall_ops.num_bytes_done = stonewall.get("bytes", 0)
        self.stonewall_ops.num_iops_done = stonewall.get("iops", 0)
        final_rw = result.get("FinalRWMixRead", {})
        stone_rw = result.get("StoneWallRWMixRead", {})
        self.live_ops_rwmix_read.num_entries_done = final_rw.get("entries", 0)
        self.live_ops_rwmix_read.num_bytes_done = final_rw.get("bytes", 0)
        self.live_ops_rwmix_read.num_iops_done = final_rw.get("iops", 0)
        self.stonewall_ops_rwmix_read.num_entries_done = \
            stone_rw.get("entries", 0)
        self.stonewall_ops_rwmix_read.num_bytes_done = \
            stone_rw.get("bytes", 0)
        self.stonewall_ops_rwmix_read.num_iops_done = stone_rw.get("iops", 0)
        self.elapsed_usec_vec = list(
            result.get(proto.KEY_ELAPSED_USEC_LIST, []))
        self.stonewall_elapsed_usec = result.get("StoneWallUSec", 0)
        self.stonewall_taken = True
        self.phase_finished = True
        self.iops_latency_histo = LatencyHistogram.from_dict(
            result.get("IOLatHisto", {}))
        self.entries_latency_histo = LatencyHistogram.from_dict(
            result.get("EntLatHisto", {}))
        self.iops_latency_histo_rwmix = LatencyHistogram.from_dict(
            result.get("IOLatHistoRWMixRead", {}))
        self.tpu_transfer_bytes = result.get("TpuHbmBytes", 0)
        self.tpu_transfer_usec = result.get("TpuHbmUSec", 0)
        self.tpu_dispatch_usec = result.get("TpuHbmDispatchUSec", 0)
        # H2D/D2H path-audit counters, schema-driven so a counter added
        # to PATH_AUDIT_COUNTERS is ingested without touching this file
        from ..tpu.device import PATH_AUDIT_COUNTERS
        for _attr, key, ingest_attr in PATH_AUDIT_COUNTERS:
            setattr(self, ingest_attr, result.get(key, 0))
        # chip ids arrive as JSON string keys; normalize back to int so
        # the master's merge can't split one chip into "0" and 0 buckets
        self.tpu_per_chip = {
            int(chip): (v.get("Bytes", 0), v.get("USec", 0))
            for chip, v in result.get("TpuPerChip", {}).items()}
        self.got_phase_work = bool(self.elapsed_usec_vec)

    def _interrupt_remote(self, quit_service: bool) -> None:
        params = {proto.KEY_INTERRUPT_QUIT: "1"} if quit_service else {}
        try:
            self.client.get_json(proto.PATH_INTERRUPT_PHASE, params)
        except OSError:
            pass  # service may already be gone


# ---------------------------------------------------------------------------
# master-side helpers (reference: Coordinator::waitForServicesReady :165-227)
# ---------------------------------------------------------------------------

def wait_for_services_ready(hosts: "list[str]", default_port: int,
                            wait_secs: int) -> None:
    deadline = time.monotonic() + max(wait_secs, 0)
    for host in hosts:
        client = ServiceClient(host, default_port)
        while True:
            try:
                status, _ = client.get_json(proto.PATH_STATUS, timeout=3)
                if status in (200, 401):
                    break
            except OSError:
                pass
            if time.monotonic() >= deadline:
                raise WorkerRemoteException(
                    f"service {host} not reachable "
                    f"(--svcwait to extend the wait)")
            time.sleep(1)


def send_interrupt_to_hosts(hosts: "list[str]", default_port: int,
                            quit: bool = False) -> None:
    """--interrupt / --quit handling (reference: Coordinator service
    control paths)."""
    for host in hosts:
        client = ServiceClient(host, default_port)
        params = {proto.KEY_INTERRUPT_QUIT: "1"} if quit else {}
        try:
            client.get_json(proto.PATH_INTERRUPT_PHASE, params)
            logger.log(0, f"sent {'quit' if quit else 'interrupt'} to {host}")
        except OSError as err:
            logger.log_error(f"could not reach {host}: {err}")
