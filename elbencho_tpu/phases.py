"""Benchmark phase model.

Reference: enum BenchPhase + PHASENAME_* (source/Common.h:141-198,43-74),
TranslatorTk::benchPhaseToPhaseName/EntryType, and the master phase ordering
table in Coordinator::runBenchmarks() (source/Coordinator.cpp:311-334) —
creates run before deletes, S3 metadata phases interleave around them.
"""

from __future__ import annotations

import enum


class BenchPhase(enum.IntEnum):
    IDLE = 0
    TERMINATE = 1
    CREATEDIRS = 2
    DELETEDIRS = 3
    CREATEFILES = 4
    DELETEFILES = 5
    READFILES = 6
    SYNC = 7
    DROPCACHES = 8
    STATFILES = 9
    STATDIRS = 10
    LISTOBJECTS = 11
    LISTOBJPARALLEL = 12
    MULTIDELOBJ = 13
    PUTOBJACL = 14
    GETOBJACL = 15
    PUTBUCKETACL = 16
    GETBUCKETACL = 17
    GET_OBJ_MD = 18
    PUT_OBJ_MD = 19
    DEL_OBJ_MD = 20
    GET_BUCKET_MD = 21
    PUT_BUCKET_MD = 22
    DEL_BUCKET_MD = 23
    S3MPUCOMPLETE = 24
    NETBENCH = 25
    TPUBENCH = 26  # TPU-native: host<->HBM / ICI transfer benchmark
    TPUSLICE = 27  # pod-slice: sharded storage ingest + ICI redistribution


# human-readable phase names (reference: PHASENAME_*, Common.h:43-74)
PHASE_NAMES = {
    BenchPhase.IDLE: "IDLE",
    BenchPhase.TERMINATE: "QUIT",
    BenchPhase.CREATEDIRS: "MKDIRS",
    BenchPhase.DELETEDIRS: "RMDIRS",
    BenchPhase.CREATEFILES: "WRITE",
    BenchPhase.DELETEFILES: "RMFILES",
    BenchPhase.READFILES: "READ",
    BenchPhase.SYNC: "SYNC",
    BenchPhase.DROPCACHES: "DROPCACHE",
    BenchPhase.STATFILES: "STAT",
    BenchPhase.STATDIRS: "STATDIRS",
    BenchPhase.LISTOBJECTS: "LISTOBJ",
    BenchPhase.LISTOBJPARALLEL: "LISTOBJ_P",
    BenchPhase.MULTIDELOBJ: "MULTIDEL",
    BenchPhase.PUTOBJACL: "PUTOBJACL",
    BenchPhase.GETOBJACL: "GETOBJACL",
    BenchPhase.PUTBUCKETACL: "PUTBACL",
    BenchPhase.GETBUCKETACL: "GETBACL",
    BenchPhase.GET_OBJ_MD: "GETOBJMD",
    BenchPhase.PUT_OBJ_MD: "PUTOBJMD",
    BenchPhase.DEL_OBJ_MD: "DELOBJMD",
    BenchPhase.GET_BUCKET_MD: "GETBUCKETMD",
    BenchPhase.PUT_BUCKET_MD: "PUTBUCKETMD",
    BenchPhase.DEL_BUCKET_MD: "DELBUCKETMD",
    BenchPhase.S3MPUCOMPLETE: "MPUCOMPL",
    BenchPhase.NETBENCH: "NETBENCH",
    BenchPhase.TPUBENCH: "TPUBENCH",
    BenchPhase.TPUSLICE: "TPUSLICE",
}

#: phases the run journal (--journal) does NOT record: the sync/dropcaches
#: interleave is cheap, idempotent, and its effect (kernel cache state)
#: does not survive a crash anyway — a --resume re-runs it around the
#: first re-run phase instead of trusting stale records. Scenario plans
#: (--scenario) route their explicit sync/dropcaches legs through the
#: same set: a coldwarm resume must never replay a cache drop as
#: "finished work" (scenarios/plan.py ScenarioPlan.resume_runs decides
#: when such a leg re-executes: exactly when its following journaled
#: step does).
UNJOURNALED_PHASES = frozenset({
    BenchPhase.IDLE, BenchPhase.TERMINATE,
    BenchPhase.SYNC, BenchPhase.DROPCACHES,
})


# bucket-flavored names used in S3 mode (reference: MKBUCKETS/RMBUCKETS/...)
PHASE_NAMES_S3 = {
    BenchPhase.CREATEDIRS: "MKBUCKETS",
    BenchPhase.DELETEDIRS: "RMBUCKETS",
    BenchPhase.DELETEFILES: "RMOBJECTS",
    BenchPhase.STATFILES: "HEADOBJ",
}


class BenchPathType(enum.IntEnum):
    """Reference: enum BenchPathType, Common.h:200-207."""
    DIR = 0
    FILE = 1
    BLOCKDEV = 2


def phase_name(phase: BenchPhase, s3_mode: bool = False) -> str:
    if s3_mode and phase in PHASE_NAMES_S3:
        return PHASE_NAMES_S3[phase]
    return PHASE_NAMES[phase]


def phase_entry_type(phase: BenchPhase, s3_mode: bool = False) -> str:
    """"dirs"/"files"/"buckets"/"objects" for the given phase
    (reference: TranslatorTk::benchPhaseToPhaseEntryType)."""
    dir_phases = {BenchPhase.CREATEDIRS, BenchPhase.DELETEDIRS,
                  BenchPhase.STATDIRS, BenchPhase.PUTBUCKETACL,
                  BenchPhase.GETBUCKETACL, BenchPhase.PUT_BUCKET_MD,
                  BenchPhase.GET_BUCKET_MD, BenchPhase.DEL_BUCKET_MD}
    if phase in dir_phases:
        return "buckets" if s3_mode else "dirs"
    return "objects" if s3_mode else "files"


class BenchMode(enum.IntEnum):
    """Reference: enum BenchMode, Common.h:148-156."""
    UNDEFINED = 0
    POSIX = 1
    S3 = 2
    HDFS = 3
    NETBENCH = 4
