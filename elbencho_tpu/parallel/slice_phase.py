"""Pod-slice redistribution step: the SPMD core of the --tpuslice phase.

The phase models what a sharded-checkpoint restore actually does to a pod
slice (ROADMAP item 2; PAPERS.md arXiv 2112.01075 "Memory-efficient array
redistribution through portable collective communication"):

  1. every host STRIPES the dataset off storage and feeds each chip of
     the mesh its shard (storage -> staging pool -> HBM DMA, the same
     StagingPool + TransferPipeline data path the single-chip phases
     use — workers/tpuslice.py drives that part);
  2. the mesh then RESHARDS the stripe over ICI with one jitted identity
     step whose input and output shardings differ — XLA lowers the
     sharding change to the minimal collective schedule (all-gather /
     all-to-all style layouts per the --redistspec target);
  3. a second jitted step fingerprints the redistributed stripe fully
     on-device (uint32 sum + xor over the global array) so the phase can
     prove bytes survived ingest + redistribution exactly.

A stripe's global array has shape (n_devices, words_per_shard), uint32,
laid out P(("host", "chip"), None): row d lives on mesh device
``mesh.devices.flat[d]`` — one contiguous block of the stripe per chip,
so byte->shard mapping stays trivially auditable. The fingerprints are
order-independent (wrapping sum + xor), so they compare exactly against
the host-side numpy fingerprints of the bytes that were read, regardless
of target layout.

Redistribution targets (--redistspec):

  alltoall   P(None, ("host","chip")) — row-sharded -> column-sharded:
             every chip exchanges a slice with every other chip (the
             all-to-all reshard; memory per chip stays constant).
             The default.
  host       P("host", None) — chips of one host all-gather their
             host's rows over intra-host ICI (replicate-within-host,
             the optimizer-state restore layout).
  chip       P("chip", None) — rows resharded onto the chip axis and
             replicated across hosts (cross-host all-gather on top of
             an all-to-all).
  replicate  P(None, None) — full all-gather: every chip materializes
             the whole stripe (memory x n_devices; sized workloads only).
"""

from __future__ import annotations

import time

import numpy as np

#: valid --redistspec names (the PartitionSpec instances are created in
#: _target_spec so importing this module stays jax-free — config
#: validation reads this tuple without initializing jax)
REDIST_SPEC_NAMES = ("alltoall", "host", "chip", "replicate")


class MeshShapeError(ValueError):
    """Mesh geometry does not fit the device count / is malformed; the
    offending axis is named in the message. Converted to ConfigError at
    the config seam and to WorkerException at phase time. Lives here
    (not mesh.py) so config validation can parse --meshshape without
    importing jax."""


def parse_mesh_shape(spec: str) -> "tuple[int, int]":
    """"HxC" (hosts x chips, e.g. "2x4") -> (hosts, chips)."""
    parts = spec.lower().replace("*", "x").split("x")
    if len(parts) != 2:
        raise MeshShapeError(
            f"--meshshape must be HOSTSxCHIPS (e.g. 2x4), got {spec!r}")
    try:
        h, c = int(parts[0]), int(parts[1])
    except ValueError:
        raise MeshShapeError(
            f"--meshshape axes must be integers, got {spec!r}") from None
    if h < 1 or c < 1:
        raise MeshShapeError(
            f"--meshshape axes must be >= 1, got {spec!r}")
    return h, c


class SliceFingerprintError(RuntimeError):
    """On-device fingerprint of the redistributed stripe diverged from
    the host fingerprint of the ingested bytes — data corrupted on the
    ingest or redistribution path."""


def _target_spec(name: str):
    from jax.sharding import PartitionSpec as P
    if name == "alltoall":
        return P(None, ("host", "chip"))
    if name == "host":
        return P("host", None)
    if name == "chip":
        return P("chip", None)
    if name == "replicate":
        return P(None, None)
    raise ValueError(
        f"unknown --redistspec {name!r} ({'|'.join(REDIST_SPEC_NAMES)})")


class SliceRunner:
    """Jitted redistribute + fingerprint steps over one mesh, reused for
    every stripe of the phase (compile once, outside the timed loop via
    warmup()). Driven by the driver worker only — in a multi-host
    runtime every process's driver must construct the same runner over
    the same global mesh and call the steps in lockstep (single SPMD
    program, like workers/tpubench.CollectiveBench)."""

    def __init__(self, mesh, redist_spec: str, words_per_shard: int):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        self.n_devices = int(mesh.devices.size)
        self.words_per_shard = words_per_shard
        self.shard_bytes = words_per_shard * 4
        self.stripe_bytes = self.n_devices * self.shard_bytes
        self.redist_spec = redist_spec
        if redist_spec == "alltoall" and words_per_shard % self.n_devices:
            raise ValueError(
                f"--redistspec alltoall cuts each shard into "
                f"{self.n_devices} slices: block size {self.shard_bytes} "
                f"must be a multiple of {4 * self.n_devices} bytes "
                f"(4-byte words x {self.n_devices} devices)")
        self.src_sharding = NamedSharding(mesh, P(("host", "chip"), None))
        self.dst_sharding = NamedSharding(mesh, _target_spec(redist_spec))
        self.global_shape = (self.n_devices, words_per_shard)
        # device indices THIS process can place shards on: everything in
        # single-process runs, only the local chips of a multi-host pod
        # (each process supplies its addressable shards; jax stitches
        # the global array across processes)
        proc = jax.process_index()
        self.local_device_indices = [
            i for i, dev in enumerate(mesh.devices.flat)
            if dev.process_index == proc]

        @jax.jit
        def _fingerprint(x):
            import jax.numpy as jnp
            total = jnp.sum(x, dtype=jnp.uint32)
            # xor across shards via bit parity: xor of N words == per-bit
            # parity of the set-bit count, and ADD reductions lower to
            # collectives on every backend (a raw cross-shard xor
            # reduction is UNIMPLEMENTED on some, e.g. CPU) — the same
            # reason parallel/ingest.py all-gathers its per-shard xors
            xor = jnp.uint32(0)
            for b in range(32):
                parity = jnp.sum((x >> jnp.uint32(b)) & jnp.uint32(1),
                                 dtype=jnp.uint32) & jnp.uint32(1)
                xor = xor | (parity << jnp.uint32(b))
            return total, xor

        # identity whose output sharding differs from the input's: XLA
        # lowers the sharding change itself to the collective schedule
        # (the "redistribution as compilation" route of arXiv 2112.01075)
        self._redist = jax.jit(lambda x: x,
                               out_shardings=self.dst_sharding)
        self._fingerprint_fn = _fingerprint
        self._block_until_ready = jax.block_until_ready

    def assemble(self, shard_arrays: "dict[int, object]"):
        """Per-device shard arrays (device index -> (1, words) array on
        mesh.devices.flat[d]) -> the global sharded stripe array. Each
        process supplies exactly its ADDRESSABLE shards (all of them in
        a single-process run). The shards may still have transfers in
        flight — assembly is metadata-only and stays async."""
        import jax
        if sorted(shard_arrays) != self.local_device_indices:
            raise ValueError(
                f"stripe assembly needs one shard per addressable "
                f"device: got {sorted(shard_arrays)}, expected "
                f"{self.local_device_indices}")
        arrays = [shard_arrays[d] for d in self.local_device_indices]
        return jax.make_array_from_single_device_arrays(
            self.global_shape, self.src_sharding, arrays)

    def launch(self, global_arr) -> dict:
        """Dispatch the redistribution asynchronously; complete() waits
        and accounts. The returned handle carries the dispatch cost so
        --tpubudget can cover the SPMD path too.

        Timing: the driver deliberately completes stripe s only after
        stripe s+1's storage ingest (the overlap this phase measures),
        so dispatch->complete() wall time would charge the whole ingest
        window to ICI whenever storage is the slower leg. A watcher
        thread therefore stamps the moment the redistributed array
        actually materializes (block_until_ready releases the GIL, so
        the feeders keep running) — that dispatch->materialized window
        is what IciRedistUSec and the tpu_ici trace span record."""
        import threading
        t0 = time.perf_counter_ns()
        out = self._redist(global_arr)
        t1 = time.perf_counter_ns()
        handle = {"out": out, "t_submit_ns": t1,
                  "dispatch_usec": (t1 - t0) // 1000, "t_done_ns": 0}

        def _stamp_done():
            self._block_until_ready(out)
            handle["t_done_ns"] = time.perf_counter_ns()

        watcher = threading.Thread(target=_stamp_done, daemon=True,
                                   name="slice-ici-watch")
        handle["watcher"] = watcher
        watcher.start()
        return handle

    def complete(self, handle: dict) -> "tuple[int, int, int]":
        """Block until the redistribution drained, THEN fingerprint the
        redistributed stripe on-device; returns (device_sum, device_xor,
        wall_usec of the redistribution alone — dispatch to
        materialized, stamped by the launch watcher). The fingerprint's
        32-reduction sweep is a verify step, not interconnect traffic,
        so it stays out of the IciRedistUSec accounting."""
        handle["watcher"].join()
        usec = max((handle["t_done_ns"] - handle["t_submit_ns"]) // 1000,
                   1)
        total, xor = self._fingerprint_fn(handle["out"])
        return int(total), int(xor), usec

    def warmup(self) -> None:
        """Compile both steps outside any timed loop (persistent jit
        cache makes this cheap across short-lived bench processes).
        Built shard-by-shard like a real stripe so it works in a
        multi-host runtime too (a plain device_put with a sharding
        spanning non-addressable devices would not)."""
        import jax
        shard = np.zeros((1, self.words_per_shard), dtype=np.uint32)
        zeros = self.assemble({
            d: jax.device_put(shard, self.mesh.devices.flat[d])
            for d in self.local_device_indices})
        handle = self.launch(zeros)
        self.complete(handle)

    def verify(self, handle_sum: int, handle_xor: int,
               host_sum: int, host_xor: int, stripe_idx: int) -> None:
        """Fingerprint-exact check: the on-device (sum, xor) of the
        redistributed stripe vs the host fingerprints of the bytes read
        off storage. Only callable where the host side saw every shard
        (single-process runs; multi-host drivers log instead)."""
        if handle_sum != host_sum or handle_xor != host_xor:
            raise SliceFingerprintError(
                f"stripe {stripe_idx}: redistributed fingerprint "
                f"(sum={handle_sum:#x}, xor={handle_xor:#x}) != host "
                f"fingerprint of the ingested bytes (sum={host_sum:#x}, "
                f"xor={host_xor:#x}) — data corrupted on the "
                f"ingest/redistribution path")


def host_fingerprint(block_u32: np.ndarray) -> "tuple[int, int]":
    """Order-independent (wrapping uint32 sum, xor) of a host block —
    the reference side of the fingerprint-exact verify."""
    total = int(block_u32.sum(dtype=np.uint64) & 0xFFFFFFFF)
    xor = int(np.bitwise_xor.reduce(block_u32.reshape(-1))) \
        if block_u32.size else 0
    return total, xor
