"""jax API compatibility shims shared by the sharded code paths."""

from __future__ import annotations

import functools
import inspect


@functools.lru_cache(maxsize=1)
def _shard_map_fn_and_kwargs():
    """(shard_map callable, name of its replication-check kwarg).

    jax >= 0.8 promotes shard_map to ``jax.shard_map`` (the experimental
    path warns and is slated for removal) and renames ``check_rep`` to
    ``check_vma``; older releases only have the experimental symbol.
    """
    import jax
    fn = getattr(jax, "shard_map", None)
    if fn is None:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as fn
    params = inspect.signature(fn).parameters
    check_kw = "check_vma" if "check_vma" in params else "check_rep"
    return fn, check_kw


def shard_map(f, *, mesh, in_specs, out_specs, check_replication=True):
    fn, check_kw = _shard_map_fn_and_kwargs()
    return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              **{check_kw: check_replication})
