"""Sharded pod-wide ingest step: the multi-chip data path.

The reference's distributed story is "N hosts x T threads each hammer
storage; the master aggregates stats over HTTP" (SURVEY.md sections 2.3,
2.4). The TPU-native equivalent keeps storage I/O on the hosts but makes
the *device side* a single SPMD program over the whole pod slice:

  - ingested data is laid out sharded over a ("host", "chip") mesh;
  - each chip fingerprints and scrambles its own HBM-resident shard
    (integrity verify + block-variance refill, fully on-device);
  - global fingerprints reduce over ICI via ``jax.lax.psum`` — no
    HTTP/DCN round-trip in the data plane.

This module is exercised single-chip by ``__graft_entry__.entry()`` and
multi-chip by ``__graft_entry__.dryrun_multichip()`` (virtual CPU mesh).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_ingest_step(mesh: Mesh):
    """Build the jitted pod-wide ingest step.

    step(batch_u32, key) -> (scrambled_batch, checksum, xor)
      batch_u32: (rows, cols) uint32, sharded P("host", "chip")
      checksum/xor: global scalars (psum/reduce over the full mesh)
    """
    data_sharding = NamedSharding(mesh, P("host", "chip"))

    from ..models.workloads import scramble_fingerprint_core
    from .compat import shard_map

    def _per_shard(data, key):
        # fold the mesh position into the key so every shard scrambles
        # differently (deterministic across runs)
        h = jax.lax.axis_index("host")
        c = jax.lax.axis_index("chip")
        shard_key = jax.random.fold_in(jax.random.fold_in(key, h), c)
        scrambled, local_sum, local_xor = scramble_fingerprint_core(
            data, shard_key)
        total_sum = jax.lax.psum(local_sum, axis_name=("host", "chip"))
        # XOR has no psum analogue: all-gather the per-shard fingerprints
        # over ICI and fold locally (associative, replicated result)
        gathered = jax.lax.all_gather(local_xor, axis_name=("host", "chip"))
        total_xor = jax.lax.reduce(gathered, jnp.uint32(0),
                                   jax.lax.bitwise_xor, (0,))
        return scrambled, total_sum, total_xor

    sharded = shard_map(
        _per_shard, mesh=mesh,
        in_specs=(P("host", "chip"), P()),
        out_specs=(P("host", "chip"), P(), P()),
        # the xor fold over the all_gather result is replicated by
        # construction, but not statically inferable
        check_replication=False,
    )

    @functools.partial(jax.jit, donate_argnums=(0,),
                       in_shardings=(data_sharding, None),
                       out_shardings=(data_sharding, None, None))
    def step(batch, key):
        return sharded(batch, key)

    return step, data_sharding


def host_shard_to_devices(mesh: Mesh, batch_np):
    """Place a host batch onto the mesh with the ingest sharding
    (host->HBM DMA across all chips; the pod-wide analogue of the
    single-chip TpuWorkerContext.host_to_device)."""
    sharding = NamedSharding(mesh, P("host", "chip"))
    return jax.device_put(batch_np, sharding)
