"""Device mesh construction for pod-wide ingest.

The reference scales by hosts x threads over HTTP (SURVEY.md section 2.4);
the TPU-native scaling axis is a ``jax.sharding.Mesh`` over all chips of a
pod slice: the ("host", "chip") mesh mirrors the reference's
hosts-by-threads work partitioning, and XLA collectives over ICI replace
the master's stats aggregation for on-device reductions.
"""

from __future__ import annotations

import threading

import jax
import numpy as np
from jax.sharding import Mesh

from .slice_phase import MeshShapeError, parse_mesh_shape  # noqa: F401
# (re-exported: the mesh factory raises MeshShapeError; the definitions
# live in the jax-free slice_phase module so config validation can use
# them without initializing jax)


_multihost_lock = threading.Lock()
_multihost_initialized = False
_multihost_spec: "str | None" = None


def init_multihost(spec: str = "auto") -> bool:
    """Join this process into a multi-host JAX runtime so meshes span the
    whole pod slice (--tpumultihost; each service VM of a distributed run
    calls this before first device use).

    spec: "auto" lets the TPU runtime discover the coordinator (GCE TPU
    VMs); "host:port[,num_processes,process_id]" configures it manually
    (the master rewrites process_id per service host). Returns True when
    initialization ran, False when this process already joined. Real
    init failures (unreachable coordinator etc.) propagate — a silent
    single-host fallback would publish wrong pod-wide numbers.

    Idempotence is lock-safe under the threaded service harness: any
    number of worker threads (possibly of several in-process service
    instances) may race here during prepare; exactly one performs the
    initialize() call, the rest return False without touching jax. A
    failed initialize leaves the latch clear so the next prepare can
    retry. A runtime that was already initialized by another component
    ("already initialized" RuntimeError from jax) is adopted as joined
    instead of failing the phase.
    """
    global _multihost_initialized, _multihost_spec
    kwargs = {}
    if spec and spec != "auto":
        parts = spec.split(",")
        kwargs["coordinator_address"] = parts[0]
        if len(parts) > 1:
            kwargs["num_processes"] = int(parts[1])
        if len(parts) > 2:
            kwargs["process_id"] = int(parts[2])
    with _multihost_lock:  # worker threads prep concurrently
        if _multihost_initialized:
            if _multihost_spec != spec:
                from ..toolkits.logger import LOG_NORMAL, log
                log(LOG_NORMAL,
                    f"NOTE: --tpumultihost {spec!r} ignored — this process "
                    f"already joined the multi-host runtime with "
                    f"{_multihost_spec!r} (one runtime per process)")
            return False
        ran = True
        try:
            jax.distributed.initialize(**kwargs)
        except RuntimeError as err:
            if "already" not in str(err).lower():
                raise
            # another component (e.g. a prior in-process service run)
            # initialized the runtime; adopt it as joined
            ran = False
        _multihost_initialized = True
        _multihost_spec = spec
        return ran


def make_ingest_mesh(devices: "list | None" = None,
                     num_hosts: "int | None" = None,
                     shape: "tuple[int, int] | None" = None) -> Mesh:
    """2D ("host", "chip") mesh over the given devices.

    On a real pod slice the "host" axis matches process boundaries
    (jax.process_count()); on a flat single-host set (or the virtual CPU
    mesh) the devices are factored into the most balanced 2D grid so both
    axes are exercised. An explicit ``shape`` (hosts, chips) — the
    --meshshape knob — must cover the device count exactly; a
    non-divisible geometry raises MeshShapeError naming the offending
    axis instead of surfacing as an XLA reshape error deep in the phase.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is not None:
        num_hosts, chips_per_host = shape
        if num_hosts * chips_per_host != n:
            # name the axis that cannot be satisfied so the error is
            # actionable: the host axis when it alone exceeds/misfits
            # the device count, else the chip axis
            if n % num_hosts:
                axis, size = "host", num_hosts
            else:
                axis, size = "chip", chips_per_host
            raise MeshShapeError(
                f"--meshshape {num_hosts}x{chips_per_host} does not fit "
                f"{n} device(s): the \"{axis}\" axis of size {size} "
                f"requires hosts*chips == {n}")
    else:
        if num_hosts is None:
            num_hosts = jax.process_count() if jax.process_count() > 1 \
                else None
        if num_hosts is None:
            # most balanced factorization h*c == n with h <= c
            num_hosts = 1
            for h in range(int(np.sqrt(n)), 0, -1):
                if n % h == 0:
                    num_hosts = h
                    break
        if n % num_hosts:
            raise MeshShapeError(
                f"device count {n} is not divisible by the \"host\" axis "
                f"({num_hosts} processes): every host must own the same "
                f"number of chips for the (\"host\", \"chip\") mesh")
        chips_per_host = n // num_hosts
    grid = np.array(devices[:num_hosts * chips_per_host]).reshape(
        num_hosts, chips_per_host)
    return Mesh(grid, axis_names=("host", "chip"))
