"""Device mesh construction for pod-wide ingest.

The reference scales by hosts x threads over HTTP (SURVEY.md section 2.4);
the TPU-native scaling axis is a ``jax.sharding.Mesh`` over all chips of a
pod slice: the ("host", "chip") mesh mirrors the reference's
hosts-by-threads work partitioning, and XLA collectives over ICI replace
the master's stats aggregation for on-device reductions.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


_multihost_lock = __import__("threading").Lock()
_multihost_initialized = False


def init_multihost(spec: str = "auto") -> bool:
    """Join this process into a multi-host JAX runtime so meshes span the
    whole pod slice (--tpumultihost; each service VM of a distributed run
    calls this before first device use).

    spec: "auto" lets the TPU runtime discover the coordinator (GCE TPU
    VMs); "host:port[,num_processes,process_id]" configures it manually
    (the master rewrites process_id per service host). Returns True when
    initialization ran, False when this process already joined. Real
    init failures (unreachable coordinator etc.) propagate — a silent
    single-host fallback would publish wrong pod-wide numbers.
    """
    global _multihost_initialized
    kwargs = {}
    if spec and spec != "auto":
        parts = spec.split(",")
        kwargs["coordinator_address"] = parts[0]
        if len(parts) > 1:
            kwargs["num_processes"] = int(parts[1])
        if len(parts) > 2:
            kwargs["process_id"] = int(parts[2])
    with _multihost_lock:  # worker threads prep concurrently
        if _multihost_initialized:
            return False
        jax.distributed.initialize(**kwargs)
        _multihost_initialized = True
        return True


def make_ingest_mesh(devices: "list | None" = None,
                     num_hosts: "int | None" = None) -> Mesh:
    """2D ("host", "chip") mesh over the given devices.

    On a real pod slice the "host" axis matches process boundaries
    (jax.process_count()); on a flat single-host set (or the virtual CPU
    mesh) the devices are factored into the most balanced 2D grid so both
    axes are exercised.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if num_hosts is None:
        num_hosts = jax.process_count() if jax.process_count() > 1 else None
    if num_hosts is None:
        # most balanced factorization h*c == n with h <= c
        num_hosts = 1
        for h in range(int(np.sqrt(n)), 0, -1):
            if n % h == 0:
                num_hosts = h
                break
    chips_per_host = n // num_hosts
    grid = np.array(devices[:num_hosts * chips_per_host]).reshape(
        num_hosts, chips_per_host)
    return Mesh(grid, axis_names=("host", "chip"))
