"""Run journal: fsync'd append-only record of a benchmark run's lifecycle.

A multi-hour sweep that dies at phase 9 of 10 should resume, not restart
(PAPERS.md "Optimizing High-Throughput Distributed Data Pipelines for
Reproducible Deep Learning at Scale": long benchmark campaigns need
journaled, reproducible restart points). ``--journal FILE`` makes the
coordinator append one JSON line per lifecycle event:

- ``run_start``    — config fingerprint, version, label, planned phases
- ``phase_start``  — (iteration, phase index, phase code/name)
- ``phase_finish`` — same key plus per-host result summaries
- ``phase_interrupted`` — a phase cut short by signal/error/crash
- ``resume``       — a ``--resume`` run took over this journal
- ``run_complete`` — terminal record; nothing left to resume

Every append is flushed AND fsync'd before the phase proceeds, so the
journal is trustworthy after a SIGKILL: the absence of a ``phase_finish``
record *proves* the phase did not complete.

``--resume`` replays the journal (`load_resume_plan`): the config
fingerprint must match (a changed workload would make the old phase
records meaningless — hard `ConfigError`), phases with ``finish`` records
are skipped, and the first incomplete phase re-runs from scratch (the
``partial_write`` hint lets delete/overwrite phases tolerate the partial
dataset the interrupted write left behind, workers/shared.py
``partial_dataset``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time

from .config.args import ConfigError
from .phases import BenchPhase

#: journal record types (the ``rec`` key of each JSONL line)
REC_RUN_START = "run_start"
REC_PHASE_START = "phase_start"
REC_PHASE_FINISH = "phase_finish"
REC_PHASE_INTERRUPTED = "phase_interrupted"
REC_RESUME = "resume"
REC_RUN_COMPLETE = "run_complete"
#: master-failover records (docs/fault-tolerance.md "Master failover"):
#: ``fleet`` pins the service topology + the run's secret takeover
#: token (possession of the journal IS the authorization to /adopt);
#: ``takeover`` marks the point a --resume --adopt master claimed the
#: fleet. Replay ignores record types it does not know, so journals
#: with these records stay readable by older readers and vice versa.
REC_FLEET = "fleet"
REC_TAKEOVER = "takeover"

#: config fields excluded from the fingerprint: outputs, observability,
#: and control-plane resilience knobs shape how a run is *watched*, not
#: what data it produces — changing them between the original run and a
#: --resume must not invalidate the journal. Everything else (workload
#: geometry, access pattern, backends, TPU path, hosts) is
#: parity-relevant: finished-phase records only transfer to an
#: identical workload.
FINGERPRINT_EXCLUDE = frozenset({
    # the journal/resume machinery itself
    "journal_file_path", "resume_run",
    # autotune search knobs: probes are unjournaled and the search is
    # master-side orchestration — the values the tuner APPLIES mutate
    # live config after the fingerprint is taken, and --resume next to
    # --autotune is rejected outright (args.check)
    "autotune_secs", "autotune_profile_path", "autotune_probes",
    "autotune_probe_secs", "autotune_repeat",
    # result/observability outputs
    "res_file_path", "csv_file_path", "json_file_path", "no_csv_labels",
    "live_csv_file_path", "live_json_file_path", "live_csv_extended",
    "live_json_extended", "live_stats_interval_ms",
    "use_single_line_live_stats", "single_line_live_stats_no_erase",
    "disable_live_stats", "show_latency", "show_latency_histogram",
    "show_latency_percentiles", "num_latency_percentile_9s",
    "show_all_elapsed", "show_cpu_util", "show_svc_elapsed",
    "show_svc_ping", "ignore_0usec_errors", "log_level",
    "ops_log_path", "ops_log_lock", "telemetry", "telemetry_port",
    "trace_file_path", "trace_sample", "trace_fleet",
    "trace_ship_cap_mib", "flightrec_file_path",
    "slow_ops_k", "op_sample_rate",
    "tpu_profile_dir",
    # control-plane resilience knobs (retry shape, not data shape)
    "svc_num_retries", "svc_retry_budget_secs", "svc_stalled_secs",
    "svc_tolerant_hosts", "svc_lease_secs", "svc_update_interval_ms",
    "svc_wait_secs", "svc_password_file",
    # master failover: the takeover machinery must not invalidate the
    # journal it resumes from — a --resume --adopt (or a standby's
    # auto-takeover) replays the SAME workload by definition
    "svc_adopt_secs", "adopt_run", "standby_str",
    # streaming control plane: pure transport (polling parity when off),
    # so a --resume may freely flip stream/tree shape
    "svc_stream", "svc_fanout",
    # role/oneshot flags a resumed master run never carries differently
    "run_as_service", "run_service_in_foreground", "quit_services",
    "interrupt_services", "do_dry_run", "config_file_path",
    # hosts ship as the DERIVED list below, not the raw spellings
    "hosts_str", "hosts_file_path",
})


def config_fingerprint(cfg) -> str:
    """Stable hash of the parity-relevant effective config. Derived from
    the post-derive() state so ``--hosts a,b`` and a hosts file listing
    the same hosts fingerprint identically, and POSIX bench paths are
    absolutized so ``data.bin`` and ``/cwd/data.bin`` name the same
    dataset (while the same relative spelling from a DIFFERENT cwd — a
    genuinely different dataset — correctly mismatches)."""
    from .phases import BenchMode
    vals: "dict[str, object]" = {}
    for f in dataclasses.fields(cfg):
        if f.name in FINGERPRINT_EXCLUDE:
            continue
        vals[f.name] = getattr(cfg, f.name)
    paths = list(getattr(cfg, "paths", []))
    if getattr(cfg, "bench_mode", None) == BenchMode.POSIX \
            and not getattr(cfg, "hosts", []):
        # master-mode paths live on the service hosts — absolutizing
        # against the MASTER's cwd would be meaningless there
        paths = [os.path.abspath(p) for p in paths]
    vals["paths"] = paths
    vals["hosts"] = list(getattr(cfg, "hosts", []))
    if getattr(cfg, "scenario", ""):
        # fingerprint the EXPANDED plan, not just the scenario name +
        # knob string: a changed built-in expansion (new default epoch
        # count, reordered steps in a newer version) must mismatch —
        # the journal's (iteration, index) records are only meaningful
        # against the exact step list they were written for
        from .scenarios import expand_scenario
        vals["scenario_plan"] = expand_scenario(cfg).describe()
    blob = json.dumps(vals, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


class RunJournal:
    """Append-only JSONL writer; every record is fsync'd before the run
    proceeds so a later --resume can trust what it reads."""

    def __init__(self, path: str, cfg):
        self.path = path
        self.cfg = cfg
        self.fingerprint = config_fingerprint(cfg)
        self._fh = None

    # -- low-level append ---------------------------------------------------

    def _append(self, rec_type: str, **fields) -> None:
        if self._fh is None:
            self._fh = open(self.path, "a")
        rec = {"rec": rec_type,
               "ts": time.strftime("%Y-%m-%dT%H:%M:%S%z"), **fields}
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    # -- lifecycle records --------------------------------------------------

    def start_fresh(self, phases, iterations: int,
                    scenario: "dict | None" = None) -> None:
        """Begin a NEW journaled run at this path. An existing journal
        holding an INCOMPLETE run is refused (it is a restart point —
        resume it with --resume or remove the file); a completed one is
        truncated. Appending a second run's records after a first would
        poison every later --resume replay (stale run_complete /
        phase_finish records masquerading as the new run's)."""
        if os.path.exists(self.path) and os.path.getsize(self.path):
            try:
                records = read_journal(self.path)
            except ConfigError:
                raise ConfigError(
                    f"--journal: {self.path} exists but is not a journal "
                    f"(undecodable lines); refusing to overwrite it — "
                    f"remove the file or pick another path") from None
            if records and not any(r.get("rec") == REC_RUN_COMPLETE
                                   for r in records):
                raise ConfigError(
                    f"--journal: {self.path} holds an INCOMPLETE run — "
                    f"resume it with --resume, or remove the file to "
                    f"start over")
            os.truncate(self.path, 0)
        self.run_start(phases, iterations, scenario)

    def run_start(self, phases, iterations: int,
                  scenario: "dict | None" = None) -> None:
        from . import __version__
        from .phases import phase_name
        fields = {}
        if scenario is not None:
            # the expanded scenario plan (scenarios.ScenarioPlan
            # .describe()): human-readable restart context — the binding
            # contract is the fingerprint, which hashes the same plan
            fields["scenario"] = scenario
        self._append(REC_RUN_START,
                     fingerprint=self.fingerprint,
                     version=__version__,
                     label=self.cfg.bench_label,
                     iterations=iterations,
                     phases=[{"code": int(p), "name": phase_name(p)}
                             for p in phases],
                     **fields)

    def resume(self, num_skipped: int) -> None:
        self._append(REC_RESUME, fingerprint=self.fingerprint,
                     skipped_phases=num_skipped)

    def fleet(self, hosts: "list[str]", takeover_token: str) -> None:
        """Fleet topology + the run's takeover token, written once after
        run_start on journaled master runs. The token is minted fresh
        per run and never printed; whoever holds the journal file holds
        the credential a service requires on /adopt."""
        self._append(REC_FLEET, hosts=list(hosts),
                     takeover_token=takeover_token)

    def takeover(self, num_adopted_hosts: int,
                 inflight: "dict | None") -> None:
        """A --resume --adopt run claimed the fleet: journal-append the
        takeover point so a SECOND takeover (or a post-mortem) sees
        where the run changed masters."""
        self._append(REC_TAKEOVER, fingerprint=self.fingerprint,
                     adopted_hosts=num_adopted_hosts,
                     inflight=inflight or {})

    @staticmethod
    def _step_fields(step_label: str) -> dict:
        # scenario runs label their phase records with the step identity
        # ("epoch2", "ckpt1.save"); resume matching stays on
        # (iteration, index) so the label is context, not contract
        return {"step": step_label} if step_label else {}

    def phase_start(self, iteration: int, idx: int, phase: BenchPhase,
                    step_label: str = "", bench_uuid: str = "") -> None:
        from .phases import phase_name
        fields = self._step_fields(step_label)
        if bench_uuid:
            # master runs pre-mint the phase's bench UUID and journal it
            # BEFORE /startphase, so an adopting master can present the
            # exact UUID the fleet is running under — the service-side
            # duplicate-start idempotency then makes re-starting the
            # in-flight phase a provable no-op
            fields["bench_uuid"] = bench_uuid
        self._append(REC_PHASE_START, iteration=iteration, index=idx,
                     code=int(phase), name=phase_name(phase), **fields)

    def phase_finish(self, iteration: int, idx: int, phase: BenchPhase,
                     host_summaries: "dict[str, dict]",
                     step_label: str = "") -> None:
        from .phases import phase_name
        self._append(REC_PHASE_FINISH, iteration=iteration, index=idx,
                     code=int(phase), name=phase_name(phase),
                     hosts=host_summaries, **self._step_fields(step_label))

    def phase_interrupted(self, iteration: int, idx: int,
                          phase: BenchPhase, reason: str,
                          step_label: str = "") -> None:
        from .phases import phase_name
        self._append(REC_PHASE_INTERRUPTED, iteration=iteration, index=idx,
                     code=int(phase), name=phase_name(phase), reason=reason,
                     **self._step_fields(step_label))

    def run_complete(self) -> None:
        self._append(REC_RUN_COMPLETE, fingerprint=self.fingerprint)


# ---------------------------------------------------------------------------
# resume replay
# ---------------------------------------------------------------------------

#: phases whose interruption leaves the dataset partial: an unfinished
#: write leaves missing entries behind, an unfinished delete leaves
#: already-deleted ones — either way the re-run must tolerate absences
_PARTIAL_DATASET_PHASES = frozenset({
    int(BenchPhase.CREATEFILES), int(BenchPhase.DELETEFILES),
    int(BenchPhase.DELETEDIRS), int(BenchPhase.MULTIDELOBJ),
})


@dataclasses.dataclass
class ResumePlan:
    """What a --resume run skips and what it must tolerate."""

    #: (iteration, phase index) pairs with a phase_finish record
    finished: "set[tuple[int, int]]"
    #: a write or delete phase started (or was interrupted) without
    #: finishing: the dataset on disk is partial, so the re-run's
    #: delete/overwrite work must tolerate missing entries
    #: (workers/shared.py partial_dataset latch)
    partial_dataset: bool
    #: terminal run_complete record present — nothing to resume
    run_complete: bool
    #: the journal's takeover token (fleet record; "" on journals from
    #: non-master or pre-failover runs) — the /adopt credential
    takeover_token: str = ""
    #: the journaled fleet topology ([] when no fleet record)
    fleet_hosts: "list[str]" = dataclasses.field(default_factory=list)
    #: the in-flight phase a --resume --adopt can take over: the LAST
    #: phase_start with neither a finish nor an interrupted record, as
    #: {"iteration", "index", "code", "name", "step", "bench_uuid"} —
    #: None when every started phase terminated (a deliberately
    #: interrupted phase is NOT adoptable: the dying master already
    #: tore its workers down)
    inflight: "dict | None" = None

    @property
    def num_finished(self) -> int:
        return len(self.finished)


def read_journal(path: str) -> "list[dict]":
    """All records of a journal file; a torn final line (crash mid-append)
    is dropped rather than failing the whole replay."""
    records: "list[dict]" = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                # only the LAST line may legitimately be torn; garbage in
                # the middle means the file is not a journal
                records.append(None)
    while records and records[-1] is None:
        records.pop()
    if any(r is None for r in records):
        raise ConfigError(
            f"--resume: {path} contains undecodable lines before the "
            f"end — not a journal written by --journal?")
    return records


def load_resume_plan(path: str, cfg) -> ResumePlan:
    """Replay a journal for --resume. Hard ConfigError when the file is
    missing/empty or the config fingerprint mismatches: resuming a
    different workload would silently mix incompatible datasets."""
    if not os.path.exists(path):
        raise ConfigError(f"--resume: journal file not found: {path}")
    records = read_journal(path)
    if not records:
        raise ConfigError(f"--resume: journal file is empty: {path}")
    start = next((r for r in records if r.get("rec") == REC_RUN_START), None)
    if start is None:
        raise ConfigError(
            f"--resume: {path} has no {REC_RUN_START} record")
    want = config_fingerprint(cfg)
    got = start.get("fingerprint", "")
    if got != want:
        raise ConfigError(
            f"--resume: config fingerprint mismatch — the journal was "
            f"written for a different workload (journal {got[:16]}..., "
            f"current {want[:16]}...). Re-run with the original "
            f"arguments, or start a fresh journal.")
    finished: "set[tuple[int, int]]" = set()
    started: "set[tuple[int, int]]" = set()
    started_code: "dict[tuple[int, int], int]" = {}
    start_recs: "dict[tuple[int, int], dict]" = {}
    interrupted: "set[tuple[int, int]]" = set()
    complete = False
    takeover_token = ""
    fleet_hosts: "list[str]" = []
    for rec in records:
        key = (rec.get("iteration", 0), rec.get("index", 0))
        if rec.get("rec") == REC_PHASE_FINISH:
            finished.add(key)
        elif rec.get("rec") == REC_PHASE_START:
            started.add(key)
            started_code[key] = rec.get("code", 0)
            start_recs[key] = rec
        elif rec.get("rec") == REC_PHASE_INTERRUPTED:
            interrupted.add(key)
        elif rec.get("rec") == REC_RUN_COMPLETE:
            complete = True
        elif rec.get("rec") == REC_FLEET:
            takeover_token = rec.get("takeover_token", "")
            fleet_hosts = list(rec.get("hosts", []))
    # a write/delete phase that started (or was interrupted) without
    # finishing left a partial dataset behind
    partial_dataset = any(
        started_code.get(key) in _PARTIAL_DATASET_PHASES
        for key in started - finished)
    # the adoptable in-flight phase: started, never finished, never
    # deliberately interrupted — a SIGKILL'd master writes neither
    inflight = None
    for key in sorted(started - finished - interrupted):
        rec = start_recs[key]
        inflight = {"iteration": key[0], "index": key[1],
                    "code": rec.get("code", 0),
                    "name": rec.get("name", ""),
                    "step": rec.get("step", ""),
                    "bench_uuid": rec.get("bench_uuid", "")}
    return ResumePlan(finished=finished, partial_dataset=partial_dataset,
                      run_complete=complete,
                      takeover_token=takeover_token,
                      fleet_hosts=fleet_hosts, inflight=inflight)
