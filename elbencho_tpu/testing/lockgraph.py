"""Runtime lock-order detector for ELBENCHO_TPU_TESTING=1 fleets.

The control plane went threaded in PR 8 (ThreadingHTTPServer +
``route_lock`` + the lease watchdog + stream push sessions) and the
native engine got ``make tsan`` for its races — but the Python side
never got a race detector of its own. This module is that detector,
for the one class of Python-level concurrency bug the GIL does NOT
forgive: lock-order inversions (deadlocks) and blocking RPCs issued
while holding the control-plane route lock.

Armed (``install()``), it:

- wraps ``threading.Lock`` / ``threading.RLock`` construction so every
  lock created afterwards is tracked. Locks are identified by their
  CREATION SITE (``file:line (name)``), not object identity — two
  processes of the same fleet then agree on node names, which is what
  makes the merged graph *fleet-wide*;
- records, per thread, the stack of currently-held locks and adds a
  site-A -> site-B order edge whenever B is acquired under A (edges
  between two locks of the SAME site are skipped: per-instance locks of
  one class cannot be ordered by site identity);
- checks each new edge against the accumulated graph and records a
  violation when it closes a cycle — the classic ABBA inversion, caught
  even when the interleaving never actually deadlocked this run;
- wraps ``http.client.HTTPConnection.request`` (the
  transport under ``RemoteWorker``, the stream relays and gcs_tk) and
  records a violation when a thread drives them while holding the
  service ``route_lock`` — a parked peer would then stall every control
  route for the full request timeout (exactly the bug the
  /interruptphase subtree forwarding had before it moved out from
  under the lock, service/http_service.py do_GET);
- dumps its edge list + violations as JSON into
  ``$ELBENCHO_TPU_LOCKGRAPH_DIR`` at process exit, and
  ``merge_check()`` unions the dumps of every fleet process (master +
  service subprocesses, see ``__main__.py``) and re-runs cycle
  detection on the union — an order established master-side and
  reversed service-side is a real inversion even though neither
  process saw both edges.

Arming is an explicit test-harness opt-in, the same contract as the
slowops/tracefleet injection seams: ``ELBENCHO_TPU_TESTING=1`` plus
either the pytest session fixture (tests/conftest.py, enabled by
``ELBENCHO_TPU_LOCKGRAPH=1``, e.g. via ``make test-chaos``) or, for
fleet subprocesses, ``ELBENCHO_TPU_LOCKGRAPH_DIR`` inherited through
the service environment. Production runs never import this module.

Violations are RECORDED, not raised at the acquisition site: raising
inside a service route would tear down the very run whose interleaving
is the evidence. The armed suites fail at session teardown with every
cycle and route-lock RPC spelled out (conftest), and unit tests assert
on ``violations()`` directly.
"""

from __future__ import annotations

import atexit
import http.client
import json
import linecache
import os
import re
import threading
import _thread

ENV_TESTING = "ELBENCHO_TPU_TESTING"
ENV_DUMP_DIR = "ELBENCHO_TPU_LOCKGRAPH_DIR"

#: creation-site source lines matching this are flagged as THE route
#: lock (service/http_service.py names the attribute route_lock); tests
#: use mark_route_lock() instead of replaying the naming convention
_ROUTE_LOCK_RE = re.compile(r"\broute_lock\b")
_ASSIGN_RE = re.compile(r"([A-Za-z_][\w.]*)\s*=[^=]")

# the detector's own state lock comes straight from _thread so it is
# never itself tracked (tracking it would re-enter the bookkeeping)
_state_lock = _thread.allocate_lock()
_tls = threading.local()

_installed = False
_orig_lock = None
_orig_rlock = None
_orig_request = None

#: site -> set of successor sites (the order graph), with one sample
#: (thread name, held-stack) per edge for the failure message
_edges: "dict[str, set[str]]" = {}
_edge_samples: "dict[tuple[str, str], str]" = {}
_violations: "list[dict]" = []
_seen_cycles: "set[frozenset]" = set()


class LockOrderError(AssertionError):
    """Raised by merge_check(strict=True) / the conftest teardown when
    the armed run recorded a lock-order cycle or a route-lock RPC."""


# -- tracked lock wrapper ----------------------------------------------------

class _TrackedLock:
    """Wraps one _thread lock/RLock. Forwards the Condition integration
    surface (_is_owned/_acquire_restore/_release_save) so
    threading.Condition treats it exactly like the raw lock."""

    def __init__(self, raw, site: str, is_route: bool):
        self._raw = raw
        self.lg_site = site
        self.lg_is_route = is_route

    def __repr__(self):
        return f"<lockgraph {self.lg_site} wrapping {self._raw!r}>"

    def acquire(self, blocking=True, timeout=-1):
        got = self._raw.acquire(blocking, timeout)
        if got:
            _note_acquired(self)
        return got

    def release(self):
        _note_released(self)
        self._raw.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._raw.locked()

    # Condition(lock) integration: Condition lifts these off the lock
    # when present; the RLock forms must keep our per-thread bookkeeping
    # in step with the full release/reacquire around wait()
    def _is_owned(self):
        if hasattr(self._raw, "_is_owned"):
            return self._raw._is_owned()
        if self._raw.acquire(False):
            self._raw.release()
            return False
        return True

    def _release_save(self):
        _note_released(self, all_depths=True)
        if hasattr(self._raw, "_release_save"):
            return self._raw._release_save()
        self._raw.release()
        return None

    def _acquire_restore(self, state):
        if hasattr(self._raw, "_acquire_restore"):
            self._raw._acquire_restore(state)
        else:
            self._raw.acquire()
        _note_acquired(self)


def _creation_site() -> "tuple[str, bool]":
    """``file:line (target)`` of the frame that called threading.Lock /
    threading.RLock, plus whether the source line names route_lock.
    Frames inside threading.py itself are skipped so a
    ``threading.Condition()`` (whose RLock is minted inside
    ``Condition.__init__``) attributes to the USER call site — otherwise
    every Condition in the fleet would collapse onto one threading.py
    node and their mutual ordering would be invisible."""
    import sys
    frame = sys._getframe(2)  # caller -> factory -> here
    thr_file = getattr(threading, "__file__", "")
    while frame.f_back is not None \
            and frame.f_code.co_filename == thr_file:
        frame = frame.f_back
    fname = frame.f_code.co_filename
    lineno = frame.f_lineno
    text = linecache.getline(fname, lineno).strip()
    short = os.sep.join(fname.split(os.sep)[-3:])
    m = _ASSIGN_RE.match(text)
    label = f" ({m.group(1)})" if m else ""
    return f"{short}:{lineno}{label}", bool(_ROUTE_LOCK_RE.search(text))


def _make_lock():
    site, is_route = _creation_site()
    return _TrackedLock(_orig_lock(), site, is_route)


def _make_rlock():
    site, is_route = _creation_site()
    return _TrackedLock(_orig_rlock(), site, is_route)


def mark_route_lock(lock) -> None:
    """Flag a tracked lock as the route lock (unit tests; production
    detection rides the creation-site source line)."""
    lock.lg_is_route = True


# -- per-thread bookkeeping + graph ------------------------------------------

#: id(lock) -> owning thread ident, for 0->1 holds only. A plain Lock
#: may legally be released by a DIFFERENT thread (handoff patterns);
#: the owner map lets the original thread prune such stale stack
#: entries instead of attributing every later acquisition to them.
_owners: "dict[int, int]" = {}


def _held_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []   # [lock, ...] outermost first
        _tls.depth = {}           # id(lock) -> reentrancy count
    return stack


def _prune_stack(stack: list) -> None:
    me = threading.get_ident()
    stale = [lk for lk in stack if _owners.get(id(lk)) != me]
    for lk in stale:
        stack.remove(lk)
        _tls.depth.pop(id(lk), None)


def _note_acquired(lock: "_TrackedLock") -> None:
    stack = _held_stack()
    depth = _tls.depth
    key = id(lock)
    me = threading.get_ident()
    if depth.get(key) and _owners.get(key) == me:
        depth[key] += 1
        return  # reentrant re-acquire: no new ordering information
    # fresh hold — including a re-acquire after a cross-thread release
    # invalidated our stale bookkeeping (depth says held, owner map
    # says not ours): re-register, or the hold would be invisible to
    # the route-lock check and record no order edges
    depth[key] = 1
    with _state_lock:
        _owners[key] = me
    if lock in stack:
        stack.remove(lock)
    _prune_stack(stack)
    for held in stack:
        _add_edge(held, lock)
    stack.append(lock)


def _note_released(lock: "_TrackedLock", all_depths: bool = False) -> None:
    stack = _held_stack()
    depth = _tls.depth
    key = id(lock)
    if key not in depth:
        # released by a thread that never acquired it (cross-thread
        # handoff): clear the owner so the acquirer prunes its entry
        with _state_lock:
            _owners.pop(key, None)
        return
    depth[key] = 0 if all_depths else depth[key] - 1
    if depth[key] <= 0:
        del depth[key]
        with _state_lock:
            _owners.pop(key, None)
        try:
            stack.remove(lock)
        except ValueError:
            pass


def _add_edge(a: "_TrackedLock", b: "_TrackedLock") -> None:
    if a is b or a.lg_site == b.lg_site:
        return  # same creation site: not orderable by site identity
    with _state_lock:
        succ = _edges.setdefault(a.lg_site, set())
        if b.lg_site in succ:
            return
        succ.add(b.lg_site)
        _edge_samples[(a.lg_site, b.lg_site)] = threading.current_thread().name
        cycle = _find_cycle(_edges, b.lg_site, a.lg_site)
        if cycle:
            _record_cycle(cycle + [b.lg_site],
                          threading.current_thread().name)


def _find_cycle(edges: "dict[str, set[str]]", start: str,
                target: str) -> "list[str] | None":
    """Path start -> ... -> target through ``edges`` (DFS), or None.
    Called with the just-added edge target->start already in the graph,
    so a hit means a cycle."""
    seen = set()
    path: "list[str]" = []

    def dfs(node: str) -> bool:
        if node == target:
            path.append(node)
            return True
        if node in seen:
            return False
        seen.add(node)
        for nxt in edges.get(node, ()):
            if dfs(nxt):
                path.append(node)
                return True
        return False

    if dfs(start):
        return list(reversed(path))
    return None


def _record_cycle(cycle: "list[str]", thread_name: str,
                  source: str = "") -> None:
    ident = frozenset(cycle)
    if ident in _seen_cycles:
        return
    _seen_cycles.add(ident)
    _violations.append({
        "kind": "lock-order-cycle",
        "cycle": cycle,
        "thread": thread_name,
        **({"source": source} if source else {}),
    })


# -- route_lock across a blocking service request ----------------------------

def _route_lock_held() -> "str | None":
    me = threading.get_ident()
    for lock in getattr(_tls, "stack", ()) or ():
        if lock.lg_is_route and _owners.get(id(lock)) == me:
            return lock.lg_site
    return None


def _check_route_rpc(what: str) -> None:
    site = _route_lock_held()
    if site is None:
        return
    with _state_lock:
        _violations.append({
            "kind": "route-lock-across-request",
            "route_lock": site,
            "request": what,
            "thread": threading.current_thread().name,
        })


def _patched_request(self, method, url, *args, **kwargs):
    # one violation per exchange: the send is where the thread commits
    # to waiting on the peer (getresponse blocks on the same socket)
    _check_route_rpc(f"{method} {url.split('?')[0]}")
    return _orig_request(self, method, url, *args, **kwargs)


# -- install / dump / merge --------------------------------------------------

def install() -> None:
    """Arm the detector in THIS process. Idempotent. Locks created
    before arming stay untracked (module-import locks: logging etc.) —
    the control-plane locks all come up with ServiceState / the worker
    pool, well after arming."""
    global _installed, _orig_lock, _orig_rlock, _orig_request
    if _installed:
        return
    _orig_lock = threading.Lock
    _orig_rlock = threading.RLock
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    _orig_request = http.client.HTTPConnection.request
    http.client.HTTPConnection.request = _patched_request
    if os.environ.get(ENV_DUMP_DIR):
        atexit.register(dump)
    _installed = True


def uninstall() -> None:
    """Restore the patched factories. Locks already created keep
    working (they wrap real primitives); they just stop reporting."""
    global _installed
    if not _installed:
        return
    threading.Lock = _orig_lock
    threading.RLock = _orig_rlock
    http.client.HTTPConnection.request = _orig_request
    _installed = False


def reset() -> None:
    """Drop accumulated edges/violations (unit-test isolation)."""
    with _state_lock:
        _edges.clear()
        _edge_samples.clear()
        _violations.clear()
        _seen_cycles.clear()


def installed() -> bool:
    return _installed


def violations() -> "list[dict]":
    with _state_lock:
        return list(_violations)


def edges() -> "list[tuple[str, str]]":
    with _state_lock:
        return sorted((a, b) for a, succ in _edges.items() for b in succ)


def dump(path: "str | None" = None) -> "str | None":
    """Write this process's graph + violations as one JSON file into
    ``path`` or ``$ELBENCHO_TPU_LOCKGRAPH_DIR``. Registered atexit when
    the env var is set, so every fleet subprocess reports."""
    directory = path or os.environ.get(ENV_DUMP_DIR)
    if not directory:
        return None
    try:
        os.makedirs(directory, exist_ok=True)
        out = os.path.join(
            directory, f"lockgraph-{os.getpid()}-{id(_edges):x}.json")
        with _state_lock:
            payload = {
                "pid": os.getpid(),
                "edges": sorted((a, b) for a, succ in _edges.items()
                                for b in succ),
                "violations": list(_violations),
            }
        with open(out, "w") as f:
            json.dump(payload, f, indent=1)
        return out
    except OSError:
        return None  # a dying subprocess must not mask the real failure


def merge_check(directory: "str | None" = None,
                strict: bool = False) -> "list[dict]":
    """Fleet-wide verdict: union this process's live graph with every
    dump in ``directory`` and re-run cycle detection on the union.
    Returns all violations (per-process ones plus any cycle only the
    union exhibits); raises LockOrderError instead when ``strict``."""
    union: "dict[str, set[str]]" = {}
    problems: "list[dict]" = []
    with _state_lock:
        for a, succ in _edges.items():
            union.setdefault(a, set()).update(succ)
        problems.extend(_violations)
    if directory and os.path.isdir(directory):
        for name in sorted(os.listdir(directory)):
            if not name.startswith("lockgraph-") \
                    or not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(directory, name)) as f:
                    payload = json.load(f)
            except (OSError, ValueError):
                continue
            for a, b in payload.get("edges", ()):
                union.setdefault(a, set()).add(b)
            problems.extend(payload.get("violations", ()))
    # dedup per-process cycle reports, then hunt union-only cycles
    seen = {frozenset(v["cycle"]) for v in problems
            if v.get("kind") == "lock-order-cycle"}
    uniq, seen_keys = [], set()
    for v in problems:
        key = json.dumps(v, sort_keys=True)
        if key not in seen_keys:
            seen_keys.add(key)
            uniq.append(v)
    problems = uniq
    for a in sorted(union):
        for b in sorted(union[a]):
            cycle = _find_cycle(union, b, a)
            if cycle:
                ident = frozenset(cycle + [b])
                if ident not in seen:
                    seen.add(ident)
                    problems.append({
                        "kind": "lock-order-cycle",
                        "cycle": cycle + [b],
                        "thread": "",
                        "source": "fleet-union",
                    })
    if strict and problems:
        raise LockOrderError(render(problems))
    return problems


def render(problems: "list[dict]") -> str:
    lines = [f"lockgraph: {len(problems)} lock-order violation(s)"]
    for v in problems:
        if v.get("kind") == "lock-order-cycle":
            where = f" [{v['source']}]" if v.get("source") else ""
            lines.append(
                f"  cycle{where}: " + " -> ".join(v["cycle"])
                + (f"  (thread {v['thread']})" if v.get("thread") else ""))
        else:
            lines.append(
                f"  {v['route_lock']} held across blocking request "
                f"{v['request']} (thread {v['thread']}) — the route lock "
                f"must never wait on a remote peer")
    return "\n".join(lines)
