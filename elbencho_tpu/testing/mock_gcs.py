"""In-memory GCS JSON-API server for CI without real object storage.

Implements the subset `toolkits/gcs_tk.GcsClient` uses: bucket
insert/get/delete/patch, media upload, object metadata GET / alt=media
download (+Range), object PATCH/DELETE, list with prefix + pageToken,
compose, object/bucket ACL lists, and the GCE metadata-server token
endpoint (for auth-path tests). Bearer tokens are recorded but not
validated (like the S3 mock accepts any signature).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def _predefined_acl(name: str) -> "list[dict]":
    """Expand a predefinedAcl name to entity entries like real GCS does
    (e.g. publicRead -> allUsers READER)."""
    base = [{"entity": "user-owner", "role": "OWNER"}]
    if name in ("publicRead", "publicReadWrite"):
        base.append({"entity": "allUsers",
                     "role": "WRITER" if name.endswith("Write")
                     else "READER"})
    elif name == "authenticatedRead":
        base.append({"entity": "allAuthenticatedUsers", "role": "READER"})
    return base


class MockGcsState:
    def __init__(self):
        self.lock = threading.Lock()
        self.buckets: "dict[str, dict]" = {}  # name -> bucket resource
        self.objects: "dict[str, dict[str, bytes]]" = {}
        self.obj_meta: "dict[tuple[str, str], dict]" = {}
        self.seen_tokens: "list[str]" = []
        self.metadata_token_calls = 0
        # resumable sessions: upload_id -> {bucket, name, data, done}
        self.resumable: "dict[str, dict]" = {}
        self.next_resumable_id = 0
        # test knob: accept only this many bytes of the first chunk PUT
        # of each session (forces the client's 308 resume loop)
        self.resumable_truncate_first_chunk = 0
        # drop the next N chunk PUT bodies entirely (308 with no Range
        # progress — the transient-backend-loss case the protocol expects
        # clients to resend through)
        self.resumable_drop_chunks = 0


def _make_handler(state: MockGcsState):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):
            pass

        def _reply(self, code: int, body: bytes = b"",
                   headers: "dict | None" = None):
            self.send_response(code)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if body:
                self.wfile.write(body)

        def _json(self, code: int, doc: dict):
            self._reply(code, json.dumps(doc).encode(),
                        {"Content-Type": "application/json"})

        def _error(self, code: int, message: str):
            self._json(code, {"error": {"code": code, "message": message}})

        def _body(self) -> bytes:
            length = int(self.headers.get("Content-Length") or 0)
            return self.rfile.read(length) if length else b""

        def _record_token(self):
            auth = self.headers.get("Authorization", "")
            if auth.startswith("Bearer "):
                state.seen_tokens.append(auth[len("Bearer "):])

        def _obj_resource(self, bucket: str, name: str) -> dict:
            data = state.objects[bucket][name]
            meta = state.obj_meta.get((bucket, name), {})
            res = {"kind": "storage#object", "name": name,
                   "bucket": bucket, "size": str(len(data)),
                   "etag": f"etag-{len(data)}"}
            res.update(meta)
            return res

        def _route(self):
            parsed = urllib.parse.urlparse(self.path)
            query = {k: v[0] for k, v in urllib.parse.parse_qs(
                parsed.query, keep_blank_values=True).items()}
            return parsed.path, query

        # -- GET -----------------------------------------------------------

        def do_GET(self):  # noqa: N802
            self._record_token()
            path, query = self._route()
            with state.lock:
                if path == ("/computeMetadata/v1/instance/"
                            "service-accounts/default/token"):
                    if self.headers.get("Metadata-Flavor") != "Google":
                        self._error(403, "missing Metadata-Flavor")
                        return
                    state.metadata_token_calls += 1
                    self._json(200, {
                        "access_token":
                            f"mock-token-{state.metadata_token_calls}",
                        "expires_in": 3600, "token_type": "Bearer"})
                    return
                parts = path.split("/")
                # /storage/v1/b/{bucket}...
                if len(parts) >= 4 and parts[1] == "storage" \
                        and parts[3] == "b":
                    bucket = urllib.parse.unquote(parts[4]) \
                        if len(parts) > 4 else ""
                    if bucket not in state.buckets:
                        self._error(404, f"bucket {bucket} not found")
                        return
                    rest = parts[5:]
                    if not rest:  # bucket resource
                        self._json(200, state.buckets[bucket])
                        return
                    if rest == ["acl"]:
                        self._json(200, {"kind": "storage#bucketAccess"
                                                 "Controls",
                                         "items": state.buckets[bucket]
                                         .get("acl", [])})
                        return
                    if rest[0] == "o" and len(rest) == 1:  # list
                        prefix = query.get("prefix", "")
                        max_results = int(query.get("maxResults", "1000"))
                        start = query.get("pageToken", "")
                        names = sorted(n for n in state.objects[bucket]
                                       if n.startswith(prefix)
                                       and n > start)
                        page, token = names[:max_results], ""
                        if len(names) > max_results:
                            token = page[-1]
                        doc = {"kind": "storage#objects",
                               "items": [self._obj_resource(bucket, n)
                                         for n in page]}
                        if token:
                            doc["nextPageToken"] = token
                        self._json(200, doc)
                        return
                    if rest[0] == "o":
                        name = urllib.parse.unquote(rest[1])
                        if name not in state.objects[bucket]:
                            self._error(404, f"object {name} not found")
                            return
                        if len(rest) > 2 and rest[2] == "acl":
                            self._json(200, {
                                "kind": "storage#objectAccessControls",
                                "items": state.obj_meta.get(
                                    (bucket, name), {}).get("acl", [])})
                            return
                        if query.get("alt") == "media":
                            data = state.objects[bucket][name]
                            rng = self.headers.get("Range", "")
                            if rng.startswith("bytes="):
                                lo, _, hi = rng[6:].partition("-")
                                lo = int(lo)
                                hi = int(hi) if hi else len(data) - 1
                                body = data[lo:hi + 1]
                                self._reply(206, body)
                                return
                            self._reply(200, data)
                            return
                        self._json(200, self._obj_resource(bucket, name))
                        return
                self._error(404, f"no route {path}")

        # -- POST ----------------------------------------------------------

        def do_POST(self):  # noqa: N802
            self._record_token()
            path, query = self._route()
            body = self._body()
            with state.lock:
                if path == "/storage/v1/b":  # bucket insert
                    doc = json.loads(body)
                    name = doc["name"]
                    if name in state.buckets:
                        self._error(409, "bucket exists")
                        return
                    state.buckets[name] = {"kind": "storage#bucket",
                                           "name": name}
                    state.objects[name] = {}
                    self._json(200, state.buckets[name])
                    return
                if path.startswith("/upload/storage/v1/b/"):
                    bucket = urllib.parse.unquote(
                        path.split("/")[5])
                    if bucket not in state.buckets:
                        self._error(404, f"bucket {bucket} not found")
                        return
                    name = query.get("name", "")
                    if query.get("uploadType") == "resumable":
                        state.next_resumable_id += 1
                        sid = f"mock-resumable-{state.next_resumable_id}"
                        state.resumable[sid] = {
                            "bucket": bucket, "name": name,
                            "data": bytearray(), "chunk_puts": 0}
                        host = self.headers.get("Host", "localhost")
                        self._reply(200, headers={
                            "Location":
                                f"http://{host}/upload/storage/v1/b/"
                                f"{urllib.parse.quote(bucket, safe='')}"
                                f"/o?upload_id={sid}"})
                        return
                    state.objects[bucket][name] = body
                    self._json(200, self._obj_resource(bucket, name))
                    return
                if path.endswith("/compose"):
                    parts = path.split("/")
                    bucket = urllib.parse.unquote(parts[4])
                    dest = urllib.parse.unquote(parts[6])
                    if bucket not in state.buckets:
                        self._error(404, f"bucket {bucket} not found")
                        return
                    doc = json.loads(body)
                    srcs = [s["name"] for s in doc["sourceObjects"]]
                    if len(srcs) > 32:
                        self._error(400, "too many compose sources")
                        return
                    missing = [s for s in srcs
                               if s not in state.objects[bucket]]
                    if missing:
                        self._error(404, f"source {missing[0]} not found")
                        return
                    state.objects[bucket][dest] = b"".join(
                        state.objects[bucket][s] for s in srcs)
                    self._json(200, self._obj_resource(bucket, dest))
                    return
                self._error(404, f"no route {path}")

        # -- PUT (resumable chunk uploads only) ----------------------------

        def do_PUT(self):  # noqa: N802
            self._record_token()
            path, query = self._route()
            body = self._body()
            with state.lock:
                sid = query.get("upload_id", "")
                sess = state.resumable.get(sid)
                if not path.startswith("/upload/storage/v1/b/") \
                        or sess is None:
                    self._error(404, f"no resumable session {sid!r}")
                    return
                rng = self.headers.get("Content-Range", "")
                data = sess["data"]

                def _finalize():
                    # the session ends with the object's creation
                    state.resumable.pop(sid, None)
                    state.objects[sess["bucket"]][sess["name"]] = \
                        bytes(data)
                    self._json(200, self._obj_resource(
                        sess["bucket"], sess["name"]))

                def _incomplete():
                    headers = {}
                    if data:
                        headers["Range"] = f"bytes=0-{len(data) - 1}"
                    self._reply(308, headers=headers)

                if rng.startswith("bytes */"):
                    total = rng[len("bytes */"):]
                    if total != "*" and len(data) == int(total):
                        _finalize()
                    else:
                        _incomplete()  # status query / wrong total
                    return
                # "bytes S-E/T" chunk
                try:
                    span, _, total = rng[len("bytes "):].partition("/")
                    start_s, _, _end_s = span.partition("-")
                    start = int(start_s)
                except ValueError:
                    self._error(400, f"bad Content-Range {rng!r}")
                    return
                if start != len(data):
                    _incomplete()  # out of sync: report committed prefix
                    return
                sess["chunk_puts"] += 1
                if state.resumable_drop_chunks > 0:
                    state.resumable_drop_chunks -= 1
                    _incomplete()  # chunk "lost": acknowledge no progress
                    return
                if sess["chunk_puts"] == 1 \
                        and state.resumable_truncate_first_chunk:
                    body = body[:state.resumable_truncate_first_chunk]
                data += body
                if total != "*" and len(data) == int(total):
                    _finalize()
                else:
                    _incomplete()

        # -- PATCH ---------------------------------------------------------

        def do_PATCH(self):  # noqa: N802
            self._record_token()
            path, query = self._route()
            body = self._body()
            doc = json.loads(body) if body else {}
            with state.lock:
                parts = path.split("/")
                bucket = urllib.parse.unquote(parts[4]) \
                    if len(parts) > 4 else ""
                if bucket not in state.buckets:
                    self._error(404, f"bucket {bucket} not found")
                    return
                if len(parts) == 5:  # bucket patch
                    for k, v in doc.items():
                        if v is None:
                            state.buckets[bucket].pop(k, None)
                        else:
                            state.buckets[bucket][k] = v
                    if "predefinedAcl" in query:
                        state.buckets[bucket]["acl"] = _predefined_acl(
                            query["predefinedAcl"])
                    self._json(200, state.buckets[bucket])
                    return
                if len(parts) >= 7 and parts[5] == "o":
                    name = urllib.parse.unquote(parts[6])
                    if name not in state.objects[bucket]:
                        self._error(404, f"object {name} not found")
                        return
                    meta = state.obj_meta.setdefault((bucket, name), {})
                    for k, v in doc.items():
                        if v is None:
                            meta.pop(k, None)
                        else:
                            meta[k] = v
                    if "predefinedAcl" in query:
                        meta["acl"] = _predefined_acl(
                            query["predefinedAcl"])
                    self._json(200, self._obj_resource(bucket, name))
                    return
                self._error(404, f"no route {path}")

        # -- DELETE --------------------------------------------------------

        def do_DELETE(self):  # noqa: N802
            self._record_token()
            path, _query = self._route()
            with state.lock:
                if path.startswith("/upload/storage/v1/b/"):
                    sid = _query.get("upload_id", "")
                    if state.resumable.pop(sid, None) is None:
                        self._error(404, f"no resumable session {sid!r}")
                        return
                    # GCS answers 499 Client Closed Request for cancel
                    self._reply(499)
                    return
                parts = path.split("/")
                bucket = urllib.parse.unquote(parts[4]) \
                    if len(parts) > 4 else ""
                if bucket not in state.buckets:
                    self._error(404, f"bucket {bucket} not found")
                    return
                if len(parts) == 5:
                    if state.objects[bucket]:
                        self._error(409, "bucket not empty")
                        return
                    state.buckets.pop(bucket)
                    state.objects.pop(bucket)
                    self._reply(204)
                    return
                if len(parts) >= 7 and parts[5] == "o":
                    name = urllib.parse.unquote(parts[6])
                    if name not in state.objects[bucket]:
                        self._error(404, f"object {name} not found")
                        return
                    state.objects[bucket].pop(name)
                    state.obj_meta.pop((bucket, name), None)
                    self._reply(204)
                    return
                self._error(404, f"no route {path}")

    return Handler


class MockGcsServer:
    """Threaded in-process mock GCS JSON endpoint (+ metadata token
    endpoint) for tests."""

    def __init__(self, port: int = 0):
        self.state = MockGcsState()
        self.server = ThreadingHTTPServer(("127.0.0.1", port),
                                          _make_handler(self.state))
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    @property
    def metadata_host(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> "MockGcsServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
