"""In-memory S3 server for CI without real object storage.

The reference has no mock backend (SURVEY.md section 4: testing is
end-to-end against real resources); the survey's test-strategy implication
is to exceed that with a fake backend. This implements the XML API subset
the benchmark uses: bucket create/delete/head, object PUT/GET(+Range)/HEAD/
DELETE, ListObjectsV2 with continuation tokens, multi-object delete,
multipart uploads, ACL and tagging. No auth validation (signatures are
accepted unchecked).
"""

from __future__ import annotations

import threading
import urllib.parse
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MockS3State:
    def __init__(self):
        self.lock = threading.Lock()
        self.buckets: "dict[str, dict[str, bytes]]" = {}
        self.uploads: "dict[str, dict]" = {}  # uploadId -> {bucket,key,parts}
        self.tags: "dict[tuple[str, str], dict]" = {}
        self.bucket_meta: "dict[tuple[str, str], bytes]" = {}
        self.next_upload_id = 0


def _make_handler(state: MockS3State):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):
            pass

        # -- helpers -------------------------------------------------------

        def _split(self):
            parsed = urllib.parse.urlparse(self.path)
            parts = parsed.path.lstrip("/").split("/", 1)
            bucket = urllib.parse.unquote(parts[0]) if parts[0] else ""
            key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
            query = {k: v[0] for k, v in
                     urllib.parse.parse_qs(parsed.query,
                                           keep_blank_values=True).items()}
            return bucket, key, query

        def _reply(self, code: int, body: bytes = b"",
                   headers: "dict | None" = None):
            self.send_response(code)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if body:
                self.wfile.write(body)

        def _error(self, code: int, s3code: str, message: str = ""):
            body = (f"<Error><Code>{s3code}</Code>"
                    f"<Message>{message}</Message></Error>").encode()
            self._reply(code, body)

        def _body(self) -> bytes:
            length = int(self.headers.get("Content-Length", 0))
            return self.rfile.read(length) if length else b""

        # -- methods -------------------------------------------------------

        def do_PUT(self):  # noqa: N802
            bucket, key, query = self._split()
            body = self._body()
            with state.lock:
                if not key:
                    if "acl" in query:
                        self._reply(200)
                        return
                    for meta in ("tagging", "versioning", "object-lock"):
                        if meta in query:
                            state.bucket_meta[(bucket, meta)] = body
                            self._reply(200)
                            return
                    state.buckets.setdefault(bucket, {})
                    self._reply(200)
                    return
                if bucket not in state.buckets:
                    self._error(404, "NoSuchBucket", bucket)
                    return
                if "partNumber" in query and "uploadId" in query:
                    upload = state.uploads.get(query["uploadId"])
                    if upload is None:
                        self._error(404, "NoSuchUpload", query["uploadId"])
                        return
                    part_num = int(query["partNumber"])
                    upload["parts"][part_num] = body
                    self._reply(200, headers={
                        "ETag": f'"part{part_num}"'})
                    return
                if "tagging" in query:
                    state.tags[(bucket, key)] = body
                    self._reply(200)
                    return
                if "acl" in query:
                    self._reply(200)
                    return
                state.buckets[bucket][key] = body
                self._reply(200, headers={"ETag": '"mock-etag"'})

        def do_POST(self):  # noqa: N802
            bucket, key, query = self._split()
            body = self._body()
            with state.lock:
                if "uploads" in query:
                    state.next_upload_id += 1
                    upload_id = f"mock-upload-{state.next_upload_id}"
                    state.uploads[upload_id] = {
                        "bucket": bucket, "key": key, "parts": {}}
                    xml_reply = (
                        "<InitiateMultipartUploadResult>"
                        f"<Bucket>{bucket}</Bucket><Key>{key}</Key>"
                        f"<UploadId>{upload_id}</UploadId>"
                        "</InitiateMultipartUploadResult>").encode()
                    self._reply(200, xml_reply)
                    return
                if "uploadId" in query:
                    upload = state.uploads.pop(query["uploadId"], None)
                    if upload is None:
                        self._error(404, "NoSuchUpload", query["uploadId"])
                        return
                    data = b"".join(upload["parts"][num]
                                    for num in sorted(upload["parts"]))
                    state.buckets.setdefault(bucket, {})[key] = data
                    self._reply(200, (
                        "<CompleteMultipartUploadResult>"
                        f"<Key>{key}</Key>"
                        "</CompleteMultipartUploadResult>").encode())
                    return
                if "delete" in query:
                    root = ET.fromstring(body)
                    deleted = []
                    for obj in root.iter("Object"):
                        k = obj.findtext("Key", "")
                        state.buckets.get(bucket, {}).pop(k, None)
                        deleted.append(k)
                    self._reply(200, b"<DeleteResult></DeleteResult>")
                    return
                self._error(400, "InvalidRequest")

        def do_GET(self):  # noqa: N802
            bucket, key, query = self._split()
            with state.lock:
                if bucket not in state.buckets:
                    self._error(404, "NoSuchBucket", bucket)
                    return
                if not key:
                    if "uploads" in query:
                        ups = "".join(
                            f"<Upload><Key>{u['key']}</Key>"
                            f"<UploadId>{uid}</UploadId></Upload>"
                            for uid, u in sorted(state.uploads.items())
                            if u["bucket"] == bucket)
                        self._reply(200, (
                            "<ListMultipartUploadsResult>"
                            "<IsTruncated>false</IsTruncated>"
                            f"{ups}</ListMultipartUploadsResult>").encode())
                        return
                    if "acl" in query:
                        self._reply(200, b"<AccessControlPolicy>"
                                         b"</AccessControlPolicy>")
                        return
                    for meta, default in (
                            ("tagging",
                             b"<Tagging><TagSet></TagSet></Tagging>"),
                            ("versioning",
                             b"<VersioningConfiguration>"
                             b"</VersioningConfiguration>"),
                            ("object-lock",
                             b"<ObjectLockConfiguration>"
                             b"</ObjectLockConfiguration>")):
                        if meta in query:
                            self._reply(200, state.bucket_meta.get(
                                (bucket, meta), default))
                            return
                    self._list(bucket, query)
                    return
                if "list-type" in query:
                    self._list(bucket, query)
                    return
                if "acl" in query:
                    self._reply(200, b"<AccessControlPolicy>"
                                     b"</AccessControlPolicy>")
                    return
                if "tagging" in query:
                    body = state.tags.get((bucket, key),
                                          b"<Tagging><TagSet></TagSet>"
                                          b"</Tagging>")
                    self._reply(200, body)
                    return
                data = state.buckets[bucket].get(key)
                if data is None:
                    self._error(404, "NoSuchKey", key)
                    return
                range_header = self.headers.get("Range")
                if range_header:
                    spec = range_header.split("=", 1)[1]
                    start_s, _, end_s = spec.partition("-")
                    start = int(start_s)
                    end = int(end_s) if end_s else len(data) - 1
                    chunk = data[start:end + 1]
                    self._reply(206, chunk, headers={
                        "Content-Range":
                            f"bytes {start}-{end}/{len(data)}"})
                    return
                self._reply(200, data)

        def _list(self, bucket: str, query: dict):
            prefix = query.get("prefix", "")
            max_keys = int(query.get("max-keys", "1000"))
            token = query.get("continuation-token", "")
            keys = sorted(k for k in state.buckets[bucket]
                          if k.startswith(prefix))
            start = int(token) if token else 0
            page = keys[start:start + max_keys]
            next_token = str(start + max_keys) \
                if start + max_keys < len(keys) else ""
            contents = "".join(
                f"<Contents><Key>{k}</Key>"
                f"<Size>{len(state.buckets[bucket][k])}</Size></Contents>"
                for k in page)
            more = (f"<NextContinuationToken>{next_token}"
                    f"</NextContinuationToken>") if next_token else ""
            xml_reply = (
                "<ListBucketResult>"
                f"<Name>{bucket}</Name><KeyCount>{len(page)}</KeyCount>"
                f"{contents}{more}</ListBucketResult>").encode()
            self._reply(200, xml_reply)

        def do_HEAD(self):  # noqa: N802
            bucket, key, _query = self._split()
            with state.lock:
                if bucket not in state.buckets:
                    self._reply(404)
                    return
                if not key:
                    self._reply(200)
                    return
                data = state.buckets[bucket].get(key)
                if data is None:
                    self._reply(404)
                    return
                self._reply(200, headers={"Content-Length-Mock":
                                          str(len(data))})

        def do_DELETE(self):  # noqa: N802
            bucket, key, query = self._split()
            with state.lock:
                if "uploadId" in query:
                    state.uploads.pop(query["uploadId"], None)
                    self._reply(204)
                    return
                if not key:
                    if "tagging" in query:
                        state.bucket_meta.pop((bucket, "tagging"), None)
                        self._reply(204)
                        return
                    if bucket in state.buckets and state.buckets[bucket]:
                        self._error(409, "BucketNotEmpty", bucket)
                        return
                    state.buckets.pop(bucket, None)
                    self._reply(204)
                    return
                if "tagging" in query:
                    state.tags.pop((bucket, key), None)
                    self._reply(204)
                    return
                state.buckets.get(bucket, {}).pop(key, None)
                self._reply(204)

    return Handler


class MockS3Server:
    """Threaded in-process mock S3 endpoint for tests."""

    def __init__(self, port: int = 0):
        self.state = MockS3State()
        self.server = ThreadingHTTPServer(("127.0.0.1", port),
                                          _make_handler(self.state))
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "MockS3Server":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
