"""Localhost service-process lifecycle for tests and the multichip dryrun.

One implementation of the spawn / /status-ready-wait / terminate-then-kill
sequence that every consumer of a local service pair needs (the reference's
localhost multi-service pattern, tools/test-examples.sh:296-330): the
service-mode pytest suite, the netbench tests, and pass 4 of
``__graft_entry__.dryrun_multichip`` (master -> HTTP -> services -> chips).
"""

from __future__ import annotations

import contextlib
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

REPO_DIR = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def free_ports(n: int) -> "list[int]":
    """n ephemeral localhost ports via bind-then-close, so concurrent
    runs don't collide on fixed port constants."""
    ports, socks = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def default_env() -> dict:
    """os.environ plus the repo on PYTHONPATH — the baseline service
    subprocess environment; callers layer their own knobs on top."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")
    return env


def wait_ready(port: int, timeout: float = 120.0) -> None:
    """Poll /status until the service answers 200 or the window closes."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=2) as r:
                if r.status == 200:
                    return
        except OSError:
            pass
        if time.monotonic() > deadline:
            raise TimeoutError(f"service on port {port} not ready "
                               f"after {timeout:.0f}s")
        time.sleep(0.2)


@contextlib.contextmanager
def service_procs(ports: "list[int]", env: "dict | None" = None,
                  extra_args: "list[str] | None" = None):
    """Spawn one --service --foreground process per port, wait for all to
    answer /status, yield the Popen list, and tear down (terminate, then
    kill after 10s) any still running on exit.

    ``env`` defaults to os.environ plus the repo on PYTHONPATH. A caller
    that expects the services to exit on their own (e.g. after --quit
    over the wire) can wait() them inside the block; teardown skips
    already-exited processes.

    Service output goes to one temp log file per process, never a pipe:
    a long-lived service pair (fuzz suite, multichip dryrun) can emit
    more than the ~64KiB pipe buffer, and an undrained pipe would then
    block the service mid-write and deadlock the run. On failure each
    log's tail is printed to stderr; the files are removed on success.
    """
    if env is None:
        env = default_env()
    procs = []
    logs = []  # (port, path, fh) per service process
    ok = False
    try:
        for port in ports:
            fd, path = tempfile.mkstemp(prefix=f"elbencho-svc-{port}-",
                                        suffix=".log")
            fh = os.fdopen(fd, "wb")
            logs.append((port, path, fh))
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "elbencho_tpu", "--service",
                 "--foreground", "--port", str(port)]
                + list(extra_args or []),
                env=env, cwd=REPO_DIR,
                stdout=fh, stderr=subprocess.STDOUT))
        for port in ports:
            wait_ready(port)
        yield procs
        ok = True
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        for port, path, fh in logs:
            with contextlib.suppress(OSError):
                fh.close()
            if not ok:
                _print_log_tail(port, path)
            with contextlib.suppress(OSError):
                os.unlink(path)


@contextlib.contextmanager
def in_process_services(num: int, extra_argv: "list[str] | None" = None):
    """``num`` threaded service instances INSIDE this process — no
    subprocess (or jax re-import) per host, which is what lets the scale
    suite stand up a 64-host loopback fleet in seconds. Yields the port
    list. Each instance is a full ServiceState + ThreadingHTTPServer on
    an ephemeral localhost port, serving the real route table (incl.
    /livestream), so a master run against them exercises the genuine
    control plane."""
    from elbencho_tpu.config.args import parse_cli
    from elbencho_tpu.service.http_service import create_service_server

    # NOTE: the real service role enables the global logger error
    # history; in-process instances deliberately do NOT — the master
    # shares this process, and its own error lines would echo back
    # through every /benchresult history replay (and re-enter the
    # history, cascading). Error-history semantics stay covered by the
    # subprocess-based suites.
    ports = free_ports(num)
    servers = []  # (server, state, holder, thread) per instance
    threads = []

    def serve(server, holder):
        while not holder["shutdown"]:
            try:
                server.handle_request()
            except OSError:  # server_close raced the accept loop
                return

    try:
        for port in ports:
            cfg, _ns = parse_cli(["--service", "--foreground",
                                  "--port", str(port)]
                                 + list(extra_argv or []))
            cfg.derive(probe_paths=False)
            cfg.check()
            server, state, holder = create_service_server(
                cfg, bind_host="127.0.0.1")
            t = threading.Thread(target=serve, args=(server, holder),
                                 name=f"inproc-svc-{port}", daemon=True)
            t.start()
            servers.append((server, state, holder))
            threads.append(t)
        for port in ports:
            wait_ready(port, timeout=30)
        yield ports
    finally:
        for _server, _state, holder in servers:
            holder["shutdown"] = True
        for t in threads:
            t.join(timeout=5)
        for server, state, _holder in servers:
            with contextlib.suppress(Exception):
                state.close()
            with contextlib.suppress(OSError):
                server.server_close()


def _print_log_tail(port: int, path: str, max_bytes: int = 8192) -> None:
    """Last chunk of a failed service's log to stderr, so the harness
    failure carries the service-side context the pipe used to hold."""
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(size - max_bytes, 0))
            tail = fh.read().decode("utf-8", errors="replace")
    except OSError:
        return
    if tail.strip():
        print(f"--- service on port {port}: log tail ---\n{tail}"
              f"--- end service log (port {port}) ---",
              file=sys.stderr)
