"""Localhost service-process lifecycle for tests and the multichip dryrun.

One implementation of the spawn / /status-ready-wait / terminate-then-kill
sequence that every consumer of a local service pair needs (the reference's
localhost multi-service pattern, tools/test-examples.sh:296-330): the
service-mode pytest suite, the netbench tests, and pass 4 of
``__graft_entry__.dryrun_multichip`` (master -> HTTP -> services -> chips).
"""

from __future__ import annotations

import contextlib
import os
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO_DIR = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def free_ports(n: int) -> "list[int]":
    """n ephemeral localhost ports via bind-then-close, so concurrent
    runs don't collide on fixed port constants."""
    ports, socks = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def default_env() -> dict:
    """os.environ plus the repo on PYTHONPATH — the baseline service
    subprocess environment; callers layer their own knobs on top."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_DIR + os.pathsep + env.get("PYTHONPATH", "")
    return env


def wait_ready(port: int, timeout: float = 120.0) -> None:
    """Poll /status until the service answers 200 or the window closes."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=2) as r:
                if r.status == 200:
                    return
        except OSError:
            pass
        if time.monotonic() > deadline:
            raise TimeoutError(f"service on port {port} not ready "
                               f"after {timeout:.0f}s")
        time.sleep(0.2)


@contextlib.contextmanager
def service_procs(ports: "list[int]", env: "dict | None" = None,
                  extra_args: "list[str] | None" = None):
    """Spawn one --service --foreground process per port, wait for all to
    answer /status, yield the Popen list, and tear down (terminate, then
    kill after 10s) any still running on exit.

    ``env`` defaults to os.environ plus the repo on PYTHONPATH. A caller
    that expects the services to exit on their own (e.g. after --quit
    over the wire) can wait() them inside the block; teardown skips
    already-exited processes.

    Service output goes to one temp log file per process, never a pipe:
    a long-lived service pair (fuzz suite, multichip dryrun) can emit
    more than the ~64KiB pipe buffer, and an undrained pipe would then
    block the service mid-write and deadlock the run. On failure each
    log's tail is printed to stderr; the files are removed on success.
    """
    if env is None:
        env = default_env()
    procs = []
    logs = []  # (port, path, fh) per service process
    ok = False
    try:
        for port in ports:
            fd, path = tempfile.mkstemp(prefix=f"elbencho-svc-{port}-",
                                        suffix=".log")
            fh = os.fdopen(fd, "wb")
            logs.append((port, path, fh))
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "elbencho_tpu", "--service",
                 "--foreground", "--port", str(port)]
                + list(extra_args or []),
                env=env, cwd=REPO_DIR,
                stdout=fh, stderr=subprocess.STDOUT))
        for port in ports:
            wait_ready(port)
        yield procs
        ok = True
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        for port, path, fh in logs:
            with contextlib.suppress(OSError):
                fh.close()
            if not ok:
                _print_log_tail(port, path)
            with contextlib.suppress(OSError):
                os.unlink(path)


def _print_log_tail(port: int, path: str, max_bytes: int = 8192) -> None:
    """Last chunk of a failed service's log to stderr, so the harness
    failure carries the service-side context the pipe used to hold."""
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(size - max_bytes, 0))
            tail = fh.read().decode("utf-8", errors="replace")
    except OSError:
        return
    if tail.strip():
        print(f"--- service on port {port}: log tail ---\n{tail}"
              f"--- end service log (port {port}) ---",
              file=sys.stderr)
