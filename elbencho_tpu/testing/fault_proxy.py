"""Fault-injection TCP/HTTP proxy for control-plane chaos testing.

Interposes between the master and a service instance (master --hosts
points at the proxy's port) and injects, per a seeded schedule, the
failure modes a flaky fleet produces: connection drops, response delays,
5xx replies, truncated bodies, and garbage JSON — so the retry/watchdog/
degradation paths in `service/remote_worker.py` can be driven end-to-end
through the REAL master code path (tests/test_fault_tolerance.py).

The master's ServiceClient opens one HTTP connection per request, so a
proxy connection corresponds 1:1 to a control-plane request; the proxy
parses the request head, which lets fault rules target specific endpoints
(e.g. fault only idempotent `/status` polls).

Loopback only, short timeouts — tier-1-safe by design.
"""

from __future__ import annotations

import random
import socket
import threading
from dataclasses import dataclass, field

#: fault kinds a rule may inject
FAULTS = ("drop", "error500", "garbage", "truncate", "delay", "hang")

_CANNED_500 = (b"HTTP/1.1 500 Internal Server Error\r\n"
               b"Content-Type: application/json\r\n"
               b"Content-Length: 35\r\n\r\n"
               b'{"Error": "injected fault: error"}\n')
_GARBAGE_200 = (b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: 24\r\n\r\n"
                b'{"NumWorkers### garbage!')


@dataclass
class FaultRule:
    """One injection rule; rules are evaluated in order, first match wins.

    ``path`` substring-matches the request path ("" = any). A request
    matches the rule when its per-rule match counter exceeds
    ``skip_first`` and then either hits ``every_nth`` (1 = every match)
    or the seeded coin with probability ``prob`` comes up. ``max_faults``
    caps total injections of the rule (0 = unlimited).
    """

    fault: str
    path: str = ""
    every_nth: int = 0
    prob: float = 0.0
    skip_first: int = 0
    max_faults: int = 0
    delay_secs: float = 0.25
    _matches: int = field(default=0, repr=False)
    _injected: int = field(default=0, repr=False)

    def __post_init__(self):
        if self.fault not in FAULTS:
            raise ValueError(f"unknown fault {self.fault!r} "
                             f"(expected one of {FAULTS})")


class FaultSchedule:
    """Deterministic (seeded) rule evaluation, shared by all proxy
    connections of one test run."""

    def __init__(self, rules: "list[FaultRule]", seed: int = 0):
        self.rules = list(rules)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def fault_for(self, method: str, path: str) -> "FaultRule | None":
        with self._lock:
            for rule in self.rules:
                if rule.path and rule.path not in path:
                    continue
                rule._matches += 1
                if rule._matches <= rule.skip_first:
                    continue
                if rule.max_faults and rule._injected >= rule.max_faults:
                    continue
                hit = (rule.every_nth
                       and (rule._matches - rule.skip_first)
                       % rule.every_nth == 0) \
                    or (rule.prob and self._rng.random() < rule.prob)
                if hit:
                    rule._injected += 1
                    return rule
        return None


def _recv_http_message(sock: socket.socket, timeout: float = 10.0) -> bytes:
    """One full HTTP message (head + Content-Length body) off a socket.
    Returns b"" when the peer closed before sending a head."""
    sock.settimeout(timeout)
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            return b""
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    content_len = 0
    for line in head.split(b"\r\n")[1:]:
        name, _, value = line.partition(b":")
        if name.strip().lower() == b"content-length":
            content_len = int(value.strip())
    while len(rest) < content_len:
        chunk = sock.recv(65536)
        if not chunk:
            break
        rest += chunk
    return head + b"\r\n\r\n" + rest


class FaultProxy:
    """One proxy instance in front of one service port. Context manager:

        with FaultProxy(svc_port, FaultSchedule([...])) as proxy:
            run_master(hosts=f"127.0.0.1:{proxy.port}")
            assert proxy.injected

    ``injected`` records (conn_idx, fault, path) per injection.
    """

    def __init__(self, target_port: int, schedule: FaultSchedule,
                 target_host: str = "127.0.0.1"):
        self.target_host = target_host
        self.target_port = target_port
        self.schedule = schedule
        self.injected: "list[tuple[int, str, str]]" = []
        self.num_connections = 0
        self.port = 0
        self._listener: "socket.socket | None" = None
        self._stop = threading.Event()
        self._threads: "list[threading.Thread]" = []

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FaultProxy":
        self._listener = socket.create_server(("127.0.0.1", 0))
        self._listener.settimeout(0.2)
        self.port = self._listener.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"fault-proxy-{self.port}")
        self._threads.append(t)
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            self._listener.close()
        for t in self._threads:
            t.join(timeout=5)

    def __enter__(self) -> "FaultProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- connection handling ------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed
            idx = self.num_connections
            self.num_connections += 1
            t = threading.Thread(target=self._handle, args=(conn, idx),
                                 daemon=True,
                                 name=f"fault-proxy-conn-{idx}")
            self._threads.append(t)
            t.start()

    def _handle(self, client: socket.socket, idx: int) -> None:
        upstream = None
        try:
            request = _recv_http_message(client)
            if not request:
                return
            first_line = request.split(b"\r\n", 1)[0].decode(
                errors="replace")
            parts = first_line.split()
            method = parts[0] if parts else ""
            path = parts[1] if len(parts) > 1 else ""
            rule = self.schedule.fault_for(method, path)
            if rule is not None:
                self.injected.append((idx, rule.fault, path))
                if rule.fault == "drop":
                    return  # close without a reply: RST/EOF at the master
                if rule.fault == "hang":
                    # accept the request, never answer (SIGSTOP-alike);
                    # released when the proxy stops
                    self._stop.wait(timeout=60)
                    return
                if rule.fault == "error500":
                    client.sendall(_CANNED_500)
                    return
                if rule.fault == "garbage":
                    client.sendall(_GARBAGE_200)
                    return
                if rule.fault == "delay":
                    self._stop.wait(timeout=rule.delay_secs)
            upstream = socket.create_connection(
                (self.target_host, self.target_port), timeout=10)
            upstream.sendall(request)
            response = _recv_http_message(upstream)
            if rule is not None and rule.fault == "truncate":
                client.sendall(response[:max(len(response) // 2, 1)])
                return
            client.sendall(response)
        except OSError:
            pass  # a torn-down test peer is not a proxy error
        finally:
            if upstream is not None:
                upstream.close()
            client.close()
