"""TPU-VM pod-slice worker enumeration: sugar for --hosts.

The reference takes an explicit host list for its distributed service mode
(--hosts, ProgArgs.cpp parseHosts). On a TPU pod slice the set of worker
VMs is already known to the platform, so --podhosts derives the list
instead (SURVEY.md section 7 step 5):

  1. TPU_WORKER_HOSTNAMES env var (set by the TPU runtime on TPU VMs) —
     comma-separated hostnames.
  2. GCE metadata server attribute ``worker-network-endpoints`` — the
     canonical per-slice list of "index:ip:port"-style entries.

Either source yields one entry per worker VM; each is expected to run
``elbencho-tpu --service``.
"""

from __future__ import annotations

import os
import urllib.error
import urllib.request

#: override for tests / non-GCE environments
METADATA_URL_ENV = "ELBENCHO_TPU_METADATA_URL"
_DEFAULT_METADATA_URL = ("http://metadata.google.internal/computeMetadata"
                         "/v1/instance/attributes/worker-network-endpoints")


def enumerate_pod_hosts(timeout: float = 5.0) -> "list[str]":
    """Worker hostnames/IPs of this pod slice, in worker-index order."""
    env_hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if env_hosts:
        hosts = [h.strip() for h in env_hosts.split(",") if h.strip()]
        if not hosts:
            raise RuntimeError(
                "--podhosts: TPU_WORKER_HOSTNAMES is set but empty")
        return hosts
    url = os.environ.get(METADATA_URL_ENV, _DEFAULT_METADATA_URL)
    req = urllib.request.Request(url,
                                 headers={"Metadata-Flavor": "Google"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read().decode()
    except (urllib.error.URLError, OSError) as err:
        raise RuntimeError(
            f"--podhosts: cannot enumerate pod workers (no "
            f"TPU_WORKER_HOSTNAMES env and metadata query failed: {err})"
        ) from err
    return parse_worker_network_endpoints(body)


def parse_worker_network_endpoints(body: str) -> "list[str]":
    """Parse the worker-network-endpoints attribute: comma-separated
    entries whose last ':'-field is the worker IP (the documented format
    is "<index>:<unused>:<ip>")."""
    hosts = []
    for entry in body.split(","):
        entry = entry.strip()
        if not entry:
            continue
        hosts.append(entry.rsplit(":", 1)[-1] if ":" in entry else entry)
    if not hosts:
        raise RuntimeError(
            "--podhosts: metadata worker-network-endpoints is empty")
    return hosts
