"""TPU HBM data path: per-worker device buffers + host<->HBM transfers.

This is the TPU-native replacement for the reference's CUDA staging
(SURVEY.md section 2.5 "GPU staging" — the north-star port target):

  cudaSetDevice / workerRank % gpuIDs  ->  worker rank % tpu_ids chip pick
                                           (reference LocalWorker.cpp:1444)
  cudaMalloc per iodepth               ->  jax device_put-allocated HBM
                                           staging arrays on the chosen chip
  cudaMemcpy H2D after reads           ->  jax.device_put onto the chip +
                                           block_until_ready (completion wait
                                           keeps per-block latency honest)
  cudaMemcpy D2H before writes         ->  np.asarray(device_array) D2H; the
                                           write-source data originates in
                                           HBM via on-device PRNG (curand
                                           analogue, ops/fill.py)
  cuFileRead (GPUDirect)               ->  --tpudirect: zero-bounce path
                                           using jax dlpack-view of the
                                           page-aligned I/O buffer
  CuFileHandleData register/deregister ->  TpuWorkerContext lifecycle

Per-chip ingest bandwidth is accounted by the worker (tpu_transfer_bytes /
tpu_transfer_usec) and reported by Statistics as "HBM ingest" rows.
"""

from __future__ import annotations

import os
import threading

import numpy as np

_jax_lock = threading.Lock()
_jax_mod = None

#: H2D/D2H path-audit counter map, the single schema for how counters
#: flow from TpuWorkerContext to result records: (context attribute,
#: wire/JSON key, RemoteWorker ingest attribute). Statistics sums by it,
#: the service payload serializes by it, RemoteWorker ingests by it —
#: adding a counter here plumbs it end to end.
PATH_AUDIT_COUNTERS = (
    ("h2d_direct_ops", "TpuH2dDirectOps", "tpu_h2d_direct_ops"),
    ("h2d_staged_ops", "TpuH2dStagedOps", "tpu_h2d_staged_ops"),
    ("h2d_direct_fallbacks", "TpuH2dDirectFallbacks",
     "tpu_h2d_direct_fallbacks"),
    ("d2h_direct_ops", "TpuD2hDirectOps", "tpu_d2h_direct_ops"),
    ("d2h_staged_ops", "TpuD2hStagedOps", "tpu_d2h_staged_ops"),
    ("d2h_direct_fallbacks", "TpuD2hDirectFallbacks",
     "tpu_d2h_direct_fallbacks"),
    ("d2h_prefetch_hits", "TpuD2hPrefetchHits", "tpu_d2h_prefetch_hits"),
    ("d2h_prefetch_misses", "TpuD2hPrefetchMisses",
     "tpu_d2h_prefetch_misses"),
    ("pipe_full_stalls", "TpuPipeFullStalls", "tpu_pipe_full_stalls"),
    ("pipe_inflight_hwm", "TpuPipeInflightHwm", "tpu_pipe_inflight_hwm"),
    # ops completed by the fused native-stream loop (--tpustream): disk
    # I/O reaped from the engine's ring and handed straight to the
    # transfer pipeline — zero means the phase ran the Python loop
    ("stream_fused_ops", "TpuStreamFusedOps", "tpu_stream_fused_ops"),
    # data-plane fault tolerance (--ioretries/--iotimeout/--tpufallback):
    # per-op retry/timeout accounting lives on the WORKER (the retries
    # happen in storage loops that exist with or without a TPU context —
    # see PATH_AUDIT_WORKER_ATTRS); chip failover lives on the context
    ("io_retries", "IoRetries", "io_retries"),
    ("io_retry_usec", "IoRetryUsec", "io_retry_usec"),
    ("io_timeouts", "IoTimeouts", "io_timeouts"),
    ("chip_failovers", "TpuChipFailovers", "tpu_chip_failovers"),
    # unified staging pool (utils/staging_pool.py): slot-reuse /
    # occupancy / fixed-buffer-registration / SQPOLL audit — the proof
    # that the shared allocator (and its one-time io_uring registration)
    # actually served the phase's I/O (see PATH_AUDIT_POOL_ATTRS)
    ("pool_buf_reuses", "PoolBufReuses", "pool_buf_reuses"),
    ("pool_occupancy_hwm", "PoolOccupancyHwm", "pool_occupancy_hwm"),
    ("pool_registered_ops", "PoolRegisteredOps", "pool_registered_ops"),
    ("pool_sqpoll_ops", "PoolSqpollOps", "pool_sqpoll_ops"),
    # pod-slice phase (--tpuslice; workers/tpuslice.py): striped storage
    # ingest across every chip of the mesh + ICI redistribution. All four
    # live on the WORKER (the slice phase runs with or without a
    # per-worker TpuWorkerContext): ShardIngestMiB counts each worker's
    # shard bytes fed onto the mesh, the Ici trio is recorded by the
    # driver worker that runs the SPMD redistribution step. IciGbpsHwm is
    # a high-water mark (best single-stripe redistribution rate) and
    # MAX-merges like the other hwm counters.
    ("shard_ingest_mib", "ShardIngestMiB", "shard_ingest_mib"),
    ("ici_redist_mib", "IciRedistMiB", "ici_redist_mib"),
    ("ici_redist_usec", "IciRedistUSec", "ici_redist_usec"),
    ("ici_gbps_hwm", "IciGbpsHwm", "ici_gbps_hwm"),
    # slow-op forensics (--slowops/--opsample; telemetry/slowops.py):
    # records captured into the per-worker K-slowest heaps, sample
    # points the density reservoirs dropped on compaction, and the
    # running p99.9 high-water mark of per-op latency (MAX merge — a
    # sum of percentiles means nothing). All worker-owned: the capture
    # happens in storage loops that exist with or without a TPU context.
    ("slow_ops_recorded", "SlowOpsRecorded", "slow_ops_recorded"),
    ("op_samples_dropped", "OpSamplesDropped", "op_samples_dropped"),
    ("tail_p999_usec_hwm", "TailP999UsecHwm", "tail_p999_usec_hwm"),
)

#: counters owned by the Worker object itself rather than the
#: TpuWorkerContext: the merge reads them from the worker even when a
#: TPU context is attached, and the context's per-phase counter reset
#: must not shadow them with zeros on the context
PATH_AUDIT_WORKER_ATTRS = frozenset({
    "io_retries", "io_retry_usec", "io_timeouts",
    "pool_buf_reuses", "pool_occupancy_hwm", "pool_registered_ops",
    "pool_sqpoll_ops", "shard_ingest_mib", "ici_redist_mib",
    "ici_redist_usec", "ici_gbps_hwm", "slow_ops_recorded",
    "op_samples_dropped", "tail_p999_usec_hwm"})

#: counters owned by the worker's StagingPool: the merge reads them
#: from worker._staging_pool when one is attached (local workers), and
#: from the ingested worker attribute otherwise (RemoteWorkers)
PATH_AUDIT_POOL_ATTRS = frozenset({
    "pool_buf_reuses", "pool_occupancy_hwm", "pool_registered_ops",
    "pool_sqpoll_ops"})

#: counters that merge across workers as MAX, not sum: a high-water mark
#: summed over workers would report an in-flight depth no single ring
#: ever reached. TpuChipFailovers is a hwm too: every worker sharing a
#: lost chip records its own failover, so a sum would multiply one chip
#: loss by the worker count — MAX reports the deepest failover chain any
#: single worker ran (~ chips lost along the worst path).
PATH_AUDIT_MAX_KEYS = frozenset({"TpuPipeInflightHwm", "TpuChipFailovers",
                                 "PoolOccupancyHwm", "IciGbpsHwm",
                                 "TailP999UsecHwm"})


def sum_path_audit_counters(workers) -> dict:
    """Total the path-audit counters over a worker list, reading local
    workers' TpuWorkerContext (or StagingPool, for PATH_AUDIT_POOL_ATTRS
    entries) directly — worker-owned entries always come from the
    worker — and RemoteWorkers' ingested attributes (keyed by wire/JSON
    name, ready to merge into records). PATH_AUDIT_MAX_KEYS entries
    merge as max instead of sum."""
    totals = {key: 0 for _, key, _ in PATH_AUDIT_COUNTERS}
    for w in workers:
        ctx = getattr(w, "_tpu", None)
        pool = getattr(w, "_staging_pool", None)
        for attr, key, ingest_attr in PATH_AUDIT_COUNTERS:
            if attr in PATH_AUDIT_POOL_ATTRS:
                val = getattr(pool, attr) if pool is not None \
                    else getattr(w, ingest_attr, 0)
            elif ctx is not None and attr not in PATH_AUDIT_WORKER_ATTRS:
                val = getattr(ctx, attr)
            else:
                val = getattr(w, ingest_attr, 0)
            if key in PATH_AUDIT_MAX_KEYS:
                totals[key] = max(totals[key], val)
            else:
                totals[key] += val
    return totals


#: conservative message markers for device-loss classification —
#: deliberately narrow so an unrelated RuntimeError (e.g. the --tpubudget
#: breach, whose message mentions DMA) can never be eaten by failover
_DEVICE_LOSS_MARKERS = (
    "device lost", "data loss", "data_loss", "failed to enqueue",
    "device is in an error state", "device unavailable",
    "chip is unavailable", "hardware failure", "device halted",
)

#: exception type names that identify an XLA runtime / device failure
#: (matched by name: jaxlib's XlaRuntimeError moves between modules
#: across versions, and tests raise a shape-compatible fake)
_DEVICE_LOSS_TYPE_NAMES = ("XlaRuntimeError", "DeviceLostError",
                           "TpuDeviceLostError")


def is_device_loss_error(err: BaseException) -> bool:
    """Classify an exception raised on the TPU transfer path: True for
    XLA-runtime/device-loss failures (the chip-failover trigger of
    --tpufallback), False for everything else — a logic error or a
    --tpubudget breach must abort, never failover."""
    for cls in type(err).__mro__:
        if cls.__name__ in _DEVICE_LOSS_TYPE_NAMES:
            return True
    msg = str(err).lower()
    return any(marker in msg for marker in _DEVICE_LOSS_MARKERS)


class TpuDeviceLostError(RuntimeError):
    """Raised when --tpufallback abort (the default) sees a device loss:
    carries the chip id so the phase error names the failed chip."""

    def __init__(self, chip_id: int, cause: BaseException):
        self.chip_id = chip_id
        super().__init__(
            f"TPU chip {chip_id} lost mid-phase "
            f"({type(cause).__name__}: {cause}); rerun with --tpufallback "
            f"chip|host to survive single-chip loss")


def _get_jax():
    """Lazy jax import so CPU-only workloads never pay for it."""
    global _jax_mod
    if _jax_mod is None:
        with _jax_lock:
            if _jax_mod is None:
                import jax
                try:
                    # persistent compile cache: TPU jit compiles are 20-40s,
                    # benchmark processes are short-lived
                    jax.config.update(
                        "jax_compilation_cache_dir",
                        os.environ.get("ELBENCHO_TPU_JIT_CACHE",
                                       "/tmp/elbencho_tpu_jit_cache"))
                    jax.config.update(
                        "jax_persistent_cache_min_compile_time_secs", 0.5)
                except Exception:  # pragma: no cover - older jax
                    pass
                _jax_mod = jax
    return _jax_mod


def available_tpu_devices() -> list:
    jax = _get_jax()
    return list(jax.devices())


class TransferPipeline:
    """Ring of up to ``depth`` in-flight device transfers with split
    dispatch-vs-DMA accounting (the io_uring-style submission/completion
    window of the reference's cuFile iodepth semantics, re-done on JAX's
    async dispatch: submit block k+1 while block k's DMA is in flight,
    wait only when the ring is full or at flush).

    Counters (all per-phase, reset via reset_counters):

    - ``dispatch_usec``  host-side submit cost: time spent issuing
      transfers (device_put / dlpack import / jitted copy dispatch) —
      the per-block overhead the VERDICT's budget targets.
    - ``transfer_usec``  DMA wall time: submission -> block_until_ready
      per transfer, measured when the ring entry is drained. In-flight
      windows overlap, so this is per-block transfer latency, not a
      divisor for aggregate bandwidth (use phase wall time for that).
    - ``full_stalls``    full-ring drains that actually had to WAIT for
      the oldest transfer (it was not yet ready) — zero on a healthy
      fully-overlapped pipeline, ~ops when the ring is capacity-bound.
    - ``inflight_hwm``   in-flight high-water mark — proof the pipeline
      actually overlapped transfers (>= 2 under any real pipelining).

    ``budget_usec`` (--tpubudget): maximum average host-side dispatch
    cost per submitted op; check_budget() fails the run LOUDLY when the
    measured overhead exceeds it.
    """

    def __init__(self, depth: int, budget_usec: int = 0):
        from collections import deque
        self.depth = max(depth, 1)
        self.budget_usec = max(budget_usec, 0)
        self._ring = deque()  # (device array, submit-done perf_counter_ns)
        self.dispatch_usec = 0
        self.transfer_usec = 0
        self.full_stalls = 0
        self.inflight_hwm = 0
        self.ops = 0
        # --tracefile sub-span recorder (telemetry/tracer.py); None keeps
        # the hot path a single attribute test per transfer
        self.tracer = None
        self.trace_rank = 0

    def submit(self, submit_fn):
        """Issue one transfer (submit_fn() -> device array) into the ring,
        then drain to at most depth-1 in flight: with io_depth rotating
        host buffers, the buffer reused next is then guaranteed drained
        (depth == 1 -> fully synchronous, per-block latency honest)."""
        import time
        t0 = time.perf_counter_ns()
        arr = submit_fn()
        t1 = time.perf_counter_ns()
        self.dispatch_usec += (t1 - t0) // 1000
        self.ops += 1
        if self.tracer is not None:
            self.tracer.record("tpu_dispatch", "tpu", t0, (t1 - t0) // 1000,
                               rank=self.trace_rank, sampled=True)
        self._ring.append((arr, t1))
        if len(self._ring) > self.inflight_hwm:
            self.inflight_hwm = len(self._ring)
        while len(self._ring) >= self.depth:
            self._drain_one(count_stall=True)
        return arr

    def note_dispatch(self, usec: int) -> None:
        """Account host-side submit cost of a transfer issued outside the
        ring (D2H exports, speculative prefetch issues) so --tpubudget
        covers both directions."""
        self.dispatch_usec += usec
        self.ops += 1
        if self.tracer is not None:
            self.tracer.record("tpu_dispatch", "tpu",
                               self.tracer.now_ns() - usec * 1000, usec,
                               rank=self.trace_rank, sampled=True)

    def note_transfer(self, usec: int) -> None:
        """Account DMA wall time of a transfer completed outside the ring
        (blocking D2H export waits)."""
        self.transfer_usec += usec
        if self.tracer is not None:
            self.tracer.record("tpu_dma", "tpu",
                               self.tracer.now_ns() - usec * 1000, usec,
                               rank=self.trace_rank, sampled=True)

    def _drain_one(self, count_stall: bool = False) -> None:
        """Complete the oldest in-flight transfer. A full-ring drain
        (count_stall) only counts as a stall when the transfer had NOT
        finished yet — a healthy fully-overlapped pipeline drains
        already-ready entries and must read as zero stalls, not ~100%.
        Arrays without is_ready (foreign device types) count
        conservatively as stalled."""
        import time
        arr, t_submit = self._ring.popleft()
        if count_stall:
            is_ready = getattr(arr, "is_ready", None)
            if is_ready is None or not is_ready():
                self.full_stalls += 1
        arr.block_until_ready()
        done_ns = time.perf_counter_ns()
        self.transfer_usec += (done_ns - t_submit) // 1000
        if self.tracer is not None:
            self.tracer.record("tpu_dma", "tpu", t_submit,
                               (done_ns - t_submit) // 1000,
                               rank=self.trace_rank, sampled=True)

    def flush(self, check_budget: bool = True) -> None:
        """Drain every in-flight transfer (phase-end completion wait); by
        default also enforce --tpubudget — teardown paths pass
        check_budget=False so a breach can't fire during cleanup."""
        while self._ring:
            self._drain_one()
        if check_budget:
            self.check_budget()

    def check_budget(self) -> None:
        """--tpubudget: fail LOUDLY when the measured per-op host dispatch
        overhead exceeds the budget (the VERDICT's 'measured per-block
        overhead budget' — a silent regression of the dispatch hot path
        must abort the run, not ship a degraded number)."""
        if not self.budget_usec or not self.ops:
            return
        avg = self.dispatch_usec / self.ops
        if avg > self.budget_usec:
            raise RuntimeError(
                f"--tpubudget exceeded: measured per-op dispatch overhead "
                f"{avg:.1f} usec > budget {self.budget_usec} usec over "
                f"{self.ops} ops ({self.dispatch_usec} usec host-side "
                f"dispatch total; DMA wall {self.transfer_usec} usec)")

    def drain_to(self, max_inflight: int) -> None:
        """Drain the ring until at most max_inflight transfers are in
        flight — the dlpack-stability helper for host-buffer reuse: a
        caller about to rewrite a buffer submitted k transfers ago
        drains to k-1 first, making the alias provably released."""
        while len(self._ring) > max(max_inflight, 0):
            self._drain_one()

    def poison(self) -> None:
        """Drop every in-flight entry WITHOUT completion waits: the chip
        failover path — block_until_ready on a lost chip would hang or
        re-raise, and the data of in-flight transfers is gone either
        way. Timing counters keep what they accumulated."""
        self._ring.clear()

    def reset_counters(self) -> None:
        self.dispatch_usec = 0
        self.transfer_usec = 0
        self.full_stalls = 0
        self.inflight_hwm = 0
        self.ops = 0


class TpuWorkerContext:
    """Per-worker handle to one TPU chip's HBM (CuFileHandleData analogue,
    reference source/CuFileHandleData.h:18-73)."""

    #: device-resident pre-filled source blocks (curand-at-alloc parity)
    _FILL_POOL_BLOCKS = 4

    def __init__(self, chip_id: int, block_size: int, direct: bool = False,
                 verify_on_device: bool = False, pipeline_depth: int = 1,
                 hbm_limit_pct: int = 90, batch_blocks: int = 1,
                 dispatch_budget_usec: int = 0, staging_pool=None):
        jax = _get_jax()
        devices = jax.devices()
        if not devices:
            raise RuntimeError("no TPU/XLA devices available")
        self.chip_id = chip_id
        self.device = devices[chip_id % len(devices)]
        self.block_size = block_size
        self.direct = direct
        self.verify_on_device = verify_on_device
        self.pipeline_depth = max(pipeline_depth, 1)
        # --tpuhbmpct budget enforcement: resident HBM is the fill pool +
        # the in-flight transfer ring + the last-ingested sink block. The
        # pool shrinks and the pipeline depth is clamped to fit the budget;
        # below the 3-block floor (1 pool/in-flight + 1 sink + 1 headroom)
        # the block size is rejected outright.
        self.hbm_budget_bytes = hbm_bytes_limit(self.device, hbm_limit_pct)
        budget_blocks = self.hbm_budget_bytes // max(block_size, 1)
        if budget_blocks < 3:
            raise RuntimeError(
                f"block size {block_size} exceeds the HBM staging budget "
                f"of chip {chip_id} ({self.hbm_budget_bytes} bytes at "
                f"--tpuhbmpct {hbm_limit_pct} fits fewer than 3 blocks)")
        # --tpubatch: coalesce N blocks into one DMA, amortizing the
        # per-transfer dispatch overhead (the dominant cost on tunneled
        # chips: ~71 ms/op measured round 2 vs ~5 ms for the extra
        # host-side copy a 16M block costs). Disabled under on-device
        # verify, which needs per-block arrays.
        self.batch_blocks = max(batch_blocks, 1)
        if verify_on_device and self.batch_blocks > 1:
            from ..toolkits.logger import LOG_NORMAL, log
            log(LOG_NORMAL, "NOTE: --tpubatch is ignored with "
                            "--tpuverify (per-block on-device checks)")
            self.batch_blocks = 1
        self._pool_blocks = min(self._FILL_POOL_BLOCKS,
                                max(budget_blocks - 2, 1))
        # a single aggregated span must itself fit the budget (alongside
        # the sink block and the D2H ring's share): clamp batch_blocks
        # BEFORE it sizes the ring math below, or one --tpubatch DMA
        # could exceed --tpuhbmpct outright
        spare_blocks = max(budget_blocks - self._pool_blocks - 1, 2)
        if self.batch_blocks > spare_blocks // 2:
            clamped = max(spare_blocks // 2, 1)
            from ..toolkits.logger import LOG_NORMAL, log
            log(LOG_NORMAL,
                f"NOTE: --tpubatch {self.batch_blocks} exceeds the HBM "
                f"staging budget; clamped to {clamped}")
            self.batch_blocks = clamped
        # both rings can be live on ONE context in the same phase (rwmix
        # interleaves reads -> H2D in-flight ring with writes -> D2H
        # speculative ring), so the depth clamp budgets for two rings of
        # pipeline_depth slots each — and with batching every H2D slot
        # holds batch_blocks blocks of HBM
        max_depth = max((budget_blocks - self._pool_blocks - 1)
                        // (2 * self.batch_blocks), 1)
        self.pipeline_depth = min(self.pipeline_depth, max_depth)
        self._h2d_agg = None
        self._h2d_agg_fill = 0  # words staged in the active agg buffer
        self._own_pool = None   # private allocator when no worker pool
        if self.batch_blocks > 1:
            # page-aligned host aggregation buffers (64B-aligned for the
            # dlpack export of the --tpudirect path). One buffer per
            # ring slot: a buffer stays aliased by its in-flight direct
            # import until the ring drains it, so the next batch must
            # stage into a different buffer (same rotation discipline
            # as the worker's iodepth I/O buffers). The byte size is
            # rounded up to a uint32 multiple so non-word-aligned block
            # sizes (e.g. -b 6 --tpubatch 3) still view cleanly.
            # Allocation comes from the worker's unified staging pool
            # (same hugepage/NUMA policy, one teardown owner); contexts
            # without a pool (tpubench probes, tests) fall back to a
            # private pool-less slab via a throwaway allocator.
            agg_bytes = self.batch_blocks * max(block_size, 1)
            agg_bytes += (-agg_bytes) % 4
            agg_bytes = max(agg_bytes, 4)
            if staging_pool is None:
                from ..utils.staging_pool import StagingPool
                staging_pool = self._own_pool = StagingPool(
                    1, 4096, register=False, log_rank=None)
            self._h2d_agg_views = staging_pool.alloc_aux(
                max(self.pipeline_depth, 1), agg_bytes)
            self._h2d_agg_ring = [np.frombuffer(mv, dtype=np.uint32)
                                  for mv in self._h2d_agg_views]
            self._h2d_agg_idx = 0
            self._h2d_agg = self._h2d_agg_ring[0]
        self._key = jax.random.PRNGKey(chip_id)
        self._num_words = max(block_size // 4, 1)
        # write-source pool: filled ONCE on first use, like the reference's
        # curandGenerate at allocGPUIOBuffer time (LocalWorker.cpp:1427);
        # device_to_host then only pays the D2H DMA, not per-block RNG.
        # Lazy so read-only workloads never compile the fill kernel.
        self._fill_pool: list = []
        self._fill_idx = 0
        # in-flight H2D transfers (pipelined up to --iodepth / --tpudepth;
        # the completion wait happens when the ring is full or at flush()),
        # with split dispatch-vs-DMA accounting and --tpubudget enforcement
        self._pipeline = TransferPipeline(self.pipeline_depth,
                                          budget_usec=dispatch_budget_usec)
        # donation-based staging-slot reuse (staged path): one HBM block
        # per ring slot, recycled by a donating jitted device-copy step so
        # steady-state ingest re-uses buffers instead of allocating one
        # per block. Latches off on backends without buffer donation.
        self._slot_prev: "list" = [None] * self.pipeline_depth
        self._staged_submits = 0
        self._copy_step = None
        self._donate_ok = True
        self._donate_probed = False
        self.staging_reuses = 0
        self._last_ingested = None
        # --tpudirect path accounting (auditable: a user A/B-ing direct vs
        # staged must be able to see which path actually executed)
        self.h2d_direct_ops = 0
        self.h2d_staged_ops = 0
        self.h2d_direct_fallbacks = 0
        self._direct_warned = False
        # the H2D import and D2H export are INDEPENDENT capabilities of
        # --tpudirect (e.g. on the virtual mesh the export works on every
        # device while the import only aliases onto device 0), so each
        # has its own works/failed latch; self.direct stays the user's
        # intent and is never mutated
        self._h2d_direct_ok = True
        # symmetric D2H audit (write path / --tpubench d2h): direct =
        # zero-copy dlpack export of the device block, staged = np.asarray
        # D2H; prefetch = async D2H issued ahead of consumption
        self.d2h_direct_ops = 0
        self.d2h_staged_ops = 0
        self.d2h_direct_fallbacks = 0
        self.d2h_prefetch_hits = 0
        self.d2h_prefetch_misses = 0
        self._d2h_direct_ok = True
        self._d2h_warned = False
        # speculative verify-pattern pipeline: (offset, length, salt) ->
        # device block with its D2H already issued. Bounded by
        # pipeline_depth; sequential write streams hit, random streams
        # miss and speculation self-disables after a miss streak.
        self._d2h_spec: dict = {}
        self._d2h_spec_miss_streak = 0
        # fused native-stream loop audit (--tpustream; schema entry in
        # PATH_AUDIT_COUNTERS): ops whose storage I/O ran in the engine's
        # submission/completion ring
        self.stream_fused_ops = 0
        # --tpufallback: chip-failover audit + host-staging degraded mode.
        # chip_failovers is per-phase (PATH_AUDIT_COUNTERS); the
        # host-staging latch persists for the run — a lost chip stays
        # lost (workers/local_worker.py drives the failover decisions)
        self.chip_failovers = 0
        self._host_staging = False
        self._host_sink = None       # host staging: H2D sink buffer
        self._host_fill_pool: list = []  # host staging: write-source pool

    # -- chip failover (--tpufallback; the data-plane analogue of
    # --svctolerant: survive single-chip loss instead of aborting) -------

    @property
    def host_staging(self) -> bool:
        """True when the context degraded to host-memory staging after a
        chip loss (--tpufallback host, or chip mode with no survivor)."""
        return self._host_staging

    def _poison_device_state(self) -> None:
        """Drop every reference to device-resident state without touching
        the (possibly dead) chip: in-flight ring entries, staging slots,
        fill pool, speculative D2H blocks, the jitted copy step. No
        block_until_ready anywhere — the chip may never answer again."""
        self._pipeline.poison()
        self._slot_prev = [None] * self.pipeline_depth
        self._staged_submits = 0
        self._copy_step = None
        self._donate_ok = True
        self._donate_probed = False
        self._fill_pool = []
        self._fill_idx = 0
        self._d2h_spec = {}
        self._d2h_spec_miss_streak = 0
        self._last_ingested = None
        self._h2d_agg_fill = 0

    def failover_to_chip(self, new_chip_id: int) -> None:
        """Drain-and-poison the failed chip's state, then redirect this
        context to a surviving chip. The caller (LocalWorker) picks the
        survivor and registers the failed chip in the shared poison set
        so sibling workers stop submitting to it."""
        from ..toolkits.logger import log_error
        self._poison_device_state()
        jax = _get_jax()
        devices = jax.devices()
        old = self.chip_id
        self.chip_id = new_chip_id
        self.device = devices[new_chip_id % len(devices)]
        self._key = jax.random.PRNGKey(new_chip_id)
        self.chip_failovers += 1
        log_error(f"TPU chip {old} lost; worker failed over to chip "
                  f"{new_chip_id} (--tpufallback chip)")

    def failover_to_host(self) -> None:
        """Degrade to host-memory staging: transfers become host memcpys
        (the accounting keeps flowing so phase results stay complete and
        the TpuChipFailovers counter marks them DEGRADED-TPU). On-device
        verify falls back to the host-side check."""
        from ..toolkits.logger import log_error
        self._poison_device_state()
        self._host_staging = True
        self.verify_on_device = False  # host memcmp takes over
        self.chip_failovers += 1
        log_error(f"TPU chip {self.chip_id} lost; worker degraded to "
                  f"host-memory staging (--tpufallback host)")

    def _host_staged_h2d(self, np_view: np.ndarray) -> None:
        """Host-staging H2D: the staging copy without a device. Counted
        as a staged op so op-count parity checks keep holding."""
        import time
        t0 = time.perf_counter_ns()
        if self._host_sink is None or len(self._host_sink) < len(np_view):
            self._host_sink = np.empty(max(len(np_view), self._num_words),
                                       dtype=np.uint32)
        self._host_sink[:len(np_view)] = np_view
        self.h2d_staged_ops += 1
        self._pipeline.note_dispatch(
            (time.perf_counter_ns() - t0) // 1000)

    def _host_staged_d2h(self, buf: memoryview, length: int,
                         verify_salt: int, file_offset: int) -> None:
        """Host-staging D2H: produce the exact bytes the device path
        would have produced — the verify pattern for --verify phases, a
        deterministic PRNG pool otherwise — so a failed-over write phase
        still writes verifiable content."""
        import time
        t0 = time.perf_counter_ns()
        dst = np.frombuffer(buf, dtype=np.uint8, count=length)
        if verify_salt:
            n_words = length // 8
            arr = np.frombuffer(buf[:n_words * 8], dtype=np.uint64)
            with np.errstate(over="ignore"):
                arr[:] = (np.arange(n_words, dtype=np.uint64)
                          * np.uint64(8) + np.uint64(file_offset)
                          + np.uint64(verify_salt))
            if length % 8:
                dst[n_words * 8:] = 0
        else:
            if not self._host_fill_pool:
                from ..toolkits.random_algos import create_rand_algo
                fill = create_rand_algo("fast", seed=self.chip_id + 1)
                blk = max(self._num_words * 4, 4)
                self._host_fill_pool = [
                    np.frombuffer(fill.fill_buffer(blk), dtype=np.uint8)
                    for _ in range(self._FILL_POOL_BLOCKS)]
            self._fill_idx = (self._fill_idx + 1) \
                % len(self._host_fill_pool)
            src = self._host_fill_pool[self._fill_idx]
            dst[:length] = src[:length]
        self.d2h_staged_ops += 1
        self._pipeline.note_dispatch(
            (time.perf_counter_ns() - t0) // 1000)

    # -- read path: host buffer -> HBM --------------------------------------

    def host_to_device(self, buf: memoryview, length: int,
                       verify_salt: int = 0, file_offset: int = 0) -> None:
        """DMA the freshly-read block into HBM (replaces cudaMemcpyAsync H2D,
        LocalWorker.cpp:2437-2490). With pipeline_depth == 1 (default) the
        call waits for completion so per-block latency stays honest; deeper
        pipelines overlap up to --iodepth transfers and only wait when the
        ring is full (documented pipelined mode, SURVEY.md section 7 "TPU
        transfer overlap"). With --tpuverify, the on-device fingerprint
        check replaces the host-side memcmp.

        Two transfer paths (the cuFileRead-vs-cudaMemcpy split of the
        reference, LocalWorker.cpp:2633-2749):

        - staged (default): ``jax.device_put`` of the buffer view. jax's
          host-buffer semantics defensively guarantee the source can be
          reused the moment the call returns, which costs an internal
          staging copy of every block.
        - direct (--tpudirect): the page-aligned I/O buffer (mmap-backed,
          64B-aligned for O_DIRECT) is exported via dlpack straight into
          the device transfer — no defensive copy; on host-backed devices
          (virtual CPU mesh) the import is true zero-copy. The stability
          guarantee dlpack shifts to the producer is exactly what the
          drain-to-depth-1 ring below provides: a host buffer is never
          rewritten before its transfer completed (CuFileHandleData
          register-once discipline, reference CuFileHandleData.h:18-73).
        """
        n_words = length // 4
        np_view = np.frombuffer(buf[:n_words * 4], dtype=np.uint32)
        if self._host_staging:  # degraded after chip loss (--tpufallback)
            self._host_staged_h2d(np_view)
            return
        if self.batch_blocks > 1:
            # --tpubatch: stage into the aggregation buffer; the DMA
            # fires once per batch_blocks blocks (or at flush), so the
            # per-transfer dispatch cost is paid once per batch. The
            # copy releases the caller's I/O buffer immediately, which
            # also means the dlpack stability contract moves to the
            # aggregation buffer (drained before reuse via the ring).
            start = self._h2d_agg_fill
            self._h2d_agg[start:start + n_words] = np_view
            self._h2d_agg_fill = start + n_words
            if self._h2d_agg_fill + self._num_words > len(self._h2d_agg):
                self._flush_h2d_batch()
            return
        self._transfer_h2d(np_view)
        if verify_salt and self.verify_on_device:
            from ..ops.verify import verify_block_on_device
            verify_block_on_device(self._last_ingested, file_offset,
                                   length, verify_salt)

    #: read access to the pipeline's ring for tests/diagnostics (the ring
    #: discipline itself lives in TransferPipeline)
    @property
    def _inflight(self):
        return self._pipeline._ring

    @property
    def pipe_full_stalls(self) -> int:
        return self._pipeline.full_stalls

    @property
    def pipe_inflight_hwm(self) -> int:
        return self._pipeline.inflight_hwm

    @property
    def dispatch_usec(self) -> int:
        """Host-side submit cost this phase (both directions)."""
        return self._pipeline.dispatch_usec

    @property
    def transfer_usec(self) -> int:
        """DMA wall time this phase (both directions)."""
        return self._pipeline.transfer_usec

    def _transfer_h2d(self, np_view: np.ndarray) -> None:
        """One DMA into the in-flight pipeline (a block, or a --tpubatch
        aggregation span). The staged path recycles per-slot HBM staging
        buffers through a donating jitted copy (see _staged_submit); the
        direct path imports the host buffer as-is (zero-bounce)."""
        if self.direct and self._h2d_direct_ok:
            arr = self._pipeline.submit(
                lambda: self._direct_import(np_view))
        else:
            arr = self._pipeline.submit(
                lambda: self._staged_submit(np_view))
        self._last_ingested = arr  # keep resident (benchmark sink)

    def _staged_submit(self, np_view: np.ndarray):
        """device_put of the block, then — when a drained staging slot of
        matching shape exists — a donation-based jitted device copy into
        it, so the slot's HBM buffer is reused instead of re-allocated
        per block (the allocGPUIOBuffer-once discipline of the reference,
        LocalWorker.cpp:1427: buffers live for the worker's lifetime).
        The slot rotation mirrors the ring: a slot is reused exactly
        depth staged SUBMITS later (a dedicated counter — pipeline.ops
        also counts D2H note_dispatch entries, so keying on it would
        reuse, and donate, a slot whose array is still in the in-flight
        ring on mixed H2D/D2H phases), by which point the ring — at most
        depth-1 deep after every drain — has drained it."""
        jax = _get_jax()
        placed = jax.device_put(np_view, self.device)
        self.h2d_staged_ops += 1
        if not self._donate_ok:
            return placed
        slot = self._staged_submits % self.pipeline_depth
        self._staged_submits += 1
        prev = self._slot_prev[slot]
        arr = placed
        if prev is not None and prev.shape == placed.shape \
                and prev.dtype == placed.dtype:
            try:
                arr = self._donated_copy(prev, placed)
                self.staging_reuses += 1
            except Exception:  # noqa: BLE001 - donation unsupported
                self._donate_ok = False
                arr = placed
        self._slot_prev[slot] = arr
        return arr

    def _donated_copy(self, dst, src):
        """dst <- src on device, donating dst so XLA reuses its buffer for
        the output (jax's canonical in-place update pattern). Probed once:
        a backend that ignores donation warns instead of reusing — latch
        the copy step off there rather than paying a copy for nothing."""
        jax = _get_jax()
        if self._copy_step is None:
            self._copy_step = jax.jit(
                lambda d, s: jax.lax.dynamic_update_slice(d, s, (0,)),
                donate_argnums=(0,))
        if not self._donate_probed:
            self._donate_probed = True
            import warnings
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                out = self._copy_step(dst, src)
            if any("donat" in str(w.message).lower() for w in caught):
                self._donate_ok = False
                raise RuntimeError("buffer donation unsupported")
            return out
        return self._copy_step(dst, src)

    def _flush_h2d_batch(self) -> None:
        if self._h2d_agg_fill:
            self._transfer_h2d(self._h2d_agg[:self._h2d_agg_fill])
            # rotate to the next aggregation buffer: the one just
            # transferred may stay aliased by a direct import until the
            # ring drains it (by then the rotation has cycled past it)
            self._h2d_agg_idx = (self._h2d_agg_idx + 1) \
                % len(self._h2d_agg_ring)
            self._h2d_agg = self._h2d_agg_ring[self._h2d_agg_idx]
            self._h2d_agg_fill = 0

    def _direct_import(self, np_view: np.ndarray):
        """Zero-bounce dlpack import of the I/O buffer (--tpudirect).
        On a host-backed device (virtual CPU mesh) copy=False demands a
        true zero-copy alias — a buffer that would need a hidden copy
        (e.g. sub-64B-aligned) falls back LOUDLY instead of silently
        degrading. On a real TPU the host->HBM copy is inherent (there is
        no storage->HBM DMA engine); what direct skips is the framework's
        defensive staging/dispatch layer: the registered page-aligned
        buffer goes straight into the PjRt import. One note + counted
        fallback to the staged path on any export failure."""
        jax = _get_jax()
        try:
            from jax import dlpack as jax_dlpack
            copy_mode = False if self.device.platform == "cpu" else None
            arr = jax_dlpack.from_dlpack(np_view, device=self.device,
                                         copy=copy_mode)
            self.h2d_direct_ops += 1
            return arr
        except Exception as err:  # noqa: BLE001 - any export failure
            if not self._direct_warned:
                self._direct_warned = True
                from ..toolkits.logger import log, LOG_NORMAL
                log(LOG_NORMAL,
                    f"NOTE: --tpudirect dlpack export failed for chip "
                    f"{self.chip_id} ({err}); falling back to the staged "
                    f"transfer path for this run")
            # the I/O buffers are fixed for the worker's lifetime, so one
            # failed import means they all fail: latch the H2D side off
            # so the hot loop doesn't pay a raise per block (the D2H
            # export is an independent capability and keeps its own latch)
            self._h2d_direct_ok = False
            self.h2d_direct_fallbacks += 1
            self.h2d_staged_ops += 1
            return jax.device_put(np_view, self.device)

    def holdback_depth(self) -> int:
        """How many freshly-ingested staging slots the fused stream loop
        must keep OUT of the engine's ring after their host_to_device:
        with an unbatched --tpudirect import the device array aliases
        the host buffer until its transfer drains, and the pipeline
        holds at most depth-1 transfers after every submit — so holding
        the last depth-1 ingested slots is exactly the drain guarantee
        the dlpack stability contract needs. The staged path (and the
        --tpubatch aggregation path) copy the buffer out at submit time
        and need no holdback."""
        if self.direct and self._h2d_direct_ok and self.batch_blocks == 1:
            return max(self.pipeline_depth - 1, 0)
        return 0

    def set_tracer(self, tracer, rank: int) -> None:
        """Arm --tracefile dispatch-vs-DMA sub-spans on this context's
        transfer pipeline (telemetry/tracer.py; no-op path untouched)."""
        self._pipeline.tracer = tracer
        self._pipeline.trace_rank = rank

    def drain_to(self, max_inflight: int) -> None:
        """Drain the in-flight transfer ring to at most max_inflight
        entries (see TransferPipeline.drain_to): the explicit form of
        the buffer-rotation guarantee for callers that reuse host
        buffers on their own schedule — the fused stream loop calls it
        to release a held-back staging slot without waiting for more
        storage completions."""
        self._pipeline.drain_to(max_inflight)

    def reset_path_counters(self) -> None:
        """Zero the H2D/D2H path-audit counters (called from the worker's
        per-phase reset_stats so each phase record reports its own ops,
        consistent with the phase-reset TpuHbmBytes). Speculation state
        resets with them: a random-offset phase must not leave prefetch
        disabled for a later sequential phase, and stale speculated
        blocks must not charge a miss to the next phase's record."""
        for attr, _key, _ingest in PATH_AUDIT_COUNTERS:
            # pipeline-owned counters reset below; worker-owned counters
            # (io_retries & co) reset in Worker.reset_stats — creating
            # zeros for them HERE would shadow the worker's real counts
            # in sum_path_audit_counters
            if not attr.startswith("pipe_") \
                    and attr not in PATH_AUDIT_WORKER_ATTRS:
                setattr(self, attr, 0)
        # dispatch/transfer timing and the ring audit are per-phase like
        # the rest; an interrupted phase must also drain its in-flight
        # window so the next phase starts with an empty ring
        self._pipeline.flush(check_budget=False)
        self._pipeline.reset_counters()
        self._d2h_spec.clear()
        self._d2h_spec_miss_streak = 0
        # a phase that ended without reaching flush() (worker error /
        # interrupt) must not leak its staged-but-untransferred batch
        # blocks into the next phase's first span
        self._h2d_agg_fill = 0

    def flush(self) -> None:
        """Drain all pipelined transfers (phase-end completion wait),
        including a partially-filled --tpubatch aggregation span, then
        enforce --tpubudget against the measured dispatch overhead."""
        if self._h2d_agg_fill:
            self._flush_h2d_batch()
        self._pipeline.flush()

    def warmup_transfer(self) -> None:
        """Run one staged ingest outside any timed loop so first-use costs
        (the donating copy step's jit compile, transfer-path setup) never
        land inside a measured phase or charge against --tpubudget; the
        counters are reset afterwards (call from worker prepare when the
        workload ingests into HBM)."""
        probe = np.zeros(self._num_words, dtype=np.uint32)
        # depth+1 submits so the first slot is REUSED once: that reuse is
        # what compiles (and donation-probes) the copy step
        for _ in range(self.pipeline_depth + 1):
            self._pipeline.submit(lambda: self._staged_submit(probe))
        self._pipeline.flush(check_budget=False)
        self._pipeline.reset_counters()
        self.h2d_staged_ops = 0
        self.staging_reuses = 0
        self._last_ingested = None

    def _ensure_fill_pool(self) -> None:
        if not self._fill_pool:
            jax = _get_jax()
            from ..ops.fill import random_block_u32
            for i in range(self._pool_blocks):
                key = jax.random.fold_in(self._key, i)
                arr = random_block_u32(key, self._num_words)
                _d2h_async(arr)  # host copies stream while later blocks fill
                self._fill_pool.append(arr)

    def warmup_fill(self) -> None:
        """Build the HBM fill pool ahead of the first measured phase so the
        jit compile never lands inside a timed loop (call from worker
        prepare when the workload includes device-originated writes)."""
        self._ensure_fill_pool()
        _get_jax().block_until_ready(self._fill_pool[-1])

    # -- write path: HBM -> host buffer --------------------------------------

    #: consecutive speculation misses before the verify-pattern prefetch
    #: pipeline concludes the offset stream is not sequential and stops
    #: wasting device compute + HBM on mispredicted blocks
    _D2H_SPEC_MISS_LIMIT = 8

    def device_to_host(self, buf: memoryview, length: int,
                       verify_salt: int = 0, file_offset: int = 0) -> None:
        """Write-source block originates in HBM (on-device PRNG fill, or the
        on-device verify pattern when --verify is active) and is DMA'd to
        the host I/O buffer (replaces curandGenerate + cudaMemcpy D2H,
        LocalWorker.cpp:1427-1537; the reference's GPU path is symmetric,
        cudaMemcpyAsync D2H :2437-2490 — this is the symmetric TPU leg).

        Pipelined like the H2D ring, with the roles flipped:

        - pool path (plain writes): every pool block's host copy is
          issued asynchronously at fill time, so steady-state calls only
          pay the copy into the I/O buffer, never a blocking D2H.
        - verify path (--verify): block content depends on file_offset,
          so the ring speculates — after serving offset o it precomputes
          the patterns for o+len .. o+depth*len on device and issues
          their D2H transfers; a sequential write stream then always
          consumes an already-in-flight block (d2h_prefetch_hits), while
          a random stream misses (d2h_prefetch_misses) and speculation
          self-disables after a miss streak. Depth rides --iodepth
          (pipeline_depth), reusing the H2D ring's HBM budget allowance —
          a phase is either reading (H2D ring live) or writing (D2H
          ring live), never both on the same context.
        - the final hop into the caller's I/O buffer uses a zero-copy
          dlpack export of the device block when --tpudirect is active
          (host-backed backends; real TPUs fall back LOUDLY to the
          staged np.asarray, whose async copy the ring already started).
        """
        import time
        if self._host_staging:  # degraded after chip loss (--tpufallback)
            self._host_staged_d2h(buf, length, verify_salt, file_offset)
            return
        n_words = max(length // 4, 1)
        t0 = time.perf_counter_ns()
        if verify_salt:
            arr = self._verify_block_pipelined(length, n_words,
                                               verify_salt, file_offset)
        else:
            # cycle the pre-filled HBM pool (curand-at-alloc parity)
            self._ensure_fill_pool()
            self._fill_idx = (self._fill_idx + 1) % len(self._fill_pool)
            arr = self._fill_pool[self._fill_idx]
            if n_words != self._num_words:
                arr = arr[:n_words]
        t1 = time.perf_counter_ns()
        # host-side submit cost (pattern/spec issue, pool rotation) vs the
        # blocking export wait: the D2H leg of the dispatch-vs-DMA split
        self._pipeline.note_dispatch((t1 - t0) // 1000)
        host = self._d2h_export(arr)
        self._pipeline.note_transfer((time.perf_counter_ns() - t1) // 1000)
        # single copy into the I/O buffer (tobytes() + slice-assign would
        # add two more full-block copies on this hot path)
        dst = np.frombuffer(buf, dtype=np.uint8, count=length)
        np.copyto(dst[:n_words * 4], host.view(np.uint8)[:length])
        if length % 4:  # trailing sub-word bytes the u32 view can't carry
            dst[n_words * 4:] = 0
        if verify_salt and length % 8:
            dst[(length // 8) * 8:] = 0

    def _verify_block_pipelined(self, length: int, n_words: int,
                                verify_salt: int, file_offset: int):
        """Serve the verify-pattern block for file_offset, preferably from
        the speculative ring, and re-arm speculation for the sequential
        continuation of the stream."""
        from ..ops.fill import verify_pattern_block_u32
        arr = self._d2h_spec.pop((file_offset, length, verify_salt), None)
        if arr is not None:
            self.d2h_prefetch_hits += 1
            self._d2h_spec_miss_streak = 0
        else:
            if self._d2h_spec:
                # mispredicted stream: the speculated blocks are stale
                # (their offsets will never be asked for in order)
                self.d2h_prefetch_misses += 1
                self._d2h_spec_miss_streak += 1
                self._d2h_spec.clear()
            arr = verify_pattern_block_u32(
                _split_u64_params(file_offset, verify_salt), n_words)
            _d2h_async(arr)
        # evaluated AFTER miss accounting so the ring cannot re-arm on
        # the very call whose miss reached the limit
        if self._d2h_spec_miss_streak < self._D2H_SPEC_MISS_LIMIT:
            # speculate the sequential continuation up to ring depth
            for k in range(1, self.pipeline_depth + 1):
                if len(self._d2h_spec) >= self.pipeline_depth:
                    break
                nxt = (file_offset + k * length, length, verify_salt)
                if nxt in self._d2h_spec:
                    continue
                spec_arr = verify_pattern_block_u32(
                    _split_u64_params(nxt[0], verify_salt), n_words)
                _d2h_async(spec_arr)
                self._d2h_spec[nxt] = spec_arr
        return arr

    def _d2h_export(self, arr) -> np.ndarray:
        """Host ndarray of a device block. Direct (--tpudirect): zero-copy
        dlpack export — the device buffer IS the host memory on
        host-backed backends, so the only copy left is the one into the
        I/O buffer (cudaMemcpy-D2H-into-registered-buffer analogue). On
        devices whose memory the host can't address (real TPU HBM) the
        export fails once, falls back LOUDLY to the staged np.asarray
        path (whose transfer the async ring already started), and stays
        disabled so the hot loop doesn't pay a raise per block."""
        if self.direct and self._d2h_direct_ok:
            try:
                host = np.from_dlpack(arr)
                self.d2h_direct_ops += 1
                return host
            except Exception as err:  # noqa: BLE001 - any export failure
                self._d2h_direct_ok = False
                self.d2h_direct_fallbacks += 1
                if not self._d2h_warned:
                    self._d2h_warned = True
                    from ..toolkits.logger import log, LOG_NORMAL
                    log(LOG_NORMAL,
                        f"NOTE: --tpudirect D2H dlpack export failed for "
                        f"chip {self.chip_id} ({err}); falling back to "
                        f"the staged transfer path for this run")
        self.d2h_staged_ops += 1
        return np.asarray(arr)

    def close(self) -> None:
        # teardown drain: no --tpubudget check here — a breach surfaces at
        # the phase-end flush(), never as a secondary error mid-cleanup
        if self._h2d_agg_fill:
            self._flush_h2d_batch()
        self._pipeline.flush(check_budget=False)
        self._last_ingested = None
        self._slot_prev = [None] * self.pipeline_depth
        self._fill_pool = []
        self._d2h_spec = {}
        if self._h2d_agg is not None:
            self._h2d_agg = None
            self._h2d_agg_ring = []
            self._h2d_agg_views = []
        if self._own_pool is not None:
            # contexts without a worker pool own their aggregation slab
            self._own_pool.close()
            self._own_pool = None


def _d2h_async(arr) -> None:
    """Start the device->host copy of arr without blocking (jax caches
    the host copy on the array; a later np.asarray completes instantly
    once the DMA lands). Best-effort: backends without the method just
    stay synchronous."""
    try:
        arr.copy_to_host_async()
    except Exception:  # pragma: no cover - non-jax.Array or old backend
        pass


def _split_u64_params(file_offset: int, salt: int):
    """(base_lo, base_hi) uint32 halves of (offset + salt) mod 2^64 for the
    on-device pattern kernel."""
    base = (file_offset + salt) & ((1 << 64) - 1)
    return (np.uint32(base & 0xFFFFFFFF), np.uint32(base >> 32))


def hbm_bytes_limit(device, pct: int) -> int:
    """--tpuhbmpct: usable HBM staging budget for a chip."""
    try:
        stats = device.memory_stats()
        total = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        if total:
            return int(total) * pct // 100
    except Exception:  # pragma: no cover - backend without memory_stats
        pass
    return 1 << 30  # conservative 1 GiB default
