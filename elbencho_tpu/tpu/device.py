"""TPU HBM data path: per-worker device buffers + host<->HBM transfers.

This is the TPU-native replacement for the reference's CUDA staging
(SURVEY.md section 2.5 "GPU staging" — the north-star port target):

  cudaSetDevice / workerRank % gpuIDs  ->  worker rank % tpu_ids chip pick
                                           (reference LocalWorker.cpp:1444)
  cudaMalloc per iodepth               ->  jax device_put-allocated HBM
                                           staging arrays on the chosen chip
  cudaMemcpy H2D after reads           ->  jax.device_put onto the chip +
                                           block_until_ready (completion wait
                                           keeps per-block latency honest)
  cudaMemcpy D2H before writes         ->  np.asarray(device_array) D2H; the
                                           write-source data originates in
                                           HBM via on-device PRNG (curand
                                           analogue, ops/fill.py)
  cuFileRead (GPUDirect)               ->  --tpudirect: zero-bounce path
                                           using jax dlpack-view of the
                                           page-aligned I/O buffer
  CuFileHandleData register/deregister ->  TpuWorkerContext lifecycle

Per-chip ingest bandwidth is accounted by the worker (tpu_transfer_bytes /
tpu_transfer_usec) and reported by Statistics as "HBM ingest" rows.
"""

from __future__ import annotations

import threading

import numpy as np

_jax_lock = threading.Lock()
_jax_mod = None


def _get_jax():
    """Lazy jax import so CPU-only workloads never pay for it."""
    global _jax_mod
    if _jax_mod is None:
        with _jax_lock:
            if _jax_mod is None:
                import jax
                _jax_mod = jax
    return _jax_mod


def available_tpu_devices() -> list:
    jax = _get_jax()
    return list(jax.devices())


class TpuWorkerContext:
    """Per-worker handle to one TPU chip's HBM (CuFileHandleData analogue,
    reference source/CuFileHandleData.h:18-73)."""

    def __init__(self, chip_id: int, block_size: int, direct: bool = False,
                 verify_on_device: bool = False):
        jax = _get_jax()
        devices = jax.devices()
        if not devices:
            raise RuntimeError("no TPU/XLA devices available")
        self.chip_id = chip_id
        self.device = devices[chip_id % len(devices)]
        self.block_size = block_size
        self.direct = direct
        self.verify_on_device = verify_on_device
        self._key = jax.random.PRNGKey(chip_id)
        self._fill_counter = 0
        # device-resident staging target for reads; rotated per transfer
        self._last_ingested = None
        # pre-warm the on-device fill (first jit compile is slow)
        self._num_words = max(block_size // 4, 1)

    # -- read path: host buffer -> HBM --------------------------------------

    def host_to_device(self, buf: memoryview, length: int,
                       verify_salt: int = 0, file_offset: int = 0) -> None:
        """DMA the freshly-read block into HBM and wait for completion
        (replaces cudaMemcpyAsync H2D + sync, LocalWorker.cpp:2437-2490).
        With --tpuverify, run the on-device fingerprint check instead of a
        host-side memcmp."""
        jax = _get_jax()
        n_words = length // 4
        np_view = np.frombuffer(buf[:n_words * 4], dtype=np.uint32)
        arr = jax.device_put(np_view, self.device)
        arr.block_until_ready()
        self._last_ingested = arr  # keep resident (benchmark sink)
        if verify_salt and self.verify_on_device:
            from ..ops.verify import verify_block_on_device
            verify_block_on_device(arr, file_offset, length, verify_salt)

    # -- write path: HBM -> host buffer --------------------------------------

    def device_to_host(self, buf: memoryview, length: int,
                       verify_salt: int = 0, file_offset: int = 0) -> None:
        """Write-source block originates in HBM (on-device PRNG fill, or the
        on-device verify pattern when --verify is active) and is DMA'd to
        the host I/O buffer (replaces curandGenerate + cudaMemcpy D2H,
        LocalWorker.cpp:1427-1537 / :2437)."""
        jax = _get_jax()
        n_words = max(length // 4, 1)
        if verify_salt:
            from ..ops.fill import verify_pattern_block_u32
            params = _split_u64_params(file_offset, verify_salt)
            arr = verify_pattern_block_u32(params, n_words)
        else:
            from ..ops.fill import random_block_u32
            self._fill_counter += 1
            key = jax.random.fold_in(self._key, self._fill_counter)
            arr = random_block_u32(key, n_words)
        host = np.asarray(arr)  # D2H transfer
        raw = host.tobytes()
        buf[:len(raw[:length])] = raw[:length]
        if verify_salt and length % 8:
            buf[(length // 8) * 8:length] = bytes(length - (length // 8) * 8)

    def close(self) -> None:
        self._last_ingested = None


def _split_u64_params(file_offset: int, salt: int):
    """(base_lo, base_hi) uint32 halves of (offset + salt) mod 2^64 for the
    on-device pattern kernel."""
    base = (file_offset + salt) & ((1 << 64) - 1)
    return (np.uint32(base & 0xFFFFFFFF), np.uint32(base >> 32))


def hbm_bytes_limit(device, pct: int) -> int:
    """--tpuhbmpct: usable HBM staging budget for a chip."""
    try:
        stats = device.memory_stats()
        total = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        if total:
            return int(total) * pct // 100
    except Exception:  # pragma: no cover - backend without memory_stats
        pass
    return 1 << 30  # conservative 1 GiB default
