"""Training-ingest scenario suite: compose phases into the workloads
people actually size TPU storage for (docs/scenarios.md).

The phase engine speaks elbencho's primitives (write/read/stat/delete
plus TPUSLICE); the questions users ask are workload-shaped: "do
checkpoint bursts starve my train reads?", "what does epoch 2 look like
warm?". ``--scenario NAME`` (with ``--scenario-opt key=val`` knobs)
expands a named scenario into a plan of existing phases with per-step
config overlays, runs it through the unchanged coordinator/worker/
service machinery, and tags every emitted record with scenario + step
identity so the whole JSON/telemetry/doctor toolchain works without
modification (arXiv 2604.21275: shuffle windows, prefetch depth and
consume cadence — not raw sequential bandwidth — determine real
input-pipeline throughput).
"""

from .plan import (SCENARIOS, ScenarioPlan, ScenarioStep,  # noqa: F401
                   expand_scenario, parse_scenario_opts,
                   validate_scenario)
from .verdict import analyze_scenario  # noqa: F401
