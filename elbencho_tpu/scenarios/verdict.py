"""Scenario-level doctor verdicts: compare the per-leg results (and,
when the flight recorder armed, the per-leg stage decompositions) of a
finished scenario and name what they mean for a training workload —
"checkpoint writes starve train reads by N%", "epoch 2 is M.Mx
warm-cache", "storage-limited input pipeline".

The per-phase doctor (telemetry/doctor.py) answers WHERE one phase's
wall time went; this layer answers the cross-leg questions a scenario
exists to pose. Its output is the ``ScenarioAnalysis`` block of the run
JSON's terminal SCENARIO record (and the text summary's "Scenario
verdicts" lines), schema-versioned and append-only like the per-phase
``Analysis`` block.
"""

from __future__ import annotations

#: ScenarioAnalysis schema version (run JSON SCENARIO record)
SCENARIO_ANALYSIS_SCHEMA = 1

#: contention slowdown (per-thread read rate drop, %) at/above which the
#: contend scenario declares the train reads starved
CONTENTION_MIN_PCT = 10.0

#: warm/cold epoch rate ratio at/above which coldwarm/epochs declare a
#: warm-cache effect (below it the dataset simply doesn't fit the cache,
#: or the storage path already runs at device speed)
WARM_MIN_RATIO = 1.2

#: achieved/target step-rate ratio at/above which the dataloader
#: scenario declares the pipeline fed (storage keeps up with the
#: consume cadence)
CADENCE_KEEPUP_RATIO = 0.9

#: stage-share growth (percentage points, per-phase doctor StagePct)
#: worth naming as cross-leg evidence
STAGE_GROWTH_PTS = 10.0


def _rate(step: "dict | None", key: str = "MiBPerSec") -> float:
    return float((step or {}).get(key) or 0.0)


def _stage_growth_evidence(a: "dict | None", b: "dict | None",
                           label_a: str, label_b: str) -> "list[str]":
    """Per-leg stage-decomposition comparison (flight-recorder runs
    only): which doctor stage share grew between leg A and leg B."""
    out: "list[str]" = []
    ana_a = (a or {}).get("Analysis") or {}
    ana_b = (b or {}).get("Analysis") or {}
    pct_a, pct_b = ana_a.get("StagePct") or {}, ana_b.get("StagePct") or {}
    for stage in pct_b:
        grew = float(pct_b.get(stage, 0.0)) - float(pct_a.get(stage, 0.0))
        if grew >= STAGE_GROWTH_PTS:
            out.append(f"{stage} share grew {pct_a.get(stage, 0.0):g}% "
                       f"({label_a}) -> {pct_b.get(stage, 0.0):g}% "
                       f"({label_b})")
    if ana_a.get("Verdict") and ana_b.get("Verdict") \
            and ana_a["Verdict"] != ana_b["Verdict"]:
        out.append(f"doctor verdict changed {ana_a['Verdict']} "
                   f"({label_a}) -> {ana_b['Verdict']} ({label_b})")
    return out


def _verdict(kind: str, verdict: str, metric: "float | None",
             evidence: "list[str]") -> dict:
    return {"Kind": kind, "Verdict": verdict,
            "Metric": round(metric, 3) if metric is not None else None,
            "Evidence": evidence}


def _contention_verdict(steps: "list[dict]") -> "dict | None":
    base = next((s for s in steps if s.get("Role") == "baseline"), None)
    cont = next((s for s in steps if s.get("Role") == "contend"), None)
    if base is None or cont is None:
        return None
    base_threads = max(int(base.get("TotalThreads")
                           or base.get("NumWorkers") or 1), 1)
    cont_readers = max(int(cont.get("ReadThreads") or 1), 1)
    per_thr_base = _rate(base) / base_threads
    per_thr_cont = _rate(cont, "ReadMiBPerSec") / cont_readers
    if per_thr_base <= 0:
        return None
    slowdown = 100.0 * (1.0 - per_thr_cont / per_thr_base)
    evidence = [
        f"baseline train read {per_thr_base:.1f} MiB/s per thread "
        f"({base_threads} threads)",
        f"contended train read {per_thr_cont:.1f} MiB/s per thread "
        f"({cont_readers} reader threads beside "
        f"{_rate(cont):.1f} MiB/s of checkpoint writes)",
    ]
    evidence += _stage_growth_evidence(base, cont, "baseline", "contended")
    if slowdown >= CONTENTION_MIN_PCT:
        text = (f"checkpoint writes starve train reads by "
                f"{slowdown:.0f}% (per-thread read rate vs the "
                f"uncontended baseline)")
    else:
        text = (f"train reads essentially unaffected by concurrent "
                f"checkpoint writes ({slowdown:.0f}% per-thread drop)")
    return _verdict("contention", text, slowdown, evidence)


def _warmup_verdict(steps: "list[dict]") -> "dict | None":
    epochs = [s for s in steps if s.get("Epoch")]
    if len(epochs) < 2:
        return None
    cold = [s for s in epochs if s.get("Cold")]
    effective_cold = [s for s in cold if not s.get("ColdDegraded")]
    reference = (effective_cold or cold or epochs[:1])[0]
    # compare against genuinely warm epochs; only when every other
    # epoch is also cold (e.g. cold == epochs) fall back to them —
    # a cold epoch must never masquerade as the warm-cache evidence
    warm = [s for s in epochs if s is not reference
            and not s.get("Cold")] \
        or [s for s in epochs if s is not reference]
    cold_rate = _rate(reference, "EpochRate") or _rate(reference)
    best = max(warm, key=lambda s: _rate(s, "EpochRate") or _rate(s))
    best_rate = _rate(best, "EpochRate") or _rate(best)
    if cold_rate <= 0:
        return None
    ratio = best_rate / cold_rate
    evidence = [f"{s['Label']}: "
                f"{_rate(s, 'EpochRate') or _rate(s):.1f} MiB/s"
                for s in epochs]
    if cold and any(s.get("ColdDegraded") for s in cold):
        evidence.append(
            "WARNING: a cache-drop leg failed (unprivileged run?) — "
            "the 'cold' epochs may have run warm")
    evidence += _stage_growth_evidence(best, reference,
                                       best["Label"], reference["Label"])
    if ratio >= WARM_MIN_RATIO:
        text = (f"{best['Label']} is {ratio:.1f}x warm-cache vs "
                f"{reference['Label']}")
    else:
        text = (f"no significant warm-cache effect: {best['Label']} runs "
                f"{ratio:.2f}x {reference['Label']} (dataset exceeds the "
                f"cache, or storage already at device speed)")
    return _verdict("cache-warmup", text, ratio, evidence)


def _burst_verdict(steps: "list[dict]") -> "dict | None":
    saves = [s for s in steps if s.get("Role") == "save"]
    restores = [s for s in steps if s.get("Role") == "restore"]
    if not saves or not restores:
        return None
    save_rate = sum(_rate(s) for s in saves) / len(saves)
    restore_rate = sum(_rate(s) for s in restores) / len(restores)
    if save_rate <= 0 or restore_rate <= 0:
        return None  # a zero side has no meaningful asymmetry ratio
    ratio = restore_rate / save_rate
    evidence = [f"save {save_rate:.1f} MiB/s over {len(saves)} burst(s)",
                f"restore {restore_rate:.1f} MiB/s over "
                f"{len(restores)} burst(s)"]
    evidence += _stage_growth_evidence(restores[0], saves[0],
                                       restores[0]["Label"],
                                       saves[0]["Label"])
    direction = "faster" if ratio >= 1 else "slower"
    text = (f"checkpoint restore runs {max(ratio, 1 / ratio):.1f}x "
            f"{direction} than save "
            f"({restore_rate:.0f} vs {save_rate:.0f} MiB/s)")
    return _verdict("burst-asymmetry", text, ratio, evidence)


def _cadence_verdict(steps: "list[dict]") -> "dict | None":
    loader = next((s for s in steps if s.get("Role") == "loader"), None)
    if loader is None:
        return None
    step_usec = int(loader.get("LoaderStepUSec") or 0)
    batch_blocks = max(int(loader.get("LoaderBatchBlocks") or 1), 1)
    block = max(int(loader.get("BlockSize") or 1), 1)
    elapsed_s = max(int(loader.get("ElapsedUSec") or 0), 1) / 1e6
    total_bytes = float(loader.get("Bytes") or 0)
    workers = max(int(loader.get("TotalThreads")
                      or loader.get("NumWorkers") or 1), 1)
    batches = total_bytes / block / batch_blocks
    achieved = batches / elapsed_s / workers  # steps/s per loader
    evidence = [f"{batches:.0f} batches of {batch_blocks} x {block} B "
                f"over {elapsed_s:.1f}s ({workers} loader worker(s))"]
    if not step_usec:
        return _verdict(
            "cadence",
            f"unpaced loader run: {achieved:.1f} steps/s per loader "
            f"(decode burn only, no consume cadence configured)",
            achieved, evidence)
    target = 1e6 / step_usec
    ratio = achieved / target
    evidence.append(f"consume cadence target {target:.1f} steps/s "
                    f"(stepusec={step_usec}, prefetch="
                    f"{loader.get('LoaderPrefetch')})")
    if ratio >= CADENCE_KEEPUP_RATIO:
        text = (f"input pipeline keeps up with the consume cadence: "
                f"{achieved:.1f} of {target:.1f} steps/s per loader")
    else:
        text = (f"storage-limited input pipeline: achieves "
                f"{achieved:.1f} of {target:.1f} steps/s per loader "
                f"({100 * ratio:.0f}% of the consume cadence)")
    return _verdict("cadence", text, ratio, evidence)


def analyze_scenario(name: str, steps: "list[dict]") -> dict:
    """Cross-leg analysis of a finished scenario. ``steps`` are the
    coordinator's per-step summaries (scenarios/plan.py order; skipped
    resume steps absent). Every applicable verdict is emitted — a
    coldwarm run gets both the warm-cache ratio and, with a flight
    recording, the stage-growth evidence inside it."""
    verdicts = [v for v in (
        _contention_verdict(steps),
        _warmup_verdict(steps),
        _burst_verdict(steps),
        _cadence_verdict(steps),
    ) if v is not None]
    return {
        "Schema": SCENARIO_ANALYSIS_SCHEMA,
        "Scenario": name,
        "NumSteps": len(steps),
        "Steps": steps,
        "Verdicts": verdicts,
    }
