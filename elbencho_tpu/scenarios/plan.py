"""Scenario expansion: named scenario + knobs -> an ordered step plan.

A step is ONE existing benchmark phase plus a config overlay the
coordinator applies (and, in master mode, re-ships to the services) for
that step only — the scenario layer composes, the phase machinery runs.
Expansion is deterministic for a given effective config, which is what
lets the run journal fingerprint the EXPANDED plan: a ``--resume``
against a journal written by a different expansion (changed knobs, or a
changed built-in default) is a hard mismatch, not a silent re-plan.

Sync/dropcaches legs ride along as explicit steps marked
``best_effort`` — they stay out of the journal (``UNJOURNALED_PHASES``)
and a resume must never replay a cache drop as "finished work"; see
``ScenarioPlan.resume_runs``.
"""

from __future__ import annotations

import dataclasses

from ..config.args import ConfigError
from ..phases import UNJOURNALED_PHASES, BenchPhase
from ..toolkits.units import parse_size


@dataclasses.dataclass
class ScenarioStep:
    """One phase of a scenario plan with its per-step config overlay."""

    phase: BenchPhase
    label: str                 # "epoch2", "ckpt1.save", ... (record tag)
    overlay: dict = dataclasses.field(default_factory=dict)
    epoch: int = 0             # > 0 tags an epoch-rate leg (EpochRateMiBs)
    role: str = ""             # setup|epoch|save|restore|baseline|contend|
                               # loader|cachedrop|sync
    delay_secs: int = 0        # sleep before the step (--scenario-opt interval)
    cold: bool = False         # coldwarm: leg measured behind a cache drop
    best_effort: bool = False  # failure logs LOUDLY but does not abort

    def describe(self) -> dict:
        """JSON-able identity of this step (journal + fingerprint)."""
        return {"phase": int(self.phase), "label": self.label,
                "overlay": {k: self.overlay[k]
                            for k in sorted(self.overlay)},
                "epoch": self.epoch, "role": self.role,
                "delay_secs": self.delay_secs, "cold": self.cold}


@dataclasses.dataclass
class ScenarioPlan:
    name: str
    opts: dict
    steps: "list[ScenarioStep]"

    def describe(self) -> dict:
        """JSON-able plan identity for the journal's run_start record and
        the config fingerprint."""
        return {"name": self.name,
                "opts": {k: str(self.opts[k]) for k in sorted(self.opts)},
                "steps": [s.describe() for s in self.steps]}

    def phases(self) -> "list[BenchPhase]":
        return [s.phase for s in self.steps]

    def resume_runs(self, finished: "set[tuple[int, int]]",
                    iteration: int = 0) -> "list[bool]":
        """Which steps a --resume run executes. Journaled steps follow
        the normal rule (skip when a phase_finish record exists).
        Unjournaled legs (sync/dropcaches) never have records — they run
        exactly when the NEXT journaled step runs, so a coldwarm resume
        re-drops caches for the epoch it re-runs but never replays a
        drop in front of a skipped (finished) epoch."""
        runs: "list[bool]" = []
        for idx, step in enumerate(self.steps):
            if step.phase not in UNJOURNALED_PHASES:
                runs.append((iteration, idx) not in finished)
                continue
            nxt = next((j for j in range(idx + 1, len(self.steps))
                        if self.steps[j].phase not in UNJOURNALED_PHASES),
                       None)
            runs.append(nxt is None or (iteration, nxt) not in finished)
        return runs


# ---------------------------------------------------------------------------
# knob parsing
# ---------------------------------------------------------------------------

def parse_scenario_opts(opts_str: str) -> "dict[str, str]":
    """``--scenario-opt epochs=4,window=16M`` -> {"epochs": "4", ...}.
    Malformed pairs fail at config time, not mid-run."""
    out: "dict[str, str]" = {}
    for part in (opts_str or "").split(","):
        part = part.strip()
        if not part:
            continue
        key, eq, val = part.partition("=")
        if not eq or not key.strip() or not val.strip():
            raise ConfigError(
                f"--scenario-opt entries must be key=val pairs, got "
                f"{part!r}")
        out[key.strip()] = val.strip()
    return out


def _opt_int(opts: dict, key: str, default: int, lo: int = 0) -> int:
    raw = opts.get(key)
    if raw is None:
        return default
    try:
        val = int(raw)
    except ValueError:
        raise ConfigError(
            f"--scenario-opt {key}={raw!r} is not an integer") from None
    if val < lo:
        raise ConfigError(f"--scenario-opt {key} must be >= {lo}")
    return val


def _opt_size(opts: dict, key: str, default: int) -> int:
    raw = opts.get(key)
    if raw is None:
        return default
    try:
        val = parse_size(raw)
    except ValueError as err:
        raise ConfigError(f"--scenario-opt {key}={raw!r}: {err}") from None
    if val < 0:
        raise ConfigError(f"--scenario-opt {key} must be >= 0")
    return val


def _check_known(name: str, opts: dict, known: "tuple[str, ...]") -> None:
    unknown = sorted(set(opts) - set(known))
    if unknown:
        raise ConfigError(
            f"--scenario {name} does not know --scenario-opt "
            f"{', '.join(unknown)} (knobs: {', '.join(known)}; "
            f"docs/scenarios.md)")


def _block_multiple(size: int, block: int) -> int:
    """Overlay sizes follow the same trim the base config gets
    (_reduce_file_size_to_block_multiple): a trailing partial block
    would short-read in striped/direct modes."""
    if block and size and size % block:
        size -= size % block
    return max(size, block)


def _mkdirs_leg(cfg, steps: "list[ScenarioStep]") -> None:
    """Dir-mode datasets need their rank/dir namespace created before
    the first write leg — master mode cannot probe the remote path type
    at expansion time, so the mkdirs leg is emitted (best-effort there)
    whenever the type is DIR or unknown."""
    from ..phases import BenchPathType
    if cfg.bench_path_type == BenchPathType.DIR or cfg.hosts:
        steps.append(ScenarioStep(BenchPhase.CREATEDIRS, "setup.mkdirs",
                                  role="setup",
                                  best_effort=bool(cfg.hosts)))


def _maybe_setup(cfg, opts: dict, steps: "list[ScenarioStep]") -> None:
    """All read-centric scenarios lay their dataset down first; the
    ``setup=0`` knob reuses an existing dataset instead."""
    if not _opt_int(opts, "setup", 1):
        return
    _mkdirs_leg(cfg, steps)
    steps.append(ScenarioStep(BenchPhase.CREATEFILES, "setup",
                              role="setup"))


# ---------------------------------------------------------------------------
# built-in scenarios
# ---------------------------------------------------------------------------

def _expand_epochs(cfg, opts: dict) -> "list[ScenarioStep]":
    """Multi-epoch shuffled shard reads: every epoch reads the whole
    dataset with block order permuted inside consecutive shuffle windows
    (the tf.data/PyTorch shuffle-buffer access shape), each epoch under
    a different permutation seed. Epoch boundaries are phase boundaries,
    so the flight recorder / tracer mark them for free."""
    _check_known("epochs", opts, ("epochs", "window", "setup"))
    epochs = _opt_int(opts, "epochs", 3, lo=1)
    window = _opt_size(opts, "window", 0)
    if window and window < cfg.block_size:
        # same rule as standalone --shufflewindow: a sub-block window
        # means one block per window, i.e. no shuffling at all — refuse
        # rather than silently measure an unshuffled "epoch"
        raise ConfigError(
            "--scenario-opt window must be at least one --block")
    if not window:
        window = 16 * max(cfg.block_size, 1)
    steps: "list[ScenarioStep]" = []
    _maybe_setup(cfg, opts, steps)
    for e in range(1, epochs + 1):
        steps.append(ScenarioStep(
            BenchPhase.READFILES, f"epoch{e}", epoch=e, role="epoch",
            overlay={"shuffle_window": window, "scenario_epoch": e}))
    return steps


def _expand_ckpt_burst(cfg, opts: dict) -> "list[ScenarioStep]":
    """All-hosts-at-once checkpoint save/restore bursts: every burst
    writes the checkpoint (CREATEFILES) and reads it back (READFILES),
    with an optional idle interval between bursts — the burst cadence
    of a real training job's checkpoint schedule."""
    _check_known("ckpt-burst", opts, ("bursts", "interval", "size"))
    bursts = _opt_int(opts, "bursts", 2, lo=1)
    interval = _opt_int(opts, "interval", 0)
    size = _opt_size(opts, "size", 0)
    overlay = {}
    if size:
        overlay["file_size"] = _block_multiple(size, cfg.block_size)
    steps: "list[ScenarioStep]" = []
    _mkdirs_leg(cfg, steps)  # the save burst IS the dataset write
    for b in range(1, bursts + 1):
        steps.append(ScenarioStep(
            BenchPhase.CREATEFILES, f"ckpt{b}.save", role="save",
            overlay=dict(overlay),
            delay_secs=interval if b > 1 else 0))
        steps.append(ScenarioStep(
            BenchPhase.READFILES, f"ckpt{b}.restore", role="restore",
            overlay=dict(overlay)))
    return steps


def _expand_contend(cfg, opts: dict) -> "list[ScenarioStep]":
    """Mixed train-read + checkpoint-write contention, reusing the
    --rwmixthr thread-split machinery: after a pure-read baseline leg,
    the contended leg runs the write phase with ``readthreads`` of its
    workers converted to train readers — read and write legs share the
    fleet, and the verdict compares per-thread read rates across legs
    ("checkpoint writes starve train reads by N%")."""
    _check_known("contend", opts, ("readthreads", "setup"))
    default_readers = max(cfg.num_threads // 2, 1)
    readers = _opt_int(opts, "readthreads", default_readers, lo=1)
    if readers >= max(cfg.num_threads, 1):
        raise ConfigError(
            f"--scenario contend: readthreads={readers} must leave at "
            f"least one writer of the {cfg.num_threads} --threads")
    steps: "list[ScenarioStep]" = []
    _maybe_setup(cfg, opts, steps)
    steps.append(ScenarioStep(BenchPhase.READFILES, "train.baseline",
                              role="baseline"))
    steps.append(ScenarioStep(
        BenchPhase.CREATEFILES, "contend", role="contend",
        overlay={"num_rwmix_read_threads": readers}))
    return steps


def _expand_coldwarm(cfg, opts: dict) -> "list[ScenarioStep]":
    """Cold-vs-warm cache epochs: the first ``cold`` epochs run behind a
    sync + kernel cache drop, later epochs run warm — the per-epoch rate
    comparison is what "epoch 2" really looks like. The cache legs are
    best-effort (an unprivileged run logs LOUDLY and its epochs are
    labeled not-cold in the verdict) and stay out of the journal."""
    _check_known("coldwarm", opts, ("epochs", "cold", "setup"))
    epochs = _opt_int(opts, "epochs", 2, lo=1)
    cold = _opt_int(opts, "cold", 1)
    cold = min(cold, epochs)
    steps: "list[ScenarioStep]" = []
    _maybe_setup(cfg, opts, steps)
    if cold:
        steps.append(ScenarioStep(BenchPhase.SYNC, "sync", role="sync",
                                  best_effort=True))
    for e in range(1, epochs + 1):
        is_cold = e <= cold
        if is_cold:
            steps.append(ScenarioStep(
                BenchPhase.DROPCACHES, f"epoch{e}.dropcaches",
                role="cachedrop", best_effort=True))
        steps.append(ScenarioStep(
            BenchPhase.READFILES,
            f"epoch{e}.{'cold' if is_cold else 'warm'}",
            epoch=e, role="epoch", cold=is_cold,
            overlay={"scenario_epoch": e}))
    return steps


def _expand_dataloader(cfg, opts: dict) -> "list[ScenarioStep]":
    """Data-loader emulation: the read leg is paced like a training
    input pipeline — ``batchblocks`` blocks per batch, a CPU decode burn
    per batch, one batch consumed per ``stepusec``, and the reader
    allowed at most ``prefetch`` batches ahead of the consume clock — so
    the result predicts whether storage keeps a real loader fed instead
    of its burst bandwidth (arXiv 2604.21275)."""
    _check_known("dataloader", opts, ("prefetch", "decodeusec", "stepusec",
                                     "batchblocks", "setup"))
    prefetch = _opt_int(opts, "prefetch", 2, lo=1)
    decode_usec = _opt_int(opts, "decodeusec", 200)
    step_usec = _opt_int(opts, "stepusec", 1000)
    batch_blocks = _opt_int(opts, "batchblocks", 8, lo=1)
    steps: "list[ScenarioStep]" = []
    _maybe_setup(cfg, opts, steps)
    steps.append(ScenarioStep(
        BenchPhase.READFILES, "loader", epoch=1, role="loader",
        overlay={"scenario_prefetch": prefetch,
                 "scenario_decode_usec": decode_usec,
                 "scenario_step_usec": step_usec,
                 "scenario_batch_blocks": batch_blocks,
                 "scenario_epoch": 1}))
    return steps


#: name -> (builder, one-line summary); the summary feeds --help,
#: docs/scenarios.md and error messages
SCENARIOS = {
    "epochs": (_expand_epochs,
               "multi-epoch shuffled shard reads (windowed permutation)"),
    "ckpt-burst": (_expand_ckpt_burst,
                   "all-hosts checkpoint save/restore bursts"),
    "contend": (_expand_contend,
                "train-read vs checkpoint-write contention (rwmixthr)"),
    "coldwarm": (_expand_coldwarm,
                 "cold-vs-warm cache epochs (dropcaches between cold ones)"),
    "dataloader": (_expand_dataloader,
                   "data-loader emulation (prefetch/decode/consume cadence)"),
}


# phase-selection flags a scenario plan replaces; any of them set
# alongside --scenario is a config error, not a silent merge
_PHASE_FLAG_ATTRS = (
    "run_create_files", "run_read_files", "run_create_dirs",
    "run_delete_dirs", "run_delete_files", "run_stat_files",
    "run_stat_dirs", "run_sync_phase", "run_drop_caches_phase",
    "run_netbench", "run_tpu_bench", "run_tpu_slice",
)


def validate_scenario(cfg) -> None:
    """Config-time validation (called from BenchConfig.check); expansion
    itself is the validator, so a bad knob fails before any phase
    runs."""
    if cfg.scenario not in SCENARIOS:
        raise ConfigError(
            f"unknown --scenario {cfg.scenario!r} (have: "
            f"{', '.join(sorted(SCENARIOS))}; docs/scenarios.md)")
    conflicting = [a for a in _PHASE_FLAG_ATTRS if getattr(cfg, a)]
    if conflicting:
        raise ConfigError(
            f"--scenario defines the phase plan itself; drop the "
            f"explicit phase flags ({', '.join(conflicting)})")
    if cfg.iterations != 1:
        raise ConfigError(
            "--scenario plans carry their own epoch/burst structure; "
            "--iterations must stay 1")
    if cfg.do_infinite_io_loop:
        raise ConfigError("--scenario is incompatible with --infloop")
    if cfg.rotate_hosts_num:
        raise ConfigError(
            "--rotatehosts re-ranks the fleet between phases, which "
            "would reshuffle a scenario's epoch seeds and contention "
            "legs mid-plan; drop it under --scenario")
    plan = expand_scenario(cfg)  # knob + geometry validation
    if any("shuffle_window" in s.overlay for s in plan.steps) \
            and (cfg.use_random_offsets or cfg.do_reverse_seq_offsets
                 or cfg.do_strided_access or cfg.use_mmap):
        # same rule as standalone --shufflewindow (args.check): the
        # per-step overlay sets shuffle_window at run time, after the
        # flag-level incompatibility check already passed on 0
        raise ConfigError(
            f"--scenario {cfg.scenario} drives its own shuffle-window "
            f"offset permutation — incompatible with "
            f"--rand/--backward/--strided/--mmap")
    # file-mode fd opens gate O_CREAT on run_create_files, which stays
    # off under --scenario — derive the "this plan writes files" fact
    # here so the manager/worker opens (and, on the wire, the services'
    # opens) can create a not-yet-existing file for the write legs
    cfg.scenario_creates_files = any(
        s.phase == BenchPhase.CREATEFILES for s in plan.steps)


def expand_scenario(cfg) -> ScenarioPlan:
    """Expand cfg.scenario/--scenario-opt into the step plan. Pure and
    deterministic over the effective config — the journal fingerprints
    its output (journal.config_fingerprint)."""
    if cfg.scenario not in SCENARIOS:
        raise ConfigError(
            f"unknown --scenario {cfg.scenario!r} (have: "
            f"{', '.join(sorted(SCENARIOS))})")
    opts = parse_scenario_opts(cfg.scenario_opts_str)
    builder, _summary = SCENARIOS[cfg.scenario]
    steps = builder(cfg, opts)
    return ScenarioPlan(name=cfg.scenario, opts=opts, steps=steps)
