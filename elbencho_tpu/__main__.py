import os
import sys

# lock-order detector arming for fleet subprocesses: the pytest session
# fixture (tests/conftest.py) arms ITS process and exports the dump dir;
# every service/master process spawned with that environment arms here —
# before cli/config imports so ServiceState's locks are created tracked.
# Both variables are required: the detector is a test-harness seam, never
# a production feature (same contract as the slowops/tracefleet injection
# gates).
if (os.environ.get("ELBENCHO_TPU_TESTING") == "1"
        and os.environ.get("ELBENCHO_TPU_LOCKGRAPH_DIR")):
    from elbencho_tpu.testing import lockgraph
    lockgraph.install()

from .cli import main

sys.exit(main())
