"""Coordinator: role dispatch and benchmark phase ordering.

Reference: source/Coordinator.{h,cpp} — main() :32 (service vs master vs
local role), runBenchmarks() :299 with the ordered phase table :311-334
(creates before deletes), sync/dropcaches interleave after every phase,
host rotation :384, SIGINT graceful shutdown :420-442, synchronized start
time :150-159, service-ready wait :165.
"""

from __future__ import annotations

import os
import signal
import sys
import time

from .phases import BenchPhase
from .stats.statistics import Statistics
from .toolkits import logger
from .workers.manager import WorkerManager
from .workers.shared import WorkerException


class Coordinator:
    def __init__(self, cfg):
        self.cfg = cfg
        self.manager = WorkerManager(cfg)
        self.statistics = Statistics(cfg, self.manager)
        self._interrupted = False
        self._profile_seq = 0
        self._profile_warned_hosts = False
        self._old_handlers = []  # (signum, previous handler) pairs
        self._telemetry = None   # BenchTelemetry when --telemetry
        self._exporter = None    # its /metrics HTTP server
        self._flightrec = None   # FlightRecorder when --flightrec
        self._journal = None     # RunJournal when --journal
        self._resume_plan = None  # ResumePlan when --resume
        self._scenario_plan = None  # ScenarioPlan when --scenario
        self._last_phase_results = None  # PhaseResults of the last phase

    # ------------------------------------------------------------------

    def main(self) -> int:
        from .toolkits.signals import register_fault_handlers
        register_fault_handlers()  # reference: SignalTk fault trace
        cfg = self.cfg
        if cfg.run_as_service:
            from .service.http_service import HTTPService
            return HTTPService(cfg).start()
        if cfg.quit_services or cfg.interrupt_services:
            from .service.remote_worker import send_interrupt_to_hosts
            # --svcfanout: interrupt/quit walk the aggregation tree, so
            # tearing down a large fleet is O(fanout) requests here too
            send_interrupt_to_hosts(cfg.hosts, cfg.service_port,
                                    quit=cfg.quit_services,
                                    fanout=cfg.svc_fanout)
            return 0
        if cfg.standby_str:
            return self._run_standby()
        return self._run_master_or_local()

    def _run_standby(self) -> int:
        """--standby HOST:PORT warm standby (docs/fault-tolerance.md
        "Master failover"): observe one sentinel service's /status — an
        observer poll carries no bench UUID, so it can never renew the
        primary's lease — and auto-take-over (--resume --adopt) the
        moment the sentinel reports AwaitingAdoption. The watch ends
        cleanly when the shared journal gains its run_complete record:
        the primary finished without needing us."""
        from .journal import REC_RUN_COMPLETE, read_journal
        from .service import protocol as proto
        from .service.remote_worker import ServiceClient
        from .workers.shared import WorkerRemoteException
        cfg = self.cfg
        client = ServiceClient(cfg.standby_str, cfg.service_port)
        logger.log(0, f"STANDBY: watching {client.hostname}:{client.port} "
                      f"for a master lease expiry (--standby); journal: "
                      f"{cfg.journal_file_path}")
        poll_secs = max(cfg.svc_update_interval_ms, 500) / 1000.0
        try:
            while True:
                try:
                    if os.path.exists(cfg.journal_file_path) and any(
                            r.get("rec") == REC_RUN_COMPLETE
                            for r in read_journal(cfg.journal_file_path)):
                        logger.log(0, "STANDBY: journal shows "
                                      "run_complete — primary finished; "
                                      "standing down")
                        return 0
                except Exception:  # noqa: BLE001 - torn journal mid-append
                    pass
                try:
                    status, stats = client.get_json(proto.PATH_STATUS,
                                                    timeout=5)
                except WorkerRemoteException:
                    status, stats = 0, {}
                if status == 200 \
                        and stats.get(proto.KEY_AWAITING_ADOPTION):
                    logger.log(0, "STANDBY: sentinel host is awaiting "
                                  "adoption — taking over the fleet "
                                  "(--resume --adopt)")
                    client.close()
                    # shed the standby role BEFORE assuming mastership:
                    # later config re-checks (the manager's path-type
                    # pass) must see a plain --resume --adopt master,
                    # not the standby+resume combination check() forbids
                    cfg.standby_str = ""
                    cfg.resume_run = True
                    cfg.adopt_run = True
                    return self._run_master_or_local()
                time.sleep(poll_secs)
        except KeyboardInterrupt:
            logger.log(0, "STANDBY: interrupted; standing down")
            return 3
        finally:
            client.close()

    def _run_master_or_local(self) -> int:
        from .config.args import ConfigError
        cfg = self.cfg
        self._install_signal_handler()
        try:
            try:
                if cfg.scenario:
                    # expand ONCE; the same plan object drives the
                    # journal's run_start, the resume filter and the
                    # step loop (journal.config_fingerprint re-expands
                    # deterministically for the hash)
                    from .scenarios import expand_scenario
                    self._scenario_plan = expand_scenario(cfg)
                if self._setup_journal():
                    return 0  # --resume against a complete journal
            except (ConfigError, OSError) as err:
                # OSError: unwritable/unreadable --journal path — fail
                # before any phase runs, not mid-run
                logger.log_error(str(err))
                return 1
            self._start_telemetry()
            self._start_flightrec()
            if cfg.hosts:
                from .service.remote_worker import wait_for_services_ready
                wait_for_services_ready(cfg.hosts, cfg.service_port,
                                        cfg.svc_wait_secs)
            self._wait_for_sync_start()
            self._arm_takeover_credentials()
            self.manager.prepare_threads()
            self._note_takeover()
            if cfg.autotune_secs:
                # closed-loop autotuning (docs/autotuning.md): probe ->
                # doctor verdict -> hill-climb, then apply the tuned
                # point (fleet rebuilt) so the REAL phases below run it
                self._run_autotune()
                if cfg.journal_file_path:
                    # journal the run NOW, against the TUNED effective
                    # config (see _setup_journal's autotune deferral);
                    # the unjournaled probes above left no records. The
                    # tuned profile is already on disk, so a refused/
                    # unwritable journal aborts without wasting the
                    # spent tune budget.
                    try:
                        from .journal import RunJournal
                        self._journal = RunJournal(
                            cfg.journal_file_path, cfg)
                        self._journal.start_fresh(cfg.enabled_phases(),
                                                  cfg.iterations)
                    except (ConfigError, OSError) as err:
                        logger.log_error(str(err))
                        return 1
            self.run_benchmarks()
            if self._journal is not None:
                self._journal_write(self._journal.run_complete)
            return 0
        except WorkerException as err:
            logger.log_error(f"Aborting due to worker error: {err}")
            self.manager.interrupt_and_notify_workers()
            self._abort_hygiene()
            return 1
        except KeyboardInterrupt:
            logger.log_error("Interrupted. Shutting down workers...")
            self.manager.interrupt_and_notify_workers()
            self._abort_hygiene()
            return 3
        finally:
            # exporter first: the abort path must free --telemetryport
            # before the (up to 30s/thread) worker join, so back-to-back
            # runs on the same port bind cleanly (stop() is idempotent)
            if self._exporter is not None:
                self._exporter.stop()
                self._exporter = None
            try:
                self.manager.join_all_threads()
            except Exception:  # noqa: BLE001 - teardown must not mask errors
                pass
            # fleet tracing: merge master + collected per-host traces
            # into the one clock-aligned timeline (after the join wrote
            # the master's final span ring); an aborted run merges
            # whatever was collected before the abort
            self._merge_fleet_trace()
            if self._flightrec is not None:
                # flush the ring so even an aborted run leaves a
                # loadable (torn-tail-tolerated) recording
                self._flightrec.close()
            self.statistics.close()
            if self._journal is not None:
                self._journal.close()
            self._restore_signal_handler()

    def _setup_journal(self) -> bool:
        """--journal/--resume wiring. Returns True when --resume finds a
        terminal run_complete record (nothing left to run). Raises
        ConfigError on a missing journal or a config-fingerprint
        mismatch — resuming a different workload would silently mix
        incompatible datasets."""
        cfg = self.cfg
        if not cfg.journal_file_path:
            return False
        if cfg.autotune_secs:
            # a fresh tuned run journals AFTER the tuner applied its
            # knobs (--resume next to --autotune is rejected at config
            # time), so the fingerprint describes the config the phases
            # actually ran — which makes `--resume -c PROFILE` the
            # working recovery path instead of a guaranteed mismatch
            return False
        from .journal import RunJournal, load_resume_plan
        if cfg.resume_run:
            plan = load_resume_plan(cfg.journal_file_path, cfg)
            if plan.run_complete:
                logger.log(0, "RESUME: journal already has run_complete — "
                              "nothing left to resume")
                return True
            self._resume_plan = plan
            # surfaced in the JSON result records ("Resumed") and the
            # summarize tool's RESUMED banner
            cfg.resumed_skipped_phases = plan.num_finished
            if plan.partial_dataset:
                # the interrupted run died inside a write/delete phase:
                # the re-run's delete/overwrite work must tolerate the
                # partial dataset it left on disk (PR 5 latch)
                self.manager.shared.mark_partial_dataset()
            logger.log(0, f"RESUME: {plan.num_finished} finished phase(s) "
                          f"will be skipped per {cfg.journal_file_path}; "
                          f"the first incomplete phase re-runs from "
                          f"scratch")
        self._journal = RunJournal(cfg.journal_file_path, cfg)
        if cfg.resume_run:
            self._journal.resume(self._resume_plan.num_finished)
            plan = self._resume_plan
            if plan.takeover_token:
                # the run was armed for failover: every resume (plain or
                # --adopt) keeps presenting the journaled token so the
                # fleet's adoption grace re-arms for the continuation
                cfg.takeover_token = plan.takeover_token
                cfg.journal_fingerprint = self._journal.fingerprint
            if cfg.adopt_run:
                if not plan.takeover_token:
                    logger.log_error(
                        "ADOPT: journal has no fleet record (the run was "
                        "not armed with --svcadoptsecs) — falling back "
                        "to a plain --resume; the fleet is re-prepared")
                    cfg.adopt_run = False
                else:
                    inf = plan.inflight
                    cfg.adopt_bench_uuid = \
                        inf.get("bench_uuid", "") if inf else ""
                    what = (f"in-flight phase {inf.get('name', '?')} "
                            f"(iteration {inf.get('iteration', 0)}) is "
                            f"adopted mid-run" if inf
                            else "no phase was in flight")
                    logger.log(0, f"ADOPT: taking over "
                                  f"{len(plan.fleet_hosts) or len(cfg.hosts)}"
                                  f" host(s) from the journaled fleet; "
                                  f"{what}")
        else:
            # a fresh run refuses to append after an incomplete journal
            # (that restart point is someone's resume) and truncates a
            # complete one — mixing runs in one file would poison every
            # later --resume replay
            if self._scenario_plan is not None:
                self._journal.start_fresh(
                    self._scenario_plan.phases(), cfg.iterations,
                    scenario=self._scenario_plan.describe())
            else:
                self._journal.start_fresh(cfg.enabled_phases(),
                                          cfg.iterations)
        return False

    def _arm_takeover_credentials(self) -> None:
        """Master failover arming (docs/fault-tolerance.md): a journaled
        fleet run with --svcadoptsecs > 0 mints a takeover token, ships
        it (plus the journal fingerprint) on /preparephase, and journals
        the fleet topology — the three things a successor master needs
        to /adopt the hosts after this process dies."""
        cfg = self.cfg
        if getattr(cfg, "takeover_token", ""):
            return  # --resume: credentials already came from the journal
        if self._journal is None or not cfg.hosts \
                or cfg.svc_adopt_secs <= 0:
            return
        cfg.takeover_token = os.urandom(16).hex()
        cfg.journal_fingerprint = self._journal.fingerprint
        self._journal_write(self._journal.fleet, cfg.hosts,
                            cfg.takeover_token)

    def _adopt_inflight(self) -> "dict | None":
        """The dead master's in-flight phase record, when this run is a
        --resume --adopt takeover (None otherwise)."""
        if getattr(self.cfg, "adopt_run", False) \
                and self._resume_plan is not None:
            return self._resume_plan.inflight
        return None

    def _note_takeover(self) -> None:
        """Post-handshake bookkeeping of a --resume --adopt takeover:
        prepare_threads ran the /adopt handshake per host instead of
        /preparephase; journal the takeover record and mark the event as
        a trace span. The MasterTakeovers counter itself lands via the
        adopted phase's audit counters (RemoteWorker)."""
        if not getattr(self.cfg, "adopt_run", False):
            return
        adopted = sum(1 for w in self.manager.workers
                      if getattr(w, "_took_over", False))
        if not adopted:
            return
        inf = self._adopt_inflight()
        if self._journal is not None:
            self._journal_write(self._journal.takeover, adopted, inf)
        tracer = self.manager.shared.tracer
        if tracer is not None:
            t0 = tracer.now_ns()
            tracer.record("takeover", "phase", t0, 1,
                          AdoptedHosts=adopted)
        logger.log(0, f"TAKEOVER: adopted {adopted} host(s); "
                      + (f"the in-flight phase continues under the "
                         f"journaled bench UUID "
                         f"{inf.get('bench_uuid', '')[:8]}..." if inf
                         else "no phase was in flight"))

    def _merge_fleet_trace(self) -> None:
        """--tracefleet: fold the master trace + the per-host rings
        collected at /benchresult into ONE clock-aligned Chrome trace
        (<tracefile base>.fleet<ext>) with a skew report. Best effort:
        a failed merge is LOUD but never fails the run — the per-host
        inputs stay on disk for tools/elbencho-tpu-trace."""
        from .telemetry.tracefleet import (FleetTraceError,
                                           fleet_trace_enabled,
                                           merge_fleet_trace,
                                           skew_report_text)
        cfg = self.cfg
        if not fleet_trace_enabled(cfg) \
                or self.manager.shared.tracer is None \
                or not os.path.exists(cfg.trace_file_path):
            return
        try:
            doc = merge_fleet_trace(cfg.trace_file_path)
        except (OSError, FleetTraceError) as err:
            logger.log_error(f"fleet trace merge failed: {err} "
                             f"(per-host inputs kept; retry with "
                             f"tools/elbencho-tpu-trace)")
            return
        logger.log(0, f"fleet trace: {doc['outPath']}")
        for line in skew_report_text(doc):
            logger.log(1, line)

    def _abort_hygiene(self) -> None:
        """Master-side abort: close the telemetry exporter socket NOW and
        drop live-stats files that never saw a data row, so back-to-back
        runs on the same port/paths never inherit stale state."""
        if self._exporter is not None:
            self._exporter.stop()
            self._exporter = None
        self.statistics.abort_cleanup()

    def _start_telemetry(self) -> None:
        """--telemetry: standalone Prometheus /metrics endpoint for
        local/master runs (service mode piggybacks onto the control
        server's route table instead, service/http_service.py). The
        provider indirection follows manager/statistics across
        --rotatehosts rebuilds."""
        cfg = self.cfg
        if not cfg.telemetry:
            return
        from .telemetry.exporter import TelemetryExporter
        from .telemetry.registry import BenchTelemetry
        telemetry = BenchTelemetry(
            cfg, lambda: (self.statistics, self.manager),
            role="master" if cfg.hosts else "local")
        self._telemetry = telemetry
        self.statistics.telemetry = telemetry
        exporter = TelemetryExporter(telemetry, cfg.telemetry_port)
        try:
            exporter.start()
        except OSError as err:
            raise WorkerException(
                f"--telemetry: cannot bind --telemetryport "
                f"{cfg.telemetry_port}: {err}") from err
        self._exporter = exporter

    def _start_flightrec(self) -> None:
        """--flightrec: arm the flight recorder (telemetry/flightrec.py).
        An unwritable recording path fails BEFORE any phase runs, like
        the journal — a run asked to explain itself must not silently
        lose its recording."""
        from .telemetry.flightrec import make_flightrec
        try:
            self._flightrec = make_flightrec(self.cfg)
        except OSError as err:
            raise WorkerException(
                f"--flightrec: cannot open "
                f"{self.cfg.flightrec_file_path}: {err}") from err
        self.statistics.flightrec = self._flightrec

    def _wait_for_sync_start(self) -> None:
        """--start: cross-host synchronized start (reference: :150-159;
        accepts "HH:MM[:SS]" UTC or a unix timestamp)."""
        spec = self.cfg.start_time_utc
        if not spec:
            return
        if ":" in spec:
            parts = [int(x) for x in spec.split(":")]
            now = time.gmtime()
            target_secs = parts[0] * 3600 + parts[1] * 60 + \
                (parts[2] if len(parts) > 2 else 0)
            now_secs = now.tm_hour * 3600 + now.tm_min * 60 + now.tm_sec
            delay = target_secs - now_secs
            if delay < 0:
                raise WorkerException("--start time is in the past")
        else:
            delay = float(spec) - time.time()
            if delay < 0:
                raise WorkerException("--start time is in the past")
        logger.log(0, f"Waiting {delay:.0f}s for synchronized start...")
        time.sleep(delay)

    # ------------------------------------------------------------------

    def run_benchmarks(self) -> None:
        """Iterations x ordered phases with sync/dropcaches interleave
        (reference: runBenchmarks, Coordinator.cpp:299-376). With
        --journal every table phase is bracketed by start/finish records;
        with --resume, phases the journal proves finished are skipped —
        host rotation still applies to skipped slots so the re-run phases
        see the same rank assignments the original run would have."""
        cfg = self.cfg
        if self._scenario_plan is not None:
            self._run_scenario()
            return
        phases = cfg.enabled_phases()
        from .phases import phase_name
        for iteration in range(cfg.iterations):
            if cfg.iterations > 1:
                logger.log(0, f"[Starting iteration {iteration + 1} of "
                              f"{cfg.iterations}...]")
            self.statistics.print_phase_results_table_header()
            self._run_sync_and_drop_caches()
            for idx, phase in enumerate(phases):
                skipped = self._resume_plan is not None \
                    and (iteration, idx) in self._resume_plan.finished
                if skipped:
                    logger.log(0, f"RESUME: skipping finished phase "
                                  f"{phase_name(phase)} "
                                  f"(iteration {iteration + 1})")
                else:
                    self._run_journaled_phase(iteration, idx, phase)
                    self._run_sync_and_drop_caches()
                if idx < len(phases) - 1:
                    if cfg.next_phase_delay_secs and not skipped:
                        time.sleep(cfg.next_phase_delay_secs)
                    self._rotate_hosts()

    # ------------------------------------------------------------------
    # training-ingest scenarios (--scenario; docs/scenarios.md)
    # ------------------------------------------------------------------

    def _run_scenario(self) -> None:
        """Drive the expanded scenario plan through the unchanged phase
        machinery: per step, apply the config overlay (re-shipping it to
        the services when the wire-relevant effective config changed),
        run the phase journaled under the step's plan index, and collect
        a per-step summary for the scenario-level verdict."""
        cfg = self.cfg
        plan = self._scenario_plan
        from .phases import phase_name
        from .scenarios.verdict import analyze_scenario
        logger.log(0, f"Scenario {plan.name}: {len(plan.steps)} step(s) — "
                      + ", ".join(s.label for s in plan.steps))
        self.statistics.print_phase_results_table_header()
        finished = self._resume_plan.finished \
            if self._resume_plan is not None else set()
        runs = plan.resume_runs(finished)
        # every attribute any step overlays, snapshotted once so each
        # step starts from the BASE config, not the previous overlay
        base = {}
        for step in plan.steps:
            for key in step.overlay:
                base.setdefault(key, getattr(cfg, key))
        base.setdefault("scenario_step_label", cfg.scenario_step_label)
        base.setdefault("scenario_epoch", cfg.scenario_epoch)
        # what the initial prepare_threads shipped to the services; the
        # step label is log-only and never worth a fleet re-prepare
        wire_keys = sorted(set(base) - {"scenario_step_label"})

        def wire_relevant(overlay: dict) -> dict:
            """The overlay keys a service actually consumes. The only
            service-side reader of scenario_epoch is the shuffle seed,
            so without a shuffle window in effect an epoch-only change
            (coldwarm's measured legs) must not bounce the fleet — the
            epoch tag on the records is stamped master-side."""
            eff = {k: overlay[k] for k in wire_keys}
            if not eff.get("shuffle_window", cfg.shuffle_window):
                eff.pop("scenario_epoch", None)
            return eff

        shipped = wire_relevant(base)
        inf = self._adopt_inflight()
        if inf is not None and 0 <= inf.get("index", -1) < len(plan.steps):
            # --resume --adopt skipped the fleet /preparephase: the
            # services still run the dead master's LAST shipped config —
            # the in-flight step's effective overlay, not the base.
            # Seeding `shipped` with it keeps the adopted step from
            # bouncing the fleet mid-flight; later differing steps still
            # re-prepare as usual.
            step0 = plan.steps[inf["index"]]
            shipped = wire_relevant({**base, **step0.overlay,
                                     "scenario_step_label": step0.label,
                                     "scenario_epoch": step0.epoch})
        summaries: "list[dict]" = []
        ran_any = False
        try:
            for idx, step in enumerate(plan.steps):
                if not runs[idx]:
                    logger.log(0, f"RESUME: skipping finished scenario "
                                  f"step {step.label} "
                                  f"({phase_name(step.phase)})")
                    continue
                if self._skip_mkdirs_leg(step):
                    continue
                if step.delay_secs:
                    time.sleep(step.delay_secs)
                elif ran_any and cfg.next_phase_delay_secs:
                    # --phasedelay idles between scenario steps exactly
                    # like between plain phases; a step's own interval
                    # knob (ckpt-burst) wins over it
                    time.sleep(cfg.next_phase_delay_secs)
                overlay = {**base, **step.overlay,
                           "scenario_step_label": step.label,
                           "scenario_epoch": step.epoch}
                for key, val in overlay.items():
                    setattr(cfg, key, val)
                from .phases import UNJOURNALED_PHASES
                if step.phase not in UNJOURNALED_PHASES:
                    # master mode ships the full config once per prepare
                    # (/preparephase): an overlay that changes the wire
                    # config needs a fleet re-prepare — the rotate-hosts
                    # rebuild, reused (identical-overlay steps share
                    # one). Sync/dropcaches legs never read the overlay,
                    # so they must not bounce the fleet just because the
                    # epoch tag reverted between two measured steps.
                    effective = wire_relevant(overlay)
                    if cfg.hosts and effective != shipped:
                        self._rebuild_manager()
                    shipped = effective
                self._last_phase_results = None
                ran_any = True
                try:
                    self._run_journaled_phase(0, idx, step.phase,
                                              step_label=step.label)
                except WorkerException as err:
                    if not step.best_effort:
                        raise
                    # sync/dropcaches legs degrade LOUDLY, never fatally:
                    # an unprivileged run still measures, but its "cold"
                    # epochs are flagged in the verdict evidence
                    logger.log_error(
                        f"scenario step {step.label} failed ({err}); "
                        f"continuing — best-effort leg, later cold "
                        f"epochs may not be cold")
                    summaries.append({"Label": step.label,
                                      "Role": step.role,
                                      "Phase": phase_name(step.phase),
                                      "Failed": True})
                    self._mark_cold_degraded(plan, idx, summaries)
                    if cfg.hosts:
                        # a failed phase leaves the RemoteWorkers in
                        # their terminal error state (unlike local
                        # workers, which respawn per phase) — the next
                        # measured leg needs a fresh fleet prepare
                        self._rebuild_manager()
                        shipped = wire_relevant(overlay)
                    continue
                summaries.append(self._scenario_step_summary(step))
        finally:
            for key, val in base.items():  # never leak the last overlay
                setattr(cfg, key, val)
        self._finish_scenario(plan, summaries, analyze_scenario)

    def _skip_mkdirs_leg(self, step) -> bool:
        """The expansion emits the setup.mkdirs leg whenever the bench
        path type is DIR **or unknown** (master mode cannot probe the
        remote path at expansion time) — but by the time the step loop
        runs, prepare_threads has exchanged the services' probed path
        type into cfg.bench_path_type. A file/blockdev fleet must skip
        the leg instead of hammering CREATEDIRS against a file."""
        from .phases import BenchPathType, BenchPhase
        if step.phase != BenchPhase.CREATEDIRS or step.role != "setup":
            return False
        if self.cfg.bench_path_type == BenchPathType.DIR:
            return False
        logger.log(0, f"Skipping scenario step {step.label}: bench path "
                      f"is not a directory")
        return True

    @staticmethod
    def _mark_cold_degraded(plan, failed_idx: int,
                            summaries: "list[dict]") -> None:
        """A failed cache-drop leg taints the cold labels that depend on
        it — record the degradation on the summary side so the verdict
        can say so instead of publishing a fake cold/warm ratio."""
        if plan.steps[failed_idx].role != "cachedrop":
            return
        for step in plan.steps[failed_idx + 1:]:
            if step.cold:
                summaries.append({"__cold_degraded__": step.label})
                return

    def _scenario_step_summary(self, step) -> dict:
        """Per-step result summary feeding scenarios/verdict.py — the
        cross-leg numbers only (full records live in the JSON file)."""
        res = self._last_phase_results
        cfg = self.cfg
        if res is None:  # phase ran without a result (should not happen)
            return {"Label": step.label, "Role": step.role,
                    "Epoch": step.epoch, "Failed": True}
        last_s = res.last_done_usec / 1e6 or 1e-9
        mibs = round(res.final["bytes"] / last_s / (1 << 20), 2)
        read_mibs = round(res.final_rwmix["bytes"] / last_s / (1 << 20), 2)
        out = {
            "Label": step.label,
            "Role": step.role,
            "Epoch": step.epoch,
            "Cold": step.cold,
            "Phase": res.phase_name,
            "ElapsedUSec": res.last_done_usec,
            "Bytes": res.final["bytes"],
            "Entries": res.final["entries"],
            "MiBPerSec": mibs,
            "ReadMiBPerSec": read_mibs,
            "EpochRate": mibs if step.epoch else 0,
            "NumWorkers": res.num_workers,
            # fleet-wide thread counts: NumWorkers counts RemoteWorkers
            # (= hosts) in master mode, so per-thread normalization in
            # the verdicts needs the real totals
            "TotalThreads": cfg.num_threads * max(1, len(cfg.hosts) or 1),
            "ReadThreads": step.overlay.get("num_rwmix_read_threads", 0)
            * max(1, len(cfg.hosts) or 1),
            "BlockSize": cfg.block_size,
        }
        for knob, key in (("scenario_step_usec", "LoaderStepUSec"),
                          ("scenario_batch_blocks", "LoaderBatchBlocks"),
                          ("scenario_prefetch", "LoaderPrefetch"),
                          ("scenario_decode_usec", "LoaderDecodeUSec")):
            if step.overlay.get(knob):
                out[key] = step.overlay[knob]
        if res.analysis is not None:
            # the per-phase doctor's stage decomposition (--flightrec):
            # what the scenario verdict compares ACROSS legs
            out["Analysis"] = {k: res.analysis[k] for k in
                               ("Verdict", "BottleneckStage", "StagePct")}
        return out

    def _finish_scenario(self, plan, summaries: "list[dict]",
                         analyze_scenario) -> None:
        """Compute + print the scenario-level verdicts and append the
        terminal SCENARIO record to the JSON results, so summarize/chart
        and the artifact pipeline see the analysis without new files."""
        degraded = {s["__cold_degraded__"] for s in summaries
                    if "__cold_degraded__" in s}
        steps = [s for s in summaries if "__cold_degraded__" not in s]
        for s in steps:
            if s.get("Label") in degraded:
                s["ColdDegraded"] = True
        analysis = analyze_scenario(plan.name, steps)
        for v in analysis["Verdicts"]:
            logger.log(0, f"Scenario verdict [{v['Kind']}]: "
                          f"{v['Verdict']}")
            for ev in v["Evidence"]:
                logger.log(1, f"  - {ev}")
        if not analysis["Verdicts"]:
            logger.log(0, "Scenario verdict: inconclusive (not enough "
                          "finished legs to compare)")
        cfg = self.cfg
        if cfg.json_file_path:
            import json as json_mod
            rec = {"ISODate": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
                   "Label": cfg.bench_label,
                   "Phase": "SCENARIO",
                   "Scenario": plan.name,
                   "ScenarioStep": "summary",
                   "ScenarioAnalysis": analysis}
            with open(cfg.json_file_path, "a") as f:
                f.write(json_mod.dumps(rec) + "\n")

    def _run_autotune(self) -> None:
        """--autotune: verdict-guided knob search BEFORE the measured
        phases (elbencho_tpu/autotune/). The Autotune block lands in
        the run JSON as its own terminal-style record immediately, so
        even an aborted main run keeps the search's trajectory and the
        emitted profile path."""
        from .autotune import run_autotune
        block = run_autotune(self)
        if block is None:
            return
        cfg = self.cfg
        if cfg.json_file_path:
            import json as json_mod
            rec = {"ISODate": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
                   "Label": cfg.bench_label,
                   "Phase": "AUTOTUNE",
                   "Autotune": block}
            with open(cfg.json_file_path, "a") as f:
                f.write(json_mod.dumps(rec) + "\n")

    def _rebuild_manager(self) -> None:
        """Tear down the worker fleet and re-prepare it against the
        CURRENT cfg — the mechanism behind --rotatehosts re-ranking and
        scenario overlay re-shipping (master mode posts the full config
        at /preparephase, so a changed step config needs a fresh
        prepare). Keeps tracer/telemetry/flightrec across the rebuild."""
        old_tracer = self.manager.shared.tracer
        self.manager.join_all_threads()
        from .workers.manager import WorkerManager
        self.manager = WorkerManager(self.cfg)
        if old_tracer is not None:
            # keep the run's span ring across the rebuild: a fresh tracer
            # at the same path would overwrite the file and silently drop
            # every earlier span at the next phase-end write()
            self.manager.shared.tracer = old_tracer
        self.statistics = Statistics(self.cfg, self.manager)
        self.statistics.telemetry = self._telemetry  # follow the rebuild
        self.statistics.flightrec = self._flightrec  # keep recording
        self.manager.prepare_threads()

    def _run_journaled_phase(self, iteration: int, idx: int,
                             phase: BenchPhase,
                             step_label: str = "") -> None:
        """One table phase, bracketed by journal records: the fsync'd
        phase_start makes a later crash provable (no finish record = the
        phase did not complete), phase_interrupted marks signal/error
        aborts, phase_finish carries per-host result summaries. Scenario
        steps pass their label so the records stay human-readable;
        sync/dropcaches legs stay out of the journal here exactly like
        the interleave (UNJOURNALED_PHASES) — a resume must never treat
        a cache drop as finished work."""
        from .phases import UNJOURNALED_PHASES
        if self._journal is None or phase in UNJOURNALED_PHASES:
            self.run_benchmark_phase(phase)
            return
        bench_uuid = ""
        if self.cfg.hosts and getattr(self.cfg, "takeover_token", ""):
            # failover-armed fleet run: pre-mint the phase's bench UUID
            # so it is journaled BEFORE /startphase — an adopting master
            # then re-presents it and the fleet's duplicate-start
            # idempotency keeps the in-flight phase running
            import uuid as uuid_mod
            bench_uuid = str(uuid_mod.uuid4())
            inf = self._adopt_inflight()
            if inf is not None and inf.get("bench_uuid") \
                    and (inf.get("iteration"), inf.get("index")) \
                    == (iteration, idx):
                bench_uuid = inf["bench_uuid"]
        self._journal_write(self._journal.phase_start, iteration, idx,
                            phase, step_label, bench_uuid)
        try:
            self.run_benchmark_phase(phase, bench_uuid=bench_uuid)
        except BaseException as err:
            reason = f"{type(err).__name__}: {err}" if str(err) \
                else type(err).__name__
            try:  # best effort: never mask the original abort cause
                self._journal.phase_interrupted(iteration, idx, phase,
                                                reason, step_label)
            except OSError:
                pass
            raise
        self._journal_write(self._journal.phase_finish, iteration, idx,
                            phase, self._phase_host_summaries(),
                            step_label)

    def _journal_write(self, method, *args) -> None:
        """A mid-run journal append failure (disk full, lost mount) must
        abort like any worker error — cleanly, with interrupt + hygiene —
        not escape as a raw OSError traceback: a run whose restart point
        can no longer be recorded must not keep running as if it could."""
        try:
            method(*args)
        except OSError as err:
            raise WorkerException(
                f"--journal write failed ({self.cfg.journal_file_path}): "
                f"{err}") from err

    def _phase_host_summaries(self) -> "dict[str, dict]":
        """Per-host finish summary for the journal: local workers fold
        into one "local" entry, RemoteWorkers report per host."""
        out: "dict[str, dict]" = {}
        for w in self.manager.workers:
            key = getattr(w, "host", None) or "local"
            s = out.setdefault(key, {"entries": 0, "bytes": 0, "iops": 0,
                                     "elapsed_usec": 0})
            s["entries"] += w.live_ops.num_entries_done
            s["bytes"] += w.live_ops.num_bytes_done
            s["iops"] += w.live_ops.num_iops_done
            s["elapsed_usec"] = max(s["elapsed_usec"],
                                    max(w.elapsed_usec_vec, default=0))
        return out

    def _run_sync_and_drop_caches(self) -> None:
        if self.cfg.run_sync_phase:
            self.run_benchmark_phase(BenchPhase.SYNC)
        if self.cfg.run_drop_caches_phase:
            self.run_benchmark_phase(BenchPhase.DROPCACHES)

    def run_benchmark_phase(self, phase: BenchPhase,
                            bench_uuid: str = "") -> None:
        """Start phase -> live stats -> wait done -> print results
        (reference: runBenchmarkPhase, Coordinator.cpp:249). A nonempty
        bench_uuid forces the phase's UUID (journal pre-mint / adoption,
        see _run_journaled_phase)."""
        from .phases import phase_name
        phase_start = time.monotonic()
        tracer = self.manager.shared.tracer
        trace_t0 = tracer.now_ns() if tracer is not None else 0
        profiling = self._start_tpu_profile(phase)
        try:
            self.manager.start_next_phase(phase, bench_uuid=bench_uuid)
            self.statistics.live_stats_loop(phase, phase_start)
            self.manager.wait_for_workers_done(phase_start)
        finally:
            if profiling:
                self._stop_tpu_profile()
            if tracer is not None:
                # phase marker span + persist the ring, so the trace file
                # is loadable after every phase (and after an abort). The
                # marker carries the phase's non-zero path-audit totals
                # (TPU path, retry, staging-pool counters) as span args —
                # the whole PATH_AUDIT_COUNTERS schema is inspectable in
                # Perfetto without cross-referencing the JSON record.
                from .service.fault_tolerance import \
                    merge_control_audit_counters
                from .tpu.device import sum_path_audit_counters
                # barrier decomposition BEFORE the marker is built, so
                # StragglerSkewUsec/BarrierWaitUSec ride the marker like
                # every control counter (recomputed harmlessly by
                # generate_phase_results right after)
                self.statistics._compute_barrier_skew()
                audit = {k: v for k, v in sum_path_audit_counters(
                    self.manager.workers).items() if v}
                # control-plane audit (retries, lease expiries/age) rides
                # the same phase marker so Perfetto shows both planes
                audit.update({k: v for k, v in merge_control_audit_counters(
                    self.manager.workers).items() if v})
                tracer.record(phase_name(phase), "phase", trace_t0,
                              (tracer.now_ns() - trace_t0) // 1000,
                              **audit)
                try:
                    tracer.write()
                except OSError as err:
                    logger.log_error(f"--tracefile write failed: {err}")
        self._last_phase_results = self.statistics.print_phase_results(phase)
        if self._interrupted:
            # user Ctrl-C: print what we have for this phase, then abort the
            # remaining phases (reference: handleInterruptSignal semantics)
            raise KeyboardInterrupt

    #: phases whose workers drive the TPU data path (H2D staging on
    #: reads, HBM-originated fills on writes, the fabric bench itself) —
    #: metadata phases (mkdir/stat/delete) never touch the device
    _TPU_PROFILE_PHASES = (BenchPhase.CREATEFILES, BenchPhase.READFILES,
                           BenchPhase.TPUBENCH, BenchPhase.TPUSLICE)

    def _start_tpu_profile(self, phase: BenchPhase) -> bool:
        """--tpuprofile DIR: bracket each TPU-touching measured phase with
        a jax profiler trace (XLA device timeline, viewable in
        TensorBoard/Perfetto — the TPU-native per-op observability the
        reference's --opslog gives for syscalls). One trace subdirectory
        per phase run."""
        cfg = self.cfg
        if not cfg.tpu_profile_dir:
            return False
        if not (cfg.tpu_ids or cfg.run_tpu_bench or cfg.run_tpu_slice):
            return False
        if phase not in self._TPU_PROFILE_PHASES:
            return False
        if cfg.hosts:
            # master mode: the TPU work happens in the remote service
            # processes; tracing this process would record an idle
            # timeline. Warn once instead of writing meaningless traces.
            if not self._profile_warned_hosts:
                self._profile_warned_hosts = True
                logger.log_error(
                    "--tpuprofile is ignored in master mode (the TPU "
                    "work runs in the remote service processes); run the "
                    "benchmark locally on each host to capture traces")
            return False
        self._profile_seq += 1
        trace_dir = os.path.join(
            cfg.tpu_profile_dir,
            f"{self._profile_seq:03d}_{phase.name.lower()}")
        try:
            import jax
            jax.profiler.start_trace(trace_dir)
        except Exception as err:  # pragma: no cover - backend-dependent
            logger.log_error(f"--tpuprofile: cannot start jax trace "
                             f"({type(err).__name__}: {err})")
            return False
        logger.log(1, f"TPU profile trace: {trace_dir}")
        return True

    @staticmethod
    def _stop_tpu_profile() -> None:
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as err:  # pragma: no cover - backend-dependent
            logger.log_error(f"--tpuprofile: stop_trace failed "
                             f"({type(err).__name__}: {err})")

    def _rotate_hosts(self) -> None:
        """--rotatehosts: shift the hosts list between phases, which
        re-ranks all remote workers (reference: rotateHosts :384-408 —
        requires a fresh prep phase)."""
        cfg = self.cfg
        if not cfg.rotate_hosts_num or not cfg.hosts:
            return
        k = cfg.rotate_hosts_num % len(cfg.hosts)
        if not k:
            return
        cfg.hosts = cfg.hosts[k:] + cfg.hosts[:k]
        self._rebuild_manager()

    # ------------------------------------------------------------------

    def _install_signal_handler(self) -> None:
        """Two-stage graceful shutdown (reference: Coordinator.cpp:23,
        :420-442, tightened for unattended runs): the FIRST SIGINT or
        SIGTERM interrupts local workers and remote services and lets the
        run unwind normally — the journal's phase_interrupted record is
        written on the way out, services get /interruptphase. A SECOND
        signal is immediate: the default disposition is restored and the
        signal re-delivered to this process."""

        def handler(signum, frame):
            if self._interrupted:
                # second signal: immediate — no more graceful anything
                for sig in (signal.SIGINT, signal.SIGTERM):
                    try:
                        signal.signal(sig, signal.SIG_DFL)
                    except (ValueError, OSError):
                        pass
                os.kill(os.getpid(), signum)
                return
            self._interrupted = True
            print("Interrupt received. Finishing up... "
                  "(send the signal again to force quit)", file=sys.stderr)
            self.manager.shared.request_interrupt()
            self.manager.interrupt_and_notify_workers()

        self._old_handlers = []
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                self._old_handlers.append((sig, signal.signal(sig, handler)))
            except ValueError:
                pass  # not on main thread (tests)

    def _restore_signal_handler(self) -> None:
        for sig, old in self._old_handlers:
            try:
                signal.signal(sig, old)
            except (ValueError, OSError, TypeError):
                pass
        self._old_handlers = []
