"""Coordinator: role dispatch and benchmark phase ordering.

Reference: source/Coordinator.{h,cpp} — main() :32 (service vs master vs
local role), runBenchmarks() :299 with the ordered phase table :311-334
(creates before deletes), sync/dropcaches interleave after every phase,
host rotation :384, SIGINT graceful shutdown :420-442, synchronized start
time :150-159, service-ready wait :165.
"""

from __future__ import annotations

import os
import signal
import sys
import time

from .phases import BenchPhase
from .stats.statistics import Statistics
from .toolkits import logger
from .workers.manager import WorkerManager
from .workers.shared import WorkerException


class Coordinator:
    def __init__(self, cfg):
        self.cfg = cfg
        self.manager = WorkerManager(cfg)
        self.statistics = Statistics(cfg, self.manager)
        self._interrupted = False
        self._profile_seq = 0
        self._profile_warned_hosts = False
        self._old_sigint = None
        self._telemetry = None   # BenchTelemetry when --telemetry
        self._exporter = None    # its /metrics HTTP server

    # ------------------------------------------------------------------

    def main(self) -> int:
        from .toolkits.signals import register_fault_handlers
        register_fault_handlers()  # reference: SignalTk fault trace
        cfg = self.cfg
        if cfg.run_as_service:
            from .service.http_service import HTTPService
            return HTTPService(cfg).start()
        if cfg.quit_services or cfg.interrupt_services:
            from .service.remote_worker import send_interrupt_to_hosts
            send_interrupt_to_hosts(cfg.hosts, cfg.service_port,
                                    quit=cfg.quit_services)
            return 0
        return self._run_master_or_local()

    def _run_master_or_local(self) -> int:
        cfg = self.cfg
        self._install_signal_handler()
        try:
            self._start_telemetry()
            if cfg.hosts:
                from .service.remote_worker import wait_for_services_ready
                wait_for_services_ready(cfg.hosts, cfg.service_port,
                                        cfg.svc_wait_secs)
            self._wait_for_sync_start()
            self.manager.prepare_threads()
            self.run_benchmarks()
            return 0
        except WorkerException as err:
            logger.log_error(f"Aborting due to worker error: {err}")
            self.manager.interrupt_and_notify_workers()
            return 1
        except KeyboardInterrupt:
            logger.log_error("Interrupted. Shutting down workers...")
            self.manager.interrupt_and_notify_workers()
            return 3
        finally:
            try:
                self.manager.join_all_threads()
            except Exception:  # noqa: BLE001 - teardown must not mask errors
                pass
            self.statistics.close()
            if self._exporter is not None:
                self._exporter.stop()
            self._restore_signal_handler()

    def _start_telemetry(self) -> None:
        """--telemetry: standalone Prometheus /metrics endpoint for
        local/master runs (service mode piggybacks onto the control
        server's route table instead, service/http_service.py). The
        provider indirection follows manager/statistics across
        --rotatehosts rebuilds."""
        cfg = self.cfg
        if not cfg.telemetry:
            return
        from .telemetry.exporter import TelemetryExporter
        from .telemetry.registry import BenchTelemetry
        telemetry = BenchTelemetry(
            cfg, lambda: (self.statistics, self.manager),
            role="master" if cfg.hosts else "local")
        self._telemetry = telemetry
        self.statistics.telemetry = telemetry
        exporter = TelemetryExporter(telemetry, cfg.telemetry_port)
        try:
            exporter.start()
        except OSError as err:
            raise WorkerException(
                f"--telemetry: cannot bind --telemetryport "
                f"{cfg.telemetry_port}: {err}") from err
        self._exporter = exporter

    def _wait_for_sync_start(self) -> None:
        """--start: cross-host synchronized start (reference: :150-159;
        accepts "HH:MM[:SS]" UTC or a unix timestamp)."""
        spec = self.cfg.start_time_utc
        if not spec:
            return
        if ":" in spec:
            parts = [int(x) for x in spec.split(":")]
            now = time.gmtime()
            target_secs = parts[0] * 3600 + parts[1] * 60 + \
                (parts[2] if len(parts) > 2 else 0)
            now_secs = now.tm_hour * 3600 + now.tm_min * 60 + now.tm_sec
            delay = target_secs - now_secs
            if delay < 0:
                raise WorkerException("--start time is in the past")
        else:
            delay = float(spec) - time.time()
            if delay < 0:
                raise WorkerException("--start time is in the past")
        logger.log(0, f"Waiting {delay:.0f}s for synchronized start...")
        time.sleep(delay)

    # ------------------------------------------------------------------

    def run_benchmarks(self) -> None:
        """Iterations x ordered phases with sync/dropcaches interleave
        (reference: runBenchmarks, Coordinator.cpp:299-376)."""
        cfg = self.cfg
        phases = cfg.enabled_phases()
        for iteration in range(cfg.iterations):
            if cfg.iterations > 1:
                logger.log(0, f"[Starting iteration {iteration + 1} of "
                              f"{cfg.iterations}...]")
            self.statistics.print_phase_results_table_header()
            self._run_sync_and_drop_caches()
            for idx, phase in enumerate(phases):
                self.run_benchmark_phase(phase)
                self._run_sync_and_drop_caches()
                if idx < len(phases) - 1:
                    if cfg.next_phase_delay_secs:
                        time.sleep(cfg.next_phase_delay_secs)
                    self._rotate_hosts()

    def _run_sync_and_drop_caches(self) -> None:
        if self.cfg.run_sync_phase:
            self.run_benchmark_phase(BenchPhase.SYNC)
        if self.cfg.run_drop_caches_phase:
            self.run_benchmark_phase(BenchPhase.DROPCACHES)

    def run_benchmark_phase(self, phase: BenchPhase) -> None:
        """Start phase -> live stats -> wait done -> print results
        (reference: runBenchmarkPhase, Coordinator.cpp:249)."""
        from .phases import phase_name
        phase_start = time.monotonic()
        tracer = self.manager.shared.tracer
        trace_t0 = tracer.now_ns() if tracer is not None else 0
        profiling = self._start_tpu_profile(phase)
        try:
            self.manager.start_next_phase(phase)
            self.statistics.live_stats_loop(phase, phase_start)
            self.manager.wait_for_workers_done(phase_start)
        finally:
            if profiling:
                self._stop_tpu_profile()
            if tracer is not None:
                # phase marker span + persist the ring, so the trace file
                # is loadable after every phase (and after an abort). The
                # marker carries the phase's non-zero path-audit totals
                # (TPU path, retry, staging-pool counters) as span args —
                # the whole PATH_AUDIT_COUNTERS schema is inspectable in
                # Perfetto without cross-referencing the JSON record.
                from .tpu.device import sum_path_audit_counters
                audit = {k: v for k, v in sum_path_audit_counters(
                    self.manager.workers).items() if v}
                tracer.record(phase_name(phase), "phase", trace_t0,
                              (tracer.now_ns() - trace_t0) // 1000,
                              **audit)
                try:
                    tracer.write()
                except OSError as err:
                    logger.log_error(f"--tracefile write failed: {err}")
        self.statistics.print_phase_results(phase)
        if self._interrupted:
            # user Ctrl-C: print what we have for this phase, then abort the
            # remaining phases (reference: handleInterruptSignal semantics)
            raise KeyboardInterrupt

    #: phases whose workers drive the TPU data path (H2D staging on
    #: reads, HBM-originated fills on writes, the fabric bench itself) —
    #: metadata phases (mkdir/stat/delete) never touch the device
    _TPU_PROFILE_PHASES = (BenchPhase.CREATEFILES, BenchPhase.READFILES,
                           BenchPhase.TPUBENCH)

    def _start_tpu_profile(self, phase: BenchPhase) -> bool:
        """--tpuprofile DIR: bracket each TPU-touching measured phase with
        a jax profiler trace (XLA device timeline, viewable in
        TensorBoard/Perfetto — the TPU-native per-op observability the
        reference's --opslog gives for syscalls). One trace subdirectory
        per phase run."""
        cfg = self.cfg
        if not cfg.tpu_profile_dir:
            return False
        if not (cfg.tpu_ids or cfg.run_tpu_bench):
            return False
        if phase not in self._TPU_PROFILE_PHASES:
            return False
        if cfg.hosts:
            # master mode: the TPU work happens in the remote service
            # processes; tracing this process would record an idle
            # timeline. Warn once instead of writing meaningless traces.
            if not self._profile_warned_hosts:
                self._profile_warned_hosts = True
                logger.log_error(
                    "--tpuprofile is ignored in master mode (the TPU "
                    "work runs in the remote service processes); run the "
                    "benchmark locally on each host to capture traces")
            return False
        self._profile_seq += 1
        trace_dir = os.path.join(
            cfg.tpu_profile_dir,
            f"{self._profile_seq:03d}_{phase.name.lower()}")
        try:
            import jax
            jax.profiler.start_trace(trace_dir)
        except Exception as err:  # pragma: no cover - backend-dependent
            logger.log_error(f"--tpuprofile: cannot start jax trace "
                             f"({type(err).__name__}: {err})")
            return False
        logger.log(1, f"TPU profile trace: {trace_dir}")
        return True

    @staticmethod
    def _stop_tpu_profile() -> None:
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as err:  # pragma: no cover - backend-dependent
            logger.log_error(f"--tpuprofile: stop_trace failed "
                             f"({type(err).__name__}: {err})")

    def _rotate_hosts(self) -> None:
        """--rotatehosts: shift the hosts list between phases, which
        re-ranks all remote workers (reference: rotateHosts :384-408 —
        requires a fresh prep phase)."""
        cfg = self.cfg
        if not cfg.rotate_hosts_num or not cfg.hosts:
            return
        k = cfg.rotate_hosts_num % len(cfg.hosts)
        if not k:
            return
        cfg.hosts = cfg.hosts[k:] + cfg.hosts[:k]
        old_tracer = self.manager.shared.tracer
        self.manager.join_all_threads()
        self.manager = WorkerManager(cfg)
        if old_tracer is not None:
            # keep the run's span ring across the rebuild: a fresh tracer
            # at the same path would overwrite the file and silently drop
            # every pre-rotation span at the next phase-end write()
            self.manager.shared.tracer = old_tracer
        self.statistics = Statistics(cfg, self.manager)
        self.statistics.telemetry = self._telemetry  # follow the rebuild
        self.manager.prepare_threads()

    # ------------------------------------------------------------------

    def _install_signal_handler(self) -> None:
        """First SIGINT interrupts workers gracefully; another SIGINT >5s
        later restores the default handler (reference: Coordinator.cpp:23,
        :420-442)."""
        self._last_sigint = 0.0

        def handler(signum, frame):
            now = time.monotonic()
            if self._interrupted and now - self._last_sigint > 5:
                signal.signal(signal.SIGINT, signal.SIG_DFL)
            self._interrupted = True
            self._last_sigint = now
            print("Interrupt received. Finishing up... "
                  "(Ctrl-C again after 5s to force quit)", file=sys.stderr)
            self.manager.shared.request_interrupt()
            self.manager.interrupt_and_notify_workers()

        try:
            self._old_sigint = signal.signal(signal.SIGINT, handler)
        except ValueError:
            self._old_sigint = None  # not on main thread (tests)

    def _restore_signal_handler(self) -> None:
        if self._old_sigint is not None:
            try:
                signal.signal(signal.SIGINT, self._old_sigint)
            except ValueError:
                pass
