"""Flagship device-side workload: the HBM ingest pipeline step.

This framework's "model" is its device-side data pipeline (the reference
has no NN models; its GPU work is buffer staging + curand fill,
LocalWorker.cpp:1427-1537). The flagship jittable step combines everything
the TPU data path does to a block resident in HBM:

  1. scramble (PRNG xor-mix; block-variance analogue)
  2. fingerprint (sum + xor reduction; on-device integrity verify)

It is what ``__graft_entry__.entry()`` exposes for the single-chip compile
check, and the per-shard body of the pod-wide sharded step in
parallel/ingest.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def scramble_fingerprint_core(block_u32, key):
    """Shared per-shard body: scramble + (sum, xor) fingerprints. Used by
    both the single-chip flagship step and the per-shard function of the
    pod-wide sharded step (parallel/ingest.py) so they cannot diverge."""
    bits = jax.random.bits(key, block_u32.shape, dtype=jnp.uint32)
    scrambled = block_u32 ^ bits
    total = jnp.sum(scrambled, dtype=jnp.uint32)
    xor = jax.lax.reduce(scrambled, jnp.uint32(0), jax.lax.bitwise_xor,
                         tuple(range(scrambled.ndim)))
    return scrambled, total, xor


@jax.jit
def ingest_block_step(block_u32, key):
    """(block, key) -> (scrambled block, sum fingerprint, xor fingerprint)."""
    return scramble_fingerprint_core(block_u32, key)


def example_block(num_bytes: int = 1 << 20):
    """Example args for the flagship step: one 1 MiB block + PRNG key."""
    import numpy as np
    block = np.zeros(num_bytes // 4, dtype=np.uint32)
    key = jax.random.PRNGKey(0)
    return block, key
