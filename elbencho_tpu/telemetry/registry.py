"""Lock-light metric registry + the benchmark sampler behind /metrics.

Design contract (ISSUE 4 tentpole): workers never push into the registry —
it SAMPLES the counters the benchmark already maintains (per-worker
``live_ops``, the ``PATH_AUDIT_COUNTERS`` / ``CONTROL_AUDIT_COUNTERS``
schemas, the TPU dispatch-vs-DMA split of ``TransferPipeline``) on the
coordinator's existing live-stats cadence and on scrape. All of those are
plain ints written by their owning thread and read here under the GIL —
the same safety argument ``Statistics._sum_live_ops`` already relies on —
so the hot paths pay nothing and the registry needs no locks beyond a
snapshot-dict swap.

Fleet aggregation (master mode): ``sum_path_audit_counters`` /
``merge_control_audit_counters`` are the SAME merge helpers the service
wire protocol uses (sum, except the documented MAX-merged high-water
marks), applied over the RemoteWorkers' live-ingested per-host counters —
the master's /metrics is therefore by construction the sum/MAX of the
per-host /metrics views.
"""

from __future__ import annotations

import re

from .. import __version__
from ..phases import phase_name
from ..service.fault_tolerance import (CONTROL_AUDIT_COUNTERS,
                                       merge_control_audit_counters)
from ..stats.latency_histogram import LatencyHistogram
from ..tpu.device import (PATH_AUDIT_COUNTERS, PATH_AUDIT_MAX_KEYS,
                          sum_path_audit_counters)

#: every exported metric name carries this prefix
METRIC_PREFIX = "elbencho_tpu_"

_SNAKE_RE = re.compile(r"(?<=[a-z0-9])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])")


def snake_case(name: str) -> str:
    """Wire/JSON key -> metric name fragment (TpuH2dDirectOps ->
    tpu_h2d_direct_ops)."""
    return _SNAKE_RE.sub("_", name).lower()


def _escape_label(value: str) -> str:
    return str(value).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


def _escape_help(text: str) -> str:
    """HELP-line escaping per the exposition format (backslash and
    newline; quotes are legal there)."""
    return text.replace("\\", r"\\").replace("\n", r"\n")


class Metric:
    """One metric family: name + kind + help + labeled samples. Samples
    are replaced wholesale per sampling pass (whole-dict swap — atomic
    under the GIL, so a concurrent render never sees a half-built
    family and never iterates a mutating dict)."""

    __slots__ = ("name", "kind", "help_txt", "samples")

    def __init__(self, name: str, kind: str, help_txt: str):
        self.name = name
        self.kind = kind          # counter | gauge | histogram
        self.help_txt = help_txt
        # labels tuple (sorted (k, v) pairs) -> value; histograms store a
        # LatencyHistogram snapshot instead of a number
        self.samples: dict = {}

    def set(self, value, labels: "tuple | None" = None) -> None:
        self.samples[labels or ()] = value

    def render(self, out: "list[str]") -> None:
        full = METRIC_PREFIX + self.name
        samples = self.samples  # one snapshot ref for the whole pass
        out.append(f"# HELP {full} {_escape_help(self.help_txt)}")
        out.append(f"# TYPE {full} "
                   f"{'counter' if self.kind == 'counter' else self.kind}")
        for labels, value in sorted(samples.items()):
            if self.kind == "histogram":
                self._render_histogram(out, full, labels, value)
                continue
            lbl = self._label_str(labels)
            out.append(f"{full}{lbl} {value}")

    @staticmethod
    def _label_str(labels: tuple, extra: "tuple | None" = None) -> str:
        pairs = tuple(labels) + tuple(extra or ())
        if not pairs:
            return ""
        return "{" + ",".join(
            f'{k}="{_escape_label(v)}"' for k, v in pairs) + "}"

    def _render_histogram(self, out: "list[str]", full: str,
                          labels: tuple, histo: LatencyHistogram) -> None:
        """Prometheus histogram exposition over the log2 buckets
        (LatencyHistogram.to_prometheus_buckets: cumulative counts)."""
        for le, cum in histo.to_prometheus_buckets():
            le_str = "+Inf" if le == float("inf") else f"{le:g}"
            out.append(f"{full}_bucket"
                       f"{self._label_str(labels, (('le', le_str),))} "
                       f"{cum}")
        out.append(f"{full}_sum{self._label_str(labels)} "
                   f"{histo.sum_micro}")
        out.append(f"{full}_count{self._label_str(labels)} "
                   f"{histo.num_values}")


class MetricRegistry:
    """Ordered family registry with Prometheus text rendering
    (exposition format 0.0.4)."""

    def __init__(self):
        self._metrics: "dict[str, Metric]" = {}
        self.scrapes = 0  # served /metrics replies (exported itself)

    def declare(self, name: str, kind: str, help_txt: str) -> Metric:
        metric = self._metrics.get(name)
        if metric is None:
            metric = Metric(name, kind, help_txt)
            self._metrics[name] = metric
        return metric

    def counter(self, name: str, help_txt: str) -> Metric:
        return self.declare(name, "counter", help_txt)

    def gauge(self, name: str, help_txt: str) -> Metric:
        return self.declare(name, "gauge", help_txt)

    def histogram(self, name: str, help_txt: str) -> Metric:
        return self.declare(name, "histogram", help_txt)

    def set(self, name: str, value, labels: "tuple | None" = None) -> None:
        self._metrics[name].set(value, labels)

    def commit(self, updates: "dict[str, dict]") -> None:
        """Swap whole sample dicts in (one assignment per family): a
        render running concurrently on another thread sees either the
        previous complete snapshot or the new one, never a mix and never
        a dict mutating under iteration."""
        for name, samples in updates.items():
            self._metrics[name].samples = samples

    def render(self) -> str:
        out: "list[str]" = []
        for metric in self._metrics.values():
            if metric.samples:
                metric.render(out)
        return "\n".join(out) + "\n"


#: Content-Type of the Prometheus text exposition format
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class BenchTelemetry:
    """Samples a live (statistics, manager) pair into a MetricRegistry and
    renders /metrics replies. ``provider`` is a zero-arg callable returning
    the CURRENT (statistics, manager) — host rotation and /preparephase
    rebuild both, so the exporter must never cache them."""

    def __init__(self, cfg, provider, role: str = "local",
                 extra_control=None):
        self.cfg = cfg
        self.provider = provider
        self.role = role
        # optional zero-arg callable returning extra CONTROL_AUDIT_COUNTERS
        # values keyed by wire name, merged by each counter's mode: the
        # service role's lease counters live on ServiceState (outside the
        # worker pool this sampler walks), not on any worker
        self.extra_control = extra_control
        self.registry = MetricRegistry()
        # tracer hookup for the trace-event drop/record gauges (optional)
        self.tracer = None
        # dedicated CPU meter (primed, rate-limited): updating the
        # benchmark's shared phase meter would reset its /proc/stat
        # baseline out from under the stonewall/last-done snapshots
        from ..stats.cpu_util import SampledCPUUtil
        self._cpu = SampledCPUUtil()
        self._declare()

    # -- declarations --------------------------------------------------------

    def _declare(self) -> None:
        reg = self.registry
        reg.gauge("info", "Build/role info (value is always 1)")
        reg.gauge("phase_code", "Numeric code of the current bench phase")
        reg.gauge("phase", "Current bench phase (label; value is 1)")
        reg.gauge("workers", "Workers in the pool (master: one per host)")
        reg.gauge("workers_done", "Workers finished with the current phase")
        reg.counter("entries_done_total",
                    "Entries completed in the current phase")
        reg.counter("bytes_done_total",
                    "Payload bytes moved in the current phase")
        reg.counter("ops_done_total",
                    "I/O operations completed in the current phase")
        reg.gauge("cpu_util_pct", "Host CPU utilization percent")
        reg.gauge("host_cpu_util_pct",
                  "Per-service-host CPU utilization percent (master only)")
        for _attr, key, _ingest in PATH_AUDIT_COUNTERS:
            if key in PATH_AUDIT_MAX_KEYS:
                reg.gauge(snake_case(key),
                          f"TPU path audit high-water mark {key} "
                          f"(MAX-merged across workers/hosts)")
            else:
                reg.counter(snake_case(key) + "_total",
                            f"TPU path audit counter {key} "
                            f"(summed across workers/hosts)")
        reg.counter("tpu_hbm_bytes_total",
                    "Bytes staged through TPU HBM this phase")
        reg.counter("tpu_dispatch_usec_total",
                    "Host-side TPU transfer submit cost this phase "
                    "(dispatch leg of the dispatch-vs-DMA split)")
        reg.counter("tpu_transfer_usec_total",
                    "TPU DMA wall time this phase (submit -> ready)")
        for _attr, key, mode in CONTROL_AUDIT_COUNTERS:
            if mode == "max":
                reg.gauge(snake_case(key),
                          f"Control-plane audit high-water mark {key} "
                          f"(MAX-merged across hosts)")
            else:
                reg.counter(snake_case(key) + "_total",
                            f"Control-plane audit counter {key}")
        reg.histogram("io_latency_usec",
                      "Per-op I/O latency in microseconds "
                      "(log2 buckets at quarter-log2 resolution)")
        # running tail gauges (slow-op forensics satellite): bucket-walk
        # percentiles over the same live histogram, so dashboards see
        # the tail mid-run without histogram_quantile() support
        reg.gauge("io_latency_p99_usec",
                  "Running p99 of per-op I/O latency this phase "
                  "(bucket-walk over the live latency histogram)")
        reg.gauge("io_latency_p999_usec",
                  "Running p99.9 of per-op I/O latency this phase "
                  "(bucket-walk over the live latency histogram)")
        reg.histogram("entry_latency_usec",
                      "Per-entry latency in microseconds")
        reg.counter("scrapes_total", "Served /metrics replies")
        reg.counter("trace_events_total",
                    "Spans recorded by the --tracefile ring buffer")
        reg.counter("trace_events_overwritten_total",
                    "Ring-buffer spans overwritten before the trace "
                    "file was written (raise the ring or --tracesample)")
        reg.counter("trace_events_dropped_total",
                    "Spans the trace LOST: sampled out by --tracesample "
                    "plus ring overwrites (TraceDropped in JSON)")

    # -- sampling ------------------------------------------------------------

    def sample(self) -> None:
        """One sampling pass over the current benchmark state. Reads
        worker-owned plain ints under the GIL — never blocks a worker.
        Built into fresh per-family dicts and committed with whole-dict
        swaps, so a concurrent render (ThreadingHTTPServer scrape vs the
        live-stats loop) always sees complete snapshots."""
        reg = self.registry
        up: "dict[str, dict]" = {}

        def put(name: str, value, labels: "tuple | None" = None) -> None:
            up.setdefault(name, {})[labels or ()] = value

        put("info", 1, (("role", self.role), ("version", __version__)))
        statistics, manager = self.provider()
        put("scrapes_total", reg.scrapes)
        tracer = self.tracer
        if tracer is None and manager is not None:
            tracer = manager.shared.tracer
        if tracer is not None:
            put("trace_events_total", tracer.num_recorded)
            put("trace_events_overwritten_total", tracer.num_overwritten)
            put("trace_events_dropped_total", tracer.num_dropped)
        if manager is None:
            # idle service (incl. after lease-orphan recovery dropped the
            # pool): the service-lifetime lease counters must still show
            if self.extra_control is not None:
                extra = self.extra_control()
                for _attr, key, mode in CONTROL_AUDIT_COUNTERS:
                    if key in extra:
                        name = snake_case(key) \
                            + ("" if mode == "max" else "_total")
                        put(name, extra[key])
            reg.commit(up)
            return
        shared = manager.shared
        workers = manager.workers
        put("phase_code", int(shared.current_phase))
        put("phase", 1, (("phase", phase_name(shared.current_phase)),))
        put("workers", len(workers))
        if statistics is not None:
            entries, num_bytes, iops, done = statistics._sum_live_ops()
            put("workers_done", done)
            put("entries_done_total", entries)
            put("bytes_done_total", num_bytes)
            put("ops_done_total", iops)
        put("cpu_util_pct", round(self._cpu.sample(), 1))
        # per-host CPU gauges: RemoteWorkers carry the last /status
        # CPUUtil (fresh dict per pass, so rotated-out hosts drop off)
        up["host_cpu_util_pct"] = {}
        for w in workers:
            host = getattr(w, "host", None)
            if host is not None:
                put("host_cpu_util_pct",
                    getattr(w, "cpu_util_pct", 0.0), (("host", host),))
        # path audit: the service wire protocol's merge rules (sum/MAX)
        # applied over local contexts AND RemoteWorker live ingests —
        # this is the fleet aggregation
        path_totals = sum_path_audit_counters(workers)
        for _attr, key, _ingest in PATH_AUDIT_COUNTERS:
            name = snake_case(key)
            if key not in PATH_AUDIT_MAX_KEYS:
                name += "_total"
            put(name, path_totals[key])
        from ..stats.statistics import (merge_live_latency_histos,
                                        sum_tpu_transfer_totals)
        tpu_bytes, tpu_usec, tpu_dispatch = sum_tpu_transfer_totals(workers)
        put("tpu_hbm_bytes_total", tpu_bytes)
        put("tpu_dispatch_usec_total", tpu_dispatch)
        put("tpu_transfer_usec_total", tpu_usec)
        ctl_totals = merge_control_audit_counters(workers)
        if self.extra_control is not None:
            extra = self.extra_control()
            for _attr, key, mode in CONTROL_AUDIT_COUNTERS:
                if key in extra:
                    ctl_totals[key] = (max(ctl_totals[key], extra[key])
                                       if mode == "max"
                                       else ctl_totals[key] + extra[key])
        for _attr, key, mode in CONTROL_AUDIT_COUNTERS:
            name = snake_case(key) + ("" if mode == "max" else "_total")
            put(name, ctl_totals[key])
        io_histo, ent_histo = merge_live_latency_histos(workers)
        put("io_latency_usec", io_histo)
        put("entry_latency_usec", ent_histo)
        if any(io_histo.buckets):
            # bucket gate, not num_values: sum-only mirrors (master-mode
            # live ingest without the bucket view) would publish 0s as
            # if the tail were measured
            put("io_latency_p99_usec", round(io_histo.percentile(99), 1))
            put("io_latency_p999_usec",
                round(io_histo.percentile(99.9), 1))
        reg.commit(up)

    def render(self) -> str:
        """Sample-then-render: a scrape always sees the current counters
        (the live-stats loop also samples at its cadence, so the snapshot
        stays warm between scrapes)."""
        self.registry.scrapes += 1
        self.sample()
        return self.registry.render()
