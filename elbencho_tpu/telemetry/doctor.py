"""Run doctor: stage-time decomposition + automatic bottleneck attribution.

Turns the counters the benchmark already records — the storage-op latency
sums, the TransferPipeline's dispatch-vs-DMA split (TpuHbmDispatchUSec /
TpuHbmUSec), the pod-slice ICI redistribution time (IciRedistUSec), the
data-plane retry/backoff account (IoRetryUsec), the pipeline-full stall
counter and the control-plane audit — into a per-phase verdict: WHERE the
wall time went, how well the overlapped legs actually overlapped, and
which stage bounds the phase.

The decomposition follows the overlap-efficiency model of "The DMA
Streaming Framework" (arXiv 2603.10030: submit vs DMA vs reap legs) and
the time-resolved stage accounting argued for by "Optimizing
High-Throughput Distributed Data Pipelines" (arXiv 2604.21275). All busy
times are SUMS across workers; the denominator is worker-time (phase wall
x workers), so a share reads as "fraction of the fleet's worker-seconds
spent in this stage" and overlapped stages can sum past 100% of wall.

Used three ways:
- in-run: Statistics attaches the verdict as the run JSON's ``Analysis``
  block and a "Bottleneck" line in the text summary when ``--flightrec``
  is armed (flightrec.FlightRecorder.finish_phase);
- ``tools/elbencho-tpu-doctor RUN.rec``: post-mortem analysis of a
  recording (recomputed from the recorded totals, so old recordings
  benefit from newer verdict logic);
- ``tools/elbencho-tpu-doctor A.rec B.rec``: regression diff between two
  recordings of the same workload.
"""

from __future__ import annotations

#: analysis block schema version (run JSON "Analysis" + phase_end rows)
ANALYSIS_SCHEMA = 1

#: (stage, totals wire key, human description) — the decomposition the
#: counters support today; appended, never reordered
STAGE_KEYS = (
    ("storage", "IoBusyUSec", "storage submit/reap (per-op I/O latency)"),
    ("tpu_dispatch", "TpuHbmDispatchUSec",
     "host->HBM transfer dispatch (submit cost)"),
    ("tpu_dma", "TpuHbmUSec", "TPU DMA wall (submit -> ready)"),
    ("ici_redist", "IciRedistUSec", "ICI redistribution (--tpuslice)"),
    ("io_retry", "IoRetryUsec", "storage retry/backoff (--ioretries)"),
)

#: verdict name per dominant stage
STAGE_VERDICTS = {
    "storage": "storage-bound",
    "tpu_dispatch": "dispatch-bound",
    "tpu_dma": "dma-bound",
    "ici_redist": "ici-bound",
    "io_retry": "retry-bound",
}

#: machine-readable verdict -> knob-axis hints for the closed-loop
#: autotuner (elbencho_tpu/autotune/; axis names are KnobSpace axes):
#: the ordered axes worth moving when a phase carries this verdict. An
#: EMPTY tuple is deliberate — retry/ici/straggler/tail problems are
#: not fixed by any of these knobs, and ``inconclusive`` tells the
#: tuner to fall back to round-robin. Attached to every Analysis block
#: as the appended ``TuneHint`` key.
VERDICT_TUNE_AXES = {
    # storage can't keep up: more ops in flight, then more workers
    "storage-bound": ("iodepth", "threads"),
    # per-transfer submit cost dominates: amortize it over batches
    "dispatch-bound": ("tpubatch",),
    # DMA wall dominates: deepen the in-flight window so transfers
    # overlap instead of serializing
    "dma-bound": ("tpudepth",),
    # producer kept finding the transfer ring full: widen the window
    "stall-bound": ("tpudepth", "iodepth"),
    # control round-trips cap the fleet: aggregate harder, poll slower
    "control-bound": ("svcfanout", "svcupint"),
    "retry-bound": (),
    "ici-bound": (),
    "straggler-bound": (),
    "tail-bound": (),
    "inconclusive": (),
}

#: TPU transfer-op counters (denominator of the stall ratio)
TPU_OP_KEYS = ("TpuH2dDirectOps", "TpuH2dStagedOps",
               "TpuD2hDirectOps", "TpuD2hStagedOps")

#: pipe-full stalls per TPU op at/above which the phase is declared
#: stall-bound (the producer kept finding the transfer ring full: the
#: in-flight window, not any single stage's speed, bounds the phase)
STALL_RATIO_BOUND = 0.5

#: minimum worker-time share for a stage to be named the bottleneck
DOMINANT_SHARE_PCT = 15.0

#: barrier-wait share of fleet worker-time at/above which a multi-host
#: phase is declared straggler-bound: the fleet spent this fraction of
#: its worker-seconds idle at the phase barrier waiting for the slowest
#: host — no per-stage tuning helps until the straggler is fixed
BARRIER_SHARE_PCT = 15.0

#: absolute straggler-skew floor for the straggler-bound verdict: on a
#: degenerate sub-second phase, scheduler jitter alone gives one host a
#: large RELATIVE share of a tiny wall — a real straggler lags by real
#: time, not only by percentage (the Straggler evidence block is
#: attached either way). The EFFECTIVE floor additionally scales with
#: the master's done-observation quantum (poll mode detects completion
#: only on a poll tick, up to --svcupint late, independently per host —
#: two hosts finishing together can look ~a poll interval apart), so a
#: verdict is never built on sampling noise.
STRAGGLER_MIN_SKEW_USEC = 50_000
OBS_QUANTUM_FLOOR_FACTOR = 2

#: tail-bound gates (--slowops TailAnalysis input): the tail (p99.9, or
#: the observed max where p99.9 is unresolved at low op counts) must be
#: this many times p50, ...
TAIL_RATIO_BOUND = 10.0
#: ... at least this slow in absolute terms (a 300us tail over a 30us
#: p50 is a curiosity, not a bottleneck), ...
TAIL_MIN_USEC = 50_000
#: ... and the captured tail ops must own a real share of the fleet's
#: storage busy time — otherwise the tail is measurable but not what
#: bounds the phase
TAIL_MIN_SHARE_PCT = 5.0


def _overlap_eff(a_usec: float, b_usec: float, wall_usec: float
                 ) -> "float | None":
    """Overlap efficiency of two per-worker busy legs against the
    observed wall: 1.0 = the smaller leg is fully hidden inside the
    larger (serial sum a+b compressed to max(a,b)), 0.0 = no overlap
    observable (wall >= a+b). None when either leg never ran."""
    if a_usec <= 0 or b_usec <= 0 or wall_usec <= 0:
        return None
    return round(min(max((a_usec + b_usec - wall_usec)
                         / min(a_usec, b_usec), 0.0), 1.0), 3)


def _series_cum(series, key: str) -> "list[tuple[float, int]]":
    """(t, cumulative value) points of one sum-merged counter over a
    phase's fleet delta series."""
    out = []
    cum = 0
    for t, d in series or ():
        cum += d.get(key, 0)
        out.append((t, cum))
    return out


def rising_after(series, key: str) -> "float | None":
    """Trend evidence: the phase-relative second after which ``key``
    started rising (first tick at/above 10% of its final total). None
    when the counter never moved or there is no series."""
    points = _series_cum(series, key)
    if not points or points[-1][1] <= 0:
        return None
    final = points[-1][1]
    for t, cum in points:
        if cum >= final * 0.1:
            return round(t, 1)
    return None


def _straggler_block(host_info: "dict | None", totals: dict,
                     wall: int, worker_usec: int) -> "dict | None":
    """Per-host straggler attribution (fleet tracing / barrier skew):
    names the host that lagged the fleet, its finish skew, the fleet's
    barrier-wait share, and — when the flight recorder counted them —
    the fraction of ticks it trailed in. None for local runs and
    single-host fleets (no barrier to decompose)."""
    if not host_info or len(host_info) < 2:
        return None
    skews = {h: int(e.get("StragglerSkewUsec", 0))
             for h, e in host_info.items()}
    if not any(skews.values()):
        return None
    straggler = max(skews, key=lambda h: (skews[h], h))
    barrier_usec = int(totals.get(
        "BarrierWaitUSec",
        sum(int(e.get("BarrierWaitUSec", 0))
            for e in host_info.values())))
    obs_quantum = max((int(e.get("ObsQuantumUsec", 0))
                       for e in host_info.values()), default=0)
    return {
        "Host": straggler,
        "SkewUSec": skews[straggler],
        "SkewFloorUsec": max(STRAGGLER_MIN_SKEW_USEC,
                             OBS_QUANTUM_FLOOR_FACTOR * obs_quantum),
        "SkewPctOfWall": round(100.0 * skews[straggler] / wall, 1)
        if wall else 0.0,
        "LastTickPct": host_info[straggler].get("LastTickPct", 0.0),
        "BarrierWaitUSec": barrier_usec,
        "BarrierWaitPct": round(100.0 * barrier_usec / worker_usec, 1)
        if worker_usec else 0.0,
        "PerHost": host_info,
    }


def analyze_phase(phase_name: str, totals: dict, elapsed_usec: int,
                  num_workers: int, series=None,
                  host_info: "dict | None" = None,
                  tail: "dict | None" = None) -> dict:
    """One phase's stage decomposition + bottleneck verdict.

    ``totals`` is the fleet-merged cumulative counter state at phase end
    (flightrec wire keys: IoBusyUSec/TpuHbmDispatchUSec/TpuHbmUSec/...);
    ``series`` is the phase's fleet delta series [(t_rel, deltas)] for
    trend evidence, optional; ``host_info`` is the per-host barrier
    decomposition ({host: {StragglerSkewUsec, BarrierWaitUSec,
    LastTickPct, ClockOffsetUsec, ...}}) for straggler attribution,
    optional; ``tail`` is the --slowops TailAnalysis block for
    tail-attribution verdicts, optional."""
    workers = max(num_workers, 1)
    wall = max(int(elapsed_usec), 0)
    worker_usec = wall * workers
    stage_usec = {name: int(totals.get(key, 0))
                  for name, key, _desc in STAGE_KEYS}
    shares = {name: round(100.0 * usec / worker_usec, 1)
              if worker_usec else 0.0
              for name, usec in stage_usec.items()}
    tpu_ops = sum(int(totals.get(k, 0)) for k in TPU_OP_KEYS)
    stalls = int(totals.get("TpuPipeFullStalls", 0))
    stall_ratio = round(stalls / tpu_ops, 3) if tpu_ops else 0.0
    evidence: "list[str]" = []

    # overlap efficiencies over PER-WORKER averages vs the phase wall
    per_worker = {n: u / workers for n, u in stage_usec.items()}
    ingest_pw = (per_worker["storage"] + per_worker["tpu_dispatch"]
                 + per_worker["tpu_dma"])
    overlap = {
        # fused ring / transfer pipeline: storage leg vs the HBM leg
        "StorageVsHbm": _overlap_eff(
            per_worker["storage"],
            per_worker["tpu_dispatch"] + per_worker["tpu_dma"], wall),
        # pod-slice: stripe ingest vs ICI redistribution of the previous
        # stripe (--tpuslice overlap timeline, docs/pod-slice.md)
        "IngestVsIci": _overlap_eff(ingest_pw, per_worker["ici_redist"],
                                    wall),
    }

    straggler = _straggler_block(host_info, totals, wall, worker_usec)
    from .slowops import describe_slowest, tail_doctor_summary
    tail_summary = tail_doctor_summary(tail)
    tail_hot = (
        tail is not None
        and max(tail.get("P999Usec", 0),
                tail.get("MaxUsec", 0)) >= TAIL_MIN_USEC
        and tail.get("TailRatio", 0.0) >= TAIL_RATIO_BOUND
        and tail.get("TailSharePct", 0.0) >= TAIL_MIN_SHARE_PCT)

    # -- verdict -------------------------------------------------------------
    verdict = "inconclusive"
    bottleneck = ""
    if tail_hot:
        # a handful of ops own the phase: tail attribution outranks the
        # coarser verdicts below (a straggler host whose lag IS a few
        # slow ops is better explained by naming those ops, and stage
        # shares describe the mean, not the ops that bound the phase)
        verdict = "tail-bound"
        bottleneck = "tail"
        evidence.append(
            f"p99.9 is {tail['TailRatio']:g}x p50 "
            f"({max(tail['P999Usec'], tail['MaxUsec'])}us vs "
            f"{tail['P50Usec']}us); captured tail ops own "
            f"{tail['TailSharePct']:g}% of storage busy time")
        if tail_summary and tail_summary["TopHost"]:
            evidence.append(
                f"{tail_summary['TopHostPct']:g}% of captured tail "
                f"time on host {tail_summary['TopHost']}")
        if tail_summary and tail_summary["TopDir"] \
                and tail_summary["TopDir"] != tail_summary["TopHost"]:
            evidence.append(
                f"{tail_summary['TopDirPct']:g}% of tail ops hit "
                f"files under {tail_summary['TopDir']}")
        slowest = describe_slowest(tail)
        if slowest:
            evidence.append(slowest)
    elif straggler is not None \
            and straggler["BarrierWaitPct"] >= BARRIER_SHARE_PCT \
            and straggler["SkewUSec"] >= straggler["SkewFloorUsec"]:
        # the fleet idled at the phase barrier for a dominant share of
        # its worker-time: the slowest HOST bounds the phase, and no
        # per-stage knob helps until that host is fixed/replaced —
        # checked before the stage decomposition because the stage sums
        # describe the busy hosts, not the wait they caused
        verdict = "straggler-bound"
        bottleneck = "barrier"
        ev = (f"host {straggler['Host']} finished "
              f"{straggler['SkewUSec'] / 1e6:.2f}s after the first host "
              f"({straggler['SkewPctOfWall']:g}% of the phase wall)")
        if straggler["LastTickPct"]:
            ev += (f"; last in {straggler['LastTickPct']:g}% of "
                   f"recorded ticks")
        evidence.append(ev)
        evidence.append(f"barrier wait = "
                        f"{straggler['BarrierWaitPct']:g}% of fleet "
                        f"worker time ({straggler['BarrierWaitUSec']} "
                        f"us summed over hosts)")
    elif stalls and stall_ratio >= STALL_RATIO_BOUND:
        # the producer kept hitting a full transfer ring: the in-flight
        # window bounds the phase, not any single stage's raw speed
        verdict = "stall-bound"
        bottleneck = "pipeline"
        evidence.append(
            f"pipe_full_stalls {stalls} (~{stall_ratio:.2f} per TPU "
            f"transfer op): producer kept finding the transfer ring "
            f"full — raise --tpudepth/--iodepth")
        t_rise = rising_after(series, "TpuPipeFullStalls")
        if t_rise is not None:
            evidence.append(f"pipe_full_stalls rising after "
                            f"t={t_rise:g}s")
    inconclusive_why: "list[str]" = []
    if verdict == "inconclusive":  # the gated verdicts above all missed
        dominant = max(stage_usec, key=lambda n: stage_usec[n]) \
            if any(stage_usec.values()) else ""
        if dominant and shares[dominant] >= DOMINANT_SHARE_PCT:
            verdict = STAGE_VERDICTS[dominant]
            bottleneck = dominant
            desc = next(d for n, _k, d in STAGE_KEYS if n == dominant)
            evidence.append(f"{shares[dominant]:g}% of worker time in "
                            f"{desc}")
            runner = sorted((n for n in stage_usec if n != dominant),
                            key=lambda n: stage_usec[n])[-1]
            if stage_usec[runner]:
                evidence.append(f"next stage: {runner} at "
                                f"{shares[runner]:g}%")
        elif int(totals.get("SvcRequests", 0)) \
                and not int(totals.get("Bytes", 0)) \
                and not int(totals.get("Entries", 0)):
            # no payload AND no entry work: a metadata phase that did
            # real entries stays out of this bucket — only a phase whose
            # sole traffic was control-plane requests lands here
            verdict = "control-bound"
            bottleneck = "control_plane"
            evidence.append(
                f"no payload bytes or entries completed while the "
                f"master exchanged {totals.get('SvcRequests', 0)} "
                f"control-plane requests "
                f"({totals.get('SvcCtlBytes', 0)} bytes)")
        else:
            # an inconclusive verdict must say WHY — which gate failed
            # — both for humans and for the autotuner, whose
            # round-robin fallback keys off this verdict
            if not wall:
                inconclusive_why.append("phase wall time is 0 — "
                                        "nothing to decompose")
            if not any(stage_usec.values()):
                inconclusive_why.append(
                    "no instrumented stage recorded any time (the "
                    "phase ran entirely outside the measured stages)")
            else:
                inconclusive_why.append(
                    f"no stage >= {DOMINANT_SHARE_PCT:g}% of worker "
                    f"time (max: {dominant} at {shares[dominant]:g}%) "
                    f"— the phase is bounded outside the measured "
                    f"stages (page cache, CPU, metadata syscalls)")
            if series is not None and len(series) < 2:
                inconclusive_why.append(
                    f"phase shorter than 2 recorded ticks "
                    f"({len(series)} sample row(s)) — lengthen the "
                    f"phase or shorten the live-stats interval for "
                    f"trend evidence")
            evidence.extend(inconclusive_why)
    if verdict not in ("stall-bound",) and stalls:
        evidence.append(f"pipe_full_stalls {stalls} "
                        f"(~{stall_ratio:.2f}/op, below the "
                        f"{STALL_RATIO_BOUND:g} stall-bound threshold)")
    if verdict != "straggler-bound" and straggler is not None:
        evidence.append(
            f"straggler: host {straggler['Host']} last by "
            f"{straggler['SkewUSec'] / 1e6:.2f}s; barrier wait "
            f"{straggler['BarrierWaitPct']:g}% of worker time (below "
            f"the straggler-bound gate: >= {BARRIER_SHARE_PCT:g}% "
            f"barrier share AND >= "
            f"{straggler['SkewFloorUsec'] / 1e6:g}s skew — floor "
            f"covers the done-observation quantum)")
    if verdict != "tail-bound" and tail_summary is not None \
            and tail_summary["TailRatio"]:
        evidence.append(
            f"tail: p99.9/p50 = {tail_summary['TailRatio']:g}x, "
            f"captured tail share "
            f"{tail_summary['TailSharePct']:g}% (below the tail-bound "
            f"gate: >= {TAIL_RATIO_BOUND:g}x AND >= "
            f"{TAIL_MIN_USEC / 1000:g}ms AND >= "
            f"{TAIL_MIN_SHARE_PCT:g}% of storage busy time)")
    if int(totals.get("IoRetries", 0)):
        evidence.append(f"storage retries: {totals.get('IoRetries', 0)} "
                        f"({stage_usec['io_retry']} us backoff)")
    if overlap["StorageVsHbm"] is not None:
        evidence.append(f"storage/HBM overlap efficiency "
                        f"{overlap['StorageVsHbm']:.0%}")
    if overlap["IngestVsIci"] is not None:
        evidence.append(f"ingest/ICI overlap efficiency "
                        f"{overlap['IngestVsIci']:.0%}")

    return {
        "Schema": ANALYSIS_SCHEMA,
        "Phase": phase_name,
        "Verdict": verdict,
        "BottleneckStage": bottleneck,
        "Evidence": evidence,
        "WallUSec": wall,
        "NumWorkers": workers,
        "WorkerUSec": worker_usec,
        "StageUSec": stage_usec,
        "StagePct": shares,
        "PipeFullStalls": stalls,
        "StallsPerTpuOp": stall_ratio,
        "OverlapEff": overlap,
        "Control": {
            "SvcRequests": int(totals.get("SvcRequests", 0)),
            "SvcCtlBytes": int(totals.get("SvcCtlBytes", 0)),
            "SvcStreamFrames": int(totals.get("SvcStreamFrames", 0)),
        },
        # fleet straggler attribution (null for local / single-host
        # phases): appended key, never reordered
        "Straggler": straggler,
        # tail forensics summary (null without --slowops): the compact
        # view verdicts and diffs consume — the full TailAnalysis block
        # lives beside this Analysis in the run JSON / phase_end row.
        # Appended key, never reordered.
        "Tail": tail_summary,
        # machine-readable verdict -> knob-axis hints for the
        # closed-loop autotuner (VERDICT_TUNE_AXES; appended key)
        "TuneHint": list(VERDICT_TUNE_AXES.get(verdict, ())),
        # which gate(s) failed when the verdict is inconclusive (empty
        # otherwise) — the tuner's round-robin trigger, and the human
        # answer to "why won't the doctor commit?" (appended key)
        "InconclusiveWhy": inconclusive_why,
    }


def analyze_recording(rec: dict) -> "list[dict]":
    """Analyses for every completed phase of a read_recording() result.
    Recomputed from the recorded totals (not the stored Analysis block)
    so old recordings get current verdict logic."""
    out = []
    for phase in rec["phases"]:
        end = phase["end"]
        if end is None:
            continue
        series = list(zip(phase["sample_ts"], phase["samples"]))
        t0 = phase.get("start_t", 0.0)
        series = [(round(t - t0, 3), d) for t, d in series]
        out.append(analyze_phase(phase["name"], end.get("Totals", {}),
                                 end.get("ElapsedUSec", 0),
                                 end.get("Workers", 0), series=series,
                                 host_info=end.get("Hosts"),
                                 tail=end.get("Tail")))
    return out


# ---------------------------------------------------------------------------
# regression diff (elbencho-tpu-doctor A.rec B.rec)
# ---------------------------------------------------------------------------

#: throughput drop (fraction) at/above which a phase is flagged
REGRESSION_RATE_DROP = 0.10
#: stage-share growth (percentage points) at/above which a stage is
#: flagged as the likely culprit
REGRESSION_SHARE_PTS = 10.0
#: tail-ratio growth factor at/above which "tail grew" is flagged as a
#: regression cause (p99.9/p50 doubling is a tail problem even when the
#: mean throughput barely moved)
REGRESSION_TAIL_GROWTH_X = 2.0


def _phase_rate_mibs(end: dict) -> float:
    wall_s = max(end.get("ElapsedUSec", 0), 1) / 1e6
    return end.get("Totals", {}).get("Bytes", 0) / (1 << 20) / wall_s


def diff_recordings(rec_a: dict, rec_b: dict) -> "list[dict]":
    """Per-phase regression report between recording A (baseline) and B
    (candidate). Phases pair by (name, occurrence index). Each entry:
    {"Phase", "RateA", "RateB", "RateRatio", "Regressed", "Causes",
    "AnalysisA", "AnalysisB"}."""
    def ends(rec):
        seen: "dict[str, int]" = {}
        out = {}
        for phase in rec["phases"]:
            if phase["end"] is None:
                continue
            idx = seen.get(phase["name"], 0)
            seen[phase["name"]] = idx + 1
            out[(phase["name"], idx)] = phase
        return out

    a_ends, b_ends = ends(rec_a), ends(rec_b)
    analyses_a = {(x["Phase"], i): x for i, x in _indexed(
        analyze_recording(rec_a))}
    analyses_b = {(x["Phase"], i): x for i, x in _indexed(
        analyze_recording(rec_b))}
    report = []
    for key in a_ends:
        if key not in b_ends:
            continue
        end_a, end_b = a_ends[key]["end"], b_ends[key]["end"]
        rate_a, rate_b = _phase_rate_mibs(end_a), _phase_rate_mibs(end_b)
        # None = undefined (baseline moved no bytes): float('inf') would
        # serialize as the non-JSON token Infinity in --json mode
        ratio = round(rate_b / rate_a, 3) if rate_a > 0 \
            else (1.0 if rate_b == 0 else None)
        ana_a, ana_b = analyses_a.get(key), analyses_b.get(key)
        causes = []
        if ana_a is not None and ana_b is not None:
            for name, _k, desc in STAGE_KEYS:
                grew = ana_b["StagePct"][name] - ana_a["StagePct"][name]
                if grew >= REGRESSION_SHARE_PTS:
                    causes.append(f"{name} share grew "
                                  f"{ana_a['StagePct'][name]:g}% -> "
                                  f"{ana_b['StagePct'][name]:g}%")
            straggler_a = ana_a.get("Straggler") or {}
            straggler_b = ana_b.get("Straggler") or {}
            barrier_grew = (straggler_b.get("BarrierWaitPct", 0.0)
                            - straggler_a.get("BarrierWaitPct", 0.0))
            if straggler_b and barrier_grew >= REGRESSION_SHARE_PTS:
                causes.append(
                    f"barrier wait grew "
                    f"{straggler_a.get('BarrierWaitPct', 0.0):g}% -> "
                    f"{straggler_b.get('BarrierWaitPct', 0.0):g}% of "
                    f"worker time (straggler: {straggler_b['Host']})")
            tail_a = ana_a.get("Tail") or {}
            tail_b = ana_b.get("Tail") or {}
            ratio_a = tail_a.get("TailRatio", 0.0)
            ratio_b = tail_b.get("TailRatio", 0.0)
            if ratio_b and ratio_b >= TAIL_RATIO_BOUND \
                    and ratio_b >= max(ratio_a, 1.0) \
                    * REGRESSION_TAIL_GROWTH_X:
                cause = (f"tail grew (p99.9/p50 {ratio_a:g}x -> "
                         f"{ratio_b:g}x")
                if tail_b.get("TopHost"):
                    cause += f"; owner: {tail_b['TopHost']}"
                causes.append(cause + ")")
            if ana_b["Verdict"] != ana_a["Verdict"]:
                causes.append(f"verdict changed {ana_a['Verdict']} -> "
                              f"{ana_b['Verdict']}")
        # a phase finished by a successor master (--resume --adopt) is
        # not comparable like-for-like: part of it ran masterless, so a
        # rate delta may be the takeover, not the workload
        if end_b.get("Totals", {}).get("MasterTakeovers", 0):
            causes.append("completed after takeover (a successor master "
                          "adopted the phase mid-flight)")
        regressed = rate_a > 0 and ratio is not None \
            and ratio <= (1.0 - REGRESSION_RATE_DROP)
        report.append({
            "Phase": key[0], "Occurrence": key[1],
            "RateA": round(rate_a, 1), "RateB": round(rate_b, 1),
            "RateRatio": ratio,
            "Regressed": regressed,
            "Causes": causes,
            "AnalysisA": ana_a, "AnalysisB": ana_b,
        })
    return report


def _indexed(analyses):
    seen: "dict[str, int]" = {}
    for ana in analyses:
        idx = seen.get(ana["Phase"], 0)
        seen[ana["Phase"]] = idx + 1
        yield idx, ana
