"""Bounded ring-buffer per-op span recorder with Chrome trace output.

``--tracefile PATH`` arms the recorder; every instrumentation point in the
workers (storage ops), the TPU transfer pipeline (dispatch-vs-DMA
sub-spans) and the native stream ring (reap sub-spans) records one
complete span ("ph": "X") per event. ``--tracesample R`` keeps only a
probabilistic R fraction of op spans so long phases fit the ring.

When tracing is OFF the recorder does not exist: workers hold
``self._tracer is None`` and every instrumentation point is a single
attribute test — no allocation, no call, no formatting (the overhead
guard in tests/test_telemetry.py pins this).

The output is Chrome trace-event JSON (the ``traceEvents`` array format),
loadable in Perfetto / chrome://tracing; ``pid`` is the service's rank
offset (host slot in a distributed run), ``tid`` the worker rank.
"""

from __future__ import annotations

import itertools
import json
import os
import random
import threading
import time

#: default ring capacity (events); old spans are overwritten when a phase
#: outgrows it — num_overwritten says how many were lost
DEFAULT_RING_EVENTS = 1 << 18

#: fleet-wide flow ids (master-generated, echoed by services): one shared
#: counter per process so concurrent RemoteWorkers can never mint the
#: same id — uniqueness across hosts holds because ONLY the master mints
_flow_ids = itertools.count(1)


def next_flow_id() -> int:
    """A process-unique Chrome flow-event id (master side of an RPC edge
    mints one; the service side echoes it back — docs/telemetry.md
    "Fleet tracing")."""
    return next(_flow_ids)


def atomic_write_json(path: str, doc) -> None:
    """Write a JSON document via temp-then-rename so a concurrent
    reader (Perfetto, a scraper, the merge) never sees a torn file —
    the one crash-safe write path shared by the trace ring, the
    collected per-host rings, and the merged fleet trace."""
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class Tracer:
    """Thread-safe bounded span ring. ``record`` is only ever called from
    instrumentation points that already checked the tracer exists, so the
    off path costs nothing; the on path takes one short lock per span."""

    def __init__(self, path: str, sample: float = 1.0,
                 max_events: int = DEFAULT_RING_EVENTS,
                 rank_offset: int = 0):
        self.path = path
        self.sample = min(max(sample, 0.0), 1.0)
        self.rank_offset = rank_offset
        self._cap = max(int(max_events), 1)
        self._ring: "list" = [None] * self._cap
        self._idx = 0
        self.num_recorded = 0
        self.num_overwritten = 0
        # spans rejected by --tracesample (never entered the ring);
        # num_dropped = these + overwritten, so a sampled trace is
        # honest about everything it lost (TraceDropped in JSON,
        # trace_events_dropped_total on /metrics)
        self.num_sampled_out = 0
        self._lock = threading.Lock()
        self._rng = random.Random(0xe1be0 + rank_offset)
        self._t0_ns = time.perf_counter_ns()
        # wall-clock anchor captured at the SAME instant as the
        # perf-counter epoch: an event at trace-ts T usec happened at
        # wall time wall_anchor_usec + T on THIS host's clock — the
        # hook the fleet merge (telemetry/tracefleet.py) aligns
        # per-host files through after subtracting the estimated
        # per-host clock offset
        self.wall_anchor_usec = time.time_ns() // 1000
        # fleet-tracing metadata merged into write()'s otherData: the
        # run trace id (master-minted, echoed by services) and — on
        # collected per-host files — the master-estimated clock offset
        self.extra_other_data: "dict" = {}

    # -- recording -----------------------------------------------------------

    def now_ns(self) -> int:
        return time.perf_counter_ns()

    def record(self, name: str, cat: str, start_ns: int, dur_usec: int,
               rank: int = 0, sampled: bool = False, **args) -> None:
        """One complete span. ``start_ns`` is a perf_counter_ns timestamp;
        ``sampled=True`` subjects the span to --tracesample (op spans and
        the per-op tpu/stream sub-spans — anything with per-op volume);
        phase markers pass sampled=False and are always kept."""
        if sampled and self.sample < 1.0 \
                and self._rng.random() >= self.sample:
            self.num_sampled_out += 1
            return
        event = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": max(start_ns - self._t0_ns, 0) // 1000,
            "dur": max(int(dur_usec), 0),
            "pid": self.rank_offset,
            "tid": rank,
            "args": args,
        }
        self._push(event)

    def _push(self, event: dict) -> None:
        with self._lock:
            slot = self._idx % self._cap
            if self._ring[slot] is not None:
                self.num_overwritten += 1
            self._ring[slot] = event
            self._idx += 1
            self.num_recorded += 1

    def to_trace_ts(self, start_ns: int) -> int:
        return max(start_ns - self._t0_ns, 0) // 1000

    def record_rpc(self, name: str, start_ns: int, dur_usec: int,
                   rank: int, flow_id: int, side: str) -> None:
        """One control-plane RPC edge end: a complete span PLUS the bound
        Chrome flow event that lets Perfetto draw the master->service
        arrow. ``side`` is "out" (master sent the request; flow start
        "s") or "in" (service handled it; flow finish "f"/bp=e). The
        flow event's ts sits at the span start so it binds to the span
        it is emitted with. Never sampled: RPC volume is per-phase, not
        per-op."""
        ts = self.to_trace_ts(start_ns)
        self.record(name, "rpc", start_ns, dur_usec, rank=rank,
                    flow=flow_id)
        flow = {
            "name": "rpc", "cat": "rpc",
            "ph": "s" if side == "out" else "f",
            "id": flow_id, "ts": ts,
            "pid": self.rank_offset, "tid": rank,
        }
        if side != "out":
            flow["bp"] = "e"  # bind to the enclosing slice
        self._push(flow)

    def record_op(self, op: str, phase: str, start_ns: int, dur_usec: int,
                  rank: int, offset: int, size: int,
                  slot: "int | None" = None) -> None:
        """Storage-op span (the ISSUE's schema: phase, rank, op type,
        offset, size, latency, staging slot). Subject to --tracesample."""
        args = {"phase": phase, "offset": offset, "size": size}
        if slot is not None:
            args["slot"] = slot
        self.record(op, "io", start_ns, dur_usec, rank=rank, sampled=True,
                    **args)

    @property
    def num_dropped(self) -> int:
        """Spans this trace LOST: sampled out by --tracesample plus
        overwritten in the ring before a write."""
        return self.num_sampled_out + self.num_overwritten

    # -- output --------------------------------------------------------------

    def snapshot_events(self) -> "list[dict]":
        """Chronological copy of the ring (oldest first)."""
        with self._lock:
            if self._idx <= self._cap:
                events = [e for e in self._ring[:self._idx]]
            else:
                head = self._idx % self._cap
                events = self._ring[head:] + self._ring[:head]
            return [e for e in events if e is not None]

    def write(self) -> None:
        """(Re)write the Chrome trace JSON file with everything recorded
        so far. Idempotent; called at phase end and at teardown so a
        killed run still leaves a loadable trace. Atomic via
        temp-then-rename so a scraper/Perfetto never reads a torn file."""
        events = self.snapshot_events()
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "tool": "elbencho-tpu",
                "rankOffset": self.rank_offset,
                "sample": self.sample,
                "numRecorded": self.num_recorded,
                "numOverwritten": self.num_overwritten,
                "numSampledOut": self.num_sampled_out,
                "numDropped": self.num_dropped,
                "wallAnchorUsec": self.wall_anchor_usec,
                **self.extra_other_data,
            },
        }
        atomic_write_json(self.path, doc)


def make_tracer(cfg) -> "Tracer | None":
    """The single arming point: a Tracer exists iff --tracefile was given
    (instrumentation stays no-op otherwise)."""
    path = getattr(cfg, "trace_file_path", "")
    if not path:
        return None
    return Tracer(path,
                  sample=getattr(cfg, "trace_sample", 1.0),
                  rank_offset=getattr(cfg, "rank_offset", 0))
