"""Standalone Prometheus /metrics HTTP endpoint (--telemetry).

Local and master runs have no HTTP server of their own (the control-plane
server only exists in --service mode, where /metrics piggybacks onto its
route table instead — service/http_service.py), so the exporter brings a
minimal one: a daemon thread serving GET /metrics in the Prometheus text
exposition format on --telemetryport. The render path samples the live
benchmark state on every scrape (registry.BenchTelemetry), reading
worker-owned counters under the GIL — a scrape can never block a worker.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..toolkits import logger
from .registry import PROMETHEUS_CONTENT_TYPE, BenchTelemetry

#: default --telemetryport (service control port 1611 + 1; netbench's
#: data port rides +1000, so +1 stays clear of both)
DEFAULT_TELEMETRY_PORT = 1612


def _make_handler(telemetry: BenchTelemetry):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet by default
            logger.log(logger.LOG_DEBUG, "telemetry HTTP " + fmt % args)

        def do_GET(self):  # noqa: N802 (http.server API)
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                try:
                    body = telemetry.render().encode()
                except Exception as err:  # noqa: BLE001 - reply over HTTP
                    self._reply(500, f"# scrape failed: {err}\n".encode(),
                                "text/plain")
                    return
                self._reply(200, body, PROMETHEUS_CONTENT_TYPE)
            elif path == "/":
                self._reply(200, b"<html><body>elbencho-tpu telemetry "
                                 b"&mdash; <a href='/metrics'>/metrics"
                                 b"</a></body></html>", "text/html")
            else:
                self._reply(404, b"unknown path (try /metrics)\n",
                            "text/plain")

        def _reply(self, code: int, body: bytes, content_type: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    return Handler


class TelemetryExporter:
    """Owns the /metrics HTTP server thread for local/master runs."""

    def __init__(self, telemetry: BenchTelemetry, port: int):
        self.telemetry = telemetry
        self.port = port
        self._server: "ThreadingHTTPServer | None" = None
        self._thread: "threading.Thread | None" = None

    def start(self) -> None:
        """Bind + serve in a daemon thread. Raises OSError on a busy port
        (the caller fails the run loudly — a benchmark whose telemetry
        the user asked for must not silently run unobserved)."""
        self._server = ThreadingHTTPServer(
            ("0.0.0.0", self.port), _make_handler(self.telemetry))
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.5},
            name="telemetry-exporter", daemon=True)
        self._thread.start()
        logger.log(logger.LOG_NORMAL,
                   f"telemetry: serving Prometheus metrics on "
                   f":{self.port}/metrics")

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
