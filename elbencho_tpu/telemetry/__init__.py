"""Telemetry subsystem: Prometheus /metrics + per-op trace recorder.

The reference's observability stops at console live stats and end-of-phase
CSV/JSON (source/Statistics.{h,cpp}); a running multi-host benchmark cannot
be scraped, and a slow phase cannot be decomposed into storage-reap vs HBM
dispatch vs DMA vs control-plane time without rerunning under the bench
harness. This package adds both, without touching the workers' hot paths:

  registry.py  lock-light metric registry (counters/gauges/histograms)
               that SAMPLES the existing per-worker live counters, the
               PATH_AUDIT_COUNTERS / CONTROL_AUDIT_COUNTERS plumbing and
               the TPU dispatch-vs-DMA split — workers pay nothing extra.
  exporter.py  Prometheus text-format /metrics HTTP endpoint
               (--telemetry/--telemetryport), standalone in local/master
               mode; in service mode the same rendering piggybacks onto
               the existing http_service route table. The master
               re-exports a FLEET-AGGREGATED view harvested from the
               /status polls it already makes (sum/MAX merge rules of
               the service wire protocol, docs/telemetry.md).
  tracer.py    bounded ring-buffer per-op span recorder (--tracefile,
               --tracesample) with Chrome trace-event JSON output
               loadable in Perfetto; instrumentation resolves to no-ops
               when tracing is off.
  flightrec.py flight recorder (--flightrec): per-tick fleet + per-host
               counter deltas sampled on the live-stats cadence into a
               schema-versioned append-only recording — in master mode
               from the /livestream frames or /status polls the master
               already ingests, so services pay zero extra requests.
  doctor.py    run doctor: post-processes a recording plus the phase's
               audit counters into a stage-time decomposition (storage
               vs HBM dispatch vs DMA vs ICI vs retry vs stalls),
               overlap efficiencies, and a named bottleneck verdict —
               the run JSON "Analysis" block, the text summary's
               Bottleneck row, and tools/elbencho-tpu-doctor.
"""

from __future__ import annotations
