"""Fleet-wide distributed tracing: clock alignment, collection, merge.

Per-host tracing (tracer.py) leaves one span ring per process: the master
writes ``--tracefile PATH``, every service writes ``PATH.r<rankoffset>``
on ITS host, each with its own clock — islands. This module turns a
master-mode run into ONE clock-aligned, causally-linked Chrome/Perfetto
trace (docs/telemetry.md "Fleet tracing"):

- **Span-context propagation.** The master mints a run ``trace_id`` and a
  per-request flow id, stamps them onto ``/preparephase``/``/startphase``/
  ``/benchresult`` (query params) and the ``/livestream`` open; the master
  records an ``rpc:<path>`` span with a Chrome flow-start event, the
  service a ``handle:<path>`` span with the matching flow-finish — so the
  merged trace renders master->service request edges as arrows.

- **Clock-skew estimation** (``ClockSyncEstimator``). NTP-style
  RTT-midpoint sampling piggybacked on exchanges the master performs
  ANYWAY (/status lease-renewal polls, the stream-open ping, the
  /benchresult fetch): the service stamps its wall clock onto the reply,
  the master brackets the exchange with its own wall clock, and
  ``offset = peer_clock - (t0+t1)/2`` with uncertainty ``rtt/2``. Samples
  are min-RTT filtered — congested exchanges only widen the bound, they
  never displace a tighter sample. Interior aggregation-tree nodes
  estimate their children the same way and the offsets CHAIN down the
  tree (stream frame ``Co``/``Cu`` host-entry keys).

- **Collection + merge.** At ``/benchresult`` the master asks each
  service to ship its bounded span ring (size-capped by
  ``--traceshipcap``; a refusal is LOUD, never fatal) and writes it next
  to its own trace as ``PATH.fleet.r<rankoffset>`` — distinct from the
  service-local ``PATH.r<rankoffset>`` name, so a shared-filesystem
  service rewrite can't clobber it — with the estimated clock offset
  recorded in ``otherData``. ``merge_fleet_trace`` folds the
  per-host files into one trace: per-host process lanes, per-host
  offsets applied to every timestamp, flows stitched, and a skew report
  in ``otherData`` (also via ``tools/elbencho-tpu-trace`` and
  ``elbencho-tpu-chart --fleet-trace``).

Invariants: everything is off unless the master armed ``--tracefile`` in
master mode (``--tracefleet auto``); no extra per-tick service requests
— sampling and collection ride existing exchanges only.
"""

from __future__ import annotations

import glob
import json
import os
import time

#: bound on retained min-RTT samples per peer (plenty for a verdict; the
#: estimator is fed once per poll tick / stream open / benchresult)
SAMPLE_CAP = 16

#: clock uncertainty can never honestly be below 1us (timestamp quantum)
MIN_UNCERTAINTY_USEC = 1

#: test-only per-port clock skew injected into svc_wall_clock_usec —
#: in-process fleets share one physical clock, so skew-path tests seed
#: this (gated on ELBENCHO_TPU_TESTING) to make offsets observable
TEST_SKEW_BY_PORT: "dict[int, int]" = {}


def svc_wall_clock_usec(port: int = 0) -> int:
    """The service-side clock stamp shipped on /status, /benchresult and
    the /livestream open (wire key ``SvcClockUsec`` / header
    ``X-Svc-Clock-Usec``). Plain epoch microseconds; the test-only skew
    injection needs the explicit ELBENCHO_TPU_TESTING opt-in."""
    usec = time.time_ns() // 1000
    if TEST_SKEW_BY_PORT \
            and os.environ.get("ELBENCHO_TPU_TESTING") == "1":
        usec += TEST_SKEW_BY_PORT.get(port, 0)
    return usec


def fleet_trace_enabled(cfg) -> bool:
    """--tracefleet auto|on|off: is fleet trace collection armed for this
    (master) process? ``auto`` = on exactly when a master-mode run is
    tracing at all; services never collect (they ship)."""
    mode = getattr(cfg, "trace_fleet", "auto")
    if mode == "off" or getattr(cfg, "run_as_service", False):
        return False
    if not getattr(cfg, "trace_file_path", ""):
        return False
    if mode == "on":
        return True
    return bool(getattr(cfg, "hosts", None))


class ClockSyncEstimator:
    """Per-peer NTP-style clock-offset estimator over piggybacked
    round trips.

    Each sample is one request/reply exchange: ``t0``/``t1`` bracket it
    on the LOCAL wall clock, ``peer_clock`` is the peer's wall-clock
    stamp taken while building the reply. The classic midpoint estimate
    assumes the reply stamp sits halfway through the RTT; asymmetric
    path delay can push the true offset anywhere inside ``±rtt/2``,
    which is exactly the uncertainty reported. Min-RTT filtering keeps
    the tightest exchanges: a congested poll (retry, loaded host) has a
    huge RTT and therefore never displaces a tight sample."""

    def __init__(self, cap: int = SAMPLE_CAP):
        self._cap = max(cap, 1)
        self._best: "list[tuple[int, int]]" = []  # (rtt_usec, offset_usec)
        self.num_samples = 0

    def add_sample(self, t0_usec: int, t1_usec: int,
                   peer_clock_usec: int) -> None:
        if t1_usec < t0_usec:  # local clock stepped backwards mid-exchange
            return
        rtt = t1_usec - t0_usec
        offset = peer_clock_usec - (t0_usec + t1_usec) // 2
        self.num_samples += 1
        self._best.append((rtt, offset))
        self._best.sort(key=lambda s: s[0])
        del self._best[self._cap:]

    @property
    def has_estimate(self) -> bool:
        return bool(self._best)

    @property
    def offset_usec(self) -> int:
        """Estimated peer_clock - local_clock, from the min-RTT sample."""
        return self._best[0][1] if self._best else 0

    @property
    def uncertainty_usec(self) -> int:
        """Half the best RTT: the true offset provably lies within
        offset ± uncertainty (up to clock drift between samples)."""
        if not self._best:
            return 0
        return max(self._best[0][0] // 2, MIN_UNCERTAINTY_USEC)

    def as_dict(self) -> dict:
        return {"OffsetUsec": self.offset_usec,
                "UncUsec": self.uncertainty_usec,
                "Samples": self.num_samples}


def record_handle_span(manager, route: str, params: dict,
                       t0_ns: int) -> None:
    """Service half of an RPC edge, shared by the HTTP route handlers
    and the /livestream open: a request stamped with a ParentSpan flow
    id gets a ``handle:<route>`` span plus the Chrome flow-finish event
    that stitches the master's ``rpc:<route>`` arrow to it (and the
    run's trace id lands in the tracer's otherData). Best effort —
    tracing must never break a route."""
    from ..service import protocol as proto
    try:
        flow_id = int(params.get(proto.KEY_PARENT_SPAN, ""))
    except (ValueError, TypeError):
        return
    try:
        tracer = manager.shared.tracer if manager is not None else None
        if tracer is None:
            return
        trace_id = params.get(proto.KEY_TRACE_ID, "")
        if trace_id:
            tracer.extra_other_data["traceId"] = trace_id
        dur = max((tracer.now_ns() - t0_ns) // 1000, 1)
        tracer.record_rpc(f"handle:{route}", t0_ns, dur, rank=0,
                          flow_id=flow_id, side="in")
    except Exception:  # noqa: BLE001 - never fail the route over a span
        pass


def chain_offsets(parent_off: int, parent_unc: int,
                  child_off: int, child_unc: int) -> "tuple[int, int]":
    """Compose offsets down the aggregation tree: master measured the
    root at ``parent``, the root measured its child at ``child`` (both
    ``peer - self``), so master->child is the sum — and so are the
    uncertainty bounds (intervals add under composition)."""
    return parent_off + child_off, parent_unc + child_unc


# ---------------------------------------------------------------------------
# collection: the master writes shipped per-host rings next to its trace
# ---------------------------------------------------------------------------

def host_trace_path(master_path: str, rank_offset: int) -> str:
    """Collected per-host file name: ``<base>.fleet.r<rankoffset><ext>``
    — deliberately DISTINCT from the ``.r<rankoffset>`` name a service
    writes locally. On a shared filesystem both exist and the service's
    teardown rewrite (its own ring, no clock stamps) must never clobber
    the master's collected copy, which carries the estimated offsets
    the merge depends on."""
    base, ext = os.path.splitext(master_path)
    return f"{base}.fleet.r{rank_offset}{ext}"


def write_collected_ring(master_path: str, rank_offset: int, ring: dict,
                         host: str, offset_usec: int, unc_usec: int,
                         trace_id: str) -> str:
    """Persist a service's shipped span ring as a loadable per-host
    Chrome trace file, stamping the master's clock estimate + host label
    into otherData for the merge. Atomic temp-then-rename like
    Tracer.write. Returns the path written."""
    from .tracer import atomic_write_json
    path = host_trace_path(master_path, rank_offset)
    doc = {
        "traceEvents": ring.get("traceEvents", []),
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "elbencho-tpu",
            **ring.get("otherData", {}),
            "host": host,
            "traceId": trace_id,
            "clockOffsetUsec": offset_usec,
            "clockUncertaintyUsec": unc_usec,
        },
    }
    atomic_write_json(path, doc)
    return path


# ---------------------------------------------------------------------------
# merge: per-host files -> one clock-aligned fleet trace
# ---------------------------------------------------------------------------

class FleetTraceError(ValueError):
    """Unreadable/mismatched input to the fleet trace merge."""


def _load_trace(path: str) -> dict:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as err:
        raise FleetTraceError(f"{path}: not a loadable Chrome trace "
                              f"({err})") from err
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise FleetTraceError(f"{path}: no traceEvents array")
    return doc


def discover_host_traces(master_path: str) -> "list[str]":
    """Per-host sibling files of a master trace, sorted by rank offset.
    Master-collected ``<base>.fleet.r*<ext>`` files (clock offsets
    stamped) win; service-local ``<base>.r*<ext>`` files — present on a
    shared filesystem, or left by a run whose collection was refused —
    fill in ranks with no collected copy (their lanes merge with offset
    0, honestly reported in the skew report)."""
    base, ext = os.path.splitext(master_path)

    def scan(pattern: str, prefix_len: int) -> "dict[int, str]":
        out: "dict[int, str]" = {}
        for path in glob.glob(pattern):
            suffix = path[prefix_len:len(path) - len(ext)] if ext \
                else path[prefix_len:]
            try:
                out[int(suffix)] = path
            except ValueError:
                continue  # not a rank-offset sibling (e.g. .rXtmp123)
        return out

    ebase = glob.escape(base)
    eext = glob.escape(ext)
    collected = scan(f"{ebase}.fleet.r*{eext}", len(base) + 8)
    local = scan(f"{ebase}.r*{eext}", len(base) + 2)
    merged = {**local, **collected}
    return [p for _off, p in sorted(merged.items())]


def merge_fleet_trace(master_path: str,
                      host_paths: "list[str] | None" = None,
                      out_path: "str | None" = None) -> dict:
    """Merge the master trace + per-host collected traces into ONE
    clock-aligned Chrome trace.

    - every input becomes its own process lane (``pid``: master = 0,
      hosts = 1.. in rank-offset order) with ``process_name`` metadata;
    - per-host timestamps are rebased onto the master timeline through
      each file's wall anchor minus its estimated clock offset
      (``otherData.wallAnchorUsec`` / ``clockOffsetUsec``);
    - flow events (the RPC arrows) pass through untouched — their ids
      were minted fleet-unique by the master;
    - host-file phase-marker spans duplicated by the master lane are
      dedup'd (counted in the skew report);
    - ``otherData`` carries the skew report: per-host offset ±
      uncertainty, the max absolute offset, and loss counters.

    Returns the merged document; writes it to ``out_path`` when given
    (default: ``<base>.fleet<ext>`` next to the master file).
    """
    master = _load_trace(master_path)
    explicit_inputs = host_paths is not None
    if host_paths is None:
        host_paths = discover_host_traces(master_path)
    m_other = master.get("otherData", {})
    m_anchor = int(m_other.get("wallAnchorUsec", 0))
    trace_id = m_other.get("traceId", "")

    events: "list[dict]" = []
    master_phase_names = set()
    for ev in master.get("traceEvents", []):
        ev = dict(ev)
        ev["pid"] = 0
        if ev.get("cat") == "phase" and ev.get("ph") == "X":
            master_phase_names.add(ev.get("name"))
        events.append(ev)
    lanes = [{"pid": 0, "name": "master", "path": master_path,
              "offsetUsec": 0, "uncUsec": 0,
              "rankOffset": int(m_other.get("rankOffset", 0))}]

    deduped_phase_markers = 0
    dropped_events = int(m_other.get("numDropped", 0))
    skipped: "list[str]" = []
    pid = 0
    for path in host_paths:
        doc = _load_trace(path)
        other = doc.get("otherData", {})
        if trace_id and other.get("traceId") \
                and other.get("traceId") != trace_id:
            if explicit_inputs:
                # the user NAMED this file: mixing runs is an error
                raise FleetTraceError(
                    f"{path}: trace id {other.get('traceId')!r} does "
                    f"not match the master's {trace_id!r} — files from "
                    f"different runs cannot merge into one timeline")
            # auto-discovered: a stale lane from a PREVIOUS run reusing
            # the same --tracefile path (retention keeps collected
            # files around) must not abort the whole merge — skip it
            # loudly in the skew report instead
            skipped.append(path)
            continue
        pid += 1
        offset = int(other.get("clockOffsetUsec", 0))
        unc = int(other.get("clockUncertaintyUsec", 0))
        anchor = int(other.get("wallAnchorUsec", 0))
        # an event at host trace-ts T happened at host wall time
        # anchor+T = master wall time anchor+T-offset, i.e. master
        # trace-ts T + (anchor - offset - m_anchor)
        delta = (anchor - offset - m_anchor) if anchor and m_anchor else 0
        host = other.get("host", f"r{other.get('rankOffset', pid)}")
        lanes.append({"pid": pid, "name": str(host), "path": path,
                      "offsetUsec": offset, "uncUsec": unc,
                      "rankOffset": int(other.get("rankOffset", 0))})
        dropped_events += int(other.get("numDropped", 0))
        for ev in doc.get("traceEvents", []):
            if ev.get("cat") == "phase" and ev.get("ph") == "X" \
                    and ev.get("name") in master_phase_names:
                # the master lane already carries this phase marker for
                # the whole fleet; a copy per host is noise
                deduped_phase_markers += 1
                continue
            ev = dict(ev)
            ev["pid"] = pid
            ev["ts"] = max(int(ev.get("ts", 0)) + delta, 0)
            events.append(ev)

    events.sort(key=lambda e: e.get("ts", 0))
    meta = []
    for lane in lanes:
        meta.append({"name": "process_name", "ph": "M", "pid": lane["pid"],
                     "tid": 0, "args": {"name": lane["name"]}})
        meta.append({"name": "process_sort_index", "ph": "M",
                     "pid": lane["pid"], "tid": 0,
                     "args": {"sort_index": lane["pid"]}})
    max_abs = max((abs(lane["offsetUsec"]) for lane in lanes), default=0)
    doc = {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "elbencho-tpu",
            "fleetMerge": True,
            "traceId": trace_id,
            "numInputs": len(lanes),
            "maxAbsClockOffsetUsec": max_abs,
            "dedupedPhaseMarkers": deduped_phase_markers,
            "numDropped": dropped_events,
            "skippedInputs": skipped,
            "skewReport": {
                lane["name"]: {"OffsetUsec": lane["offsetUsec"],
                               "UncUsec": lane["uncUsec"],
                               "RankOffset": lane["rankOffset"]}
                for lane in lanes},
        },
    }
    if out_path is None:
        base, ext = os.path.splitext(master_path)
        out_path = f"{base}.fleet{ext or '.json'}"
    from .tracer import atomic_write_json
    atomic_write_json(out_path, doc)
    doc["outPath"] = out_path
    return doc


def skew_report_text(doc: dict) -> "list[str]":
    """Human-readable skew-report lines for a merged fleet trace (the
    CLI/report header of elbencho-tpu-trace and --fleet-trace)."""
    other = doc.get("otherData", {})
    report = other.get("skewReport", {})
    lines = [f"fleet trace: {other.get('numInputs', 0)} lane(s), "
             f"max |clock offset| "
             f"{other.get('maxAbsClockOffsetUsec', 0)}us, "
             f"{other.get('dedupedPhaseMarkers', 0)} phase marker(s) "
             f"dedup'd, {other.get('numDropped', 0)} event(s) lost to "
             f"ring/sampling bounds"]
    for path in other.get("skippedInputs", []):
        lines.append(f"  SKIPPED {path}: trace id from a different run "
                     f"(stale leftover? delete it or merge explicitly)")
    for name, entry in report.items():
        lines.append(f"  {name or 'master'}: offset "
                     f"{entry.get('OffsetUsec', 0)}us "
                     f"± {entry.get('UncUsec', 0)}us")
    return lines
