"""Flight recorder: per-tick fleet time-series capture (--flightrec).

A finished run used to leave only sums and high-water marks — the per-tick
fleet state that already flows through the live-stats loop (local mode) and
the /livestream frames / /status polls the master ingests anyway (service
mode) evaporated at phase end. The flight recorder samples that state into
a compact append-only JSONL artifact so the run doctor (doctor.py) and the
``elbencho-tpu-doctor`` CLI can answer "was this run storage-bound,
DMA-bound, or stalled on the pipeline — and when?" after the fact.

Design contract (mirrors the tracer/telemetry rules):

- **Off by default, zero overhead off.** A FlightRecorder exists iff
  ``--flightrec FILE`` was given; every hook is a single ``is None`` test
  (the overhead guard in tests/test_flightrec.py pins this).
- **Zero extra service requests.** The recorder samples the SAME worker
  counters the live-stats loop already reads: local workers' live counters
  directly, RemoteWorkers' ingest mirrors that /livestream frames
  (--svcstream) or /status polls already populate. Arming it changes no
  wire traffic (asserted against SvcRequests in the scale-style test).
- **Per-host and fleet-merged rows, same wire rules.** The fleet row is by
  construction the sum/MAX merge (PATH_AUDIT_MAX_KEYS + the control
  counters' merge modes) of the per-host rows — property-tested.
- **Bounded memory.** Rows buffer in a capped ring and flush+fsync
  periodically; overflow drops the OLDEST rows and counts them
  (RowsDropped in phase_end records), so a recording is honest about loss.
- **Schema-versioned header** so readers can refuse a future format
  instead of misparsing it; the reader tolerates a torn final line (a
  crashed run still leaves a loadable recording) but rejects mid-file
  garbage like the run journal does.

Row formats (one JSON object per line):

  {"Type":"header","Schema":1,...,"SumKeys":[...],"MaxKeys":[...]}
  {"Type":"phase_start","Phase":"WRITE","T":1.50}
  {"Type":"s","T":2.00,"D":{"Bytes":1048576,...}}            # fleet row
  {"Type":"s","T":2.00,"Host":"node1:1611","D":{...}}        # per-host row
  {"Type":"phase_end","Phase":"WRITE","T":9.51,"ElapsedUSec":...,
   "Workers":N,"Totals":{...},"Analysis":{...}|null,"RowsDropped":0}

Sample rows are DELTA-encoded: sum-merged counters carry the change since
the entity's previous row (zero changes are omitted, idle entities emit no
row), MAX-merged high-water marks carry the absolute value when it moved.
Cumulative state is reconstructed by ``accumulate_rows``.
"""

from __future__ import annotations

import json
import os
import time

from .. import __version__
from ..service.fault_tolerance import (CONTROL_AUDIT_COUNTERS,
                                       merge_control_audit_counters)
from ..tpu.device import (PATH_AUDIT_COUNTERS, PATH_AUDIT_MAX_KEYS,
                          sum_path_audit_counters)

#: bump when the row format changes incompatibly; readers refuse unknown
SCHEMA_VERSION = 1

#: key for the fleet-merged entity in the per-entity snapshot maps
FLEET = ""

#: buffered rows are flushed+fsync'd at whichever comes first
FLUSH_ROWS = 64
FLUSH_SECS = 1.0

#: pending-row ring bound: beyond this the oldest buffered rows are
#: dropped (and counted) instead of growing without bound when the
#: target filesystem stalls
RING_CAP = 8192

#: in-memory per-phase fleet series bound (doctor trend evidence); when
#: full, adjacent ticks are coalesced so the window keeps covering the
#: whole phase at half the resolution
SERIES_CAP = 4096


def counter_schema() -> "tuple[tuple[str, str], ...]":
    """(wire key, merge mode) for every recorded counter: the live-ops
    triple, the TPU transfer split, the whole PATH_AUDIT / CONTROL_AUDIT
    schemas, and the storage-op busy time. Modes are the SAME sum/MAX
    rules the service wire protocol merges by."""
    rows: "list[tuple[str, str]]" = [
        ("Entries", "sum"), ("Bytes", "sum"), ("Iops", "sum"),
        ("TpuHbmBytes", "sum"), ("TpuHbmUSec", "sum"),
        ("TpuHbmDispatchUSec", "sum"),
        # storage-op busy time: per-op latencies summed across workers
        # (the sum_micro of the io histograms) — the "storage submit/
        # reap" leg of the doctor's stage decomposition
        ("IoBusyUSec", "sum"),
    ]
    for _attr, key, _ingest in PATH_AUDIT_COUNTERS:
        rows.append((key, "max" if key in PATH_AUDIT_MAX_KEYS else "sum"))
    for _attr, key, mode in CONTROL_AUDIT_COUNTERS:
        rows.append((key, mode))
    return tuple(rows)


def max_keys() -> "frozenset[str]":
    return frozenset(k for k, mode in counter_schema() if mode == "max")


def snapshot_fleet(statistics) -> dict:
    """Absolute fleet-merged counter snapshot, read from the same
    worker-owned plain ints the live-stats loop sums (local workers'
    counters, RemoteWorkers' ingest mirrors) — never a wire request."""
    from ..stats.statistics import sum_tpu_transfer_totals
    entries, num_bytes, iops, _done = statistics._sum_live_ops()
    workers = statistics.manager.workers
    tpu_bytes, tpu_usec, tpu_dispatch = sum_tpu_transfer_totals(workers)
    snap = {"Entries": entries, "Bytes": num_bytes, "Iops": iops,
            "TpuHbmBytes": tpu_bytes, "TpuHbmUSec": tpu_usec,
            "TpuHbmDispatchUSec": tpu_dispatch,
            "IoBusyUSec": sum(w.iops_latency_histo.sum_micro
                              + w.iops_latency_histo_rwmix.sum_micro
                              for w in workers)}
    snap.update(sum_path_audit_counters(workers))
    snap.update(merge_control_audit_counters(workers))
    return snap


def snapshot_host(worker) -> dict:
    """Absolute per-host snapshot of one RemoteWorker's ingest mirrors
    (populated by the /livestream or /status ingest the master already
    performs). Fleet == merge(hosts) by construction: every key here is
    exactly one addend/operand of the snapshot_fleet merge."""
    snap = {
        "Entries": (worker.live_ops.num_entries_done
                    + worker.live_ops_rwmix_read.num_entries_done),
        "Bytes": (worker.live_ops.num_bytes_done
                  + worker.live_ops_rwmix_read.num_bytes_done),
        "Iops": (worker.live_ops.num_iops_done
                 + worker.live_ops_rwmix_read.num_iops_done),
        "TpuHbmBytes": worker.tpu_transfer_bytes,
        "TpuHbmUSec": worker.tpu_transfer_usec,
        "TpuHbmDispatchUSec": worker.tpu_dispatch_usec,
        "IoBusyUSec": (worker.iops_latency_histo.sum_micro
                       + worker.iops_latency_histo_rwmix.sum_micro),
    }
    for _attr, key, ingest_attr in PATH_AUDIT_COUNTERS:
        snap[key] = getattr(worker, ingest_attr, 0)
    for attr, key, _mode in CONTROL_AUDIT_COUNTERS:
        snap[key] = getattr(worker, attr, 0)
    return snap


def delta_row(prev: dict, cur: dict, maxed: "frozenset[str]") -> dict:
    """Compact delta between two absolute snapshots: sum keys as change
    (omitted when 0; a counter reset — new phase — re-bases to the
    absolute value), MAX keys as absolute value when it moved."""
    out = {}
    for key, val in cur.items():
        if key in maxed:
            if val != prev.get(key, 0):
                out[key] = val
        else:
            d = val - prev.get(key, 0)
            if d < 0:  # per-phase counter reset: re-base
                d = val
            if d:
                out[key] = d
    return out


def accumulate_rows(rows, maxed: "frozenset[str]") -> dict:
    """Reconstruct the cumulative counter state from delta rows
    (``D`` dicts): sum keys add up, MAX keys keep the last (and largest
    — high-water marks are monotonic within a phase) value."""
    out: dict = {}
    for d in rows:
        for key, val in d.items():
            if key in maxed:
                out[key] = max(out.get(key, 0), val)
            else:
                out[key] = out.get(key, 0) + val
    return out


def merge_entities(cums: "list[dict]", maxed: "frozenset[str]") -> dict:
    """Merge per-entity cumulative states with the wire rules (sum,
    except MAX keys) — the property the fleet row must equal. The fold
    is the same one delta accumulation uses, so the two can never
    drift."""
    return accumulate_rows(cums, maxed)


class FlightRecorder:
    """Append-only recorder driven from the live-stats loop. All methods
    run on the coordinator thread (the same thread that renders live
    stats), so no locking is needed."""

    def __init__(self, path: str, cfg, role: str = "local"):
        self.path = path
        self.cfg = cfg
        self.role = role
        self._maxed = max_keys()
        self._fh = open(path, "w")
        self._t0 = time.monotonic()
        self._pending: "list[str]" = []
        self._last_flush = self._t0
        self.rows_dropped = 0
        self.rows_written = 0
        self._dead_err: "str | None" = None
        # per-entity absolute snapshots of the CURRENT phase ("" = fleet);
        # doubles as the delta baseline and the cumulative state
        self._prev: "dict[str, dict]" = {}
        # current phase bookkeeping for the doctor
        self._phase: "str | None" = None
        self._phase_t0 = self._t0
        self._series: "list[tuple[float, dict]]" = []
        # straggler attribution: per tick, which host trails the fleet
        # (lowest cumulative bytes) — "host X last in N% of ticks"
        self._host_last_ticks: "dict[str, int]" = {}
        self._progress_ticks = 0
        schema = counter_schema()
        self._append({
            "Type": "header", "Schema": SCHEMA_VERSION,
            "Tool": "elbencho-tpu", "Version": __version__,
            "Role": role, "Label": getattr(cfg, "bench_label", ""),
            "IntervalMs": getattr(cfg, "live_stats_interval_ms", 0),
            "Hosts": list(getattr(cfg, "hosts", []) or []),
            "SumKeys": [k for k, m in schema if m == "sum"],
            "MaxKeys": [k for k, m in schema if m == "max"],
            "UtcStart": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        })
        self.flush(force=True)

    # -- write path ----------------------------------------------------------

    def _now(self) -> float:
        return round(time.monotonic() - self._t0, 3)

    def _append(self, rec: dict) -> None:
        if self._dead_err is not None:
            return
        if len(self._pending) >= RING_CAP:
            # bounded memory: drop the OLDEST buffered row, honestly
            self._pending.pop(0)
            self.rows_dropped += 1
        self._pending.append(json.dumps(rec, separators=(",", ":")))

    def flush(self, force: bool = False) -> None:
        """Flush+fsync the pending ring when a bound is hit (or forced).
        A failing recording disables itself LOUDLY once instead of
        failing the benchmark — the run's results outrank its telemetry."""
        if self._dead_err is not None or self._fh is None:
            return
        now = time.monotonic()
        if not force and len(self._pending) < FLUSH_ROWS \
                and now - self._last_flush < FLUSH_SECS:
            return
        if not self._pending:
            self._last_flush = now
            return
        try:
            self._fh.write("\n".join(self._pending) + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
        except (OSError, ValueError) as err:
            self._dead_err = str(err)
            from ..toolkits.logger import log_error
            log_error(f"--flightrec: recording to {self.path} failed "
                      f"({err}); flight recording DISABLED for the rest "
                      f"of the run")
        else:
            self.rows_written += len(self._pending)
        self._pending = []
        self._last_flush = now

    # -- sampling hooks (live-stats loop / coordinator) ----------------------

    def phase_start(self, phase_label: str) -> None:
        """New phase: per-phase counters reset with the workers, so the
        delta baselines and the doctor's trend series reset too."""
        self._phase = phase_label
        self._phase_t0 = time.monotonic()
        self._prev = {}
        self._series = []
        self._host_last_ticks = {}
        self._progress_ticks = 0
        self._append({"Type": "phase_start", "Phase": phase_label,
                      "T": self._now()})
        self.flush()

    def sample(self, statistics) -> None:
        """One tick: fleet row + per-host rows (master mode), delta
        encoded against each entity's previous snapshot."""
        t = self._now()
        fleet = snapshot_fleet(statistics)
        self._record_entity(FLEET, fleet, t)
        host_bytes: "dict[str, int]" = {}
        for w in statistics.manager.workers:
            host = getattr(w, "host", None)
            if host is not None:
                snap = snapshot_host(w)
                host_bytes[host] = snap.get("Bytes", 0)
                self._record_entity(host, snap, t)
        if len(host_bytes) > 1 and any(host_bytes.values()):
            # straggler evidence: the host trailing the fleet this tick
            # (ties break deterministically by label)
            laggard = min(host_bytes, key=lambda h: (host_bytes[h], h))
            self._host_last_ticks[laggard] = \
                self._host_last_ticks.get(laggard, 0) + 1
            self._progress_ticks += 1
        self.flush()

    def _record_entity(self, entity: str, snap: dict, t: float) -> None:
        d = delta_row(self._prev.get(entity, {}), snap, self._maxed)
        self._prev[entity] = snap
        if not d:
            return  # idle tick: no row (delta compaction)
        row = {"Type": "s", "T": t, "D": d}
        if entity != FLEET:
            row["Host"] = entity
        self._append(row)
        if entity == FLEET:
            self._series_push(round(t - (self._phase_t0 - self._t0), 3), d)

    def _series_push(self, t_rel: float, d: dict) -> None:
        if len(self._series) >= SERIES_CAP:
            # halve resolution, keep whole-phase coverage
            halved = []
            for i in range(0, len(self._series) - 1, 2):
                ta, da = self._series[i]
                _tb, db = self._series[i + 1]
                merged = dict(da)
                for key, val in db.items():
                    if key in self._maxed:
                        merged[key] = max(merged.get(key, 0), val)
                    else:
                        merged[key] = merged.get(key, 0) + val
                halved.append((ta, merged))
            if len(self._series) % 2:
                halved.append(self._series[-1])
            self._series = halved
        self._series.append((t_rel, d))

    def finish_phase(self, statistics, res) -> "dict | None":
        """Final tick + phase_end record + doctor analysis. Called after
        the phase barrier (RemoteWorkers have ingested their final
        /benchresult by then, so the totals are exact). Returns the
        Analysis dict for the run JSON / text summary."""
        if self._phase is None:
            return None
        self.sample(statistics)
        totals = dict(self._prev.get(FLEET, {}))
        host_info = self._host_info(statistics)
        tail = getattr(res, "tail_analysis", None)
        from .doctor import analyze_phase
        analysis = analyze_phase(res.phase_name, totals,
                                 res.last_done_usec, res.num_workers,
                                 series=self._series, host_info=host_info,
                                 tail=tail)
        rec = {
            "Type": "phase_end", "Phase": self._phase, "T": self._now(),
            "ElapsedUSec": res.last_done_usec,
            "Workers": res.num_workers,
            "Totals": totals,
            "Analysis": analysis,
            "RowsDropped": self.rows_dropped,
        }
        if tail is not None:
            # full --slowops TailAnalysis (bounded by construction), so
            # the doctor CLI can recompute tail verdicts and the diff's
            # "tail grew" cause from the recording alone
            rec["Tail"] = tail
        if host_info:
            # per-host barrier decomposition + clock estimates, so the
            # doctor CLI can recompute straggler verdicts (and the skew
            # report survives) from the recording alone
            rec["Hosts"] = host_info
        self._append(rec)
        self._phase = None
        self.flush(force=True)
        return analysis

    def _host_info(self, statistics) -> "dict[str, dict]":
        """Per-host straggler/clock view for the doctor: the barrier
        decomposition Statistics computed after the phase barrier plus
        this recording's last-in-tick counts."""
        stats_fn = getattr(statistics, "per_host_barrier_stats", None)
        host_info = dict(stats_fn()) if stats_fn is not None else {}
        if self._progress_ticks:
            for host, count in self._host_last_ticks.items():
                entry = host_info.setdefault(host, {})
                entry["LastTickPct"] = round(
                    100.0 * count / self._progress_ticks, 1)
        return host_info

    def close(self) -> None:
        if self._fh is None:
            return
        self.flush(force=True)
        try:
            self._fh.close()
        except OSError:
            pass
        self._fh = None


def make_flightrec(cfg) -> "FlightRecorder | None":
    """The single arming point: a FlightRecorder exists iff --flightrec
    was given AND this process is the master/local coordinator (services
    never record — the master taps the frames it already ingests, so the
    fleet pays zero extra requests)."""
    path = getattr(cfg, "flightrec_file_path", "")
    if not path or getattr(cfg, "run_as_service", False):
        return None
    return FlightRecorder(path, cfg,
                          role="master" if getattr(cfg, "hosts", None)
                          else "local")


# ---------------------------------------------------------------------------
# reading side (doctor CLI / chart tool / tests)
# ---------------------------------------------------------------------------

class RecordingError(ValueError):
    """Unreadable/incompatible flight recording."""


def read_recording(path: str) -> dict:
    """Parse a recording into {"header", "phases": [...]}. The final
    line may be torn (crashed run mid-append) and is dropped; garbage
    anywhere else is an error — a recording that lies in the middle
    must not be silently half-trusted. Each phase entry:
    {"name", "start_t", "samples": [fleet D rows], "host_samples":
    {host: [D rows]}, "end": phase_end record or None}."""
    with open(path) as f:
        lines = f.read().splitlines()
    records = []
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except ValueError as err:
            if i == len(lines) - 1:
                break  # torn tail: tolerated
            raise RecordingError(
                f"{path}:{i + 1}: corrupt mid-file record: {err}") from err
    if not records or records[0].get("Type") != "header":
        raise RecordingError(f"{path}: not a flight recording "
                             f"(missing header)")
    header = records[0]
    if header.get("Schema", 0) > SCHEMA_VERSION:
        raise RecordingError(
            f"{path}: schema {header.get('Schema')} is newer than this "
            f"reader (supports <= {SCHEMA_VERSION})")
    phases: "list[dict]" = []
    cur: "dict | None" = None
    for rec in records[1:]:
        rtype = rec.get("Type")
        if rtype == "phase_start":
            cur = {"name": rec.get("Phase", "?"),
                   "start_t": rec.get("T", 0.0),
                   "samples": [], "sample_ts": [],
                   "host_samples": {}, "end": None}
            phases.append(cur)
        elif rtype == "s" and cur is not None:
            host = rec.get("Host")
            if host is None:
                cur["samples"].append(rec.get("D", {}))
                cur["sample_ts"].append(rec.get("T", 0.0))
            else:
                cur["host_samples"].setdefault(host, []).append(
                    rec.get("D", {}))
        elif rtype == "phase_end" and cur is not None \
                and rec.get("Phase") == cur["name"]:
            cur["end"] = rec
            cur = None
    return {"header": header, "phases": phases}
