"""Slow-op forensics: fleet-wide tail-latency capture (--slowops/--opsample).

The aggregate histograms answer "HOW slow is the tail" (LatP50/P99/P99.9);
nothing in the system could answer "WHICH ops, files, offsets, or hosts own
it" — the question every storage sizing exercise ultimately reduces to
(PAPERS.md arXiv 2604.21275: input-pipeline stalls at scale are driven by
tail ops, not means). This module closes that gap:

- **Per-worker capture.** Each worker holds a ``SlowOpRecorder``: a bounded
  min-heap of its K slowest op records (op, phase, rank, file path or
  blockdev, offset, size, latency, retry/timeout chain, storage-vs-
  dispatch-vs-DMA stage split where a TPU context is attached, and the
  op's trace span timestamp when ``--tracefile`` is armed) plus a
  deterministic systematic sample of op latencies over time for density
  estimation (the heatmap lanes). Off by default: workers hold
  ``self._slowops is None`` and every instrumentation point is a single
  attribute test — the same zero-overhead contract as the tracer.

- **Fleet collection.** Services attach their merged worker snapshots to
  the ``/benchresult`` reply when the master asks (``ShipSlowOps`` —
  size-capped by ``--traceshipcap``, refusal LOUD never fatal, zero extra
  per-tick service requests, the ``--tracefleet`` discipline). The master
  merges everything into the run JSON's ``TailAnalysis`` block.

- **Three consumers.** The run doctor learns tail-attribution verdicts
  with evidence (``tail-bound``, diff cause "tail grew");
  ``elbencho-tpu-chart --tail`` renders time x host and offset x latency
  heatmaps; new audit counters (``SlowOpsRecorded``/``OpSamplesDropped``
  sum, ``TailP999UsecHwm`` MAX) auto-plumb through PATH_AUDIT_COUNTERS
  into wire/JSON//metrics/flightrec.
"""

from __future__ import annotations

import heapq
import os
import time

#: TailAnalysis block schema version (run JSON + flightrec phase_end rows)
TAIL_ANALYSIS_SCHEMA = 1

#: ordered key list of the TailAnalysis block — appended, never reordered
#: (tools/check-schema lints this tuple against the previous commit, the
#: same mechanical append-only gate the counter schemas ride)
TAIL_ANALYSIS_KEYS = (
    "Schema", "K", "SampleRate", "OpsSeen", "SlowOpsRecorded",
    "OpSamplesDropped", "P50Usec", "P99Usec", "P999Usec", "MaxUsec",
    "TailRatio", "TailSharePct", "SlowOps", "Owners", "Lanes", "Refusals")

#: per-worker bound on retained (t, latency) sample points; overflow
#: halves the kept set and doubles the effective stride (counted in
#: OpSamplesDropped, so a sampled density is honest about what it lost)
RESERVOIR_CAP = 4096

#: per-host bound on merged heatmap lane points (the run-JSON block must
#: stay a report, not a second trace file)
MERGED_LANE_CAP = 2048

#: recompute the TailP999UsecHwm mirror every this many recorded ops
#: (bucket-walk over the recorder's own histogram; cheap but not free)
P999_REFRESH_OPS = 512

#: test-only per-port op-delay injection: in-process fleets share one
#: process, so the chaos suite seeds this (gated on ELBENCHO_TPU_TESTING,
#: the same opt-in as the stream ring's fault injection and the clock-skew
#: seam tracefleet.TEST_SKEW_BY_PORT) to make exactly ONE op on ONE host
#: provably slow: {service_port: (op_index, delay_usec)}
TEST_OP_DELAY_BY_PORT: "dict[int, tuple[int, int]]" = {}


def test_op_delay(cfg) -> "tuple[int, int] | None":
    """(op_index, delay_usec) this worker's loop must inject, or None.
    Resolved once per phase by the storage loops; needs the explicit
    ELBENCHO_TPU_TESTING=1 opt-in, so production hot paths never even
    consult the dict."""
    if not TEST_OP_DELAY_BY_PORT \
            or os.environ.get("ELBENCHO_TPU_TESTING") != "1":
        return None
    return TEST_OP_DELAY_BY_PORT.get(getattr(cfg, "service_port", 0))


#: test-only EVERY-op delay injection, per service port: the autotune
#: chaos suite seeds this (same ELBENCHO_TPU_TESTING gate as
#: TEST_OP_DELAY_BY_PORT) to give storage a deterministic per-op
#: latency floor — a constructed storage-bound bottleneck the tuner
#: provably beats by raising parallelism: {service_port: delay_usec}
TEST_UNIFORM_OP_DELAY_BY_PORT: "dict[int, int]" = {}


def test_uniform_op_delay(cfg) -> int:
    """Per-op delay (usec) every storage op of this worker must inject,
    0 outside an opted-in test fleet. Resolved once per phase like
    test_op_delay, so production hot paths pay one dict test."""
    if not TEST_UNIFORM_OP_DELAY_BY_PORT \
            or os.environ.get("ELBENCHO_TPU_TESTING") != "1":
        return 0
    return TEST_UNIFORM_OP_DELAY_BY_PORT.get(
        getattr(cfg, "service_port", 0), 0)


class SlowOpRecorder:
    """Per-worker slow-op capture. Owned and written by the worker thread
    (no locks — like every live counter, snapshot readers ride the GIL);
    the heap keeps the K slowest ops, the reservoir keeps a deterministic
    systematic sample of (t, latency) points for density estimation."""

    def __init__(self, worker, k: int, sample_rate: float):
        self.worker = worker
        self.k = max(int(k), 1)
        self.sample_rate = min(max(sample_rate, 0.0), 1.0)
        # (lat_usec, seq, record) entries — seq breaks latency ties so
        # heapq never falls through to comparing dicts
        self._heap: "list[tuple[int, int, dict]]" = []
        self._heap_min = -1  # lat of the heap root once K records exist
        self._seq = 0
        self.ops_seen = 0
        # deterministic systematic sample: keep every _stride'th op
        self._stride = max(round(1.0 / self.sample_rate), 1) \
            if self.sample_rate else 0
        self._sample: "list[tuple[int, int]]" = []  # (t_ms, lat_usec)
        # own histogram for the running p99.9 high-water mark (the
        # worker's phase histograms reset per phase underneath us)
        from ..stats.latency_histogram import LatencyHistogram
        self._histo = LatencyHistogram()
        self._p999_refresh = 0

    # -- hot path ------------------------------------------------------------

    def record(self, op: str, phase: str, lat_usec: int, offset: int,
               size: int, path: str = "", retries: int = 0,
               timed_out: bool = False, dispatch_usec: int = 0,
               dma_usec: int = 0, slot: "int | None" = None,
               start_ns: "int | None" = None) -> None:
        """One completed storage op. The common case (op faster than the
        current K'th slowest, not sampled this stride) is two integer
        comparisons past the caller's ``is None`` test."""
        worker = self.worker
        self.ops_seen += 1
        lat_usec = int(lat_usec)
        self._histo.add_latency(lat_usec)
        self._p999_refresh += 1
        if self._p999_refresh >= P999_REFRESH_OPS:
            self._p999_refresh = 0
            self.refresh_hwm()
        if self._stride:
            if self.ops_seen % self._stride == 0:
                t_ms = int((time.monotonic()
                            - worker.shared.phase_start_monotonic) * 1000)
                self._sample.append((t_ms, lat_usec))
                if len(self._sample) >= RESERVOIR_CAP:
                    # halve resolution, keep whole-phase coverage; the
                    # dropped half is counted honestly
                    worker.op_samples_dropped += len(self._sample) // 2
                    self._sample = self._sample[::2]
                    self._stride *= 2
        if lat_usec <= self._heap_min:
            return
        rec = {"Op": op, "Phase": phase, "Rank": worker.rank,
               "LatUsec": lat_usec, "Offset": int(offset),
               "Size": int(size),
               "TMs": int((time.monotonic()
                           - worker.shared.phase_start_monotonic) * 1000)}
        if path:
            rec["File"] = path
        if retries:
            rec["Retries"] = int(retries)
        if timed_out:
            rec["TimedOut"] = True
        if dispatch_usec or dma_usec:
            # stage split: storage latency is LatUsec itself; the TPU
            # legs are the context's dispatch/DMA accounting deltas
            # around this op's transfer hand-off
            rec["DispatchUsec"] = int(dispatch_usec)
            rec["DmaUsec"] = int(dma_usec)
        if slot is not None:
            rec["Slot"] = slot
        tracer = getattr(worker, "_tracer", None)
        if tracer is not None and start_ns is not None:
            # Perfetto linkage: the instant event marks the captured op
            # at its span's trace timestamp, so a heatmap cell can be
            # found on the (fleet) trace timeline
            rec["SpanTs"] = tracer.to_trace_ts(start_ns)
            tracer.record("slow_op", "tail", start_ns, 0,
                          rank=worker.rank, lat_usec=lat_usec,
                          offset=int(offset), size=int(size), op=op)
        self._seq += 1
        entry = (lat_usec, self._seq, rec)
        if len(self._heap) < self.k:
            heapq.heappush(self._heap, entry)
        else:
            heapq.heappushpop(self._heap, entry)
        if len(self._heap) >= self.k:
            self._heap_min = self._heap[0][0]
        worker.slow_ops_recorded += 1

    def refresh_hwm(self) -> None:
        """Fold the current p99.9 into the worker's TailP999UsecHwm
        mirror. Also called from the worker's phase-finish hook so the
        counter is final BEFORE the wire/result reads sum it."""
        self.worker.tail_p999_usec_hwm = max(
            self.worker.tail_p999_usec_hwm,
            int(self._histo.percentile(99.9)))

    # -- snapshot / reset ----------------------------------------------------

    def snapshot(self) -> dict:
        """Shippable per-worker state (plain JSON types only). Called by
        the coordinator/service thread at phase end; list copies keep it
        safe against a still-running worker appending."""
        self.refresh_hwm()
        return {
            "K": self.k,
            "Rank": self.worker.rank,
            "OpsSeen": self.ops_seen,
            "Records": [e[2] for e in sorted(self._heap, reverse=True)],
            "Recorded": self.worker.slow_ops_recorded,
            "Sample": [list(p) for p in self._sample],
            "SamplesDropped": self.worker.op_samples_dropped,
            "P999Usec": self.worker.tail_p999_usec_hwm,
        }

    def reset_phase(self) -> None:
        """Per-phase reset, called from the worker's reset_stats next to
        every other per-phase counter (the worker attrs are zeroed
        there)."""
        self._heap = []
        self._heap_min = -1
        self._seq = 0
        self.ops_seen = 0
        self._sample = []
        self._stride = max(round(1.0 / self.sample_rate), 1) \
            if self.sample_rate else 0
        self._histo.reset()
        self._p999_refresh = 0


def make_recorder(worker) -> "SlowOpRecorder | None":
    """The single arming point: a recorder exists iff --slowops K > 0
    (instrumentation stays a no-op ``is None`` test otherwise)."""
    cfg = worker.shared.config
    k = getattr(cfg, "slow_ops_k", 0)
    if not k:
        return None
    return SlowOpRecorder(worker, k, getattr(cfg, "op_sample_rate", 1.0))


# ---------------------------------------------------------------------------
# merge: per-worker / per-host snapshots -> one TailAnalysis block
# ---------------------------------------------------------------------------

def merge_snapshots(parts: "list[dict]", k: int) -> dict:
    """Merge per-worker snapshot dicts into one (service-side, before the
    ship; also the master's first fold). Top-K of the union, samples
    concatenated (the per-host lane split happens master-side where the
    host labels live), counters summed, P999 MAX-merged."""
    records: "list[dict]" = []
    sample: "list[list[int]]" = []
    ops_seen = recorded = dropped = p999 = 0
    for part in parts:
        records.extend(part.get("Records", []))
        sample.extend(part.get("Sample", []))
        ops_seen += part.get("OpsSeen", 0)
        recorded += part.get("Recorded", len(part.get("Records", [])))
        dropped += part.get("SamplesDropped", 0)
        p999 = max(p999, part.get("P999Usec", 0))
    records.sort(key=lambda r: (-r.get("LatUsec", 0), r.get("TMs", 0)))
    return {"K": k, "OpsSeen": ops_seen, "Records": records[:k],
            "Recorded": recorded, "Sample": sorted(sample),
            "SamplesDropped": dropped, "P999Usec": p999}


def thin_points(points: "list", cap: int = MERGED_LANE_CAP) -> "list":
    """Decimate a time-sorted (t, lat) point list to at most ``cap``
    points by stride, keeping whole-phase coverage (used on the ship
    path so a host never serializes more sample bytes than the merged
    lane keeps, and on the master's per-host lane fold)."""
    if len(points) <= cap:
        return points
    return points[::(len(points) + cap - 1) // cap]


def _owner_shares(records: "list[dict]", key_fn, top: int
                  ) -> "dict[str, float]":
    """{owner: fraction of captured tail-op TIME} for the heaviest
    owners — time-weighted, so one 250ms op outranks ten 1ms ones."""
    total = sum(r.get("LatUsec", 0) for r in records)
    if not total:
        return {}
    shares: "dict[str, float]" = {}
    for rec in records:
        owner = key_fn(rec)
        if owner:
            shares[owner] = shares.get(owner, 0) + rec.get("LatUsec", 0)
    ranked = sorted(shares.items(), key=lambda kv: -kv[1])[:top]
    return {owner: round(usec / total, 3) for owner, usec in ranked}


def _file_dir(rec: dict) -> str:
    path = rec.get("File", "")
    if not path:
        return ""
    head = os.path.dirname(path)
    return (head + "/") if head else path


def build_tail_analysis(parts: "list[tuple[str, dict]]", io_histo,
                        k: int, sample_rate: float) -> dict:
    """The run JSON's ``TailAnalysis`` block for one phase.

    ``parts`` is [(host_label, snapshot)] — "" labels the local worker
    pool; ``io_histo`` is the fleet-merged per-op latency histogram
    (rwmix reads folded in, like the live view), which carries the EXACT
    percentiles — the captured records and samples add the attribution
    and density the histogram cannot."""
    labeled_records: "list[dict]" = []
    lanes: "dict[str, list]" = {}
    refusals: "list[str]" = []
    merged_parts = []
    for host, snap in parts:
        if snap is None:
            refusals.append(host or "local")
            continue
        label = host or "local"
        for rec in snap.get("Records", []):
            rec = dict(rec)
            if host:
                rec["Host"] = host
            labeled_records.append(rec)
        lane = [list(p) for p in snap.get("Sample", [])]
        if lane:
            # EXTEND, never assign: a local run contributes one part per
            # worker and they all share the "local" label
            lanes.setdefault(label, []).extend(lane)
        merged_parts.append(snap)
    for label in lanes:
        lanes[label] = thin_points(sorted(lanes[label]))
    merged = merge_snapshots(merged_parts, k)
    labeled_records.sort(key=lambda r: (-r.get("LatUsec", 0),
                                        r.get("TMs", 0)))
    top = labeled_records[:k]
    p50 = int(io_histo.percentile(50))
    p99 = int(io_histo.percentile(99))
    p999 = int(io_histo.percentile(99.9))
    max_usec = int(io_histo.max_micro)
    tail_usec = max(p999, max_usec)
    ratio = round(tail_usec / p50, 1) if p50 else 0.0
    captured_usec = sum(r.get("LatUsec", 0) for r in top)
    share = round(100.0 * captured_usec / io_histo.sum_micro, 1) \
        if io_histo.sum_micro else 0.0
    owners = {
        "ByHost": _owner_shares(top, lambda r: r.get("Host", "local"), 8),
        "ByFile": _owner_shares(top, lambda r: r.get("File", ""), 5),
        "ByDir": _owner_shares(top, _file_dir, 3),
        "ByOp": _owner_shares(top, lambda r: r.get("Op", ""), 5),
    }
    out = {
        "Schema": TAIL_ANALYSIS_SCHEMA,
        "K": k,
        "SampleRate": sample_rate,
        "OpsSeen": merged["OpsSeen"],
        "SlowOpsRecorded": merged["Recorded"],
        "OpSamplesDropped": merged["SamplesDropped"],
        "P50Usec": p50,
        "P99Usec": p99,
        "P999Usec": p999,
        "MaxUsec": max_usec,
        "TailRatio": ratio,
        "TailSharePct": share,
        "SlowOps": top,
        "Owners": owners,
        "Lanes": lanes,
        "Refusals": refusals,
    }
    assert tuple(out) == TAIL_ANALYSIS_KEYS, "TailAnalysis schema drift"
    return out


def tail_doctor_summary(tail: "dict | None") -> "dict | None":
    """The compact Tail block the doctor attaches to its Analysis dict
    (the full TailAnalysis lives in the run JSON / flightrec phase_end;
    the Analysis copy carries only what verdicts and diffs consume)."""
    if not tail:
        return None
    by_host = tail.get("Owners", {}).get("ByHost", {})
    by_dir = tail.get("Owners", {}).get("ByDir", {})
    top_host = max(by_host, key=by_host.get) if by_host else ""
    top_dir = max(by_dir, key=by_dir.get) if by_dir else ""
    return {
        "TailRatio": tail.get("TailRatio", 0.0),
        "P50Usec": tail.get("P50Usec", 0),
        "P999Usec": tail.get("P999Usec", 0),
        "MaxUsec": tail.get("MaxUsec", 0),
        "TailSharePct": tail.get("TailSharePct", 0.0),
        "TopHost": top_host,
        "TopHostPct": round(100.0 * by_host.get(top_host, 0.0), 1),
        "TopDir": top_dir,
        "TopDirPct": round(100.0 * by_dir.get(top_dir, 0.0), 1),
    }


def describe_slowest(tail: dict) -> str:
    """One evidence line naming the slowest captured op (host, file,
    offset, size, latency, retry chain) — the doctor's "WHICH op" line."""
    ops = tail.get("SlowOps", [])
    if not ops:
        return ""
    rec = ops[0]
    where = rec.get("File", "")
    host = rec.get("Host", "")
    parts = [f"slowest op: {rec.get('Op', '?')} "
             f"{rec.get('Size', 0)}B at offset {rec.get('Offset', 0)}"]
    if where:
        parts.append(f"of {where}")
    if host:
        parts.append(f"on {host}")
    parts.append(f"— {rec.get('LatUsec', 0) / 1000:.1f}ms")
    if rec.get("Retries"):
        parts.append(f"after {rec['Retries']} retry(s)")
    if rec.get("TimedOut"):
        parts.append("(timed out)")
    return " ".join(parts)
