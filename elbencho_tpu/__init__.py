"""elbencho-tpu: TPU-native distributed storage benchmark.

A brand-new framework with the capabilities of breuner/elbencho (reference:
/root/reference, C++17): throughput/IOPS/latency benchmarking of files, block
devices and object storage (S3/GCS), locally or coordinated across many hosts
via an HTTP service mode — with the GPU data path (CUDA/cuFile) re-designed
TPU-first: per-worker HBM buffer allocation and host->device DMA via PjRt/JAX
(``--tpuids``), Pallas kernels for on-device block fill/verify, and a
``jax.sharding.Mesh`` pod-wide ingest path.

Package layout (reference layer map: SURVEY.md section 1):
  toolkits/   L1 pure-logic toolkits (offset gens, PRNGs, units, treefile, ...)
  config/     L6 flag/config system (ProgArgs parity incl. JSON round-trip)
  workers/    L3/L4 workload engine + worker runtime
  stats/      L0 statistics, latency histograms, CPU util
  service/    L5 HTTP control plane (service + master/RemoteWorker)
  tpu/        TPU data path: HBM buffers, H2D/D2H transfer seam (PjRt via JAX)
  ops/        on-device ops (Pallas / jax): block fill PRNG, verify checksum
  parallel/   device-mesh sharded ingest (multi-chip / pod-slice scaling)
  models/     benchmark workload pipelines ("flagship" = HBM ingest pipeline)
"""

__version__ = "0.1.0"

# Messaging protocol version for master<->service compatibility checks.
# (Reference: HTTP_PROTOCOLVERSION, source/Common.h:91 — exact match required.)
HTTP_PROTOCOL_VERSION = "tpu-0.4"  # 0.4: fleet tracing (span-context
# propagation, SvcClockUsec skew sampling, /benchresult trace shipping)
