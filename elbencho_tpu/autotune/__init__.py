"""Closed-loop autotuning (``--autotune``; docs/autotuning.md).

The observability stack names the bottleneck (flight recorder -> run
doctor); this package acts on it: short bounded probe phases through
the unchanged coordinator/worker/service machinery, a doctor-driven
coordinate hill-climb over the bounded knob space, and a reproducible
tuned profile in the config-file format ``--configfile`` already loads
— plus the before/after doctor decomposition as proof of WHY the tuned
point wins. ROADMAP item 5; the sweep-tool face of the same executor
lives in ``tools/elbencho-tpu-sweep --knob``.
"""

from __future__ import annotations

import os
import time

from ..toolkits import logger
from .probe import ProbeExecutor, probe_phase_for, standalone_session
from .search import (NOISE_PCT, ProbeOutcome, STOP_EMPTY, TuneResult,
                     hill_climb)
from .space import AXIS_ATTRS, AXIS_FLAGS, KnobSpace

__all__ = [
    "AUTOTUNE_SCHEMA", "AXIS_ATTRS", "AXIS_FLAGS", "KnobSpace",
    "NOISE_PCT", "ProbeExecutor", "ProbeOutcome", "TuneResult",
    "build_autotune_block", "hill_climb", "probe_phase_for",
    "run_autotune", "standalone_session", "write_profile",
]

#: Autotune run-JSON block schema; keys are append-only like every
#: other schema-versioned block (Analysis, TailAnalysis, ...)
AUTOTUNE_SCHEMA = 1


def default_profile_path(cfg) -> str:
    """Default tuned-profile location: beside the JSON results when the
    run writes them, else the working directory."""
    if cfg.json_file_path:
        return os.path.join(os.path.dirname(cfg.json_file_path) or ".",
                            "elbencho-tpu-tuned.conf")
    return "elbencho-tpu-tuned.conf"


def write_profile(path: str, chosen: "dict[str, int]", cfg,
                  gain_pct: float, verdict: str) -> str:
    """Emit the tuned profile as an ini config file (``flag = value``
    lines) the CLI already loads via ``--configfile``/``-c`` — the
    reproducibility contract: re-running with the profile and WITHOUT
    --autotune runs at the tuned point."""
    lines = [
        "# elbencho-tpu tuned profile (written by --autotune)",
        f"# {time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())} "
        f"gain {gain_pct:+.1f}% vs defaults; final verdict: {verdict}",
        "# load with: elbencho-tpu -c THIS_FILE <your workload flags>",
    ]
    for name in sorted(chosen):
        lines.append(f"{AXIS_FLAGS[name]} = {chosen[name]}")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write("\n".join(lines) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def _compact_analysis(ana: "dict | None") -> "dict | None":
    """The doctor fields the before/after diff compares (the full
    Analysis blocks stay in the trajectory's probe recordings)."""
    if not ana:
        return None
    return {"Verdict": ana.get("Verdict", ""),
            "BottleneckStage": ana.get("BottleneckStage", ""),
            "StagePct": dict(ana.get("StagePct", {})),
            "StallsPerTpuOp": ana.get("StallsPerTpuOp", 0.0),
            "TuneHint": list(ana.get("TuneHint", []))}


def doctor_diff(baseline, best) -> "dict | None":
    """Before/after proof: the default point's doctor decomposition vs
    the tuned point's, with the stage shares that shrank/grew."""
    ana_a = _compact_analysis(baseline.analysis)
    ana_b = _compact_analysis(best.analysis)
    if ana_a is None and ana_b is None:
        return None
    causes: "list[str]" = []
    if ana_a and ana_b:
        for stage, pct_a in ana_a["StagePct"].items():
            pct_b = ana_b["StagePct"].get(stage, 0.0)
            if abs(pct_b - pct_a) >= 5.0:
                causes.append(f"{stage} share {pct_a:g}% -> {pct_b:g}%")
        if ana_a["Verdict"] != ana_b["Verdict"]:
            causes.append(f"verdict {ana_a['Verdict']} -> "
                          f"{ana_b['Verdict']}")
    return {"Default": ana_a, "Tuned": ana_b, "Changes": causes}


def build_autotune_block(result: TuneResult, axes_desc: "list[dict]",
                         phase_label: str, cfg,
                         profile_path: str) -> dict:
    """The schema-versioned Autotune run-JSON block. Keys are
    append-only, never reordered."""
    base, best = result.baseline, result.best

    def point(p):
        if p is None:
            return None
        return {"Values": dict(p.values),
                "MiBPerSec": round(p.rate_mibs, 2),
                "Verdict": p.verdict}

    # trajectory probes carry the doctor outcome that steered each move
    return {
        "Schema": AUTOTUNE_SCHEMA,
        "Phase": phase_label,
        "BudgetSecs": cfg.autotune_secs,
        "ProbeSecs": cfg.autotune_probe_secs,
        "Repeat": cfg.autotune_repeat,
        "ProbesUsed": result.probes_used,
        "StopReason": result.stop_reason,
        "Axes": axes_desc,
        "Default": point(base),
        "Chosen": point(best),
        "GainPct": result.gain_pct,
        "Trajectory": [p.describe() for p in result.trajectory],
        "ProfilePath": profile_path,
        "DoctorDiff": doctor_diff(base, best)
        if base is not None and best is not None else None,
    }


def run_autotune(coordinator) -> "dict | None":
    """The coordinator seam: probe, climb, emit the profile, apply the
    chosen values (fleet rebuilt so the REAL phases run tuned), and
    return the Autotune block. Returns None when this config admits no
    axes (nothing to tune — logged, never fatal)."""
    cfg = coordinator.cfg
    space = KnobSpace(cfg)
    phase = probe_phase_for(cfg)
    from ..phases import BenchMode, BenchPhase, phase_name
    if not space.axes or phase is None:
        logger.log(0, "AUTOTUNE: nothing to tune for this config "
                      "(no applicable axes) — running untuned")
        return None
    label = phase_name(phase, cfg.bench_mode == BenchMode.S3)
    logger.log(0, f"AUTOTUNE: budget {cfg.autotune_secs}s, "
                  f"{cfg.autotune_probe_secs}s probes "
                  f"(x{cfg.autotune_repeat}) on phase {label}; axes: "
                  + ", ".join(space.names()))
    axes_desc = space.describe()  # the PRE-tuning starting point
    executor = ProbeExecutor(
        coordinator, phase, cfg.autotune_probe_secs,
        # dir-mode write probes need the rank/dir namespace the run's
        # own MKDIRS phase would only create AFTER tuning — and the
        # namespace is per-RANK, so every probe that changes the thread
        # count needs it refreshed (the phase is idempotent: makedirs
        # exist_ok; the main run's journaled MKDIRS still runs after)
        ensure_dirs=(cfg.run_create_dirs
                     and phase == BenchPhase.CREATEFILES))
    try:
        result = hill_climb(
            space, executor.run, budget_secs=cfg.autotune_secs,
            now=time.monotonic, max_probes=cfg.autotune_probes,
            repeat=cfg.autotune_repeat,
            log=lambda msg: logger.log(0, f"AUTOTUNE: {msg}"))
    except BaseException:
        # restore, never leave probe state; no rebuild — the run is
        # aborting and the coordinator only interrupts/joins from here
        executor.finish(chosen=None, rebuild=False)
        raise
    chosen = result.chosen
    gain = result.gain_pct
    if gain <= 0 and result.baseline is not None \
            and result.baseline.ok and result.baseline.rate_mibs > 0:
        # never ship a config that lost to a MEASURED default: the
        # climb only adopts improvements, but a budget expiry right
        # after a noisy baseline could leave best == baseline with
        # gain 0 — keep the default values then (the block still
        # records the search so the trajectory is auditable). A FAILED
        # or zero-rate baseline must NOT reclaim the win: the climb's
        # best is a point that provably worked where the defaults did
        # not (gain stays 0 — no measured baseline to compare against).
        chosen = dict(result.baseline.values)
        result.best = result.baseline
    executor.finish(chosen=chosen)
    profile_path = cfg.autotune_profile_path or default_profile_path(cfg)
    best_verdict = result.best.verdict if result.best else "inconclusive"
    try:
        write_profile(profile_path, chosen, cfg, gain, best_verdict)
    except OSError as err:
        logger.log_error(f"--autotune-profile: cannot write "
                         f"{profile_path}: {err}")
        profile_path = ""
    block = build_autotune_block(result, axes_desc, label, cfg,
                                 profile_path)
    # stamp every later phase record of this run (JSON-only keys
    # AutotuneTuned/AutotuneGainPct; summarize-json Tuned/Gain% columns)
    cfg.autotune_applied = {"gain_pct": gain, "chosen": chosen,
                            "profile": profile_path}
    base_r = result.baseline.rate_mibs if result.baseline else 0.0
    best_r = result.best.rate_mibs if result.best else 0.0
    logger.log(0, f"AUTOTUNE: done ({result.stop_reason}, "
                  f"{result.probes_used} probes): "
                  f"{base_r:.1f} -> {best_r:.1f} MiB/s "
                  f"({gain:+.1f}%) at {chosen}"
               + (f"; profile: {profile_path}" if profile_path else ""))
    diff = block["DoctorDiff"] or {}
    for change in diff.get("Changes", []):
        logger.log(1, f"AUTOTUNE: doctor diff: {change}")
    return block
