"""Knob space: the bounded, typed axes the autotuner may move.

Each axis maps one CLI flag onto a geometric value ladder plus the
config-validation constraints that flag already enforces at
``BenchConfig.check`` time — the tuner must never propose a point the
CLI would reject (``--tpudepth`` > ``--iodepth`` under ``--tpudirect``,
``--tpubatch`` > 1 next to ``--tpuverify``, a poll interval at/above
the ``--svcleasesecs`` lease, ...). The space is derived from the
EFFECTIVE config, so axes that cannot apply to this run (TPU knobs
without a TPU path, control-plane knobs without a fleet) simply do not
exist rather than being probed and rejected at run time.

The axis set mirrors the doctor's verdict->axis hints
(telemetry/doctor.VERDICT_TUNE_AXES): every axis named by a hint is
defined here, and the search falls back to round-robin over whatever
subset this run's config admits.
"""

from __future__ import annotations

import dataclasses
import os

#: knob-space schema version (the Autotune run-JSON block embeds it)
SPACE_SCHEMA = 1

#: (axis name, BenchConfig attr, value ladder ascending, one-line doc) —
#: appended, never reordered; the ladder is geometric so a handful of
#: probes covers orders of magnitude
AXIS_DEFS = (
    ("threads", "num_threads", (1, 2, 4, 8, 16, 32, 64),
     "I/O worker threads per host (--threads)"),
    ("iodepth", "io_depth", (1, 2, 4, 8, 16, 32, 64),
     "async ops in flight per thread (--iodepth)"),
    ("tpudepth", "tpu_depth", (1, 2, 4, 8, 16, 32),
     "in-flight TPU transfer-ring depth (--tpudepth)"),
    ("tpubatch", "tpu_batch_blocks", (1, 2, 4, 8, 16),
     "blocks coalesced per host->HBM DMA (--tpubatch)"),
    ("svcupint", "svc_update_interval_ms", (100, 250, 500, 1000, 2000),
     "service status poll interval in ms (--svcupint; 'up' = slower "
     "polling, fewer control round-trips)"),
    ("svcfanout", "svc_fanout", (0, 2, 4, 8, 16),
     "aggregation-tree fanout (--svcfanout; 0 = flat)"),
)


@dataclasses.dataclass(frozen=True)
class Axis:
    name: str
    attr: str
    ladder: "tuple[int, ...]"
    doc: str


def _threads_cap() -> int:
    """Threads ladder upper bound: twice the machine's cores — past
    that, more threads only add scheduler pressure on every storage
    backend this benchmark drives."""
    return 2 * max(os.cpu_count() or 1, 1)


class KnobSpace:
    """The axes applicable to one effective config, with constraint-aware
    candidate stepping. Pure over the config snapshot it was built from
    (plus the current value map the search threads through), so the
    search loop and its tests never need a live coordinator."""

    def __init__(self, cfg):
        self.axes: "list[Axis]" = []
        self._cfg = cfg
        tpu_path = bool(getattr(cfg, "tpu_ids", None)
                        or cfg.tpu_ids_str or cfg.run_tpu_bench
                        or cfg.run_tpu_slice)
        for name, attr, ladder, doc in AXIS_DEFS:
            if name == "threads":
                cap = _threads_cap()
                ladder = tuple(v for v in ladder if v <= cap) or (1,)
            elif name == "iodepth":
                # a pinned sync engine locks iodepth to 1; object modes
                # use iodepth for connection parallelism, so the axis
                # stays for them
                if cfg.io_engine == "sync":
                    continue
            elif name in ("tpudepth", "tpubatch"):
                if not tpu_path:
                    continue
                if name == "tpubatch" and (cfg.do_tpu_verify
                                           or cfg.run_tpu_bench
                                           or cfg.run_tpu_slice):
                    # --tpubatch>1 is rejected next to --tpuverify, and
                    # the synthetic/slice phases drive their own batching
                    continue
            elif name in ("svcupint", "svcfanout"):
                if not getattr(cfg, "hosts", None):
                    continue
                if name == "svcfanout" and (
                        not cfg.svc_stream or len(cfg.hosts) < 3):
                    # config check rejects --svcfanout without
                    # --svcstream; a 2-host tree is a flat list anyway
                    continue
            self.axes.append(Axis(name, attr, ladder, doc))
        self._by_name = {a.name: a for a in self.axes}

    # -- value access --------------------------------------------------------

    def axis(self, name: str) -> "Axis | None":
        return self._by_name.get(name)

    def names(self) -> "list[str]":
        return [a.name for a in self.axes]

    def current_values(self) -> "dict[str, int]":
        """The effective starting point. ``tpudepth`` 0 means "ride
        --iodepth", so its effective value is the iodepth it rides."""
        out: "dict[str, int]" = {}
        for a in self.axes:
            val = int(getattr(self._cfg, a.attr))
            if a.name == "tpudepth" and not val:
                val = int(getattr(self._cfg, "io_depth", 1))
            out[a.name] = val
        return out

    # -- constraint validation ----------------------------------------------

    def invalid_reason(self, values: "dict[str, int]", name: str,
                       candidate: int) -> "str | None":
        """Why ``candidate`` on axis ``name`` cannot combine with the
        rest of ``values`` (None = valid). Mirrors BenchConfig.check so
        the tuner never proposes a config the CLI would refuse."""
        cfg = self._cfg
        if candidate < (0 if name == "svcfanout" else 1):
            return "below the axis minimum"
        if name == "threads":
            if candidate <= cfg.num_rwmix_read_threads:
                return ("--rwmixthr must stay below --threads "
                        "(needs at least one writer)")
        if name == "tpudepth" and cfg.use_tpu_direct:
            iodepth = values.get(
                "iodepth", int(getattr(cfg, "io_depth", 1)))
            if candidate > iodepth:
                return ("--tpudepth is clamped to --iodepth under "
                        "--tpudirect")
        if name == "iodepth" and cfg.use_tpu_direct:
            # partial value maps (sweep grids) fall back to the PINNED
            # config value, not 0 — a pinned --tpudepth must clamp a
            # swept iodepth exactly like a swept tpudepth would
            tpudepth = values.get("tpudepth",
                                  int(getattr(cfg, "tpu_depth", 0)))
            if tpudepth and candidate < tpudepth:
                return ("--iodepth below the current --tpudepth would "
                        "silently re-clamp the ring under --tpudirect")
        if name == "svcupint":
            lease_ms = cfg.svc_lease_secs * 1000
            if lease_ms and candidate >= lease_ms:
                return ("--svcupint must stay below --svcleasesecs "
                        "(every poll renews the lease)")
        if name == "svcfanout" and candidate >= max(
                len(getattr(cfg, "hosts", []) or []), 1):
            return "fanout at/above the host count is a flat tree"
        return None

    def step(self, values: "dict[str, int]", name: str,
             direction: int) -> "int | None":
        """Next valid ladder value from ``values[name]`` in ``direction``
        (+1 up, -1 down), skipping constraint-invalid rungs. None when
        the ladder (or every remaining rung) is exhausted that way."""
        axis = self._by_name[name]
        cur = values[name]
        if direction > 0:
            rungs = [v for v in axis.ladder if v > cur]
        else:
            rungs = [v for v in reversed(axis.ladder) if v < cur]
        for cand in rungs:
            if self.invalid_reason(values, name, cand) is None:
                return cand
        return None

    def describe(self) -> "list[dict]":
        """JSON-able axis table for the Autotune block / --dryrun."""
        vals = self.current_values()
        return [{"Axis": a.name, "Flag": f"--{a.name}",
                 "Current": vals[a.name], "Ladder": list(a.ladder),
                 "Doc": a.doc} for a in self.axes]


#: BenchConfig attr per axis name (profile emission + probe overlays)
AXIS_ATTRS = {name: attr for name, attr, _l, _d in AXIS_DEFS}

#: CLI flag spelling per axis name (tuned-profile emission: the profile
#: is an ini config file of ``flag = value`` lines --configfile loads)
AXIS_FLAGS = {
    "threads": "threads", "iodepth": "iodepth", "tpudepth": "tpudepth",
    "tpubatch": "tpubatch", "svcupint": "svcupint",
    "svcfanout": "svcfanout",
}
