"""Probe executor: short bounded benchmark phases at candidate configs.

A probe is ONE existing table phase run through the UNCHANGED
coordinator/worker/service machinery — exactly the scenario engine's
per-step overlay discipline (apply attrs from a base snapshot, rebuild
the worker fleet so master mode re-ships the changed config over
/preparephase, run, restore). What makes it a probe rather than a
measured phase:

- it is TIME-BOXED (``--autotune-probesecs`` via the existing
  ``--timelimit`` interrupt machinery, so a probe at a terrible config
  costs seconds, not the workload's natural length);
- its results never reach the run's result files (res/csv/json paths
  are blanked for the probe's duration) — probes are search traffic,
  not published numbers;
- the flight recorder is ALWAYS armed (a private recording in the
  run's temp dir when the user didn't pass ``--flightrec``) because the
  doctor's stage decomposition is the search signal; the user's own
  recording, when present, is parked during probes so tuning traffic
  never pollutes it;
- probes are unjournaled (run_benchmark_phase directly, never the
  journaled wrapper) and every ``--autotune-*`` knob is
  FINGERPRINT_EXCLUDEd, so --journal/--resume semantics are untouched.

The same executor drives both ``--autotune`` (search.hill_climb picks
the points) and ``tools/elbencho-tpu-sweep --knob`` (an explicit grid
picks them).
"""

from __future__ import annotations

import contextlib
import os
import tempfile

from ..phases import BenchPhase
from ..toolkits import logger
from ..workers.shared import WorkerException
from .search import ProbeOutcome
from .space import AXIS_ATTRS

#: config attrs forced for the duration of every probe (beyond the
#: candidate's axis values); saved/restored with the same base-snapshot
#: discipline the scenario engine uses
_PROBE_CONTROL_ATTRS = (
    "time_limit_secs", "res_file_path", "csv_file_path",
    "json_file_path", "disable_live_stats", "next_phase_delay_secs",
)


def probe_phase_for(cfg) -> "BenchPhase | None":
    """The phase probes run: the FIRST data phase of this run's plan, so
    a write-then-read run probes the self-sufficient write leg and a
    read-only run probes the read leg against its existing dataset."""
    if cfg.run_create_files:
        return BenchPhase.CREATEFILES
    if cfg.run_read_files:
        return BenchPhase.READFILES
    return None


class ProbeExecutor:
    """Runs probes against a live Coordinator. Construction parks the
    user's flight recorder and arms a probe recorder; callers MUST end
    with ``finish()`` — it restores the base config/recorder and
    applies the chosen values for the real run. The context-manager
    form only covers the ABNORMAL exit (restore-without-apply on an
    in-flight exception); a clean exit still requires finish()."""

    def __init__(self, coordinator, phase: BenchPhase,
                 probe_secs: int, keep_flightrec_path: str = "",
                 ensure_dirs: bool = False):
        self.coord = coordinator
        self.phase = phase
        self.probe_secs = max(int(probe_secs), 1)
        # dir-mode write probes: refresh the per-rank dir namespace
        # after every fleet rebuild (a threads move changes the ranks)
        self.ensure_dirs = ensure_dirs
        self.num_probes = 0
        self._base: "dict[str, object]" = {}
        cfg = coordinator.cfg
        for attr in _PROBE_CONTROL_ATTRS:
            self._base[attr] = getattr(cfg, attr)
        # the fleet the coordinator prepared was built against the BASE
        # config; the first probe at base values can reuse it
        self._built_values: "dict | None" = None
        self._saved_flightrec = coordinator._flightrec
        rec_dir = keep_flightrec_path or tempfile.mkdtemp(
            prefix="elbencho_tpu_autotune_")
        self._rec_dir_owned = not keep_flightrec_path
        self._rec_dir = rec_dir
        self._probe_rec_path = os.path.join(rec_dir, "probe.rec")

    # -- probing -------------------------------------------------------------

    def run(self, values: "dict[str, int]") -> ProbeOutcome:
        """One probe at the full axis-value map. A worker error marks
        the outcome failed (the search treats it as a rejected move)
        and rebuilds the fleet so the next probe starts clean."""
        from ..telemetry.flightrec import FlightRecorder
        coord = self.coord
        cfg = coord.cfg
        self.num_probes += 1
        for name, val in values.items():
            attr = AXIS_ATTRS[name]
            self._base.setdefault(attr, getattr(cfg, attr))
            setattr(cfg, attr, val)
        cfg.time_limit_secs = self.probe_secs
        cfg.res_file_path = cfg.csv_file_path = cfg.json_file_path = ""
        cfg.disable_live_stats = True
        cfg.next_phase_delay_secs = 0
        # fresh probe recording each probe: finish_phase reads only the
        # in-memory series/totals, the file is just the doctor contract
        probe_rec = FlightRecorder(self._probe_rec_path, cfg,
                                   role="autotune")
        coord._flightrec = probe_rec
        try:
            if self._built_values != values:
                # geometry and wire-relevant knobs changed: re-prepare
                # the fleet (master mode re-ships the config exactly
                # like a scenario overlay step)
                coord._rebuild_manager()
                if self.ensure_dirs:
                    coord.run_benchmark_phase(BenchPhase.CREATEDIRS)
                self._built_values = dict(values)
            else:
                coord.statistics.flightrec = probe_rec
            coord.run_benchmark_phase(self.phase)
        except WorkerException as err:
            self._built_values = None  # failed fleet: rebuild next time
            if cfg.hosts:
                with contextlib.suppress(WorkerException, OSError):
                    coord._rebuild_manager()
                    self._built_values = dict(values)
            return ProbeOutcome(0.0, ok=False, error=str(err))
        finally:
            # the fleet-merged counter state at probe end, BEFORE the
            # recorder is dropped — the truncated-probe re-analysis
            # below needs it
            from ..telemetry.flightrec import FLEET
            probe_totals = dict(probe_rec._prev.get(FLEET, {}))
            probe_rec.close()
            coord._flightrec = self._saved_flightrec
        res = coord._last_phase_results
        if res is None:
            return ProbeOutcome(0.0, ok=False, error="no phase results")
        elapsed_usec = res.last_done_usec
        analysis = res.analysis or {}
        if not elapsed_usec:
            # the probe hit its time limit: interrupted workers record
            # no elapsed, so the honest window is the probe box itself
            # — and the doctor's verdict must be recomputed against it
            # (the in-run analysis saw wall 0 and said inconclusive)
            elapsed_usec = self.probe_secs * 1_000_000
            from ..telemetry.doctor import analyze_phase
            analysis = analyze_phase(res.phase_name, probe_totals,
                                     elapsed_usec, res.num_workers)
        rate = res.final["bytes"] / (elapsed_usec / 1e6) / (1 << 20)
        return ProbeOutcome(
            rate_mibs=round(rate, 2),
            verdict=analysis.get("Verdict", "inconclusive"),
            analysis=analysis or None)

    # -- teardown ------------------------------------------------------------

    def finish(self, chosen: "dict[str, int] | None" = None,
               rebuild: bool = True) -> None:
        """Restore the base config (and the user's flight recorder),
        then apply ``chosen`` axis values and rebuild the fleet so the
        real run executes at the tuned point. ``rebuild=False`` skips
        the fleet re-prepare — for callers that tear the coordinator
        down right after (sweep-tool teardown, abort paths), where a
        rebuilt fleet would only be joined again immediately."""
        coord = self.coord
        cfg = coord.cfg
        for attr, val in self._base.items():
            setattr(cfg, attr, val)
        coord._flightrec = self._saved_flightrec
        if chosen:
            for name, val in chosen.items():
                setattr(cfg, AXIS_ATTRS[name], val)
        try:
            if rebuild:
                coord._rebuild_manager()
        finally:
            if self._rec_dir_owned:
                import shutil
                shutil.rmtree(self._rec_dir, ignore_errors=True)

    def __enter__(self) -> "ProbeExecutor":
        return self

    def __exit__(self, exc_type, _exc, _tb) -> None:
        if exc_type is None:
            return
        # abnormal exit: restore without applying anything (no rebuild
        # — the caller is aborting/tearing down)
        self.finish(chosen=None, rebuild=False)


@contextlib.contextmanager
def standalone_session(cfg, probe_secs: int):
    """Probe session for tools (elbencho-tpu-sweep --knob): owns the
    whole coordinator lifecycle around a bare ProbeExecutor. The config
    must be derived+checked; phases come from probe_phase_for."""
    from ..coordinator import Coordinator
    phase = probe_phase_for(cfg)
    if phase is None:
        raise ValueError("knob sweep needs a write or read phase "
                         "(-w/-r) to probe")
    coord = Coordinator(cfg)
    if cfg.hosts:
        from ..service.remote_worker import wait_for_services_ready
        wait_for_services_ready(cfg.hosts, cfg.service_port,
                                cfg.svc_wait_secs)
    coord.manager.prepare_threads()
    executor = ProbeExecutor(coord, phase, probe_secs)
    try:
        yield executor
    finally:
        try:
            # no rebuild: the fleet is joined right below anyway
            executor.finish(chosen=None, rebuild=False)
        except WorkerException as err:  # teardown must not mask results
            logger.log_error(f"knob sweep teardown: {err}")
        coord.manager.join_all_threads()
        coord.statistics.close()
