"""Doctor-driven coordinate hill-climb over the knob space.

The loop is deliberately dumb and auditable: probe the current point,
read the doctor's verdict, move the ONE axis the verdict names (the
machine-readable hint table telemetry/doctor.VERDICT_TUNE_AXES), keep
the move iff the repeat-probe MEDIAN beats the incumbent by more than
the noise margin, and stop on plateau (every admissible move rejected),
budget expiry, or the probe cap. An unhinted verdict (inconclusive,
retry/tail/straggler-bound — problems no knob fixes) falls back to
round-robin over the remaining axes, so the tuner still makes progress
when the doctor cannot point.

Everything here is pure over two injected callables — ``run_probe``
(one bounded probe at a full value map -> ProbeOutcome) and ``now`` —
which is what lets tests/test_autotune.py prove convergence against a
deterministic fake doctor without ever running a benchmark.
"""

from __future__ import annotations

import dataclasses

#: default noise margin: a candidate must beat the incumbent's median
#: rate by this many percent to be adopted — repeat-probe medians plus
#: this gate are what keep filesystem-cache jitter from walking the
#: tuner to a random corner of the space
NOISE_PCT = 3.0

#: stop reasons (Autotune block "StopReason"; appended, never renamed)
STOP_PLATEAU = "plateau"
STOP_BUDGET = "budget"
STOP_PROBES = "probe-limit"
STOP_EMPTY = "no-axes"


@dataclasses.dataclass
class ProbeOutcome:
    """One probe's result as the search sees it."""

    rate_mibs: float
    verdict: str = "inconclusive"
    ok: bool = True
    error: str = ""
    analysis: "dict | None" = None


@dataclasses.dataclass
class TrajectoryPoint:
    index: int
    values: "dict[str, int]"
    rate_mibs: float
    verdict: str
    repeats: "list[float]"
    ok: bool
    axis: str = ""          # the axis this probe moved ("" = baseline)
    accepted: bool = False
    error: str = ""
    # the median repeat's full doctor Analysis (None when the probe ran
    # without one) — what the before/after DoctorDiff compares
    analysis: "dict | None" = None

    def describe(self) -> dict:
        return {"Probe": self.index, "Values": dict(self.values),
                "MiBPerSec": round(self.rate_mibs, 2),
                "Verdict": self.verdict,
                "Repeats": [round(r, 2) for r in self.repeats],
                "Axis": self.axis, "Accepted": self.accepted,
                "Ok": self.ok, **({"Error": self.error}
                                  if self.error else {})}


@dataclasses.dataclass
class TuneResult:
    baseline: "TrajectoryPoint | None"
    best: "TrajectoryPoint | None"
    trajectory: "list[TrajectoryPoint]"
    stop_reason: str
    probes_used: int

    @property
    def gain_pct(self) -> float:
        if self.baseline is None or self.best is None \
                or self.baseline.rate_mibs <= 0:
            return 0.0
        return round(100.0 * (self.best.rate_mibs
                              / self.baseline.rate_mibs - 1.0), 1)

    @property
    def chosen(self) -> "dict[str, int]":
        return dict(self.best.values) if self.best is not None else {}


def _median_outcome(outcomes: "list[ProbeOutcome]") \
        -> "tuple[float, ProbeOutcome]":
    """(median rate, the outcome carrying it) over the OK repeats; a
    fully failed set keeps the last failure for its error text."""
    oks = sorted((o for o in outcomes if o.ok), key=lambda o: o.rate_mibs)
    if not oks:
        return 0.0, outcomes[-1]
    med = oks[len(oks) // 2]
    return med.rate_mibs, med


def hill_climb(space, run_probe, budget_secs: float, now,
               max_probes: int = 0, repeat: int = 1,
               noise_pct: float = NOISE_PCT,
               verdict_axes=None, log=None) -> TuneResult:
    """Coordinate hill-climb. ``space`` is a KnobSpace (or anything with
    ``names()``/``current_values()``/``step()``), ``run_probe(values)``
    returns a ProbeOutcome, ``now()`` is the clock the budget is
    measured on. ``verdict_axes`` maps a doctor verdict to the axis
    preference list (defaults to doctor.VERDICT_TUNE_AXES)."""
    if verdict_axes is None:
        from ..telemetry.doctor import VERDICT_TUNE_AXES
        verdict_axes = VERDICT_TUNE_AXES
    log = log or (lambda _msg: None)
    repeat = max(int(repeat), 1)
    t0 = now()
    trajectory: "list[TrajectoryPoint]" = []
    probes_used = 0

    def measure(values: "dict[str, int]", axis: str) -> TrajectoryPoint:
        nonlocal probes_used
        outcomes = []
        for _ in range(repeat):
            outcomes.append(run_probe(dict(values)))
            probes_used += 1
        med_rate, med = _median_outcome(outcomes)
        point = TrajectoryPoint(
            index=len(trajectory), values=dict(values),
            rate_mibs=med_rate, verdict=med.verdict,
            repeats=[o.rate_mibs for o in outcomes if o.ok],
            ok=any(o.ok for o in outcomes), axis=axis,
            error=med.error, analysis=med.analysis)
        trajectory.append(point)
        return point

    names = space.names()
    if not names:
        return TuneResult(None, None, trajectory, STOP_EMPTY, 0)

    cur = space.current_values()
    baseline = measure(cur, "")
    baseline.accepted = True
    best = baseline
    log(f"baseline: {baseline.rate_mibs:.1f} MiB/s "
        f"(verdict: {baseline.verdict}) at {cur}")

    # (axis, direction) moves rejected since the last improvement;
    # when every admissible move is in here, the climb has plateaued
    exhausted: "set[tuple[str, int]]" = set()
    rr = 0  # round-robin pointer for unhinted verdicts

    def pick_move(verdict: str) -> "tuple[str, int] | None":
        nonlocal rr
        hinted = [a for a in verdict_axes.get(verdict, ()) if a in names]
        for axis in hinted:
            for direction in (1, -1):
                if (axis, direction) not in exhausted:
                    return axis, direction
        # round-robin fallback: unhinted (or fully exhausted hint set)
        order = [(names[(rr + i) % len(names)], d)
                 for i in range(len(names)) for d in (1, -1)]
        for axis, direction in order:
            if (axis, direction) not in exhausted:
                rr = (names.index(axis) + 1) % len(names)
                return axis, direction
        return None

    stop = STOP_PLATEAU
    while True:
        if now() - t0 >= budget_secs:
            stop = STOP_BUDGET
            break
        if max_probes and probes_used + repeat > max_probes:
            stop = STOP_PROBES
            break
        move = pick_move(best.verdict)
        if move is None:
            stop = STOP_PLATEAU
            break
        axis, direction = move
        cand_val = space.step(cur, axis, direction)
        if cand_val is None:
            exhausted.add((axis, direction))
            continue
        cand = dict(cur)
        cand[axis] = cand_val
        point = measure(cand, axis)
        improved = point.ok and point.rate_mibs \
            > best.rate_mibs * (1.0 + noise_pct / 100.0)
        log(f"probe {point.index}: {axis} {cur[axis]} -> {cand_val}: "
            f"{point.rate_mibs:.1f} MiB/s (verdict: {point.verdict}) "
            f"{'ACCEPTED' if improved else 'rejected'}")
        if improved:
            point.accepted = True
            cur = cand
            best = point
            # a new incumbent reopens every direction: moves that lost
            # against the OLD point may win from here
            exhausted = set()
        else:
            exhausted.add((axis, direction))
    return TuneResult(baseline, best, trajectory, stop, probes_used)
