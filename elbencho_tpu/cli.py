"""CLI entry point (reference: source/Main.cpp:14-69 — parse args,
help/version handling, delegate to Coordinator)."""

from __future__ import annotations

import sys

from . import __version__
from .config.args import (FLAG_DEFS, HELP_CATEGORIES, ConfigError, parse_cli)
from .phases import BenchMode, BenchPathType
from .toolkits import logger
from .toolkits.units import format_bytes


def _print_help(category: "str | None") -> None:
    print(f"elbencho-tpu {__version__} — TPU-native distributed storage "
          f"benchmark\n")
    print("Usage: elbencho-tpu [OPTIONS] PATH [MORE_PATHS]\n")
    tier_info = {
        "essential": "Basic options", "multi": "Multi-dir/custom-tree",
        "large": "Large file / random I/O", "dist": "Distributed mode",
        "s3": "S3/object storage", "tpu": "TPU HBM data path",
        "misc": "Miscellaneous"}
    for cat, title in tier_info.items():
        if category is not None and cat != category:
            continue
        print(f"{title}:")
        for flag, short, _dest, kind, default, fcat, help_txt in FLAG_DEFS:
            if fcat != cat:
                continue
            names = f"--{flag}" + (f", -{short}" if short else "")
            arg = "" if kind == "bool" else " V"
            print(f"  {names + arg:<26} {help_txt}")
        print()
    if category is None or category == "essential":
        print("Help tiers: --help-multi --help-large --help-dist --help-s3 "
              "--help-tpu --help-all")
        print("\nExamples:")
        print("  elbencho-tpu -w -r -t 4 -b 1M -s 10g /mnt/scratch/file")
        print("  elbencho-tpu -w -d -t 8 -n 2 -N 4 -s 4K /mnt/scratch")
        print("  elbencho-tpu -r -b 1M -s 10g --tpuids 0 /mnt/file  "
              "# read into TPU HBM")
        print("  elbencho-tpu --service --foreground --port 1611")
        print("  elbencho-tpu --hosts h1,h2 -w -t 16 -s 1g /mnt/shared")
        print("  elbencho-tpu --scenario epochs --scenario-opt "
              "epochs=4,window=64M \\")
        print("      -t 8 -n 1 -N 64 -s 16M /mnt/dataset  "
              "# training-ingest scenario")


def _print_dry_run(cfg) -> None:
    """--dryrun: show workload totals without running (reference:
    Statistics::printDryRunInfo, Statistics.cpp:2865)."""
    from .workers.manager import WorkerManager
    manager = WorkerManager(cfg)
    print("Dry run — workload overview:")
    print(f"  bench mode     : {cfg.bench_mode.name}")
    print(f"  path type      : {cfg.bench_path_type.name}")
    print(f"  hosts          : {len(cfg.hosts) or 1}")
    print(f"  threads/host   : {cfg.num_threads}")
    print(f"  dataset threads: {cfg.num_dataset_threads}")
    if cfg.tpu_ids:
        print(f"  tpu chips      : {cfg.tpu_ids}")
    from .phases import phase_name
    if cfg.scenario:
        # --scenario --dryrun: show the expanded step plan (the exact
        # list the journal fingerprints) without running anything
        from .scenarios import expand_scenario
        plan = expand_scenario(cfg)
        print(f"  scenario       : {plan.name} ({len(plan.steps)} steps)")
        for step in plan.steps:
            overlay = " ".join(f"{k}={v}"
                               for k, v in sorted(step.overlay.items()))
            print(f"    {step.label:<18} {phase_name(step.phase):<10}"
                  f" {overlay}")
        return
    for phase in cfg.enabled_phases():
        entries, num_bytes = manager.get_phase_num_entries_and_bytes(phase)
        print(f"  {phase_name(phase):<10}: {entries} entries, "
              f"{format_bytes(num_bytes)}B")


def _run_tree_scan(cfg) -> int:
    """--treescan DIR --treefile OUT: build a treefile from a real tree
    (reference: --treescan + tools/elbencho-scan-path). An s3:// or
    gs:// scan path lists the BUCKET into the treefile instead
    (reference: ProgArgs::scanCustomTree S3 branch, ProgArgs.cpp:2799 +
    S3Tk::scanCustomTree)."""
    import os
    from .toolkits.file_tk import scan_tree, write_treefile
    if not cfg.tree_file_path:
        print("ERROR: --treescan requires --treefile OUT for the result",
              file=sys.stderr)
        return 1
    if cfg.tree_scan_path.startswith(("s3://", "gs://")):
        return _run_bucket_tree_scan(cfg)
    if not os.path.isdir(cfg.tree_scan_path):
        print(f"ERROR: --treescan path is not a directory: "
              f"{cfg.tree_scan_path}", file=sys.stderr)
        return 1
    dirs, files, needs_b64 = scan_tree(cfg.tree_scan_path)
    write_treefile(cfg.tree_file_path, dirs, files, use_base64=needs_b64)
    total = sum(e.total_len for e in files.elems)
    print(f"Scanned {cfg.tree_scan_path}: {dirs.num_paths} dirs, "
          f"{files.num_paths} files, {format_bytes(total)}B total -> "
          f"{cfg.tree_file_path}")
    return 0


def _run_bucket_tree_scan(cfg) -> int:
    """--treescan s3://bucket[/prefix] (or gs://): paginated object
    listing written as treefile "f <size> <name>" lines, so an existing
    bucket becomes a custom-tree workload (reference:
    S3Tk::scanCustomTree, S3Tk.cpp:330-430)."""
    from .toolkits.path_store import PathStore, PathStoreElem
    from .toolkits.file_tk import write_treefile
    from .toolkits.s3_tk import S3Error, make_client_for_rank

    scheme, _, rest = cfg.tree_scan_path.partition("://")
    bucket, _, prefix = rest.partition("/")
    if not bucket:
        print(f"ERROR: --treescan {scheme}:// path needs a bucket",
              file=sys.stderr)
        return 1
    # the scan path is not a bench path, so it never participated in
    # config derivation's backend selection: the scheme picks the
    # client here, and a conflicting pre-derived backend (e.g. gs://
    # scan with --s3endpoints) is the same ambiguity bench paths
    # reject explicitly
    want_backend = "gcs" if scheme == "gs" else "s3"
    have_backend = cfg.object_backend or ""
    if have_backend and have_backend != want_backend:
        print(f"ERROR: --treescan {scheme}:// conflicts with the "
              f"{have_backend!r} object backend configured by the other "
              f"flags; pick one explicitly with --objectbackend",
              file=sys.stderr)
        return 1
    if want_backend == "gcs":
        cfg.object_backend = "gcs"
    try:
        client = make_client_for_rank(cfg, 0)
    except ValueError as err:  # e.g. no --s3endpoints configured
        print(f"ERROR: {err}", file=sys.stderr)
        return 1
    files = PathStore()
    # keys go into the store directly — formatting them through treefile
    # text lines would corrupt names with newlines/leading whitespace
    # before the base64 decision is even made
    needs_b64 = False
    token = ""
    try:
        while True:
            entries, token = client.list_objects_entries(
                bucket, prefix=prefix, continuation_token=token)
            for key, size in entries:
                files.elems.append(PathStoreElem(
                    key, total_len=size, range_start=0, range_len=size))
                if key != key.strip() or "\n" in key or "\r" in key:
                    needs_b64 = True
            if not token:
                break
    except S3Error as err:
        print(f"ERROR: bucket treescan failed: {err}", file=sys.stderr)
        return 1
    finally:
        client.close()
    write_treefile(cfg.tree_file_path, PathStore(), files,
                   use_base64=needs_b64)
    total = sum(e.total_len for e in files.elems)
    print(f"Scanned {scheme}://{bucket}"
          f"{'/' + prefix if prefix else ''}: {files.num_paths} objects, "
          f"{format_bytes(total)}B total -> {cfg.tree_file_path}")
    return 0


def main(argv: "list[str] | None" = None) -> int:
    try:
        cfg, ns = parse_cli(argv)
    except ConfigError as err:
        print(f"ERROR: {err}", file=sys.stderr)
        return 1
    if ns.version:
        from .utils.native import get_native_engine
        native = get_native_engine(try_build=False)  # no compile here
        engine = "not built (make -C csrc)"
        if native is not None:
            engine = native.version()
            if native.uring_supported():
                engine += ", io_uring ok"
        print(f"elbencho-tpu {__version__} (jax-based TPU data path; "
              f"native engine: {engine})")
        return 0
    for help_flag, cat in HELP_CATEGORIES.items():
        if getattr(ns, help_flag.replace("-", "_")):
            _print_help(cat)
            return 0
    if not cfg.paths and not (cfg.run_as_service or cfg.quit_services
                              or cfg.interrupt_services
                              or cfg.run_netbench or cfg.tree_scan_path
                              or cfg.run_tpu_bench):
        _print_help("essential")
        return 1
    try:
        # master mode: paths live on the service hosts, don't probe locally
        # (services reply with BenchPathInfo; the manager then checks
        # consistency and re-validates). Probe only for true local runs.
        cfg.derive(probe_paths=False)
        if not cfg.hosts:
            if cfg.hosts_str or cfg.hosts_file_path:
                raise ConfigError(
                    "hosts were specified but none are usable "
                    "(empty hosts file or --numhosts 0?)")
            if cfg.bench_mode == BenchMode.POSIX and cfg.paths:
                cfg.probe_local_paths()
        cfg.check()
    except (ConfigError, OSError) as err:
        print(f"ERROR: {err}", file=sys.stderr)
        return 1
    logger.set_log_level(cfg.log_level)
    if cfg.csv_file_path:
        from .stats.statistics import Statistics
        try:  # fail before any phase runs, like the reference
            Statistics.check_csv_file_compatibility(cfg)
        except (ValueError, OSError) as err:
            print(f"ERROR: {err}", file=sys.stderr)
            return 1
    if cfg.tree_scan_path:
        return _run_tree_scan(cfg)
    if cfg.do_dry_run:
        _print_dry_run(cfg)
        return 0
    from .coordinator import Coordinator
    return Coordinator(cfg).main()


if __name__ == "__main__":
    sys.exit(main())
