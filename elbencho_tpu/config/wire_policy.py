"""Wire/fingerprint classification of every BenchConfig field.

THE single source of truth the ``wire-hygiene`` lint rule checks the
implementation against: every config field is declared in exactly one
class below, and the rule cross-checks the declaration against what
``BenchConfig.to_service_dict`` actually strips/rewrites and what
``journal.FINGERPRINT_EXCLUDE`` actually excludes. Adding a field
without classifying it here fails ``make lint`` — the mechanical end of
the "is this knob wire-relevant?" review question that used to be
re-litigated one regression at a time.

The two independent axes:

- **wire**: does the field ship meaningfully to services over POST
  /preparephase, or does the master neutralize it first?
- **fingerprint**: does the field change *what data the run produces*
  (parity-relevant, fingerprinted for --resume) or only *how the run
  is watched* (excluded)?

Classes (field appears in exactly one):

``MASTER_ONLY``     neutralized in to_service_dict AND excluded from
                    the fingerprint — pure master-side machinery
                    (result files, hosts lists, the journal itself,
                    the streaming-transport shape).
``MASTER_FINGERPRINTED``
                    neutralized on the wire but still fingerprinted —
                    the scenario plan: services receive each step's
                    EFFECTIVE config, never the plan, yet a changed
                    plan must invalidate a --resume.
``PER_HOST``        rewritten (not neutralized) per service instance
                    by to_service_dict — rank offsets, per-service
                    chip pinning, netbench topology.
``WIRE_OBSERVABILITY``
                    ships untouched but excluded from the fingerprint
                    — shapes how a run is watched (live stats, traces,
                    telemetry, control-plane resilience), never what
                    it produces.
``WIRE``            ships untouched and fingerprinted — workload
                    geometry, access pattern, backends, TPU path: the
                    parity-relevant payload.
"""

from __future__ import annotations

MASTER_ONLY = frozenset({
    "adopt_run", "autotune_probe_secs", "autotune_probes",
    "autotune_profile_path", "autotune_repeat", "autotune_secs",
    "csv_file_path", "flightrec_file_path", "hosts_file_path",
    "hosts_str", "journal_file_path", "json_file_path", "res_file_path",
    "resume_run", "run_as_service", "standby_str", "svc_fanout",
    "svc_stalled_secs", "svc_stream", "svc_tolerant_hosts",
})

MASTER_FINGERPRINTED = frozenset({
    "scenario", "scenario_opts_str",
})

PER_HOST = frozenset({
    "netbench_servers_str", "netbench_total_hosts",
    "num_dataset_threads_override", "rank_offset", "tpu_ids_str",
    "tpu_multihost",
})

WIRE_OBSERVABILITY = frozenset({
    "config_file_path", "disable_live_stats", "do_dry_run",
    "ignore_0usec_errors", "interrupt_services", "live_csv_extended",
    "live_csv_file_path", "live_json_extended", "live_json_file_path",
    "live_stats_interval_ms", "log_level", "no_csv_labels",
    "num_latency_percentile_9s", "op_sample_rate", "ops_log_lock",
    "ops_log_path", "quit_services", "run_service_in_foreground",
    "show_all_elapsed", "show_cpu_util", "show_latency",
    "show_latency_histogram", "show_latency_percentiles",
    "show_svc_elapsed", "show_svc_ping",
    "single_line_live_stats_no_erase", "slow_ops_k", "svc_adopt_secs",
    "svc_lease_secs",
    "svc_num_retries", "svc_password_file", "svc_retry_budget_secs",
    "svc_update_interval_ms", "svc_wait_secs", "telemetry",
    "telemetry_port", "tpu_profile_dir", "trace_file_path",
    "trace_fleet", "trace_sample", "trace_ship_cap_mib",
    "use_single_line_live_stats",
})

WIRE = frozenset({
    # workload selection + geometry
    "run_create_files", "run_read_files", "run_create_dirs",
    "run_delete_dirs", "run_delete_files", "run_stat_files",
    "run_stat_dirs", "run_sync_phase", "run_drop_caches_phase",
    "run_netbench", "num_threads", "num_dirs", "num_files", "file_size",
    "block_size", "paths",
    # I/O engine + resilience knobs that change op sequencing
    "io_depth", "io_engine", "io_num_retries", "io_retry_budget_secs",
    "io_timeout_secs", "io_sqpoll", "io_sqpoll_idle_ms",
    "pool_registration",
    # access pattern
    "use_random_offsets", "random_amount", "no_random_align",
    "rand_offset_algo", "do_reverse_seq_offsets", "do_strided_access",
    "do_infinite_io_loop",
    # file handling
    "use_direct_io", "no_direct_io_check", "use_mmap", "use_file_locks",
    "fadvise_flags", "madvise_flags", "do_truncate",
    "do_truncate_to_size", "do_prealloc_file", "no_fd_sharing",
    "do_dir_sharing", "show_dirs_stats", "ignore_delete_errors",
    "use_hdfs", "no_path_expansion", "integrity_check_salt",
    "do_direct_verify", "do_read_inline", "block_variance_pct",
    "block_variance_algo", "rwmix_read_pct", "num_rwmix_read_threads",
    "rwmix_thr_read_pct", "limit_read_bps", "limit_write_bps",
    "iterations", "time_limit_secs", "next_phase_delay_secs",
    "bench_label", "use_base10_units",
    # distributed topology (what services do, not how they're watched)
    "num_hosts_limit", "service_port", "no_shared_service_path",
    "rotate_hosts_num", "start_time_utc", "netdevs_str", "servers_str",
    "clients_str", "servers_file_path", "clients_file_path",
    "num_netbench_servers", "netbench_response_size",
    "sock_recv_buf_size", "sock_send_buf_size",
    # TPU data path
    "assign_tpu_per_service", "use_tpu_direct", "tpu_batch_blocks",
    "tpu_depth", "tpu_stream", "tpu_dispatch_budget_usec",
    "tpu_fallback", "do_tpu_verify", "tpu_hbm_limit_pct",
    "run_tpu_bench", "tpu_bench_pattern", "run_tpu_slice",
    "mesh_shape_str", "redist_spec", "use_pod_hosts", "numa_zones_str",
    "cpu_cores_str",
    # custom tree
    "tree_file_path", "use_custom_tree_rand",
    "use_custom_tree_round_robin", "tree_round_up_size",
    "file_share_size", "tree_scan_path", "do_stat_inline",
    # object storage
    "s3_endpoints_str", "s3_access_key", "s3_secret_key",
    "s3_session_token", "s3_region", "s3_object_prefix",
    "s3_rand_obj_select", "s3_no_mpu", "use_s3_client_singleton",
    "run_list_objects_num", "run_list_objects_parallel",
    "do_list_objects_verify", "run_multi_delete_num",
    "s3_virtual_hosted", "s3_sign_policy", "s3_max_connections",
    "s3_mpu_sharing", "run_s3_mpu_complete_phase", "s3_cred_file_path",
    "s3_cred_list", "s3_num_retries", "run_s3_acl_put",
    "run_s3_acl_get", "s3_acl_grantee", "s3_acl_grantee_type",
    "s3_acl_grants", "do_s3_acl_put_inline", "do_s3_acl_verify",
    "s3_checksum_algo", "s3_no_mpu_completion",
    "s3_ignore_part_num_check", "s3_ignore_mpu_completion_404",
    "s3_fast_get", "s3_fast_put", "s3_no_compression",
    "s3_mpu_size_variance", "s3_log_level", "s3_log_prefix",
    "run_s3_bucket_acl_put", "run_s3_bucket_acl_get",
    "run_s3_object_tagging", "do_s3_object_tagging_verify",
    "run_s3_bucket_tagging", "do_s3_bucket_tagging_verify",
    "run_s3_bucket_versioning", "do_s3_bucket_versioning_verify",
    "run_s3_object_lock_cfg", "do_s3_object_lock_cfg_verify", "s3_sse",
    "s3_sse_customer_key", "s3_sse_kms_key_id", "s3_ignore_errors",
    "gcs_endpoint_str", "gcs_project", "gcs_token", "gcs_resumable",
    "gcs_anonymous", "object_backend",
    # scenario per-step overlay knobs: ship with each step's effective
    # config (the plan itself is MASTER_FINGERPRINTED) and are
    # parity-relevant — a changed shuffle window is a different run
    "shuffle_window", "scenario_step_label", "scenario_epoch",
    "scenario_prefetch", "scenario_decode_usec", "scenario_step_usec",
    "scenario_batch_blocks", "scenario_creates_files",
})

#: every class, for exhaustiveness checks
ALL_CLASSES = {
    "master-only": MASTER_ONLY,
    "master-fingerprinted": MASTER_FINGERPRINTED,
    "per-host": PER_HOST,
    "wire-observability": WIRE_OBSERVABILITY,
    "wire": WIRE,
}


def classify(field_name: str) -> "str | None":
    for cls_name, members in ALL_CLASSES.items():
        if field_name in members:
            return cls_name
    return None
