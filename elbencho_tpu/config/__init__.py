from .args import BenchConfig, ConfigError  # noqa: F401
