"""Flag/config system — single source of truth for every benchmark setting.

Reference: source/ProgArgs.{h,cpp} (~5.2 kLoC; 238 flags declared in
defineAllowedArgs() ProgArgs.cpp:216-860, defaults :861, config-file merge,
unit-suffix conversion, implicit derivation initImplicitValues() :1148,
cross-validation checkArgs() :1349, and — crucially — JSON serialization of
the full effective config for the service protocol:
getAsPropertyTreeForService() :3921 / setFromPropertyTreeForService() :3754
with per-host rank offsets).

Here: a table-driven flag registry builds both the argparse CLI and the
JSON round-trip, so every flag automatically ships to remote services.
The reference's ``--gpuids`` GPU data path becomes ``--tpuids`` (worker ->
TPU chip mapping; BASELINE.json north_star).
"""

from __future__ import annotations

import dataclasses
import os
import stat as stat_mod
from dataclasses import dataclass, field

from ..phases import BenchMode, BenchPathType, BenchPhase
from ..toolkits.units import parse_size, parse_uint_list


class ConfigError(ValueError):
    """Reference: ProgException for invalid argument combinations."""


# ---------------------------------------------------------------------------
# flag registry: (flag, short, dest, kind, default, category, help)
# kind: bool | int | size | float | str | strlist | intlist
# category: essential | multi | large | dist | s3 | tpu | misc  (help tiers)
# ---------------------------------------------------------------------------

FLAG_DEFS = [
    # essential workload selection
    ("write", "w", "run_create_files", "bool", False, "essential",
     "Run write phase (create files / upload objects)"),
    ("read", "r", "run_read_files", "bool", False, "essential",
     "Run read phase"),
    ("mkdirs", "d", "run_create_dirs", "bool", False, "essential",
     "Run create-directories phase (or create buckets in S3 mode)"),
    ("deldirs", "D", "run_delete_dirs", "bool", False, "essential",
     "Run delete-directories phase"),
    ("delfiles", "F", "run_delete_files", "bool", False, "essential",
     "Run delete-files phase"),
    ("stat", None, "run_stat_files", "bool", False, "essential",
     "Run stat/getattr phase"),
    ("statdirs", None, "run_stat_dirs", "bool", False, "multi",
     "Run stat-directories phase"),
    ("sync", None, "run_sync_phase", "bool", False, "misc",
     "Sync write caches to stable storage between phases"),
    ("dropcaches", None, "run_drop_caches_phase", "bool", False, "misc",
     "Drop kernel page/dentry/inode caches between phases"),
    ("netbench", None, "run_netbench", "bool", False, "dist",
     "Run network benchmarking (first hosts are servers, rest clients)"),

    # geometry
    ("threads", "t", "num_threads", "int", 1, "essential",
     "Number of I/O worker threads per host"),
    ("dirs", "n", "num_dirs", "int", 1, "essential",
     "Number of directories per thread (dir mode)"),
    ("files", "N", "num_files", "int", 1, "essential",
     "Number of files per directory (dir mode)"),
    ("size", "s", "file_size", "size", 0, "essential",
     "File / object size (unit suffixes allowed, e.g. 4K, 1M, 10g)"),
    ("block", "b", "block_size", "size", 1 << 20, "essential",
     "Number of bytes per read/write op"),
    ("iodepth", None, "io_depth", "int", 1, "large",
     "Async I/O depth (queued ops per thread; 1 = sync I/O)"),
    ("ioengine", None, "io_engine", "str", "auto", "large",
     "Native block-loop engine: auto|sync|aio|uring (auto = sync when "
     "iodepth is 1, kernel AIO otherwise)"),
    ("ioretries", None, "io_num_retries", "int", 0, "large",
     "Per-op retries on transient storage errors (EINTR/EAGAIN/"
     "ETIMEDOUT/short reads, EIO on network filesystems; jittered "
     "exponential backoff; permanent errors like ENOSPC/EROFS still "
     "fail fast; 0 = fail on first error, the default). Object modes "
     "take the larger of this and --s3retries"),
    ("ioretrybudget", None, "io_retry_budget_secs", "int", 30, "large",
     "Per-phase, per-worker cap on total I/O retry backoff seconds; "
     "when spent, the next transient error is final (--ioretries)"),
    ("iotimeout", None, "io_timeout_secs", "int", 0, "large",
     "Per-op deadline in seconds for storage ops in the native "
     "streaming ring (--tpustream): a hung op is cancelled and "
     "surfaces as ETIMEDOUT — transient, so --ioretries can re-drive "
     "it on the re-armed slot (0 = no deadline)"),
    ("iosqpoll", None, "io_sqpoll", "bool", False, "large",
     "Run the staging pool's persistent io_uring with a kernel "
     "submission-queue polling thread (SQPOLL): submission becomes a "
     "shared-memory tail store — no io_uring_enter syscall on the hot "
     "path. Falls back LOUDLY to enter-based submission when the "
     "kernel/process cannot get an SQPOLL ring (needs io_uring, "
     "kernel 5.11+ unprivileged)"),
    ("iosqpollidle", None, "io_sqpoll_idle_ms", "int", 2000, "large",
     "SQPOLL thread idle timeout in milliseconds before the kernel "
     "thread sleeps; a sleeping thread costs one wakeup enter on the "
     "next submit (--iosqpoll)"),
    ("poolreg", None, "pool_registration", "str", "auto", "large",
     "Staging-pool fixed-buffer registration: auto (default) registers "
     "the worker's staging slab ONCE with io_uring where the kernel "
     "supports it — shared by the classic block engine and the "
     "streaming ring; off keeps the per-call buffer registration "
     "paths (the A/B baseline isolating the registration win)"),

    # access pattern
    ("rand", None, "use_random_offsets", "bool", False, "large",
     "Random offsets instead of sequential"),
    ("randamount", None, "random_amount", "size", 0, "large",
     "Total bytes to read/write in random mode (default: full size)"),
    ("norandalign", None, "no_random_align", "bool", False, "large",
     "Do not align random offsets to block size"),
    ("randalgo", None, "rand_offset_algo", "str", "fast", "large",
     "Random offset generator: strong|balanced_single|balanced|fast"),
    ("backward", None, "do_reverse_seq_offsets", "bool", False, "large",
     "Do backward sequential reads/writes"),
    ("strided", None, "do_strided_access", "bool", False, "large",
     "Strided access across shared files"),
    ("infloop", None, "do_infinite_io_loop", "bool", False, "misc",
     "Let each worker loop its workload forever (until time limit/interrupt)"),

    # file handling
    ("direct", None, "use_direct_io", "bool", False, "essential",
     "Use direct I/O (O_DIRECT), bypassing page cache"),
    ("nodiocheck", None, "no_direct_io_check", "bool", False, "misc",
     "Skip direct-I/O alignment sanity checks"),
    ("mmap", None, "use_mmap", "bool", False, "large",
     "Use memory-mapped I/O instead of read/write syscalls"),
    ("flock", None, "use_file_locks", "str", "", "misc",
     "File range locking mode: range|full"),
    ("fadv", None, "fadvise_flags", "str", "", "misc",
     "posix_fadvise flags (comma-sep: seq,rand,willneed,dontneed,noreuse)"),
    ("madv", None, "madvise_flags", "str", "", "misc",
     "madvise flags (comma-sep: seq,rand,willneed,dontneed,hugepage,"
     "nohugepage) for --mmap file mappings; hugepage/nohugepage also "
     "steer the staging pool's slab (THP advice, or skipping the "
     "MAP_HUGETLB attempt)"),
    ("trunc", None, "do_truncate", "bool", False, "misc",
     "Truncate files to 0 on open for write"),
    ("trunctosize", None, "do_truncate_to_size", "bool", False, "misc",
     "Truncate files to full size on open for write"),
    ("preallocfile", None, "do_prealloc_file", "bool", False, "misc",
     "Preallocate file disk space on creation (fallocate)"),
    ("nofdsharing", None, "no_fd_sharing", "bool", False, "misc",
     "Each worker opens its own FDs for given file/bdev paths"),
    ("dirsharing", None, "do_dir_sharing", "bool", False, "multi",
     "All threads share the same dirs (d0..dN) instead of per-rank dirs"),
    ("dirstats", None, "show_dirs_stats", "bool", False, "multi",
     "Show dirs/s in write phase results"),
    ("nodelerr", None, "ignore_delete_errors", "bool", False, "misc",
     "Do not treat deletion of non-existing files as error"),
    ("hdfs", None, "use_hdfs", "bool", False, "misc",
     "Use HDFS for file/dir benchmark paths (alternative to hdfs:// "
     "path prefix)"),
    ("no0usecerr", None, "ignore_0usec_errors", "bool", False, "misc",
     "Do not warn about operations completing in 0 microseconds"),
    ("nopathexp", None, "no_path_expansion", "bool", False, "misc",
     "Disable {N..M} numeric range expansion in bench paths"),

    # integrity / variance
    ("verify", None, "integrity_check_salt", "int", 0, "misc",
     "Enable data integrity check with given salt (!=0)"),
    ("verifydirect", None, "do_direct_verify", "bool", False, "misc",
     "Verify data by reading immediately after each write"),
    ("readinline", None, "do_read_inline", "bool", False, "misc",
     "Read each block immediately after writing it (same FD)"),
    ("blockvarpct", None, "block_variance_pct", "int", 0, "large",
     "Percentage of each block to refill with random data between writes"),
    ("blockvaralgo", None, "block_variance_algo", "str", "fast", "large",
     "PRNG for block variance: strong|balanced_single|balanced|fast"),

    # rwmix
    ("rwmixpct", None, "rwmix_read_pct", "int", 0, "large",
     "Percentage of reads in write phase (per-op modulo split)"),
    ("rwmixthr", None, "num_rwmix_read_threads", "int", 0, "large",
     "Number of threads of the write phase that do reads instead"),
    ("rwmixthrpct", None, "rwmix_thr_read_pct", "int", 0, "large",
     "Target read byte percentage for rwmixthr balancing"),

    # rate limiting
    ("limitread", None, "limit_read_bps", "size", 0, "misc",
     "Per-thread read bandwidth limit (bytes/sec, unit suffixes allowed)"),
    ("limitwrite", None, "limit_write_bps", "size", 0, "misc",
     "Per-thread write bandwidth limit (bytes/sec)"),

    # results & stats
    ("iterations", "i", "iterations", "int", 1, "misc",
     "Number of iterations of the full phase set"),
    ("timelimit", None, "time_limit_secs", "int", 0, "misc",
     "Phase time limit in seconds"),
    ("phasedelay", None, "next_phase_delay_secs", "int", 0, "misc",
     "Delay between phases in seconds"),
    ("lat", None, "show_latency", "bool", False, "essential",
     "Show min/avg/max latency"),
    ("lathisto", None, "show_latency_histogram", "bool", False, "misc",
     "Show latency histogram"),
    ("latpercent", None, "show_latency_percentiles", "bool", False, "misc",
     "Show latency percentiles"),
    ("latpercent9s", None, "num_latency_percentile_9s", "int", 2, "misc",
     "Number of nines for top latency percentile (2=99, 3=99.9, ...)"),
    ("allelapsed", None, "show_all_elapsed", "bool", False, "misc",
     "Show elapsed time of every single worker thread"),
    ("cpu", None, "show_cpu_util", "bool", False, "misc",
     "Show CPU utilization in live stats and results"),
    ("resfile", None, "res_file_path", "str", "", "misc",
     "Also write human-readable results to this file"),
    ("csvfile", None, "csv_file_path", "str", "", "misc",
     "Also write results to this CSV file"),
    ("jsonfile", None, "json_file_path", "str", "", "misc",
     "Also write results to this JSON file"),
    ("nocsvlabels", None, "no_csv_labels", "bool", False, "misc",
     "Do not print config labels line to CSV file"),
    ("livecsv", None, "live_csv_file_path", "str", "", "misc",
     "Write live stats to this CSV file ('stdout' allowed)"),
    ("livejson", None, "live_json_file_path", "str", "", "misc",
     "Write live stats to this JSON file ('stdout' allowed)"),
    ("livecsvex", None, "live_csv_extended", "bool", False, "misc",
     "Live CSV: one row per worker instead of totals"),
    ("livejsonex", None, "live_json_extended", "bool", False, "misc",
     "Live JSON: one entry per worker instead of totals"),
    ("liveint", None, "live_stats_interval_ms", "int", 2000, "misc",
     "Live statistics refresh interval in milliseconds"),
    ("live1", None, "use_single_line_live_stats", "bool", False, "misc",
     "Single-line live stats instead of fullscreen"),
    ("live1n", None, "single_line_live_stats_no_erase", "bool", False, "misc",
     "Single-line live stats, new line per update (for logs/pipes)"),
    ("nolive", None, "disable_live_stats", "bool", False, "misc",
     "Disable live statistics"),
    ("label", None, "bench_label", "str", "", "misc",
     "Custom benchmark label for result files"),
    ("base10", None, "use_base10_units", "bool", False, "misc",
     "Use base-10 (MB/s) instead of base-2 (MiB/s) units in output"),
    ("log", None, "log_level", "int", 0, "misc",
     "Log level (0=normal, 1=verbose, 2=debug)"),
    ("dryrun", None, "do_dry_run", "bool", False, "misc",
     "Show workload totals and config without running any phase"),
    ("opslog", None, "ops_log_path", "str", "", "misc",
     "Log every single I/O operation as JSONL to this file"),
    ("opsloglock", None, "ops_log_lock", "bool", False, "misc",
     "Serialize ops log writes via file lock (for shared-file logs)"),

    # telemetry (Prometheus /metrics + per-op tracing; docs/telemetry.md)
    ("telemetry", None, "telemetry", "bool", False, "misc",
     "Serve a Prometheus /metrics endpoint while the benchmark runs "
     "(local/master: standalone server on --telemetryport; the master "
     "exports a fleet-aggregated view harvested from its /status polls; "
     "services always serve /metrics on their control port)"),
    ("telemetryport", None, "telemetry_port", "int", 1612, "misc",
     "TCP port of the standalone /metrics endpoint (--telemetry in "
     "local/master mode; service mode reuses the --port control server)"),
    ("tracefile", None, "trace_file_path", "str", "", "misc",
     "Record per-op spans (phase, rank, op, offset, size, latency, "
     "staging slot; TPU dispatch-vs-DMA and stream-reap sub-spans) into "
     "this Chrome trace-event JSON file, loadable in Perfetto; services "
     "write per-host files suffixed .r<rankoffset>; the plain native "
     "block loops fall back to the (instrumented) Python loop while "
     "tracing — the fused --tpustream ring records its own spans and "
     "stays engaged"),
    ("tracesample", None, "trace_sample", "float", 1.0, "misc",
     "Keep only this fraction of spans in the --tracefile ring (0..1; "
     "applies to op spans and the per-op tpu/stream sub-spans; phase "
     "markers are always kept)"),
    ("flightrec", None, "flightrec_file_path", "str", "", "misc",
     "Record per-tick fleet + per-host counter deltas (live ops, the "
     "TPU dispatch-vs-DMA split, the path/control audit counters) into "
     "this append-only flight recording on the live-stats cadence, and "
     "attach the run doctor's bottleneck verdict (Analysis block) to "
     "the JSON results; in master mode the recorder taps the live "
     "frames the master already ingests, so services pay zero extra "
     "requests; post-process with tools/elbencho-tpu-doctor "
     "(docs/telemetry.md)"),
    ("tracefleet", None, "trace_fleet", "str", "auto", "misc",
     "Fleet-wide trace collection+merge (auto|on|off; needs --tracefile): "
     "a master-mode run stamps a run trace id + per-request span context "
     "onto the control plane, estimates per-host clock offsets from the "
     "exchanges it already performs (NTP-style RTT midpoint, min-RTT "
     "filtered), collects each service's span ring at /benchresult, and "
     "merges everything into ONE clock-aligned Chrome/Perfetto trace "
     "(<tracefile base>.fleet.json) with cross-host RPC flow arrows and "
     "a skew report; 'auto' (default) arms exactly when a master-mode "
     "run traces at all; zero extra per-tick service requests "
     "(docs/telemetry.md)"),
    ("traceshipcap", None, "trace_ship_cap_mib", "int", 16, "misc",
     "Max MiB of serialized span ring a service ships back at "
     "/benchresult for the fleet trace merge; an over-cap ring is "
     "refused LOUDLY on both ends (never fatal) and the host's lane "
     "stays local-only"),
    ("slowops", None, "slow_ops_k", "int", 0, "misc",
     "Slow-op forensics: each worker captures its K slowest storage ops "
     "(op, phase, rank, file/offset/size, latency, retry/timeout chain, "
     "storage-vs-dispatch-vs-DMA split under TPU staging, trace span "
     "link) plus a deterministic latency sample; services ship the "
     "capture with the /benchresult reply (zero extra requests, "
     "--traceshipcap bounds it) and the master merges everything into "
     "the run JSON's TailAnalysis block for the doctor's tail-bound "
     "verdict and elbencho-tpu-chart --tail heatmaps (0 = off, the "
     "default; docs/telemetry.md \"Tail forensics\")"),
    ("opsample", None, "op_sample_rate", "float", 1.0, "misc",
     "Fraction of ops the --slowops density sample keeps (0..1, "
     "deterministic systematic sampling by op index; the bounded "
     "per-worker reservoir halves its resolution instead of growing — "
     "drops are counted in OpSamplesDropped). Default 1.0 = every op "
     "feeds the sample until the reservoir bound bites"),

    # distribution
    ("hosts", None, "hosts_str", "str", "", "dist",
     "Comma-separated service hosts (host[:port])"),
    ("hostsfile", None, "hosts_file_path", "str", "", "dist",
     "File with one service host per line"),
    ("numhosts", None, "num_hosts_limit", "int", -1, "dist",
     "Use only this many of the given hosts"),
    ("service", None, "run_as_service", "bool", False, "dist",
     "Run as service (daemonized HTTP server for remote workers)"),
    ("foreground", None, "run_service_in_foreground", "bool", False, "dist",
     "Run service in foreground (don't daemonize)"),
    ("port", None, "service_port", "int", 1611, "dist",
     "TCP port of service HTTP server"),
    ("quit", None, "quit_services", "bool", False, "dist",
     "Tell given hosts' services to quit"),
    ("rankoffset", None, "rank_offset", "int", 0, "dist",
     "Offset for worker thread rank numbers"),
    ("nosvcshare", None, "no_shared_service_path", "bool", False, "dist",
     "Bench paths are not shared between service instances"),
    ("svcupint", None, "svc_update_interval_ms", "int", 500, "dist",
     "Service status poll interval in milliseconds"),
    ("svcwait", None, "svc_wait_secs", "int", 0, "dist",
     "Seconds to wait for services to come up at start"),
    ("svcpwfile", None, "svc_password_file", "str", "", "dist",
     "File with shared secret for service authorization"),
    ("svcelapsed", None, "show_svc_elapsed", "bool", False, "dist",
     "Show per-service elapsed times in results"),
    ("svcping", None, "show_svc_ping", "bool", False, "dist",
     "Show per-service control-plane round-trip latency in live stats"),
    ("svcretries", None, "svc_num_retries", "int", 3, "dist",
     "Transient-error retries per control-plane request to a service "
     "(connection failures, malformed replies, 5xx/429; jittered "
     "exponential backoff; 0 = fail on first error)"),
    ("svcretrybudget", None, "svc_retry_budget_secs", "int", 30, "dist",
     "Max total seconds of control-plane retry backoff per phase per "
     "service host before the host counts as failed"),
    ("svcstalledsecs", None, "svc_stalled_secs", "int", 0, "dist",
     "Declare a service stalled when its live counters stop advancing "
     "(or it stops answering /status) for this many seconds (0 = off)"),
    ("svctolerant", None, "svc_tolerant_hosts", "int", 0, "dist",
     "Max service hosts that may be lost mid-run; lost hosts are "
     "dropped and results are marked DEGRADED (0 = fail fast, the "
     "default)"),
    ("svcleasesecs", None, "svc_lease_secs", "int", 0, "dist",
     "Master liveness lease in seconds: each service arms a watchdog at "
     "/preparephase and treats every master poll as a lease renewal; "
     "when the lease expires (master crashed/partitioned), the service "
     "interrupts its workers, logs ORPHANED, and returns to idle so the "
     "host is immediately reusable by a new run (0 = off, the default; "
     "must exceed --svcupint when set)"),
    ("svcadoptsecs", None, "svc_adopt_secs", "int", 0, "dist",
     "Adoption grace window in seconds after a --svcleasesecs lease "
     "expiry: instead of orphan recovery the service enters an "
     "awaiting-adoption state — workers keep running, per-run state is "
     "NOT scrubbed — so a replacement master (--resume --adopt) can "
     "claim the host via /adopt; grace expiry with no adopter falls "
     "through to the normal orphan recovery (0 = off, the default: "
     "immediate-orphan parity)"),
    ("svcstream", None, "svc_stream", "bool", False, "dist",
     "Replace master-mode /status polling with one persistent "
     "server-push live-stats stream per attached host (chunked HTTP, "
     "delta-encoded frames, sequence-checked with full-snapshot "
     "resync). Falls back LOUDLY to per-request polling per host when "
     "a stream cannot serve it (stream -> poll, like the data path's "
     "uring -> AIO -> Python ladder). Default off = per-request "
     "polling parity"),
    ("svcfanout", None, "svc_fanout", "int", 0, "dist",
     "Arrange the service hosts into an aggregation tree with this "
     "fanout: the master streams from only N root services; interior "
     "services aggregate their subtree's live stats with the wire "
     "merge rules (sum/MAX) before forwarding, so the master holds "
     "O(fanout) connections instead of O(hosts). Subtree failures "
     "fall back to direct attachment. 0 = flat (every host attached "
     "directly). Requires --svcstream; --interrupt/--quit also walk "
     "the tree so teardown is O(fanout)"),
    ("rotatehosts", None, "rotate_hosts_num", "int", 0, "dist",
     "Rotate hosts list by this many positions between phases"),
    ("datasetthreads", None, "num_dataset_threads_override", "int", 0, "dist",
     "Override number of dataset partitioning threads"),
    ("start", None, "start_time_utc", "str", "", "dist",
     "Synchronized start time (HH:MM[:SS] UTC or unix timestamp)"),
    ("netdevs", None, "netdevs_str", "str", "", "dist",
     "Comma-separated network devices for netbench client binding"),
    ("servers", None, "servers_str", "str", "", "dist",
     "Comma-separated service hosts acting as netbench servers "
     "(host[:port]); combined with --clients this replaces --hosts"),
    ("clients", None, "clients_str", "str", "", "dist",
     "Comma-separated service hosts acting as netbench clients"),
    ("serversfile", None, "servers_file_path", "str", "", "dist",
     "File with line-separated netbench server hosts"),
    ("clientsfile", None, "clients_file_path", "str", "", "dist",
     "File with line-separated netbench client hosts"),
    ("netbenchservers", None, "num_netbench_servers", "int", 1, "dist",
     "Number of hosts acting as netbench servers"),
    ("respsize", None, "netbench_response_size", "size", 1, "dist",
     "Netbench server response size in bytes"),
    # internal (master -> service): netbench topology facts the services
    # cannot derive themselves (the hosts list is stripped from the wire)
    ("netbenchsrvlist", None, "netbench_servers_str", "str", "", "dist",
     "[internal] netbench server endpoints host:port, set by the master"),
    ("netbenchtotalhosts", None, "netbench_total_hosts", "int", 0, "dist",
     "[internal] total number of hosts in the run, set by the master"),
    ("recvbuf", None, "sock_recv_buf_size", "size", 0, "dist",
     "Socket receive buffer size"),
    ("sendbuf", None, "sock_send_buf_size", "size", 0, "dist",
     "Socket send buffer size"),

    # TPU data path (reference GPU flags --gpuids/--gpuperservice/--cufile/
    # --gdsbufreg become the PjRt/HBM path; SURVEY.md section 2.5 "GPU staging")
    ("tpuids", None, "tpu_ids_str", "str", "", "tpu",
     "Comma-separated TPU chip ids to use for HBM buffer staging "
     "(round-robin worker->chip by rank, like reference --gpuids)"),
    ("tpuperservice", None, "assign_tpu_per_service", "bool", False, "tpu",
     "Round-robin TPU chips across service instances instead of workers"),
    ("tpudirect", None, "use_tpu_direct", "bool", False, "tpu",
     "Direct host->HBM DMA path, skipping the bounce buffer where possible "
     "(cuFile/GDS analogue on PjRt)"),
    ("tpubatch", None, "tpu_batch_blocks", "int", 1, "tpu",
     "Coalesce this many blocks into one host->HBM DMA (amortizes "
     "per-transfer dispatch overhead, e.g. on tunneled chips; costs one "
     "host-side copy per block and defers the DMA to every Nth block; "
     "rejected with --tpuverify — the aggregated span has no per-block "
     "on-device check)"),
    ("tpudepth", None, "tpu_depth", "int", 0, "tpu",
     "In-flight TPU transfer ring depth (submission/completion window of "
     "the HBM pipeline; overrides the default of riding --iodepth, like "
     "the reference's cuFile iodepth semantics)"),
    ("tpustream", None, "tpu_stream", "str", "auto", "tpu",
     "Fused storage<->HBM streaming loop: the native engine keeps up to "
     "--iodepth io_uring (or kernel-AIO) ops in flight over the "
     "registered staging slots while Python overlaps HBM DMA dispatch "
     "(the cuFileRead overlap analogue). auto = on where eligible with "
     "a logged fallback to the Python loop; on = required (fail "
     "loudly when ineligible); off = always use the Python loop"),
    ("tpubudget", None, "tpu_dispatch_budget_usec", "int", 0, "tpu",
     "Fail the run when the measured per-block host-side dispatch "
     "overhead of the TPU transfer pipeline exceeds this many "
     "microseconds (0 = no budget)"),
    ("tpufallback", None, "tpu_fallback", "str", "abort", "tpu",
     "Reaction to a TPU chip lost mid-phase (XLA runtime/device-loss "
     "error): abort = fail fast (default); chip = drain+poison the "
     "failed chip and redistribute its workers across surviving "
     "--tpuids chips (degrading to host staging when none survive); "
     "host = degrade straight to host-memory staging. Failovers are "
     "audited as TpuChipFailovers and flagged DEGRADED-TPU by "
     "summarize-json"),
    ("tpuverify", None, "do_tpu_verify", "bool", False, "tpu",
     "Run integrity verification on-device (Pallas kernel) instead of host"),
    ("tpuprofile", None, "tpu_profile_dir", "str", "", "tpu",
     "Write a jax profiler trace (XLA device timeline for TensorBoard/"
     "Perfetto) per TPU-touching phase into this directory"),
    ("tpuhbmpct", None, "tpu_hbm_limit_pct", "int", 90, "tpu",
     "Max percentage of per-chip HBM to use for staging buffers"),
    ("tpubench", None, "run_tpu_bench", "bool", False, "tpu",
     "Run TPU transfer benchmark (no storage; the netbench analogue over "
     "the device fabric: host<->HBM DMA and ICI collectives)"),
    ("tpubenchpat", None, "tpu_bench_pattern", "str", "h2d", "tpu",
     "TPU bench pattern: h2d|d2h|both|ici|allgather|reducescatter|"
     "alltoall|psum (ici = ring ppermute; the rest time one XLA "
     "collective per step over all chips, NCCL-perf-test style)"),
    ("tpuslice", None, "run_tpu_slice", "bool", False, "tpu",
     "Run the pod-slice phase: stripe the dataset off storage across "
     "every chip of the mesh (each worker feeds its chips' shards "
     "through the staging pool + transfer pipeline), then redistribute "
     "each stripe over ICI with JAX collectives (--redistspec), "
     "overlapping the next stripe's storage ingest with the previous "
     "stripe's redistribution — the sharded-checkpoint-restore shape "
     "(docs/pod-slice.md)"),
    ("meshshape", None, "mesh_shape_str", "str", "", "tpu",
     "HOSTSxCHIPS mesh geometry for --tpuslice (e.g. 2x4); default: "
     "process boundaries on a real pod, else the most balanced 2D "
     "factorization of the device count"),
    ("redistspec", None, "redist_spec", "str", "alltoall", "tpu",
     "--tpuslice redistribution target layout: alltoall (row-sharded -> "
     "column-sharded reshard, memory-constant; default) | host "
     "(all-gather within each host's chips) | chip (reshard onto the "
     "chip axis, replicated across hosts) | replicate (full all-gather)"),
    ("podhosts", None, "use_pod_hosts", "bool", False, "tpu",
     "Derive --hosts from this TPU pod slice's worker VMs "
     "(TPU_WORKER_HOSTNAMES env or GCE metadata; each worker must run "
     "--service)"),
    ("tpumultihost", None, "tpu_multihost", "str", "", "tpu",
     "Join the multi-host JAX runtime before device use so --tpubench/"
     "--tpuids meshes span the whole pod ('auto' on TPU VMs, or "
     "'host:port[,nprocs,procid]')"),

    # NUMA/core binding
    ("zones", None, "numa_zones_str", "str", "", "multi",
     "Comma-separated NUMA zones to bind workers to (round-robin)"),
    ("cores", None, "cpu_cores_str", "str", "", "multi",
     "Comma-separated CPU cores to bind workers to (round-robin)"),

    # custom tree
    ("treefile", None, "tree_file_path", "str", "", "multi",
     "Path to custom tree file (see elbencho-tpu-scan-path)"),
    ("treerand", None, "use_custom_tree_rand", "bool", False, "multi",
     "Randomize custom tree file order"),
    ("treeroundrob", None, "use_custom_tree_round_robin", "bool", False, "multi",
     "Round-robin block assignment for shared custom tree files"),
    ("treeroundup", None, "tree_round_up_size", "size", 0, "multi",
     "Round file sizes in tree file up to multiple of this"),
    ("sharesize", None, "file_share_size", "size", 0, "multi",
     "Custom tree: files >= this size are shared between workers"),
    ("treescan", None, "tree_scan_path", "str", "", "multi",
     "Scan this directory tree — or an s3://bucket[/prefix] / gs:// "
     "bucket — and write a treefile (with --treefile OUT)"),
    ("statinline", None, "do_stat_inline", "bool", False, "misc",
     "Stat each file inline during write/read phases"),

    # S3/object storage (front-end parity; stdlib SigV4 client)
    ("s3endpoints", None, "s3_endpoints_str", "str", "", "s3",
     "Comma-separated S3 endpoint URLs"),
    ("s3key", None, "s3_access_key", "str", "", "s3", "S3 access key"),
    ("s3secret", None, "s3_secret_key", "str", "", "s3", "S3 secret key"),
    ("s3sessiontoken", None, "s3_session_token", "str", "", "s3",
     "S3 session token for temporary credentials (x-amz-security-token)"),
    ("s3region", None, "s3_region", "str", "us-east-1", "s3", "S3 region"),
    ("s3objprefix", None, "s3_object_prefix", "str", "", "s3",
     "Prefix for object names in bucket"),
    ("s3randobj", None, "s3_rand_obj_select", "bool", False, "s3",
     "Read at random offsets of random objects"),
    ("s3nompu", None, "s3_no_mpu", "bool", False, "s3",
     "Single-part upload even for large objects (no multipart)"),
    ("s3single", None, "use_s3_client_singleton", "bool", False, "s3",
     "Share one S3/GCS client object among all workers of this process "
     "(reference: S3 client singleton; per-worker clients otherwise)"),
    ("s3listobj", None, "run_list_objects_num", "int", 0, "s3",
     "Run bucket listing phase for this many objects"),
    ("s3listobjpar", None, "run_list_objects_parallel", "bool", False, "s3",
     "Run parallel bucket listing phase"),
    ("s3listverify", None, "do_list_objects_verify", "bool", False, "s3",
     "Verify listing results against expected object set"),
    ("s3multidel", None, "run_multi_delete_num", "int", 0, "s3",
     "Run multi-object delete phase with this many objects per request"),
    ("s3virtaddr", None, "s3_virtual_hosted", "bool", False, "s3",
     "Use virtual-hosted-style addressing instead of path-style"),
    ("s3sign", None, "s3_sign_policy", "int", 0, "s3",
     "Request signing policy (0=signed v4)"),
    ("s3maxconns", None, "s3_max_connections", "int", 0, "s3",
     "Max parallel S3 connections per worker (0=iodepth)"),
    ("s3mpusharing", None, "s3_mpu_sharing", "bool", False, "s3",
     "Multiple workers upload parts of the same (shared-name) objects"),
    ("s3mpucomplphase", None, "run_s3_mpu_complete_phase", "bool", False,
     "s3", "Complete shared multipart uploads in a separate MPUCOMPL "
     "phase instead of inline"),
    ("s3credfile", None, "s3_cred_file_path", "str", "", "s3",
     "File with one 'accesskey:secret' credential pair per line "
     "(round-robin across workers)"),
    ("s3credlist", None, "s3_cred_list", "str", "", "s3",
     "Comma-separated 'accesskey:secret' pairs (round-robin)"),
    ("s3retries", None, "s3_num_retries", "int", 3, "s3",
     "Transient-error retries per S3 request (5xx / connection errors)"),
    ("s3aclput", None, "run_s3_acl_put", "bool", False, "s3",
     "Run object ACL put phase"),
    ("s3aclget", None, "run_s3_acl_get", "bool", False, "s3",
     "Run object ACL get phase"),
    ("s3aclgrantee", None, "s3_acl_grantee", "str", "", "s3",
     "ACL grantee; canned values (private, public-read, public-read-write, "
     "authenticated-read) ignore grantee type/permissions"),
    ("s3aclgtype", None, "s3_acl_grantee_type", "str", "", "s3",
     "ACL grantee type: id|email|uri|group"),
    ("s3aclgrants", None, "s3_acl_grants", "str", "", "s3",
     "Comma-separated ACL grantee permissions: "
     "none|full|read|write|racp|wacp"),
    ("s3aclputinl", None, "do_s3_acl_put_inline", "bool", False, "s3",
     "Set object ACL inline in upload requests (grantee as "
     "'id=...'/'emailAddress=...'/'uri=...')"),
    ("s3aclverify", None, "do_s3_acl_verify", "bool", False, "s3",
     "Verify object/bucket ACLs against given grantee+permissions in the "
     "ACL get phases"),
    ("s3checksumalgo", None, "s3_checksum_algo", "str", "", "s3",
     "Upload checksum algorithm: crc32|crc32c|sha1|sha256 "
     "(x-amz-sdk-checksum-algorithm + per-request checksum header)"),
    ("s3nompucompl", None, "s3_no_mpu_completion", "bool", False, "s3",
     "Don't send CompleteMultipartUpload after uploading all parts "
     "(cleanup later via elbencho-tpu-cleanup-mpu)"),
    ("s3nompcheck", None, "s3_ignore_part_num_check", "bool", False, "s3",
     "Don't check for multipart uploads exceeding 10,000 parts"),
    ("s3multiignore404", None, "s3_ignore_mpu_completion_404", "bool",
     False, "s3", "Ignore 404 responses to CompleteMultipartUpload "
     "(upload already completed by a retried request)"),
    ("s3fastget", None, "s3_fast_get", "bool", False, "s3",
     "Discard downloaded object data unbuffered (incompatible with "
     "--verify and --tpuids staging)"),
    ("s3fastput", None, "s3_fast_put", "bool", False, "s3",
     "Reduce upload CPU overhead (implies unsigned payloads)"),
    ("s3nocompress", None, "s3_no_compression", "bool", False, "s3",
     "Disable S3 request compression (accepted for reference parity; "
     "this client never compresses)"),
    ("s3mpusizevar", None, "s3_mpu_size_variance", "size", 0, "s3",
     "Max bytes to randomly subtract from each MPU part (last part grows "
     "to keep the object size)"),
    ("s3log", None, "s3_log_level", "int", 0, "s3",
     "S3 request log level (0=off; >0 logs each request to the log file)"),
    ("s3logprefix", None, "s3_log_prefix", "str", "s3_", "s3",
     "Path/filename prefix for the S3 request log (DATE.log appended)"),
    ("s3baclput", None, "run_s3_bucket_acl_put", "bool", False, "s3",
     "Run bucket ACL put phase"),
    ("s3baclget", None, "run_s3_bucket_acl_get", "bool", False, "s3",
     "Run bucket ACL get phase"),
    ("s3otag", None, "run_s3_object_tagging", "bool", False, "s3",
     "Run object tagging put/get/del phases"),
    ("s3otagverify", None, "do_s3_object_tagging_verify", "bool", False,
     "s3", "Verify object tags read back correctly"),
    ("s3btag", None, "run_s3_bucket_tagging", "bool", False, "s3",
     "Run bucket tagging put/get/del phases"),
    ("s3btagverify", None, "do_s3_bucket_tagging_verify", "bool", False,
     "s3", "Verify bucket tags read back correctly"),
    ("s3bversion", None, "run_s3_bucket_versioning", "bool", False, "s3",
     "Run bucket versioning put/get phases"),
    ("s3bversionverify", None, "do_s3_bucket_versioning_verify", "bool",
     False, "s3", "Verify bucket versioning status reads back correctly"),
    ("s3olockcfg", None, "run_s3_object_lock_cfg", "bool", False, "s3",
     "Run bucket object-lock configuration put/get phases"),
    ("s3olockcfgverify", None, "do_s3_object_lock_cfg_verify", "bool",
     False, "s3", "Verify object-lock configuration reads back correctly"),
    ("s3sse", None, "s3_sse", "bool", False, "s3",
     "Server-side encryption (SSE-S3 AES256 header) for uploads"),
    ("s3sseckey", None, "s3_sse_customer_key", "str", "", "s3",
     "SSE-C customer key (base64) for uploads/downloads"),
    ("s3ssekmskey", None, "s3_sse_kms_key_id", "str", "", "s3",
     "SSE-KMS key id for uploads"),
    ("s3ignoreerrors", None, "s3_ignore_errors", "bool", False, "s3",
     "Continue on S3 request errors (stress mode)"),

    # GCS-native backend (JSON API; selected by gs:// paths)
    ("gcsendpoint", None, "gcs_endpoint_str", "str", "", "s3",
     "GCS JSON API endpoint(s), comma-sep, round-robin by worker rank "
     "(default https://storage.googleapis.com; any use selects the "
     "GCS-native backend like gs:// paths do)"),
    ("gcsproject", None, "gcs_project", "str", "", "s3",
     "GCP project id (required by GCS for bucket creation)"),
    ("gcstoken", None, "gcs_token", "str", "", "s3",
     "OAuth2 access token (default: GOOGLE_OAUTH_ACCESS_TOKEN env, then "
     "the GCE/TPU-VM metadata server / workload identity)"),
    ("gcsresumable", None, "gcs_resumable", "bool", False, "s3",
     "Use GCS resumable upload sessions for multipart uploads instead of "
     "compose (native large-single-object protocol; parts are sequential "
     "per worker, so incompatible with --s3mpusharing)"),
    ("gcsanon", None, "gcs_anonymous", "bool", False, "s3",
     "Anonymous GCS access (public buckets, unauthenticated endpoints)"),
    ("objectbackend", None, "object_backend", "str", "", "s3",
     "Object-storage backend: s3|gcs (derived from path scheme if unset)"),

    # crash-safe run lifecycle (docs/fault-tolerance.md "Run lifecycle")
    ("journal", None, "journal_file_path", "str", "", "misc",
     "Append-only run journal (fsync'd JSONL): config fingerprint, "
     "per-phase start/finish/interrupted records with per-host result "
     "summaries, and a terminal run_complete record — the restart "
     "point --resume replays"),
    ("resume", None, "resume_run", "bool", False, "misc",
     "Resume an interrupted journaled run (requires --journal FILE): "
     "phases with finish records are skipped, the first incomplete "
     "phase re-runs from scratch, and a config-fingerprint mismatch "
     "against the journal is a hard error"),
    ("adopt", None, "adopt_run", "bool", False, "misc",
     "With --resume: instead of re-running the first incomplete phase "
     "from scratch, take over the crashed master's live fleet — "
     "claim every awaiting-adoption service host via /adopt (journal "
     "fingerprint + takeover token), adopt the in-flight phase at "
     "whatever completion state it reached (never restarting it), and "
     "continue the journaled plan from the takeover point (requires "
     "--hosts services armed with --svcleasesecs + --svcadoptsecs)"),
    ("standby", None, "standby_str", "str", "", "misc",
     "Warm-standby master (HOST:PORT of one fleet service): observe "
     "that service's /status as a liveness proxy for the primary "
     "master and auto-run the --resume --adopt takeover the moment "
     "the host reports awaiting-adoption — no human in the loop "
     "(requires --journal FILE on storage this standby can read)"),

    # training-ingest scenario layer (docs/scenarios.md)
    ("scenario", None, "scenario", "str", "", "essential",
     "Run a named training-ingest scenario that composes multiple "
     "phases with per-step config overlays: epochs (multi-epoch "
     "shuffled shard reads) | ckpt-burst (all-hosts checkpoint "
     "save/restore bursts) | contend (train-read vs checkpoint-write "
     "contention) | coldwarm (cold-vs-warm cache epochs) | dataloader "
     "(prefetch/decode/consume-cadence emulation). The scenario "
     "defines the phase plan, so explicit phase flags (-w/-r/...) are "
     "rejected; every record is tagged with scenario + step identity "
     "and the run ends with a scenario-level verdict "
     "(docs/scenarios.md)"),
    ("scenario-opt", None, "scenario_opts_str", "str", "", "essential",
     "Comma-separated key=val knobs for --scenario, e.g. "
     "'epochs=4,window=16M' or 'prefetch=4,stepusec=2000' (each "
     "scenario's knob table: docs/scenarios.md)"),
    ("shufflewindow", None, "shuffle_window", "size", 0, "large",
     "Read phases: emit block offsets as a seeded permutation within "
     "consecutive windows of this many bytes — every block exactly "
     "once, locality bounded by the window (the shuffle-buffer access "
     "shape of training input pipelines; the epochs scenario sets it "
     "per epoch with a per-epoch seed). 0 = off; incompatible with "
     "--rand/--backward/--strided/--mmap"),
    # internal (master -> service): per-step scenario identity + the
    # dataloader pacing knobs, set by the scenario engine's overlays so
    # remote workers shape their loops like local ones
    ("scenstep", None, "scenario_step_label", "str", "", "misc",
     "[internal] scenario step label, set by the scenario engine"),
    ("scenepoch", None, "scenario_epoch", "int", 0, "misc",
     "[internal] scenario epoch number (seeds --shufflewindow "
     "permutations; tags EpochRateMiBs records)"),
    ("loaderprefetch", None, "scenario_prefetch", "int", 0, "misc",
     "[internal] dataloader emulation: max batches the reader may run "
     "ahead of the consume clock"),
    ("loaderdecodeusec", None, "scenario_decode_usec", "int", 0, "misc",
     "[internal] dataloader emulation: CPU decode burn per batch "
     "(busy-spin microseconds)"),
    ("loaderstepusec", None, "scenario_step_usec", "int", 0, "misc",
     "[internal] dataloader emulation: consume cadence — one batch "
     "per this many microseconds (0 = unpaced)"),
    ("loaderbatchblocks", None, "scenario_batch_blocks", "int", 0, "misc",
     "[internal] dataloader emulation: blocks per batch/step"),
    ("scencreates", None, "scenario_creates_files", "bool", False, "misc",
     "[internal] the expanded scenario plan contains a write leg — "
     "file-mode fd opens need O_CREAT even though the explicit phase "
     "flags stay off under --scenario (set by validate_scenario on the "
     "master, shipped to services on the wire)"),

    # closed-loop autotuning (docs/autotuning.md)
    ("autotune", None, "autotune_secs", "optint", 0, "misc",
     "Before the measured phases run, spend up to SECS seconds (bare "
     "flag = 60) hill-climbing --threads/--iodepth/--tpudepth/"
     "--tpubatch (and --svcupint/--svcfanout on master-mode fleets) "
     "with short bounded probe phases steered by the run doctor's "
     "bottleneck verdicts, then run the real phases at the tuned "
     "point; emits a reproducible tuned profile (--configfile format) "
     "plus a schema-versioned Autotune block with the probe "
     "trajectory and the before/after doctor diff as proof (probes "
     "are unjournaled and never land in result files; 0 = off)"),
    ("autotune-profile", None, "autotune_profile_path", "str", "",
     "misc",
     "Path for the tuned profile --autotune emits (default: "
     "elbencho-tpu-tuned.conf beside the JSON results); load it with "
     "-c to reproduce the tuned run without re-tuning"),
    ("autotune-probes", None, "autotune_probes", "int", 0, "misc",
     "Hard cap on total --autotune probe phases (0 = bounded by the "
     "time budget only)"),
    ("autotune-probesecs", None, "autotune_probe_secs", "int", 3,
     "misc",
     "Length of each --autotune probe phase in seconds (the probe "
     "rides the --timelimit interrupt machinery, so a probe at a bad "
     "config costs this much, not the workload's natural length)"),
    ("autotune-repeat", None, "autotune_repeat", "int", 1, "misc",
     "Probes per candidate config; the search compares repeat-probe "
     "MEDIANS, so values > 1 buy noise rejection at probe-budget cost"),

    # misc
    ("configfile", "c", "config_file_path", "str", "", "misc",
     "Read benchmark settings from this file (ini-style: flag = value)"),
    ("interrupt", None, "interrupt_services", "bool", False, "dist",
     "Interrupt the current phase of the given service hosts"),
]

_KIND_PARSERS = {
    "int": int,
    "optint": int,
    "float": float,
    "str": str,
    "size": parse_size,
}

#: bare value of "optint" flags (value optional on the CLI): using the
#: flag without a number means this
OPTINT_BARE = {
    "autotune": 60,
}

#: registry default per dest — THE source any code comparing against or
#: resetting to "the default" must use (a literal copy would silently
#: drift when the FLAG_DEFS default changes)
FLAG_DEFAULTS = {dest: default
                 for _f, _s, dest, _k, default, _c, _h in FLAG_DEFS}


def _make_field(flag_def):
    _, _, dest, kind, default, _, _ = flag_def
    if kind in ("strlist", "intlist"):
        return (dest, list, field(default_factory=list))
    py_type = {"bool": bool, "int": int, "optint": int, "float": float,
               "str": str, "size": int}[kind]
    return (dest, py_type, field(default=default))


_CONFIG_FIELDS = [_make_field(fd) for fd in FLAG_DEFS]
_CONFIG_FIELDS.append(("paths", list, field(default_factory=list)))


@dataclass
class _BenchConfigBase:
    pass


BenchConfigBase = dataclasses.make_dataclass(
    "BenchConfigBase", _CONFIG_FIELDS, bases=(_BenchConfigBase,))


class BenchConfig(BenchConfigBase):
    """Typed effective configuration (ProgArgs equivalent).

    Constructed from CLI args (parse_cli), a config file, or a service
    protocol dict (from_service_dict). Derived values (bench mode, path
    type, dataset threads, per-host ranks) are computed by derive().
    """

    def __init__(self, **kwargs):
        unknown = set(kwargs) - {f.name for f in dataclasses.fields(self)}
        if unknown:
            raise ConfigError(f"unknown config keys: {sorted(unknown)}")
        super().__init__(**kwargs)
        # derived state (not part of the flag registry)
        self.bench_mode: BenchMode = BenchMode.UNDEFINED
        self.bench_path_type: BenchPathType = BenchPathType.DIR
        self.hosts: "list[str]" = []
        self.tpu_ids: "list[int]" = []
        self.num_dataset_threads: int = self.num_threads
        self.bench_path_fds: "list[int]" = []   # opened by worker prep
        self.derived_done = False

    # -- derivation (reference: initImplicitValues/checkArgs) ---------------

    def derive(self, probe_paths: bool = True) -> "BenchConfig":
        if not self.derived_done:
            # remember which size-ish values the user gave explicitly, so
            # a late path probe (probe_local_paths) can recompute the
            # defaults derived from them without clobbering user input;
            # from_service_dict may have pre-set these from the master's
            # wire declaration — the local bool() guess must not clobber
            # that (the master's values arrive already default-filled)
            if not hasattr(self, "_random_amount_explicit"):
                self._random_amount_explicit = bool(self.random_amount)
            if not hasattr(self, "_file_size_explicit"):
                self._file_size_explicit = bool(self.file_size)
        self._parse_hosts()
        self.tpu_ids = parse_uint_list(self.tpu_ids_str)
        self._init_bench_mode()
        if probe_paths and self.bench_mode == BenchMode.POSIX and self.paths:
            self._probe_path_types_and_sizes()
        self._calc_dataset_threads()
        self._apply_implicit_values()
        self.derived_done = True
        return self

    def probe_local_paths(self) -> None:
        """Late local-path probe for callers that derived with
        probe_paths=False (the CLI defers probing until it knows the run
        is local, not master mode): detect the path type and blockdev
        size, then re-derive the size-dependent implicit values —
        _apply_implicit_values recomputes every non-explicit default
        against the freshly probed state. (The service side gets the same
        treatment through its plain derive(), which runs after the
        pinned-path overrides are applied.)"""
        self._probe_path_types_and_sizes()
        self._apply_implicit_values()

    @staticmethod
    def _read_hosts(hosts_str: str, file_path: str) -> "list[str]":
        hosts: "list[str]" = []
        if file_path:
            with open(file_path) as f:
                hosts += [ln.strip() for ln in f
                          if ln.strip() and not ln.startswith("#")]
        if hosts_str:
            hosts += [h.strip() for h in hosts_str.split(",") if h.strip()]
        return hosts

    def _parse_hosts(self) -> None:
        hosts = self._read_hosts(self.hosts_str, self.hosts_file_path)
        if self.use_pod_hosts:
            if hosts:
                raise ConfigError(
                    "--podhosts and --hosts are mutually exclusive")
            from ..tpu.pod import enumerate_pod_hosts
            try:
                hosts = enumerate_pod_hosts()
            except RuntimeError as err:
                raise ConfigError(str(err)) from err
        # netbench topology via explicit --servers/--clients lists
        # (reference: parseHosts, ProgArgs.cpp:2343-2460 — servers first,
        # clients last, numNetBenchServers = len(servers))
        servers = self._read_hosts(self.servers_str, self.servers_file_path)
        clients = self._read_hosts(self.clients_str, self.clients_file_path)
        if servers or clients:
            if not self.run_netbench:
                raise ConfigError(
                    "--servers/--clients are netbench-mode flags "
                    "(use --hosts otherwise)")
            if hosts:
                raise ConfigError(
                    "--hosts and --servers/--clients are mutually exclusive")
            if not servers or not clients:
                raise ConfigError(
                    "netbench needs both --servers and --clients")
            if self.num_hosts_limit >= 0:
                raise ConfigError(
                    "--numhosts cannot be combined with --servers/"
                    "--clients (it would truncate the merged list and "
                    "silently drop clients)")
            hosts = servers + clients
            self.num_netbench_servers = len(servers)
        if 0 <= self.num_hosts_limit < len(hosts):
            hosts = hosts[:self.num_hosts_limit]
        if len(set(hosts)) != len(hosts):
            raise ConfigError("list of hosts contains duplicates")
        self.hosts = hosts

    @staticmethod
    def _expand_path_braces(paths: "list[str]") -> "list[str]":
        """"{N..M}" numeric range expansion in bench paths (reference:
        ProgArgs path expansion; disable with --nopathexp)."""
        import re
        out: "list[str]" = []
        pattern = re.compile(r"\{(\d+)\.\.(\d+)\}")
        for p in paths:
            m = pattern.search(p)
            if not m:
                out.append(p)
                continue
            lo_str, hi_str = m.group(1), m.group(2)
            lo, hi = int(lo_str), int(hi_str)
            # bash-style zero-padding: {01..03} -> 01 02 03; bash pads to
            # the widest endpoint when either has a leading zero
            width = max(len(lo_str), len(hi_str)) \
                if lo_str.startswith("0") or hi_str.startswith("0") else 0
            step = 1 if hi >= lo else -1
            for i in range(lo, hi + step, step):
                num = str(i).zfill(width)
                out.extend(BenchConfig._expand_path_braces(
                    [p[:m.start()] + num + p[m.end():]]))
        return out

    def _init_bench_mode(self) -> None:
        """Bench mode from flags/path prefixes (reference: initBenchMode,
        ProgArgs.cpp:1112 — s3:// and hdfs:// prefixes, --netbench flag)."""
        if not self.no_path_expansion:
            self.paths = self._expand_path_braces(self.paths)
        if self.run_netbench:
            self.bench_mode = BenchMode.NETBENCH
            return
        has_gs = any(p.startswith("gs://") for p in self.paths)
        has_s3 = any(p.startswith("s3://") for p in self.paths)
        if (has_gs or has_s3 or self.s3_endpoints_str
                or self.gcs_endpoint_str or self.object_backend):
            # object mode; backend from the explicit --objectbackend if
            # given (e.g. the S3-interop XML path against gs:// buckets),
            # else derived from the path scheme / endpoint flags —
            # ambiguous mixes are rejected rather than silently routed
            if has_gs and has_s3:
                raise ConfigError(
                    "cannot mix gs:// and s3:// bench paths in one run")
            if not self.object_backend and (
                    (has_gs or self.gcs_endpoint_str)
                    and (has_s3 or self.s3_endpoints_str)):
                raise ConfigError(
                    "both S3 and GCS endpoints/paths configured — pick "
                    "the backend explicitly with --objectbackend s3|gcs")
            self.bench_mode = BenchMode.S3
            if not self.object_backend:
                self.object_backend = "gcs" \
                    if (has_gs or self.gcs_endpoint_str) else "s3"
            if self.use_s3_client_singleton:
                from ..toolkits.logger import log
                # the flag changed meaning in round 5 (it briefly meant
                # single-part upload here): surface the semantics so old
                # scripts notice
                log(0, "NOTE: --s3single shares ONE client object among "
                       "all workers (reference client-singleton "
                       "semantics); for single-part uploads without "
                       "multipart use --s3nompu")
            self.paths = [p.removeprefix("s3://").removeprefix("gs://")
                          for p in self.paths]
            return
        if self.use_hdfs or any(p.startswith("hdfs://") for p in self.paths):
            self.bench_mode = BenchMode.HDFS
            self.paths = [p[len("hdfs://"):] if p.startswith("hdfs://")
                          else p for p in self.paths]
            return
        self.paths = [p[len("file://"):] if p.startswith("file://") else p
                      for p in self.paths]
        self.bench_mode = BenchMode.POSIX

    def _find_bench_path_type(self) -> None:
        """DIR|FILE|BLOCKDEV via stat; all paths must agree
        (reference: findBenchPathType, ProgArgs.cpp:3062)."""
        types = set()
        for p in self.paths:
            try:
                st = os.stat(p)
                if stat_mod.S_ISDIR(st.st_mode):
                    types.add(BenchPathType.DIR)
                elif stat_mod.S_ISBLK(st.st_mode):
                    types.add(BenchPathType.BLOCKDEV)
                else:
                    types.add(BenchPathType.FILE)
            except FileNotFoundError:
                # non-existing => will be created as file in write phase
                types.add(BenchPathType.FILE)
        if len(types) > 1:
            raise ConfigError(
                f"all bench paths must have the same type, got: "
                f"{[t.name for t in types]}")
        self.bench_path_type = types.pop() if types else BenchPathType.DIR

    def _detect_blockdev_size(self) -> None:
        """Blockdev mode: detect the device size so -s is optional, and
        refuse a -s larger than the device (reference:
        prepareBenchPathFDsVec, ProgArgs.cpp:2306-2330). Runs before the
        implicit-value derivation so random-amount defaults see the
        detected size."""
        if self.bench_path_type != BenchPathType.BLOCKDEV:
            return
        dev_size = None
        for p in self.paths:
            try:
                fd = os.open(p, os.O_RDONLY)
            except OSError as err:
                raise ConfigError(
                    f"unable to open block device {p}: {err.strerror}") \
                    from err
            try:
                size = os.lseek(fd, 0, os.SEEK_END)
            except OSError as err:
                raise ConfigError(
                    f"unable to check size of block device through lseek: "
                    f"{p}: {err.strerror}") from err
            finally:
                os.close(fd)
            if not size:
                raise ConfigError(f"block device size seems to be 0: {p}")
            dev_size = size if dev_size is None else min(dev_size, size)
        if not self.file_size \
                or not getattr(self, "_file_size_explicit", True):
            # a size the user never gave (0, or filled by an earlier
            # derivation's defaults) yields to the detected device size
            from ..toolkits.logger import LOG_NORMAL, log
            log(LOG_NORMAL,
                f"NOTE: Setting file size to block dev size: {dev_size}")
            self.file_size = dev_size
        elif self.file_size > dev_size:
            raise ConfigError(
                f"given size to use is larger than detected block device "
                f"size. Detected size: {dev_size}; "
                f"Given size: {self.file_size}")

    def _probe_path_types_and_sizes(self) -> None:
        """The local path probe: type detection plus blockdev/file size
        detection, kept as ONE unit so the derive() probe and the late
        probe_local_paths() can never diverge."""
        self._find_bench_path_type()
        self._detect_blockdev_size()
        self._detect_file_size()

    def _detect_file_size(self) -> None:
        """File mode: auto-set the file size from an existing file so -s
        is optional, refuse a read-only -s larger than the file, and
        refuse a size of 0 (reference: prepareFileSize,
        ProgArgs.cpp:2193-2227). A path that does not exist yet behaves
        like the reference's freshly O_CREAT-ed empty file: size 0, which
        a read or create phase without -s must reject rather than run a
        silent zero-byte benchmark."""
        if self.bench_path_type != BenchPathType.FILE:
            return
        explicit = self.file_size \
            and getattr(self, "_file_size_explicit", True)
        detected = explicit
        for p in self.paths:
            try:
                st = os.stat(p)
            except OSError:
                st = None  # created (empty) by the write phase
            cur_size = st.st_size if st else 0
            if not detected:
                # first path wins, like the reference's sequential fd
                # probe; a value filled by an earlier derivation's
                # defaults is recomputed, never validated against
                detected = True
                if not cur_size and (self.run_read_files
                                     or self.run_create_files
                                     or self.scenario):
                    # a scenario always reads and/or writes the dataset,
                    # so a missing file without -s must refuse exactly
                    # like -w/-r would, not run a silent 0-byte plan
                    raise ConfigError(
                        "file size must not be 0 when benchmark path is "
                        f"a file (give -s): {p}")
                from ..toolkits.logger import LOG_NORMAL, log
                log(LOG_NORMAL,
                    f"NOTE: Auto-setting file size. Size: {cur_size}; "
                    f"Path: {p}")
                self.file_size = cur_size
            elif not self.run_create_files \
                    and not self._scenario_writes_dataset() \
                    and st is not None \
                    and cur_size < self.file_size \
                    and stat_mod.S_ISREG(st.st_mode):
                # a scenario's write legs (setup, ckpt saves) grow the
                # file to -s themselves, so the read-only size refusal
                # does not apply to a plan that writes — but a write-less
                # plan (e.g. --scenario-opt setup=0) must refuse an
                # undersized file here, exactly like plain -r would
                # ignore character devices like /dev/zero, as the
                # reference does
                raise ConfigError(
                    f"given size to use is larger than detected size. "
                    f"File: {p}; Detected size: {cur_size}; "
                    f"Given size: {self.file_size}")

    def _scenario_writes_dataset(self) -> bool:
        """Whether the effective run's scenario plan contains a write
        leg. Master side this expands the plan (the probe runs before
        check()/validate_scenario set scenario_creates_files); a service
        sees the wire-shipped scenario_creates_files instead — the
        scenario name itself is stripped from its config."""
        if self.scenario_creates_files:
            return True
        if not self.scenario:
            return False
        try:
            from ..phases import BenchPhase
            from ..scenarios import expand_scenario
            plan = expand_scenario(self)
        except ConfigError:
            # a bad scenario/knob gets its own config-time error from
            # validate_scenario; don't mask it with a size refusal here
            return True
        return any(s.phase == BenchPhase.CREATEFILES for s in plan.steps)

    def _calc_dataset_threads(self) -> None:
        """numDataSetThreads = threads * hosts if paths shared between
        services, else threads (reference: ProgArgs.cpp:1408-1409)."""
        if self.num_dataset_threads_override > 0:
            self.num_dataset_threads = self.num_dataset_threads_override
        elif self.hosts and not self.no_shared_service_path:
            self.num_dataset_threads = self.num_threads * len(self.hosts)
        else:
            self.num_dataset_threads = self.num_threads

    def _reduce_file_size_to_block_multiple(self) -> None:
        """Direct/random/strided IO: a trailing partial block would straddle
        a file boundary in striped modes and hard-fail with a short read;
        the reference auto-adjusts with a note (ProgArgs.cpp:1664-1676).
        Must run BEFORE the random_amount default so the amount matches the
        reduced dataset size (reference order: :1664 before :1680)."""
        if (self.use_direct_io or self.use_random_offsets
                or self.do_strided_access or self.run_tpu_slice) \
                and self.file_size \
                and self.block_size \
                and (self.run_create_files or self.run_read_files
                     or self.run_tpu_slice) \
                and self.file_size % self.block_size:
            new_size = self.file_size - (self.file_size % self.block_size)
            from ..toolkits.logger import LOG_NORMAL, log
            log(LOG_NORMAL,
                "NOTE: File size has to be a multiple of block size for "
                "direct IO, random IO and strided IO. Reducing file size. "
                f"Old: {self.file_size}; New: {new_size}")
            self.file_size = new_size

    def _apply_implicit_values(self) -> None:
        if self.run_tpu_slice and not self.file_size:
            # BEFORE the block-multiple trim below: a defaulted dataset
            # must honor the same stripe geometry as an explicit one (a
            # shard block straddling a file boundary would short-read)
            self.file_size = 256 << 20
        if self.file_size and 0 < self.file_size < self.block_size:
            # reference reduces blocksize to filesize (also before the
            # reductions below; check() re-applies for non-derive callers)
            self.block_size = self.file_size
        self._reduce_file_size_to_block_multiple()
        if not getattr(self, "_random_amount_explicit", True):
            # a value filled by an earlier derivation (possibly against a
            # not-yet-probed path type, or on the master for different
            # paths) is recomputed, never treated as user input
            self.random_amount = 0
        if self.use_random_offsets and not self.random_amount:
            # default random amount = full dataset size
            if self.bench_path_type != BenchPathType.DIR:
                self.random_amount = self.file_size * max(1, len(self.paths))
            else:
                self.random_amount = self.file_size
        if self.run_as_service:
            self.disable_live_stats = True
        self._apply_default_result_files()
        self._apply_s3_env_credentials()
        if self.run_tpu_bench:
            if not self.tpu_ids:
                self.tpu_ids = [0]  # default to the first chip
            if not self.file_size:
                self.file_size = 256 << 20  # sensible default amount
        if self.num_rwmix_read_threads and not self.run_create_files \
                and not self.scenario_step_label:
            # the step-label exemption covers the service side only: a
            # contend step ships its overlay with the phase flags
            # stripped but the label set. A USER-given --rwmixthr next
            # to --scenario still lands here (label empty at parse
            # time) — the scenario engine owns the thread split, and a
            # stray rwmixthr would convert setup-write threads into
            # readers of files not yet written
            raise ConfigError(
                "--rwmixthr requires the write phase (-w)"
                + ("; with --scenario use the contend scenario's "
                   "readthreads knob instead" if self.scenario else ""))

    @staticmethod
    def _default_results_base() -> str:
        """Base dir for default result files (separate hook for tests)."""
        return "/var/tmp"

    def _apply_default_result_files(self) -> None:
        """Non-service runs default result files into
        /var/tmp/elbencho-tpu_results_<user>/ with date-stamped names
        (reference: RESFILE_DIR_USER_DEFAULT, ProgArgs.cpp:71,1174-1187).
        Disable with ELBENCHO_TPU_NO_DEFAULT_RESFILES=1 (CI/sandboxes)."""
        if self.run_as_service or getattr(self, "_service_side", False) \
                or os.environ.get("ELBENCHO_TPU_NO_DEFAULT_RESFILES") == "1":
            return
        if self.res_file_path and self.csv_file_path \
                and self.json_file_path:
            return
        import datetime
        import getpass
        try:
            user = getpass.getuser()
        except (KeyError, OSError):
            user = f"uid{os.getuid()}"
        res_dir = os.path.join(self._default_results_base(),
                               f"elbencho-tpu_results_{user}")
        try:
            os.makedirs(res_dir, mode=0o700, exist_ok=True)
            # /var/tmp is world-writable and the dir name predictable: only
            # trust a real directory owned by us (no attacker symlink/dir)
            st = os.lstat(res_dir)
            if not stat_mod.S_ISDIR(st.st_mode) or st.st_uid != os.getuid():
                return
        except OSError:
            return  # read-only /var/tmp: keep explicit-only result files
        date = datetime.date.today().strftime("%Y%m%d")
        if not self.res_file_path:
            self.res_file_path = \
                f"{res_dir}/elbencho-tpu_results_{date}.txt"
        if not self.csv_file_path:
            self.csv_file_path = \
                f"{res_dir}/elbencho-tpu_results_{date}.csv"
            # an implicit file may be rotated on column-count mismatch
            # (a flag-set change across versions must not fail runs that
            # never asked for CSV output; explicit --csvfile still errors)
            self._defaulted_csv = True
        if not self.json_file_path:
            self.json_file_path = \
                f"{res_dir}/elbencho-tpu_results_{date}.json"

    def _apply_s3_env_credentials(self) -> None:
        """S3 credentials/endpoint from the standard environment variables
        when flags are empty (reference: S3_ENV_* handling,
        ProgArgs.cpp:1207-1230; non-service runs only — a service must use
        exactly what the master shipped, not its own local environment)."""
        if self.run_as_service or getattr(self, "_service_side", False) \
                or self.bench_mode != BenchMode.S3:
            return
        if not self.s3_access_key:
            self.s3_access_key = os.environ.get("AWS_ACCESS_KEY_ID", "")
        if not self.s3_secret_key:
            self.s3_secret_key = os.environ.get("AWS_SECRET_ACCESS_KEY", "")
        if not self.s3_session_token:
            self.s3_session_token = os.environ.get("AWS_SESSION_TOKEN", "")
        if not self.s3_endpoints_str:
            self.s3_endpoints_str = os.environ.get(
                "AWS_ENDPOINT_URL_S3", os.environ.get(
                    "AWS_ENDPOINT_URL", ""))

    # -- validation (reference: checkArgs/checkPathDependentArgs) -----------

    def check(self) -> None:
        if self.num_threads < 1:
            raise ConfigError("--threads must be >= 1")
        if self.block_size < 1 and self.file_size > 0:
            raise ConfigError("--block must be >= 1")
        if self.file_size and self.block_size > self.file_size:
            # reference reduces blocksize to filesize with a note
            self.block_size = self.file_size
        self._reduce_file_size_to_block_multiple()
        if self.use_direct_io and not self.no_direct_io_check:
            align = 512
            if self.file_size % align or self.block_size % align:
                raise ConfigError(
                    "direct I/O requires file size and block size to be "
                    "multiples of 512 bytes (use --nodiocheck to override)")
        if self.tpu_multihost and self.tpu_multihost != "auto":
            parts = self.tpu_multihost.split(",")
            if ":" not in parts[0] or len(parts) > 3:
                raise ConfigError(
                    "--tpumultihost must be 'auto' or "
                    "'host:port[,num_processes,process_id]'")
            try:
                [int(p) for p in parts[1:]]
            except ValueError as err:
                raise ConfigError(
                    "--tpumultihost process counts must be integers") \
                    from err
            if len(parts) == 3 and self.hosts:
                raise ConfigError(
                    "--tpumultihost with an explicit process_id cannot be "
                    "combined with --hosts (every service would join with "
                    "the same id; give just 'host:port' and the master "
                    "assigns per-host ids)")
        if self.io_engine not in ("auto", "sync", "aio", "uring"):
            raise ConfigError("--ioengine must be auto|sync|aio|uring")
        if self.pool_registration not in ("auto", "off"):
            raise ConfigError("--poolreg must be auto|off")
        if self.io_sqpoll:
            if self.pool_registration == "off":
                raise ConfigError(
                    "--iosqpoll rides the registered staging-pool ring; "
                    "it cannot be combined with --poolreg off")
            if self.io_engine in ("sync", "aio"):
                raise ConfigError(
                    "--iosqpoll applies to the io_uring paths only; "
                    "--ioengine sync/aio would silently never use it")
        if self.io_sqpoll_idle_ms <= 0:
            raise ConfigError("--iosqpollidle must be > 0 milliseconds")
        if self.madvise_flags:
            flags = [f.strip() for f in self.madvise_flags.split(",")
                     if f.strip()]
            known = {"seq", "rand", "willneed", "dontneed", "hugepage",
                     "nohugepage"}
            unknown = [f for f in flags if f not in known]
            if unknown:
                raise ConfigError(
                    f"unknown --madv flag(s): {', '.join(unknown)} "
                    f"(valid: {', '.join(sorted(known))})")
            if "hugepage" in flags and "nohugepage" in flags:
                # genuinely contradictory: one advice per region wins in
                # the kernel, so accepting both would silently ignore one
                raise ConfigError(
                    "--madv hugepage and nohugepage are contradictory")
        if self.object_backend not in ("", "s3", "gcs"):
            raise ConfigError("--objectbackend must be s3 or gcs")
        if self.gcs_resumable and self.s3_mpu_sharing:
            raise ConfigError(
                "--gcsresumable uploads are sequential per worker and "
                "cannot serve shared cross-worker multipart uploads "
                "(--s3mpusharing); use the default compose mode instead")
        if self.gcs_resumable and self.io_depth > 1:
            # the async pipeline gives each executor thread its own
            # client: part uploads would miss the session-owning client's
            # state, silently fall through to the compose path, and the
            # finalize would commit a zero-byte object (data loss)
            raise ConfigError(
                "--gcsresumable uploads are sequential per worker and "
                "cannot be pipelined (--iodepth > 1); use the default "
                "compose mode for parallel part uploads")
        if self.use_file_locks not in ("", "range", "full"):
            raise ConfigError("--flock must be range or full")
        if self.io_engine == "sync" and self.io_depth > 1:
            raise ConfigError("--ioengine sync requires --iodepth 1")
        if self.io_engine != "auto" and self.bench_mode != BenchMode.POSIX:
            raise ConfigError(
                "--ioengine selects the native POSIX block-loop engine; "
                "it does not apply to S3/HDFS/netbench modes")
        if self.rwmix_read_pct and not (0 <= self.rwmix_read_pct <= 100):
            raise ConfigError("--rwmixpct must be in 0..100")
        if self.block_variance_pct and \
                not (0 <= self.block_variance_pct <= 100):
            raise ConfigError("--blockvarpct must be in 0..100")
        if self.num_rwmix_read_threads >= max(1, self.num_threads):
            if self.num_rwmix_read_threads:
                raise ConfigError("--rwmixthr must be < number of threads")
        if self.integrity_check_salt and self.block_variance_pct:
            raise ConfigError("--verify and --blockvarpct are incompatible")
        if self.use_random_offsets and self.integrity_check_salt \
                and not self.no_random_align and self.run_create_files \
                and self.run_read_files:
            pass  # full-coverage LCG makes this safe (every block exactly once)
        if self.use_mmap and self.use_direct_io:
            raise ConfigError("--mmap and --direct are incompatible")
        if self.use_mmap and self.bench_mode == BenchMode.POSIX \
                and self.bench_path_type != BenchPathType.DIR \
                and len(self.paths) > 1:
            raise ConfigError(
                "--mmap supports a single file/blockdev path (striping "
                "across multiple mappings is not implemented)")
        if self.bench_mode == BenchMode.POSIX \
                and self.bench_path_type != BenchPathType.DIR \
                and (self.run_create_dirs or self.run_delete_dirs
                     or self.run_stat_dirs):
            raise ConfigError(
                "directory phases (--mkdirs/--deldirs/--statdirs) require "
                "directory bench paths (path does not exist or is a file/"
                "blockdev)")
        if self.tpu_ids_str and self.bench_mode == BenchMode.NETBENCH:
            raise ConfigError("--tpuids not supported in netbench mode")
        if self.tpu_depth < 0:
            raise ConfigError("--tpudepth must be >= 0 (0 = use --iodepth)")
        if self.tpu_dispatch_budget_usec < 0:
            raise ConfigError("--tpubudget must be >= 0 (0 = no budget)")
        if (self.tpu_depth or self.tpu_dispatch_budget_usec) \
                and not self.tpu_ids_str and not self.tpu_ids \
                and not self.run_tpu_bench and not self.run_tpu_slice:
            raise ConfigError(
                "--tpudepth/--tpubudget tune the TPU transfer pipeline — "
                "they need --tpuids (or --tpubench/--tpuslice)")
        if self.run_tpu_slice:
            if self.bench_mode != BenchMode.POSIX:
                raise ConfigError(
                    "--tpuslice stripes POSIX file/blockdev paths over "
                    "the chip mesh; it does not apply to "
                    "S3/HDFS/netbench modes")
            if self.bench_path_type == BenchPathType.DIR \
                    and not self.hosts:
                # master mode defers to the services' probed path type
                # (_check_service_bench_path_infos re-runs check() with
                # it; each service validates its own probe too)
                raise ConfigError(
                    "--tpuslice requires file/blockdev bench paths (a "
                    "directory tree is not striped over chips)")
            if self.use_mmap:
                raise ConfigError(
                    "--tpuslice reads shards through the staging pool; "
                    "incompatible with --mmap")
            if self.block_size % 4:
                raise ConfigError(
                    "--tpuslice shards are uint32 arrays: --block must "
                    "be a multiple of 4 bytes")
        from ..parallel.slice_phase import REDIST_SPEC_NAMES
        if self.redist_spec not in REDIST_SPEC_NAMES:
            raise ConfigError(
                f"--redistspec must be one of "
                f"{'|'.join(REDIST_SPEC_NAMES)}")
        if self.redist_spec != "alltoall" and not self.run_tpu_slice:
            raise ConfigError(
                "--redistspec shapes the --tpuslice redistribution "
                "target — it does nothing without --tpuslice")
        if self.mesh_shape_str:
            if not self.run_tpu_slice:
                raise ConfigError(
                    "--meshshape shapes the --tpuslice mesh — it does "
                    "nothing without --tpuslice")
            from ..parallel.slice_phase import (MeshShapeError,
                                                parse_mesh_shape)
            try:  # geometry vs device count is checked at phase time
                parse_mesh_shape(self.mesh_shape_str)
            except MeshShapeError as err:
                raise ConfigError(str(err)) from None
        if self.tpu_stream not in ("auto", "on", "off"):
            raise ConfigError("--tpustream must be auto|on|off")
        if self.tpu_stream == "on" and not self.tpu_ids_str \
                and not self.tpu_ids and not self.run_tpu_slice:
            raise ConfigError(
                "--tpustream on requires --tpuids or --tpuslice (the "
                "fused loop streams storage into TPU staging slots)")
        if self.tpu_stream == "on" and self.run_tpu_bench:
            # --tpubench does synthetic HBM transfers only and never
            # reaches the block loop: "on" would silently pass green
            raise ConfigError(
                "--tpustream on has no effect under --tpubench (no "
                "storage loop to fuse); drop one of the two")
        if self.tpu_stream == "on" and (
                self.use_mmap or self.bench_mode != BenchMode.POSIX):
            # paths that never reach the block-sized file loop (mmap
            # memcpy, object/netbench data planes) can't honor the
            # fail-loudly contract — reject up front instead of letting
            # a CI gate pass green with the fused loop never engaged
            raise ConfigError(
                "--tpustream on requires the POSIX block I/O path "
                "(incompatible with --mmap and object/netbench modes); "
                "use --tpustream auto there")
        if self.tpu_batch_blocks > 1 and self.do_tpu_verify:
            # the aggregated DMA span skips the per-block on-device check
            # (host_to_device's aggregation branch returns before the
            # verify hook) — reject the combination instead of silently
            # verifying nothing
            raise ConfigError(
                "--tpubatch > 1 cannot be combined with --tpuverify: the "
                "aggregated span has no per-block on-device check — drop "
                "one of the two")
        if self.run_s3_mpu_complete_phase and not self.s3_mpu_sharing:
            raise ConfigError(
                "--s3mpucomplphase requires --s3mpusharing (only shared "
                "uploads defer completion to the MPUCOMPL phase)")
        if self.s3_checksum_algo and self.s3_checksum_algo.lower() not in (
                "crc32", "crc32c", "sha1", "sha256"):
            raise ConfigError(
                "--s3checksumalgo must be crc32|crc32c|sha1|sha256")
        if self.s3_checksum_algo and self.s3_mpu_sharing:
            raise ConfigError(
                "--s3checksumalgo is not supported with --s3mpusharing "
                "(shared completions don't track per-part checksums)")
        if self.s3_acl_grantee_type and self.s3_acl_grantee_type not in (
                "id", "email", "uri", "group"):
            raise ConfigError("--s3aclgtype must be id|email|uri|group")
        if self.s3_fast_get and (self.integrity_check_salt
                                 or self.tpu_ids_str):
            raise ConfigError(
                "--s3fastget discards downloaded data — incompatible with "
                "--verify and --tpuids staging")
        if self.bench_mode == BenchMode.S3 and self.run_create_files \
                and self.file_size and self.block_size \
                and not self.s3_ignore_part_num_check \
                and not self.s3_no_mpu \
                and self.file_size > self.block_size \
                and (self.file_size + self.block_size - 1) \
                // self.block_size > 10000:
            raise ConfigError(
                "object size / block size exceeds 10,000 multipart parts "
                "(the S3 protocol limit; --s3nompcheck to override)")
        if self.s3_acl_grantee and (
                self.run_s3_acl_put or self.run_s3_bucket_acl_put
                or self.do_s3_acl_put_inline):
            from ..toolkits.s3_tk import build_acl_headers
            try:  # surface grant mistakes at config time, not mid-phase
                build_acl_headers(self.s3_acl_grantee,
                                  self.s3_acl_grantee_type,
                                  self.s3_acl_grants)
            except ValueError as err:
                raise ConfigError(str(err)) from err
        if not (0 < self.telemetry_port < 65536):
            raise ConfigError("--telemetryport must be in 1..65535")
        # no service-side telemetry-port checks: the standalone exporter
        # only ever starts in local/master mode (service mode serves
        # /metrics on its control --port), and the master's flags travel
        # the config wire to hosts where its port numbers mean nothing
        if not (0.0 <= self.trace_sample <= 1.0):
            raise ConfigError("--tracesample must be in 0..1")
        if self.trace_sample != 1.0 and not self.trace_file_path:
            raise ConfigError(
                "--tracesample tunes the --tracefile span recorder — "
                "give --tracefile PATH")
        if self.flightrec_file_path and self.run_as_service:
            raise ConfigError(
                "--flightrec records at the master/local coordinator "
                "(service counters already reach it over the existing "
                "wire) — arm --flightrec on the master instead")
        if self.trace_fleet not in ("auto", "on", "off"):
            raise ConfigError("--tracefleet must be auto|on|off")
        if self.trace_fleet == "on" and not self.trace_file_path:
            raise ConfigError(
                "--tracefleet merges --tracefile span rings — give "
                "--tracefile PATH")
        if self.trace_ship_cap_mib < 1:
            raise ConfigError("--traceshipcap must be >= 1 (MiB)")
        if self.slow_ops_k < 0:
            raise ConfigError("--slowops must be >= 0 (0 = off)")
        if not (0.0 <= self.op_sample_rate <= 1.0):
            raise ConfigError("--opsample must be in 0..1")
        if self.op_sample_rate != 1.0 and not self.slow_ops_k:
            raise ConfigError(
                "--opsample tunes the --slowops density sample — give "
                "--slowops K")
        if self.io_num_retries < 0:
            raise ConfigError("--ioretries must be >= 0")
        if self.io_retry_budget_secs < 0:
            raise ConfigError("--ioretrybudget must be >= 0")
        if self.io_timeout_secs < 0:
            raise ConfigError("--iotimeout must be >= 0")
        if self.io_timeout_secs and self.bench_mode != BenchMode.POSIX:
            raise ConfigError(
                "--iotimeout applies to the native streaming ring (POSIX "
                "block I/O); object/netbench transports already bound "
                "their requests via HTTP timeouts")
        if self.tpu_fallback not in ("abort", "chip", "host"):
            raise ConfigError("--tpufallback must be abort|chip|host")
        if self.tpu_fallback != "abort" and not self.tpu_ids_str \
                and not self.tpu_ids:
            raise ConfigError(
                "--tpufallback tunes the TPU chip-failover path — it "
                "needs --tpuids")
        if os.environ.get("ELBENCHO_TPU_IO_FAULT") \
                and os.environ.get("ELBENCHO_TPU_TESTING") != "1":
            # deterministic fault injection is a TEST-ONLY knob: a
            # release benchmark run with it set would silently publish
            # corrupted-by-design numbers
            raise ConfigError(
                "ELBENCHO_TPU_IO_FAULT is a test-only fault-injection "
                "knob (docs/fault-tolerance.md); refusing to run with it "
                "set outside a test harness (ELBENCHO_TPU_TESTING=1)")
        if os.environ.get("ELBENCHO_TPU_IO_FAULT"):
            from ..utils.native import parse_fault_spec
            try:  # malformed specs fail at config time, not mid-phase
                parse_fault_spec(os.environ["ELBENCHO_TPU_IO_FAULT"])
            except ValueError as err:
                raise ConfigError(str(err)) from None
        if self.svc_num_retries < 0:
            raise ConfigError("--svcretries must be >= 0")
        if self.svc_retry_budget_secs < 0:
            raise ConfigError("--svcretrybudget must be >= 0")
        if self.svc_stalled_secs < 0:
            raise ConfigError("--svcstalledsecs must be >= 0")
        if self.svc_tolerant_hosts < 0:
            raise ConfigError("--svctolerant must be >= 0")
        if self.svc_tolerant_hosts and self.hosts \
                and self.svc_tolerant_hosts >= len(self.hosts):
            raise ConfigError(
                "--svctolerant must leave at least one surviving host "
                "(got tolerance for all given --hosts)")
        if self.svc_tolerant_hosts and self.run_netbench:
            raise ConfigError(
                "--svctolerant is incompatible with --netbench (the "
                "client/server topology cannot lose hosts mid-run)")
        if self.svc_fanout < 0:
            raise ConfigError("--svcfanout must be >= 0")
        if self.svc_fanout and not (self.svc_stream or self.quit_services
                                    or self.interrupt_services):
            raise ConfigError(
                "--svcfanout shapes the --svcstream aggregation tree "
                "(or the --interrupt/--quit fan-out) — it does nothing "
                "for the polling control plane")
        # NOTE: per-host stream state is keyed by host label; duplicate
        # --hosts entries are already rejected for everyone at derive().
        # Netbench topologies ride --svcstream like any other phase (the
        # client/server roles only shape the DATA plane; live stats flow
        # over /livestream unchanged) — the former rejection is lifted
        # (ROADMAP item 3 leftover; tests/test_netbench.py covers it).
        if self.svc_lease_secs < 0:
            raise ConfigError("--svcleasesecs must be >= 0")
        if self.svc_lease_secs \
                and self.svc_lease_secs * 1000 <= self.svc_update_interval_ms:
            # the /status poll IS the lease renewal: a lease shorter than
            # the poll cadence would orphan services mid-run with the
            # master alive and well
            raise ConfigError(
                "--svcleasesecs must exceed the --svcupint poll interval "
                "(every /status poll renews the lease)")
        if self.autotune_secs < 0:
            raise ConfigError("--autotune must be >= 0 seconds (0 = off)")
        if self.autotune_probes < 0:
            raise ConfigError("--autotune-probes must be >= 0 (0 = "
                              "bounded by the time budget only)")
        if self.autotune_probe_secs < 1:
            raise ConfigError("--autotune-probesecs must be >= 1")
        if self.autotune_repeat < 1:
            raise ConfigError("--autotune-repeat must be >= 1")
        if not self.autotune_secs and (
                self.autotune_profile_path or self.autotune_probes
                or self.autotune_probe_secs
                != FLAG_DEFAULTS["autotune_probe_secs"]
                or self.autotune_repeat
                != FLAG_DEFAULTS["autotune_repeat"]):
            raise ConfigError(
                "--autotune-profile/--autotune-probes/"
                "--autotune-probesecs/--autotune-repeat tune the "
                "--autotune search — give --autotune [SECS]")
        if self.autotune_secs:
            if self.run_as_service:
                raise ConfigError(
                    "--autotune runs at the master/local coordinator "
                    "(services execute probes like any phase, they "
                    "never tune) — arm it on the master instead")
            if self.resume_run:
                raise ConfigError(
                    "--autotune cannot be combined with --resume: the "
                    "journaled phases ran at a tuned point this resume "
                    "would not reproduce — re-run with -c PROFILE "
                    "instead of re-tuning")
            if self.scenario:
                raise ConfigError(
                    "--autotune and --scenario both drive per-step "
                    "config overlays through the coordinator — tune a "
                    "plain -w/-r run first, then run the scenario with "
                    "the emitted -c PROFILE")
            if not self.run_create_files and not self.run_read_files:
                raise ConfigError(
                    "--autotune probes the run's first write or read "
                    "phase — it needs -w or -r")
        if self.resume_run and not self.journal_file_path:
            raise ConfigError(
                "--resume replays a run journal — give --journal FILE "
                "(the same path the interrupted run journaled to)")
        if self.svc_adopt_secs < 0:
            raise ConfigError("--svcadoptsecs must be >= 0")
        if self.adopt_run and not self.resume_run:
            raise ConfigError(
                "--adopt is a takeover mode of --resume (the journal "
                "names the fleet and the in-flight phase) — give "
                "--resume --adopt --journal FILE")
        if self.standby_str:
            if not self.journal_file_path:
                raise ConfigError(
                    "--standby takes over by replaying the primary's "
                    "journal — give --journal FILE on storage this "
                    "standby can read")
            if self.resume_run or self.adopt_run:
                raise ConfigError(
                    "--standby arms --resume --adopt by itself at "
                    "takeover time — do not combine them")
            if self.run_as_service:
                raise ConfigError(
                    "--standby is a master role (a warm replacement "
                    "coordinator) — it cannot run as --service")
        if self.scenario_opts_str and not self.scenario:
            raise ConfigError(
                "--scenario-opt tunes a --scenario; give --scenario NAME")
        if self.shuffle_window:
            if self.use_random_offsets or self.do_reverse_seq_offsets \
                    or self.do_strided_access or self.use_mmap:
                raise ConfigError(
                    "--shufflewindow drives its own offset permutation — "
                    "incompatible with --rand/--backward/--strided/--mmap")
            if self.block_size and self.shuffle_window < self.block_size:
                raise ConfigError(
                    "--shufflewindow must be at least one --block")
        if self.scenario:
            from ..scenarios import validate_scenario
            validate_scenario(self)
        if self.run_netbench:
            if not self.hosts and not self.netbench_total_hosts:
                raise ConfigError(
                    "netbench requires distributed mode: --hosts with at "
                    "least 2 hosts (first --netbenchservers act as servers)")
            if self.num_netbench_servers < 1:
                raise ConfigError("--netbenchservers must be >= 1")
            if self.hosts and len(self.hosts) <= self.num_netbench_servers:
                raise ConfigError(
                    "netbench needs more --hosts than --netbenchservers "
                    "(servers don't generate load)")

    # -- phase selection getters (used by Coordinator ordering table) --------

    def enabled_phases(self) -> "list[BenchPhase]":
        """Ordered phase list (reference: the 21-entry ordering table in
        Coordinator.cpp:311-334 — creates before deletes, bucket metadata
        around bucket lifecycle, object metadata around object lifecycle)."""
        p = []
        bucket_md = (self.run_s3_bucket_tagging
                     or self.run_s3_bucket_versioning
                     or self.run_s3_object_lock_cfg)
        if self.run_create_dirs:
            p.append(BenchPhase.CREATEDIRS)
        if self.run_s3_bucket_acl_put:
            p.append(BenchPhase.PUTBUCKETACL)
        # PUT/DEL metadata phases mutate the dataset, so they are gated on
        # the create/delete phases (reference: ProgArgs.h:659-667); GET
        # phases run whenever the metadata flag is set
        if bucket_md and self.run_create_dirs:
            p.append(BenchPhase.PUT_BUCKET_MD)
        if self.run_stat_dirs:
            p.append(BenchPhase.STATDIRS)
        if bucket_md:
            p.append(BenchPhase.GET_BUCKET_MD)
        if self.run_create_files:
            p.append(BenchPhase.CREATEFILES)
        if self.run_s3_mpu_complete_phase:
            p.append(BenchPhase.S3MPUCOMPLETE)
        if self.run_s3_acl_put:
            p.append(BenchPhase.PUTOBJACL)
        if self.run_s3_object_tagging and self.run_create_files:
            p.append(BenchPhase.PUT_OBJ_MD)
        if self.run_stat_files:
            p.append(BenchPhase.STATFILES)
        if self.run_s3_object_tagging:
            p.append(BenchPhase.GET_OBJ_MD)
        if self.run_s3_acl_get:
            p.append(BenchPhase.GETOBJACL)
        if self.run_list_objects_num and not self.run_list_objects_parallel:
            p.append(BenchPhase.LISTOBJECTS)
        if self.run_list_objects_parallel:
            p.append(BenchPhase.LISTOBJPARALLEL)
        if self.run_read_files:
            p.append(BenchPhase.READFILES)
        if self.run_tpu_slice:
            # after the read phase, before deletes: the slice phase reads
            # the striped dataset the write phase of this run created
            p.append(BenchPhase.TPUSLICE)
        if self.run_s3_object_tagging and self.run_delete_files:
            p.append(BenchPhase.DEL_OBJ_MD)
        if self.run_multi_delete_num:
            p.append(BenchPhase.MULTIDELOBJ)
        if self.run_delete_files:
            p.append(BenchPhase.DELETEFILES)
        if bucket_md and self.run_delete_dirs:
            p.append(BenchPhase.DEL_BUCKET_MD)
        if self.run_s3_bucket_acl_get:
            p.append(BenchPhase.GETBUCKETACL)
        if self.run_delete_dirs:
            p.append(BenchPhase.DELETEDIRS)
        if self.run_netbench:
            p.append(BenchPhase.NETBENCH)
        if self.run_tpu_bench:
            p.append(BenchPhase.TPUBENCH)
        return p

    # -- service protocol round-trip ----------------------------------------

    def to_service_dict(self, service_rank_offset: int = 0,
                        protocol_version: "str | None" = None) -> dict:
        """Full effective config as a JSON-able dict for POST /preparephase
        (reference: getAsPropertyTreeForService, ProgArgs.cpp:3921 — ships
        every flag plus the per-host rank offset)."""
        from .. import HTTP_PROTOCOL_VERSION
        d = {f.name: getattr(self, f.name)
             for f in dataclasses.fields(self)}
        d["rank_offset"] = self.rank_offset + service_rank_offset
        d["ProtocolVersion"] = protocol_version or HTTP_PROTOCOL_VERSION
        # which size values the USER gave (vs master-side derived
        # defaults): the service's own probe must be allowed to recompute
        # defaults for ITS paths, but never to clobber explicit input
        d["RandomAmountExplicit"] = getattr(
            self, "_random_amount_explicit", bool(self.random_amount))
        d["FileSizeExplicit"] = getattr(
            self, "_file_size_explicit", bool(self.file_size))
        # master never ships its own hosts list / service flags to services
        d["hosts_str"] = ""
        d["hosts_file_path"] = ""
        d["run_as_service"] = False
        # control-plane fault tolerance is the MASTER's job; a service
        # makes no outbound control calls (and e.g. --svctolerant would
        # trip host-count validation against the stripped hosts list)
        d["svc_tolerant_hosts"] = 0
        d["svc_stalled_secs"] = 0
        # the streaming plane is master-side transport; services learn
        # their tree role per /livestream request (Subtree/Fanout params),
        # never from the config wire
        d["svc_stream"] = False
        d["svc_fanout"] = 0
        # result files are written by the master only (the reference never
        # serializes resFilePath* to services)
        d["res_file_path"] = d["csv_file_path"] = d["json_file_path"] = ""
        # the flight recorder is master-side only: the master samples the
        # live frames it already ingests, so services never record (and
        # pay zero extra requests for a recorded run)
        d["flightrec_file_path"] = ""
        # the run journal is the MASTER's restart point; services never
        # journal (svc_lease_secs deliberately stays on the wire — it IS
        # the lease advertisement the service watchdog arms on)
        d["journal_file_path"] = ""
        d["resume_run"] = False
        # takeover orchestration is master-side; svc_adopt_secs stays on
        # the wire like svc_lease_secs (the /preparephase IS the grace
        # advertisement the awaiting-adoption state arms on)
        d["adopt_run"] = False
        d["standby_str"] = ""
        # scenario composition is master-side: services receive each
        # step's EFFECTIVE config (the overlay knobs below stay on the
        # wire: shuffle_window, scenario_epoch, the loader pacing set,
        # scenario_step_label), never the plan itself — a service must
        # not re-expand and re-run the whole scenario per step
        d["scenario"] = ""
        d["scenario_opts_str"] = ""
        # the autotune search is master-side orchestration: services run
        # probe phases exactly like measured phases (each probe's tuned
        # candidate arrives via the normal re-prepare), they never tune.
        # Sub-knobs reset to their DEFAULTS so the service-side check()
        # never trips the "--autotune-* without --autotune" gate.
        d["autotune_secs"] = 0
        d["autotune_profile_path"] = ""
        d["autotune_probes"] = 0
        d["autotune_probe_secs"] = FLAG_DEFAULTS["autotune_probe_secs"]
        d["autotune_repeat"] = FLAG_DEFAULTS["autotune_repeat"]
        d["num_dataset_threads_override"] = self.num_dataset_threads
        if self.assign_tpu_per_service and self.tpu_ids:
            # --tpuperservice: round-robin chips across service instances —
            # each service gets ONE chip from the list instead of all
            # workers sharing it (reference: --gpuperservice, ProgArgs.h:378)
            host_idx = service_rank_offset // max(self.num_threads, 1)
            d["tpu_ids_str"] = str(
                self.tpu_ids[host_idx % len(self.tpu_ids)])
        if self.tpu_multihost and self.tpu_multihost != "auto" \
                and self.hosts:
            # manual coordinator: every service joins with its own
            # process_id (host index); num_processes = number of hosts
            host_idx = service_rank_offset // max(self.num_threads, 1)
            coordinator = self.tpu_multihost.split(",")[0]
            d["tpu_multihost"] = \
                f"{coordinator},{len(self.hosts)},{host_idx}"
        if self.run_netbench and self.hosts:
            # netbench topology: server data port = service port + 1000
            # (reference: LocalWorker.cpp:646 servers listen on svc+1000)
            servers = []
            for host in self.hosts[:self.num_netbench_servers]:
                name, _, port = host.partition(":")
                data_port = (int(port) if port else self.service_port) + 1000
                servers.append(f"{name}:{data_port}")
            d["netbench_servers_str"] = ",".join(servers)
            d["netbench_total_hosts"] = len(self.hosts)
        return d

    @classmethod
    def from_service_dict(cls, d: dict, derive: bool = True) \
            -> "BenchConfig":
        """Rebuild effective config on the service side
        (reference: setFromPropertyTreeForService, ProgArgs.cpp:3754).

        derive=False defers derivation/validation so the caller can apply
        service-side overrides (pinned --path / --tpuids) FIRST — deriving
        against the master's paths would probe devices this service will
        never touch. The caller must then run derive() + check() itself."""
        d = dict(d)
        d.pop("ProtocolVersion", None)
        cfg = cls(**{k: v for k, v in d.items()
                     if k in {f.name for f in dataclasses.fields(cls)}})
        cfg._service_side = True  # no default result files on services
        # master-declared explicitness beats the local bool(value) guess:
        # a master-derived default must stay recomputable against the
        # service's own (possibly pinned) paths
        if "RandomAmountExplicit" in d:
            cfg._random_amount_explicit = bool(d["RandomAmountExplicit"])
        if "FileSizeExplicit" in d:
            cfg._file_size_explicit = bool(d["FileSizeExplicit"])
        if derive:
            cfg.derive()
            cfg.check()
        return cfg

    def config_labels(self) -> "dict[str, str]":
        """Flat config key/value labels for CSV/JSON results
        (reference: getAsStringVec, ProgArgs.cpp:4065)."""
        out = {}
        for f in dataclasses.fields(self):
            val = getattr(self, f.name)
            if isinstance(val, list):
                val = ",".join(str(v) for v in val)
            out[f.name] = str(val)
        return out


# ---------------------------------------------------------------------------
# CLI building
# ---------------------------------------------------------------------------

HELP_CATEGORIES = {
    "help": "essential",
    "help-multi": "multi",
    "help-large": "large",
    "help-dist": "dist",
    "help-s3": "s3",
    "help-tpu": "tpu",
    "help-bdev": "large",  # reference tier name; block devices use the
                           # large-file/random-I/O flag set here
    "help-all": None,  # all categories
}

# reference CUDA/GPU flags -> the TPU-native replacement to suggest; using
# one produces a directed error instead of "unrecognized argument"
CUDA_FLAG_HINTS = {
    "gpuids": "--tpuids", "gpuperservice": "--tpuperservice",
    "cufile": "--tpudirect", "gds": "--tpudirect",
    "gdsbufreg": "--tpudirect", "cuhostbufreg": "--tpuids",
    "cufiledriveropen": "--tpudirect",
}

# reference long-flag spellings accepted as aliases, so command lines
# written for the reference keep working (alias -> our canonical flag)
REF_FLAG_ALIASES = {
    "dropcache": "dropcaches",       # reference: ARG_DROPCACHESPHASE_LONG
    "nodetach": "foreground",        # reference: ARG_NODETACH_LONG
    "numservers": "netbenchservers",  # reference: ARG_NUMSERVERS_LONG
    "s3statdirs": "statdirs",        # "bucket attributes query phase"
    "s3chksumalgo": "s3checksumalgo",  # reference hidden compat alias
}


def build_arg_parser():
    import argparse
    # allow_abbrev=False: with 180+ flags, silent prefix matching is a
    # data-semantics hazard — e.g. "--s3nompu" would resolve to
    # --s3nompucompl (deliberately-unfinalized MPUs) while reading like
    # "single PUT, no multipart" (--s3single). The reference's
    # boost::program_options CLI matches flags exactly too.
    parser = argparse.ArgumentParser(
        prog="elbencho-tpu", add_help=False, allow_abbrev=False,
        description="TPU-native distributed storage benchmark "
                    "(files, block devices, object storage; HBM data path)")
    parser.add_argument("paths", nargs="*", help="Benchmark paths "
                        "(dirs, files, block devices, or s3:// buckets)")
    # reference compat: paths can also be passed as "--path P" options
    # (ARG_BENCHPATHS_LONG is the positional-args name there); separate
    # dest because the empty positional list would clobber appended values
    parser.add_argument("--path", dest="path_opts", action="append",
                        default=[], metavar="V", help=argparse.SUPPRESS)
    for cuda_flag in CUDA_FLAG_HINTS:
        # nargs="?" so both "--gpuids 0,1" and bare "--cufile" parse; any
        # use is rejected in parse_cli with the TPU-equivalent hint
        parser.add_argument(f"--{cuda_flag}", dest=f"cuda_{cuda_flag}",
                            nargs="?", const=True, default=None,
                            help=argparse.SUPPRESS)
    for hf in HELP_CATEGORIES:
        names = [f"--{hf}"] + (["-h"] if hf == "help" else [])
        parser.add_argument(*names, action="store_true",
                            dest=hf.replace("-", "_"),
                            help=argparse.SUPPRESS)
    parser.add_argument("--version", action="store_true",
                        help="Show version and build info")
    for flag, short, dest, kind, default, _cat, help_txt in FLAG_DEFS:
        names = [f"--{flag}"] + ([f"-{short}"] if short else [])
        names += [f"--{alias}" for alias, target in REF_FLAG_ALIASES.items()
                  if target == flag]
        if kind == "bool":
            parser.add_argument(*names, dest=dest, action="store_true",
                                default=default, help=help_txt)
        elif kind == "optint":
            # optional value: the bare flag means OPTINT_BARE[flag]
            parser.add_argument(*names, dest=dest, metavar="V",
                                type=int, nargs="?",
                                const=OPTINT_BARE[flag],
                                default=default, help=help_txt)
        else:
            parser.add_argument(*names, dest=dest, metavar="V",
                                type=_KIND_PARSERS[kind], default=default,
                                help=help_txt)
    return parser


def _apply_config_file(cfg_path: str, namespace, parser) -> None:
    """ini-style "flag = value" config file merge (reference: --configfile,
    docs/example_configuration/random-write.elbencho). CLI args win."""
    import argparse
    defaults = parser.parse_args([])
    with open(cfg_path) as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith(("#", ";", "[")):
                continue
            key, _, val = line.partition("=")
            key, val = key.strip(), val.strip()
            match = next((fd for fd in FLAG_DEFS if fd[0] == key), None)
            if match is None:
                raise ConfigError(f"unknown flag in config file: {key!r}")
            _, _, dest, kind, _, _, _ = match
            # only apply if user did not override on the CLI
            if getattr(namespace, dest) != getattr(defaults, dest):
                continue
            if kind == "bool":
                parsed = val.lower() not in ("0", "false", "no", "")
            else:
                parsed = _KIND_PARSERS[kind](val)
            setattr(namespace, dest, parsed)


def _normalize_optint_argv(argv: "list[str]") -> "list[str]":
    """optint flags take an OPTIONAL integer: when the next token is
    not a plain integer (usually the bench path), the flag is bare —
    rewrite it to its =BARE form so argparse never eats the path."""
    out: "list[str]" = []
    flags = {f"--{flag}": bare for flag, bare in OPTINT_BARE.items()}
    for i, tok in enumerate(argv):
        if tok in flags and not (i + 1 < len(argv)
                                 and argv[i + 1].isdigit()):
            out.append(f"{tok}={flags[tok]}")
            continue
        out.append(tok)
    return out


def parse_cli(argv: "list[str] | None" = None) -> "tuple[BenchConfig, object]":
    """Parse CLI into (BenchConfig, raw_namespace). Help/version handling is
    the caller's job (cli.py) so it can render tiered help."""
    import sys as sys_mod
    parser = build_arg_parser()
    argv = list(sys_mod.argv[1:]) if argv is None else list(argv)
    ns = parser.parse_args(_normalize_optint_argv(argv))
    if ns.config_file_path:
        _apply_config_file(ns.config_file_path, ns, parser)
    ns.paths = list(ns.paths) + list(ns.path_opts)  # merge --path options
    for cuda_flag, hint in CUDA_FLAG_HINTS.items():
        if getattr(ns, f"cuda_{cuda_flag}") is not None:
            raise ConfigError(
                f"--{cuda_flag} is a CUDA/GPU flag of the reference; this "
                f"framework drives TPUs — use {hint} instead")
    field_names = {f.name for f in dataclasses.fields(BenchConfig)}
    kwargs = {k: v for k, v in vars(ns).items() if k in field_names}
    cfg = BenchConfig(**kwargs)
    return cfg, ns
