"""Netbench phase (placeholder until the raw-TCP benchmark lands;
reference surface: LocalWorker.cpp:626-819, 7789-8064)."""

from __future__ import annotations

from .shared import WorkerException


def run_netbench_phase(worker, phase) -> None:
    raise WorkerException("netbench mode is not available yet in this build")
