"""Netbench: raw-TCP request/response network benchmark.

Reference: the netbench mode of source/workers/LocalWorker.cpp — init
:626-819 (first N hosts are servers listening on service port + 1000; the
first worker of a server accepts ALL connections and distributes them to
its sibling workers :646-728; each remaining host is a client whose threads
open one connection each, round-robin across servers, optional --netdevs
SO_BINDTODEVICE :762-766, 20s connect retry :784-818), transfer loop
:7789-8064 (server polls its connection share and answers each received
block of --block bytes with --respsize bytes; client sends blocks and
awaits responses), cleanup :825-881.

Connections are established during worker preparation (a cross-host
barrier: clients retry while servers come up), so the measured NETBENCH
phase contains only transfer traffic.
"""

from __future__ import annotations

import selectors
import socket as socket_mod
import time

from ..phases import BenchPhase
from ..toolkits import logger
from ..toolkits.sockets import BasicSocket, SocketError
from .shared import WorkerException

ACCEPT_TIMEOUT_SECS = 30.0
NETBENCH_PORT_OFFSET = 1000


def _topology(cfg):
    """(host_idx, num_hosts, num_servers, server_endpoints)."""
    if cfg.netbench_total_hosts:
        num_hosts = cfg.netbench_total_hosts
    elif cfg.hosts:
        num_hosts = len(cfg.hosts)
    else:
        raise WorkerException(
            "netbench requires distributed mode (--hosts with at least "
            "2 hosts; first --netbenchservers hosts act as servers)")
    num_servers = max(1, cfg.num_netbench_servers)
    if num_servers >= num_hosts:
        raise WorkerException(
            "netbench needs more hosts than --netbenchservers "
            "(servers don't generate load)")
    host_idx = cfg.rank_offset // max(1, cfg.num_threads)
    servers = [s for s in cfg.netbench_servers_str.split(",") if s]
    return host_idx, num_hosts, num_servers, servers


def prepare_netbench(worker) -> None:
    """Connection establishment during worker prep (reference: :626-819)."""
    cfg = worker.cfg
    host_idx, num_hosts, num_servers, servers = _topology(cfg)
    local_rank = worker.rank % max(1, cfg.num_threads)
    shared = worker.shared
    if host_idx < num_servers:
        _prepare_server(worker, shared, host_idx, num_hosts, num_servers,
                        local_rank)
    else:
        _prepare_client(worker, host_idx, num_servers, servers, local_rank)


def _expected_server_conns(host_idx: int, num_hosts: int, num_servers: int,
                           num_threads: int) -> int:
    total_client_threads = (num_hosts - num_servers) * num_threads
    return sum(1 for c in range(total_client_threads)
               if c % num_servers == host_idx)


def _prepare_server(worker, shared, host_idx, num_hosts, num_servers,
                    local_rank) -> None:
    cfg = worker.cfg
    with shared.cond:
        if not hasattr(shared, "netbench_conns"):
            shared.netbench_conns = None  # set by the accepting worker
    if local_rank == 0:
        # first worker of the server accepts ALL connections (:646-728)
        expected = _expected_server_conns(host_idx, num_hosts, num_servers,
                                          cfg.num_threads)
        listener = BasicSocket()
        listener.set_buffer_sizes(cfg.sock_recv_buf_size,
                                  cfg.sock_send_buf_size)
        listener.listen("0.0.0.0", cfg.service_port + NETBENCH_PORT_OFFSET)
        conns = []
        logger.log(1, f"netbench server: awaiting {expected} connections")
        for _ in range(expected):
            conns.append(listener.accept(timeout=ACCEPT_TIMEOUT_SECS))
        listener.close()
        with shared.cond:
            shared.netbench_conns = conns
            shared.cond.notify_all()
    else:
        with shared.cond:
            deadline = time.monotonic() + ACCEPT_TIMEOUT_SECS + 10
            while shared.netbench_conns is None:
                if time.monotonic() > deadline:
                    raise WorkerException(
                        "netbench: timed out waiting for connections")
                shared.cond.wait(1.0)
    # round-robin distribution of accepted conns to this server's workers
    with shared.cond:
        conns = shared.netbench_conns
    worker._netbench_conns = [c for i, c in enumerate(conns)
                              if i % cfg.num_threads == local_rank]
    worker._netbench_role = "server"


def _prepare_client(worker, host_idx, num_servers, servers,
                    local_rank) -> None:
    cfg = worker.cfg
    if not servers:
        raise WorkerException(
            "netbench: no server endpoints received from master")
    conn_global_idx = ((host_idx - num_servers) * cfg.num_threads
                       + local_rank)
    server = servers[conn_global_idx % num_servers]
    name, _, port = server.partition(":")
    sock = BasicSocket()
    netdevs = [d for d in cfg.netdevs_str.split(",") if d]

    def setup(s: BasicSocket) -> None:
        s.set_buffer_sizes(cfg.sock_recv_buf_size, cfg.sock_send_buf_size)
        if netdevs:
            s.bind_to_device(netdevs[local_rank % len(netdevs)])

    setup(sock)
    sock.connect_with_retry(
        name, int(port), retry_secs=20.0,
        interrupt_check=lambda: worker.check_interruption_request(
            force=True),
        setup_fn=setup)
    worker._netbench_conns = [sock]
    worker._netbench_role = "client"


def cleanup_netbench(worker) -> None:
    for conn in getattr(worker, "_netbench_conns", []):
        conn.close()
    worker._netbench_conns = []


# ---------------------------------------------------------------------------
# transfer phase (reference: :7789-8064)
# ---------------------------------------------------------------------------

def run_netbench_phase(worker, phase: BenchPhase) -> None:
    role = getattr(worker, "_netbench_role", None)
    if role is None:
        prepare_netbench(worker)
        role = worker._netbench_role
    if role == "server":
        _run_server(worker)
    else:
        _run_client(worker)


def _set_native_socket_mode(basic_sock, recv_timeout_secs: int,
                            send_timeout_secs: int) -> None:
    """Blocking fd with kernel-level timeouts for the C++ data plane
    (python settimeout() would flip the fd to non-blocking instead)."""
    import struct
    s = basic_sock.sock
    s.setblocking(True)
    if recv_timeout_secs:
        s.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_RCVTIMEO,
                     struct.pack("ll", recv_timeout_secs, 0))
    if send_timeout_secs:
        s.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_SNDTIMEO,
                     struct.pack("ll", send_timeout_secs, 0))


def _native_engine(worker):
    """The C++ data plane handles the hot loops when no per-op Python
    feature (rate limit, opslog) is active — the netbench analogue of the
    block-loop delegation (reference: BasicSocket C++ plane)."""
    from ..utils.native import get_native_engine
    native = get_native_engine()
    if (native is not None and worker._ops_log is None
            and worker._rate_limiter_read is None
            and worker._rate_limiter_write is None):
        return native
    return None


def _run_client(worker) -> None:
    """Send --size bytes in --block requests; each answered with
    --respsize bytes. Latency = request+response round trip."""
    cfg = worker.cfg
    sock = worker._netbench_conns[0]
    bs = cfg.block_size
    # whole blocks only: the server replies per full --block received, so a
    # trailing partial block would deadlock awaiting a response
    total = max(bs, (cfg.file_size // bs) * bs)
    payload = bytes(worker._io_buf[:bs])
    native = _native_engine(worker)
    if native is not None:
        # BasicSocket timeouts leave the fd non-blocking; the C++ loop
        # needs blocking send/recv. SO_RCVTIMEO bounds each recv to 5s
        # (like the Python path) so the EAGAIN retry inside the C++ loop
        # re-checks the interrupt flag without busy-spinning.
        _set_native_socket_mode(sock, recv_timeout_secs=5,
                                send_timeout_secs=30)
        n_ops = total // bs
        # chunk round trips so interrupts/live stats stay fresh
        per_call = max(1, min(4096, (64 << 20) // max(bs, 1)))
        done = 0
        while done < n_ops:
            worker.check_interruption_request(force=True)
            native.run_net_client_loop(
                sock.sock.fileno(), payload, cfg.netbench_response_size,
                min(per_call, n_ops - done), worker,
                interrupt_flag=worker._native_interrupt)
            done += min(per_call, n_ops - done)
        _client_shutdown(sock)
        return
    sent = 0
    while sent < total:
        worker.check_interruption_request()
        length = min(bs, total - sent)
        if worker._rate_limiter_write:
            worker._rate_limiter_write.wait(length)
        t0 = time.perf_counter_ns()
        sock.send_all(memoryview(payload)[:length], timeout=30.0)
        resp = sock.recv_exact(
            cfg.netbench_response_size, timeout=5.0,
            interrupt_check=lambda: worker.check_interruption_request(
                force=True))
        lat_usec = (time.perf_counter_ns() - t0) // 1000
        worker.iops_latency_histo.add_latency(lat_usec)
        worker.live_ops.num_bytes_done += length + len(resp)
        worker.live_ops.num_iops_done += 1
        sent += length
    _client_shutdown(sock)


def _client_shutdown(sock) -> None:
    # clean shutdown signals EOF to the server's poll loop; ignore a peer
    # that already closed — the measured transfer is complete either way
    try:
        sock.sock.shutdown(socket_mod.SHUT_WR)
        sock.recv_exact(1, timeout=5.0)  # drain until server closes
    except (SocketError, OSError):
        pass


def _run_server(worker) -> None:
    """Poll this worker's connection share; reply --respsize per received
    --block bytes; finish when every connection reached EOF."""
    cfg = worker.cfg
    conns = worker._netbench_conns
    if not conns:
        worker.got_phase_work = False
        return
    bs = cfg.block_size
    response = bytes(cfg.netbench_response_size)
    native = _native_engine(worker)
    if native is not None:
        import ctypes
        for c in conns:
            # poll() gates the recvs; sends must be blocking (with a
            # bound) so a full socket buffer never reads as a dead conn
            _set_native_socket_mode(c, recv_timeout_secs=0,
                                    send_timeout_secs=30)
        fds = [c.sock.fileno() for c in conns]
        conn_state = (ctypes.c_uint64 * len(fds))(*([0] * len(fds)))
        try:
            while True:
                worker.check_interruption_request(force=True)
                open_left = native.run_net_server_slice(
                    fds, conn_state, bs, response, worker,
                    interrupt_flag=worker._native_interrupt)
                if not open_left:
                    return
        finally:
            for conn in conns:
                conn.close()
            worker._netbench_conns = []
    sel = selectors.DefaultSelector()
    states = {}
    for conn in conns:
        conn.sock.setblocking(False)
        sel.register(conn.sock, selectors.EVENT_READ, conn)
        states[conn] = 0  # bytes received toward the current block
    open_conns = set(conns)
    try:
        while open_conns:
            worker.check_interruption_request(force=True)
            for key, _events in sel.select(timeout=1.0):
                conn = key.data
                try:
                    chunk = conn.sock.recv(1 << 20)
                except BlockingIOError:
                    continue
                except OSError:
                    chunk = b""
                if not chunk:
                    sel.unregister(conn.sock)
                    open_conns.discard(conn)
                    continue
                worker.live_ops.num_bytes_done += len(chunk)
                states[conn] += len(chunk)
                while states[conn] >= bs:
                    states[conn] -= bs
                    t0 = time.perf_counter_ns()
                    conn.sock.setblocking(True)
                    conn.send_all(response, timeout=30.0)
                    conn.sock.setblocking(False)
                    worker.iops_latency_histo.add_latency(
                        (time.perf_counter_ns() - t0) // 1000)
                    worker.live_ops.num_bytes_done += len(response)
                    worker.live_ops.num_iops_done += 1
    finally:
        sel.close()
        for conn in conns:
            conn.close()
        worker._netbench_conns = []
