"""WorkerManager: owns the thread pool and the phase barrier.

Reference: source/workers/WorkerManager.{h,cpp} — spawns LocalWorkers
(local/service role) or one RemoteWorker per host (master role)
(WorkerManager.cpp:159-178), prepareThreads() :143, startNextPhase() :292,
waitForWorkersDone() :246 (condvar + periodic wakeups + time-limit check
:110), per-phase work accounting getPhaseNumEntriesAndBytes() :334-489.
"""

from __future__ import annotations

import os
import threading
import time

from ..phases import BenchMode, BenchPathType, BenchPhase
from .local_worker import LocalWorker
from .shared import WorkerException, WorkersSharedData

WAIT_WAKEUP_SECS = 2.0  # periodic wakeup for time-limit/interrupt checks


class WorkerManager:
    def __init__(self, config, shared: "WorkersSharedData | None" = None):
        self.cfg = config
        self.shared = shared or WorkersSharedData(config)
        self.workers: list = []
        self.threads: "list[threading.Thread]" = []
        self._shared_fds: "list[int]" = []
        self._error_interrupt_sent = False

    # -- lifecycle ----------------------------------------------------------

    def prepare_threads(self) -> None:
        """Create workers + threads; prep acts as a barrier
        (reference: prepareThreads + waitForWorkersDone on prep)."""
        self._open_shared_path_fds()
        if self.cfg.bench_mode == BenchMode.S3:
            from ..toolkits.s3_upload_store import shared_upload_store
            shared_upload_store.clear()  # no stale MPU state across runs
        if self.cfg.hosts and not self.cfg.run_as_service:
            from ..service.remote_worker import RemoteWorker
            for host_idx, host in enumerate(self.cfg.hosts):
                worker = RemoteWorker(self.shared, host_idx, host)
                self.workers.append(worker)
            if self.shared.stream_control is not None:
                # --svcstream: root stream readers mirror per-host frame
                # entries straight into these workers' live counters
                self.shared.stream_control.register_workers(self.workers)
        else:
            for rank in range(self.cfg.num_threads):
                worker = LocalWorker(self.shared,
                                     self.cfg.rank_offset + rank)
                self.workers.append(worker)
        for worker in self.workers:
            t = threading.Thread(target=worker.thread_start,
                                 name=f"worker-{worker.rank}", daemon=True)
            self.threads.append(t)
            t.start()
        self._wait_for_prep_done()
        if self.cfg.hosts and not self.cfg.run_as_service:
            self._check_service_bench_path_infos()

    def _check_service_bench_path_infos(self) -> None:
        """All services must report consistent path info; the master adopts
        the services' path type and re-validates path-dependent flags
        (reference: checkServiceBenchPathInfos, WorkerManager.cpp:498 +
        ProgArgs.cpp:4206)."""
        from ..config.args import ConfigError
        from ..phases import BenchPathType
        from ..service import protocol as proto
        infos = [getattr(w, "bench_path_info", None) for w in self.workers]
        infos = [i for i in infos if i]
        if not infos:
            return
        first = infos[0]
        for info in infos[1:]:
            if (info.get(proto.KEY_BENCH_PATH_TYPE)
                    != first.get(proto.KEY_BENCH_PATH_TYPE)) \
                    or (info.get(proto.KEY_NUM_BENCH_PATHS)
                        != first.get(proto.KEY_NUM_BENCH_PATHS)):
                raise WorkerException(
                    f"services report inconsistent bench path info ({infos})")
        self.cfg.bench_path_type = BenchPathType(
            first.get(proto.KEY_BENCH_PATH_TYPE, 0))
        try:
            self.cfg.check()  # path-type-dependent validation, now for real
        except ConfigError as err:
            raise WorkerException(str(err)) from err

    def _open_shared_path_fds(self) -> None:
        """Open file/bdev bench paths once, shared across workers
        (reference: prepareBenchPathFDsVec, ProgArgs.cpp:1981)."""
        cfg = self.cfg
        if cfg.bench_mode != BenchMode.POSIX \
                or cfg.bench_path_type == BenchPathType.DIR \
                or cfg.no_fd_sharing or not cfg.paths or cfg.hosts:
            return
        flags = os.O_RDWR
        if cfg.run_create_files or cfg.scenario_creates_files:
            flags |= os.O_CREAT
        if cfg.use_direct_io:
            flags |= os.O_DIRECT
        self._shared_fds = []
        for p in cfg.paths:
            try:
                # append as we go so a partial failure leaves the already-
                # opened fds where join_all_threads can close them
                self._shared_fds.append(os.open(p, flags, 0o644))
            except OSError as err:
                # reference: "Unable to open benchmark path" ProgException
                # (prepareBenchPathFDsVec) — a clean error, not a crash
                raise WorkerException(
                    f"unable to open benchmark path: {err.filename}: "
                    f"{err.strerror}") from err
        cfg.bench_path_fds = self._shared_fds

    def _wait_for_prep_done(self) -> None:
        shared = self.shared
        with shared.cond:
            while (shared.num_workers_done
                   + shared.num_workers_done_with_error) < len(self.workers):
                shared.cond.wait(WAIT_WAKEUP_SECS)
            if shared.num_workers_done_with_error:
                raise WorkerException(
                    f"worker preparation failed: {shared.first_error}")
            shared.num_workers_done = 0

    def start_next_phase(self, phase: BenchPhase,
                         bench_uuid: str = "") -> str:
        for worker in self.workers:
            worker.reset_stats()  # keeps degraded hosts excluded
        self._error_interrupt_sent = False
        return self.shared.start_phase(phase, bench_uuid=bench_uuid)

    def check_fail_fast_interrupt(self) -> None:
        """True fail-fast: the moment one worker errors out, interrupt the
        survivors instead of letting them run the phase to completion
        before the error surfaces (an --infloop phase would otherwise
        hide a dead host until the time limit). Called from the
        live-stats poll loop and the done-wait loop, like the time-limit
        check. Degraded hosts (--svctolerant) do NOT count as errors."""
        if self.shared.num_workers_done_with_error \
                and not self._error_interrupt_sent:
            self._error_interrupt_sent = True
            self.interrupt_and_notify_workers()

    def check_phase_time_limit(self, phase_start: float) -> None:
        """--timelimit enforcement; called from the live-stats poll loop and
        the done-wait loop (reference: checkPhaseTimeLimit :110)."""
        limit = self.cfg.time_limit_secs
        if not limit or self.shared.phase_time_expired:
            return
        if (time.monotonic() - phase_start) > limit:
            self.shared.mark_phase_time_expired()
            self.interrupt_and_notify_workers()

    def wait_for_workers_done(self, phase_start: float) -> None:
        """Block until all workers finished the phase; periodic wakeups
        check the phase time limit (reference: waitForWorkersDone :246 +
        checkPhaseTimeLimit :110). Raises on worker error (fail-fast)."""
        shared = self.shared
        with shared.cond:
            while True:
                # degraded hosts (--svctolerant) dropped out of the run;
                # the barrier completes with the survivors
                total = shared.num_workers_done \
                    + shared.num_workers_done_with_error \
                    + shared.num_workers_degraded
                if total >= len(self.workers):
                    break
                self.check_phase_time_limit(phase_start)
                self.check_fail_fast_interrupt()
                shared.cond.wait(WAIT_WAKEUP_SECS)
            shared.cpu_util_last_done = shared.cpu_util.update()
            if shared.num_workers_done_with_error:
                raise WorkerException(str(shared.first_error))

    def all_workers_done(self) -> bool:
        shared = self.shared
        return (shared.num_workers_done
                + shared.num_workers_done_with_error
                + shared.num_workers_degraded) >= len(self.workers)

    def interrupt_and_notify_workers(self) -> None:
        if self.shared.rwmix_balancer is not None:
            self.shared.rwmix_balancer.interrupt()  # wake blocked waiters
        for worker in self.workers:
            worker.interrupt_execution()

    def join_all_threads(self) -> None:
        self.start_next_phase(BenchPhase.TERMINATE)
        for t in self.threads:
            t.join(timeout=30)
        if self.shared.tracer is not None:
            try:  # a killed run must still leave a loadable trace file
                self.shared.tracer.write()
            except OSError:
                pass
        for fd in self._shared_fds:
            try:
                os.close(fd)
            except OSError:
                pass
        self._shared_fds = []
        self.cfg.bench_path_fds = []
        # --s3single: the shared client is owned by no worker (each one
        # deliberately skips it in cleanup), so the manager closes it once
        # after ALL workers are done — otherwise its tracked connections
        # and the --s3log file handle leak per-run in a long-lived
        # --service process, which rebuilds a manager per /preparephase
        client = getattr(self.shared, "s3_client_singleton", None)
        if client is not None:
            self.shared.s3_client_singleton = None
            try:
                client.close()
            except Exception:  # noqa: BLE001 - teardown is best effort
                pass

    # -- pod-slice rank->shard assignment (--tpuslice) ----------------------

    @staticmethod
    def slice_shard_assignment(n_devices: int, n_workers: int,
                               local_rank: int) -> "list[int]":
        """Mesh device indices fed by the worker at local_rank: devices
        are dealt round-robin over this process's workers (device d ->
        worker d % n_workers), so every chip of the mesh has exactly one
        feeder and the per-worker load differs by at most one shard.
        The single authority for the slice phase's rank->shard map —
        workers/tpuslice.py and the tests both read it from here."""
        n_workers = max(n_workers, 1)
        return [d for d in range(n_devices)
                if d % n_workers == local_rank % n_workers]

    # -- per-phase work accounting (reference: getPhaseNumEntriesAndBytes) --

    def get_phase_num_entries_and_bytes(self, phase: BenchPhase
                                        ) -> "tuple[int, int]":
        cfg = self.cfg
        nthreads = cfg.num_threads * max(1, len(cfg.hosts) or 1)
        if phase == BenchPhase.TPUSLICE:
            # striped over chips: the whole dataset crosses storage->HBM
            # once, then again over ICI (entries = stripes, unknown until
            # the mesh size is probed — report bytes only)
            return (0, cfg.file_size * max(1, len(cfg.paths)))
        if phase in (BenchPhase.CREATEDIRS, BenchPhase.DELETEDIRS,
                     BenchPhase.STATDIRS):
            return (nthreads * cfg.num_dirs, 0)
        if cfg.bench_path_type == BenchPathType.DIR:
            entries = nthreads * cfg.num_dirs * cfg.num_files
            num_bytes = entries * cfg.file_size \
                if phase in (BenchPhase.CREATEFILES, BenchPhase.READFILES) \
                else 0
            return (entries, num_bytes)
        # file/bdev mode
        entries = len(cfg.paths)
        if phase in (BenchPhase.CREATEFILES, BenchPhase.READFILES):
            if cfg.use_random_offsets:
                return (entries, cfg.random_amount)
            return (entries, cfg.file_size * entries)
        return (entries, 0)
