"""TPUSLICE: pod-slice sharded ingest + ICI redistribution phase.

The step from "TPU benchmark" to "pod-slice benchmark" (ROADMAP item 2):
where TPUBENCH moves synthetic bytes and the --tpuids read path feeds ONE
chip per worker, this phase runs the data plane of a sharded-checkpoint
restore as one composable benchmark:

  stripe s of the dataset          (file/bdev paths, striped by chip)
    -> every worker reads its chips' shards off storage
       (StagingPool slots; the fused --tpustream ring where eligible)
    -> host->HBM DMA through the worker's TransferPipeline
       (one shard per chip of the mesh, P(("host","chip")) layout)
    -> ICI redistribution of the assembled stripe to --redistspec
       (jitted sharding change; parallel/slice_phase.SliceRunner)
    -> on-device fingerprint verify against the host bytes

with stripe s+1's storage ingest OVERLAPPING stripe s's ICI
redistribution: the driver dispatches the redistribution asynchronously
and only completes it after the next stripe's shards are read, so
storage, PCIe/DMA and ICI are all in flight together — the pipeline
shape real restores have.

Roles: every local worker is a FEEDER for the mesh devices
``WorkerManager.slice_shard_assignment`` gives it; the first local
worker is additionally the DRIVER that assembles stripes and runs the
SPMD steps (one SPMD program per process, like the collective patterns).

Counters (PATH_AUDIT_COUNTERS; auto-plumbed to JSON//metrics/traces):
ShardIngestMiB per feeder, IciRedistMiB/IciRedistUSec sums and the
IciGbpsHwm MAX on the driver. Redistribution records its own ``tpu_ici``
trace spans (--tracefile), giving the chart tool a redistribution lane.

Fault policy: a chip lost mid-phase ABORTS the phase loudly — a slice
stripe is one SPMD program over every chip, so the per-worker
--tpufallback chip/host failover of the single-chip paths cannot apply
(there is no "surviving subset" of an in-flight collective).
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from ..phases import BenchPhase
from ..toolkits import logger
from .shared import WorkerException, WorkerInterruptedException

#: barrier poll interval; every wait slice re-checks interrupts
_WAIT_SLICE_SECS = 0.2


class SliceAbortError(WorkerException):
    """The slice phase failed on a sibling worker; carriers re-raise a
    quiet interrupt so only the original error reaches the report."""


class _SliceState:
    """Per-phase rendezvous shared by this process's workers: shard
    publication, host-fingerprint folding, and the feed/redistribute
    lockstep. Created lazily by the first worker entering the phase
    (keyed by the phase's bench UUID)."""

    def __init__(self, n_workers: int, n_devices: int):
        self.cond = threading.Condition()
        self.n_workers = n_workers
        self.n_devices = n_devices
        self.shards: "dict[int, object]" = {}
        self.host_sum = 0
        self.host_xor = 0
        self.published = 0
        self.consumed_stripe = -1  # last stripe the driver consumed
        self.failed: "Exception | None" = None

    def fail(self, err: Exception) -> None:
        with self.cond:
            if self.failed is None:
                self.failed = err
            self.cond.notify_all()

    def _check(self, worker) -> None:
        worker.check_interruption_flag_only()
        if self.failed is not None:
            raise SliceAbortError(
                f"slice phase aborted by a sibling worker: "
                f"{type(self.failed).__name__}: {self.failed}")

    def publish(self, worker, shards: "dict[int, object]",
                host_sum: int, host_xor: int) -> None:
        with self.cond:
            self._check(worker)
            self.shards.update(shards)
            self.host_sum = (self.host_sum + host_sum) & 0xFFFFFFFF
            self.host_xor ^= host_xor
            self.published += 1
            self.cond.notify_all()

    def wait_all_published(self, worker) -> "tuple[dict, int, int]":
        """Driver: block until every worker published its shards of the
        current stripe; returns (shards, host_sum, host_xor) and resets
        the slots for the next stripe."""
        with self.cond:
            while self.published < self.n_workers:
                self._check(worker)
                self.cond.wait(_WAIT_SLICE_SECS)
            self._check(worker)
            shards, s, x = self.shards, self.host_sum, self.host_xor
            self.shards = {}
            self.host_sum = 0
            self.host_xor = 0
            self.published = 0
            return shards, s, x

    def mark_consumed(self, stripe_idx: int) -> None:
        with self.cond:
            self.consumed_stripe = stripe_idx
            self.cond.notify_all()

    def wait_consumed(self, worker, stripe_idx: int) -> None:
        """Feeders: block until the driver consumed stripe_idx, keeping
        feed and redistribute in lockstep (at most one stripe of ingest
        ahead of the in-flight redistribution)."""
        with self.cond:
            while self.consumed_stripe < stripe_idx:
                self._check(worker)
                self.cond.wait(_WAIT_SLICE_SECS)
            self._check(worker)


def _get_state(shared, n_workers: int, n_devices: int) -> _SliceState:
    with shared.cond:
        st = getattr(shared, "slice_state", None)
        if st is None or st[0] != shared.bench_uuid:
            st = (shared.bench_uuid, _SliceState(n_workers, n_devices))
            shared.slice_state = st
        return st[1]


# ----------------------------------------------------------------------
# storage shard readers: plain preadv loop vs the fused native stream
# ----------------------------------------------------------------------

class _PreadShardReader:
    """Baseline reader: preadv into rotating staging-pool slots, per-op
    --ioretries via the worker's retrier (same classifier as the Python
    block loop)."""

    def __init__(self, worker, fds):
        self._worker = worker
        self._fds = fds
        self._slots = worker._staging_pool.views
        self._next = 0

    def read_block(self, fd_idx: int, offset: int,
                   length: int) -> "tuple[np.ndarray, int]":
        worker = self._worker
        slot = self._slots[self._next % len(self._slots)]
        self._next += 1

        def one_op():
            t0 = time.perf_counter_ns()
            n = os.preadv(self._fds[fd_idx], [slot[:length]], offset)
            if n != length:
                from .io_errors import ShortIOError
                raise ShortIOError(True, offset, n, length)
            return (time.perf_counter_ns() - t0) // 1000

        if worker._io_retrier is None:
            lat_usec = one_op()
        else:
            lat_usec = worker._io_retrier.run(
                one_op, path=worker._retry_path_hint())
        return (np.frombuffer(slot[:length], dtype=np.uint32), lat_usec)

    def close(self) -> None:
        pass


class _StreamShardReader:
    """Fused reader: the native engine's streaming ring keeps the shard
    reads of a stripe in flight over the registered staging slots
    (io_uring/AIO with the GIL released) while the feeder overlaps HBM
    DMA dispatch — the --tpustream ring reused for the slice phase.
    Reads are submitted for the WHOLE stripe up front (bounded by the
    slot count) and reaped in completion order."""

    def __init__(self, worker, fds, native):
        from ..utils.native import NativeStreamError
        pool = worker._staging_pool
        self._worker = worker
        self._slots = pool.views
        try:
            self._stream = native.open_stream(
                fds, pool.slot_addrs, max(worker.cfg.block_size, 1),
                pool=None if pool.broken else pool.native_pool)
        except NativeStreamError as err:
            raise _StreamUnavailable(str(err)) from err
        if worker.cfg.io_timeout_secs:
            self._stream.set_timeout(
                worker.cfg.io_timeout_secs * 1_000_000)
        if worker._tracer is not None:
            self._stream.tracer = worker._tracer
            self._stream.trace_rank = worker.rank
        self.backend_name = self._stream.backend_name
        self.pooled = self._stream.pooled

    def read_blocks(self, ops: "list[tuple[int, int, int]]"):
        """ops: [(fd_idx, offset, length)] — submit up to slot-count
        reads, yield (op_index, np.uint32 view, lat_usec) in completion
        order. The yielded view is only valid until the slot is
        re-submitted; callers must consume (device_put) before the next
        yield loop iteration submits more."""
        worker = self._worker
        free = list(range(len(self._slots)))
        slot_op: "dict[int, int]" = {}
        next_op = 0
        while next_op < len(ops) or slot_op:
            worker.check_interruption_request(force=True)
            while free and next_op < len(ops):
                slot = free.pop()
                fd_idx, off, length = ops[next_op]
                self._stream.submit(slot, fd_idx, off, length, False)
                slot_op[slot] = next_op
                next_op += 1
            for slot, lat_usec, res in self._stream.reap(
                    min_complete=1, timeout_msecs=1000,
                    interrupt_flag=worker._native_interrupt):
                op_idx = slot_op.pop(slot)
                fd_idx, off, length = ops[op_idx]
                if res != length:
                    if res < 0:
                        raise WorkerException(
                            f"slice shard read failed at offset {off}: "
                            f"{os.strerror(-res)}")
                    from .io_errors import ShortIOError
                    raise WorkerException(
                        str(ShortIOError(True, off, max(res, 0), length)))
                view = np.frombuffer(self._slots[slot][:length],
                                     dtype=np.uint32)
                yield op_idx, view, lat_usec
                free.append(slot)

    def close(self) -> None:
        if self._stream.close() != 0:
            self._worker._stream_drain_failed = True
            logger.log_error(
                f"worker {self._worker.rank}: slice stream ring drain "
                f"failed; keeping I/O buffers mapped until process exit")


class _StreamUnavailable(Exception):
    """Stream ring could not be opened; feeder falls back to preadv."""


def _stream_blocker(worker) -> "str | None":
    """Why the fused ring cannot serve the slice feeder (None =
    eligible); mirrors LocalWorker._tpu_stream_blocker for the features
    the slice reader supports."""
    from ..utils.native import get_native_engine
    cfg = worker.cfg
    if cfg.tpu_stream == "off":
        return "--tpustream off"
    native = get_native_engine()
    if native is None:
        return "native ioengine unavailable"
    if not native.stream_supported():
        return "kernel lacks both io_uring and AIO"
    if worker._ops_log is not None:
        return "--opslog per-op records"
    if worker._rate_limiter_read or worker._rate_limiter_write:
        return "per-op rate limits"
    if worker._io_retrier is not None:
        return "--ioretries per-op retry (slice ring has no re-arm)"
    return None


# ----------------------------------------------------------------------
# the phase
# ----------------------------------------------------------------------

def run_tpu_slice_phase(worker, phase: BenchPhase) -> None:
    """Entry point from LocalWorker._dispatch_phase_inner."""
    from ..tpu.device import is_device_loss_error
    try:
        _run_slice_phase_inner(worker, phase)
    except (WorkerInterruptedException, WorkerException):
        raise
    except Exception as err:  # noqa: BLE001 - classified below
        if is_device_loss_error(err):
            # a stripe is ONE SPMD program over every chip: the
            # per-worker --tpufallback failover of the single-chip paths
            # cannot save an in-flight collective — abort loudly
            raise WorkerException(
                f"TPU chip lost during the --tpuslice phase "
                f"({type(err).__name__}: {err}); slice phases abort on "
                f"chip loss (--tpufallback does not apply to SPMD mesh "
                f"phases)") from err
        raise


def _run_slice_phase_inner(worker, phase: BenchPhase) -> None:
    # via _get_jax so the persistent compile cache is configured: slice
    # jits are the most expensive in the repo and bench processes are
    # short-lived
    from ..tpu.device import _get_jax
    jax = _get_jax()

    from .tpubench import _select_collective_devices

    cfg = worker.cfg
    n_local = max(1, cfg.num_threads)
    local_rank = worker.rank % n_local
    is_driver = local_rank == 0

    devices = _select_collective_devices(cfg, jax)
    state = _get_state(worker.shared, n_local, len(devices))
    try:
        _run_slice_phase_guarded(worker, state, devices, is_driver,
                                 local_rank, n_local)
    except (SliceAbortError, WorkerInterruptedException):
        raise
    except BaseException as err:
        state.fail(err)  # wake siblings parked on the barrier
        raise


def _run_slice_phase_guarded(worker, state, devices, is_driver,
                             local_rank, n_local) -> None:
    from ..parallel.mesh import (MeshShapeError, make_ingest_mesh,
                                 parse_mesh_shape)
    from ..parallel.slice_phase import SliceRunner, host_fingerprint
    from ..tpu.device import TransferPipeline

    cfg = worker.cfg
    n_dev = len(devices)
    bs = cfg.block_size
    if bs % 4:
        raise WorkerException(
            "--tpuslice shards are uint32 arrays: --block must be a "
            "multiple of 4 bytes")

    # dataset geometry: file/bdev mode, one file of file_size per path,
    # striped by chip — stripe s places block (s, d) on mesh device d at
    # dataset offset s*stripe_bytes + d*block_size
    fds = worker._path_fds
    if not fds:
        raise WorkerException(
            "--tpuslice requires file/blockdev bench paths (no open "
            "path fds; directory-tree paths are not striped over chips)")
    dataset_bytes = cfg.file_size * len(fds)
    stripe_bytes = n_dev * bs
    n_stripes = dataset_bytes // stripe_bytes
    if n_stripes == 0:
        raise WorkerException(
            f"--tpuslice dataset too small: {dataset_bytes} bytes is "
            f"less than one stripe ({n_dev} devices x {bs} block bytes "
            f"= {stripe_bytes})")
    trimmed = dataset_bytes - n_stripes * stripe_bytes
    if trimmed and is_driver:
        logger.log(logger.LOG_NORMAL,
                   f"NOTE: --tpuslice dataset trimmed to "
                   f"{n_stripes * stripe_bytes} bytes ({n_stripes} "
                   f"stripes of {stripe_bytes}); the trailing {trimmed} "
                   f"bytes do not fill a whole stripe")

    # per-chip rank->shard assignment (manager owns the rank math).
    # Feeders only ever place shards on ADDRESSABLE devices: in a
    # multi-host runtime each process feeds its own chips and jax
    # stitches the global stripe from every process's local shards —
    # exactly how a real restore stripes a pod.
    import jax

    from .manager import WorkerManager
    proc = jax.process_index()
    local_dev_indices = [i for i, dev in enumerate(devices)
                         if dev.process_index == proc]
    if not local_dev_indices:
        raise WorkerException(
            "--tpuslice: this process addresses no device of the mesh")
    picks = WorkerManager.slice_shard_assignment(
        len(local_dev_indices), n_local, local_rank)
    my_devices = [local_dev_indices[k] for k in picks]
    worker.got_phase_work = bool(my_devices) or is_driver

    # the driver builds the mesh + jitted steps; feeders only need their
    # device handles. Compiles land OUTSIDE the timed loop via warmup().
    runner = None
    if is_driver:
        shape = None
        if cfg.mesh_shape_str:
            shape = parse_mesh_shape(cfg.mesh_shape_str)
        try:
            mesh = make_ingest_mesh(devices, shape=shape)
        except MeshShapeError as err:
            raise WorkerException(str(err)) from None
        try:
            runner = SliceRunner(mesh, cfg.redist_spec or "alltoall",
                                 bs // 4)
        except ValueError as err:
            raise WorkerException(str(err)) from None
        runner.warmup()
        logger.log(logger.LOG_VERBOSE,
                   f"slice mesh {mesh.devices.shape[0]}x"
                   f"{mesh.devices.shape[1]}, {n_stripes} stripes, "
                   f"redistspec {cfg.redist_spec or 'alltoall'}")

    # per-worker transfer pipeline: HBM ingest accounting + --tpubudget,
    # the same split dispatch-vs-DMA discipline as the single-chip path
    depth = min(max(cfg.tpu_depth or cfg.io_depth, 1),
                max(len(worker._staging_pool.views), 1))
    pipeline = TransferPipeline(depth,
                                budget_usec=cfg.tpu_dispatch_budget_usec)
    if worker._tracer is not None:
        pipeline.tracer = worker._tracer
        pipeline.trace_rank = worker.rank

    # storage reader: fused native-stream ring where eligible, else the
    # preadv loop — logged once per phase like the single-chip path
    reader = None
    stream_reader = None
    blocker = _stream_blocker(worker)
    if blocker is None:
        from ..utils.native import get_native_engine
        try:
            stream_reader = _StreamShardReader(worker, fds,
                                               get_native_engine())
            if is_driver:
                logger.log(logger.LOG_NORMAL,
                           f"slice ingest ring engaged (backend="
                           f"{stream_reader.backend_name}"
                           + (", pool-registered"
                              if stream_reader.pooled else "") + ")")
        except _StreamUnavailable as err:
            blocker = f"stream ring setup failed ({err})"
    if stream_reader is None:
        if cfg.tpu_stream == "on":
            raise WorkerException(
                f"--tpustream on: fused slice ingest ring unavailable "
                f"({blocker})")
        if is_driver and cfg.tpu_stream != "off":
            logger.log(logger.LOG_NORMAL,
                       f"NOTE: fused slice ingest ineligible ({blocker}); "
                       f"using the preadv loop")
        reader = _PreadShardReader(worker, fds)

    pending = None  # in-flight redistribution of the previous stripe
    per_chip: "dict[int, int]" = {}
    try:
        for s in range(n_stripes):
            shards, host_sum, host_xor = _ingest_stripe(
                worker, s, my_devices, devices, fds, stripe_bytes, bs,
                cfg.file_size, pipeline, reader, stream_reader,
                host_fingerprint, per_chip)
            state.publish(worker, shards, host_sum, host_xor)
            if is_driver:
                all_shards, stripe_sum, stripe_xor = \
                    state.wait_all_published(worker)
                global_arr = runner.assemble(all_shards)
                if pending is not None:
                    # stripe s-1's ICI ran while stripe s was read off
                    # storage — the overlap this phase exists to measure
                    _complete_redistribution(worker, runner, pipeline,
                                             pending)
                pending = _launch_redistribution(worker, runner, pipeline,
                                                global_arr, s,
                                                stripe_sum, stripe_xor)
                state.mark_consumed(s)
            else:
                state.wait_consumed(worker, s)
        if is_driver and pending is not None:
            _complete_redistribution(worker, runner, pipeline, pending)
    finally:
        if stream_reader is not None:
            stream_reader.close()
        elif reader is not None:
            reader.close()
        # drain the transfer ring; --tpubudget covers ingest dispatch +
        # the driver's SPMD dispatch cost — but only on the clean path:
        # a budget breach must never mask the in-flight abort cause
        import sys as _sys
        pipeline.flush(check_budget=_sys.exc_info()[0] is None)
        worker.tpu_dispatch_usec = pipeline.dispatch_usec
        worker.tpu_transfer_usec = pipeline.transfer_usec
        if worker._tpu is None and per_chip:
            # per-chip rows for workers without a single-chip context
            # (statistics reads tpu_per_chip when _tpu is None)
            worker.tpu_per_chip = {c: (b, 0) for c, b in per_chip.items()}


def _ingest_stripe(worker, stripe_idx, my_devices, devices, fds,
                   stripe_bytes, bs, file_size, pipeline, reader,
                   stream_reader, host_fingerprint, per_chip):
    """Read this worker's shards of one stripe and place each onto its
    mesh device through the transfer pipeline. Returns
    ({device_idx: shard array}, host_sum, host_xor)."""
    import jax

    shards: "dict[int, object]" = {}
    host_sum = 0
    host_xor = 0
    ops = []
    for d in my_devices:
        off = stripe_idx * stripe_bytes + d * bs
        ops.append((off // file_size, off % file_size, bs))

    def place(op_idx, view, lat_usec):
        nonlocal host_sum, host_xor
        d = my_devices[op_idx]
        s, x = host_fingerprint(view)
        # an OWNED copy, never the slot view: jax's CPU backend may
        # zero-copy alias an aligned host buffer on device_put, and the
        # slice phase recycles slots for stripe s+1 while stripe s is
        # still in flight on the mesh — an aliased shard would mutate
        # under the running redistribution (caught by the fingerprint
        # verify when it bit). Shard rows are (1, words) so assembly is
        # a pure layout map.
        block = np.array(view.reshape(1, -1))
        arr = pipeline.submit(
            lambda: jax.device_put(block, devices[d]))
        shards[d] = arr
        host_sum = (host_sum + s) & 0xFFFFFFFF
        host_xor ^= x
        worker.iops_latency_histo.add_latency(lat_usec)
        worker.live_ops.num_bytes_done += bs
        worker.live_ops.num_iops_done += 1
        worker.tpu_transfer_bytes += bs
        worker._shard_ingest_bytes += bs
        worker.shard_ingest_mib = worker._shard_ingest_bytes >> 20
        per_chip[d] = per_chip.get(d, 0) + bs
        if worker._staging_pool is not None:
            worker._staging_pool.account_ops(1)

    if stream_reader is not None:
        for op_idx, view, lat_usec in stream_reader.read_blocks(ops):
            place(op_idx, view, lat_usec)
    else:
        for op_idx, (fd_idx, off, length) in enumerate(ops):
            worker.check_interruption_request(force=True)
            view, lat_usec = reader.read_block(fd_idx, off, length)
            place(op_idx, view, lat_usec)
    return shards, host_sum, host_xor


def _launch_redistribution(worker, runner, pipeline, global_arr,
                           stripe_idx, host_sum, host_xor) -> dict:
    handle = runner.launch(global_arr)
    # the SPMD dispatch cost rides the pipeline's budget accounting so
    # --tpubudget bounds the slice phase's host-side overhead too
    pipeline.note_dispatch(handle["dispatch_usec"])
    handle["stripe_idx"] = stripe_idx
    handle["host_sum"] = host_sum
    handle["host_xor"] = host_xor
    return handle


def _complete_redistribution(worker, runner, pipeline, handle) -> None:
    import jax

    from ..parallel.slice_phase import SliceFingerprintError

    dev_sum, dev_xor, usec = runner.complete(handle)
    stripe_bytes = runner.stripe_bytes
    if jax.process_count() == 1:
        # fingerprint-exact verify: only a single-process driver saw the
        # host bytes of EVERY shard; multi-host runs verify on-device
        # consistency implicitly via the replicated fingerprint
        try:
            runner.verify(dev_sum, dev_xor, handle["host_sum"],
                          handle["host_xor"], handle["stripe_idx"])
        except SliceFingerprintError as err:
            raise WorkerException(str(err)) from None
    worker._ici_redist_bytes += stripe_bytes
    worker.ici_redist_mib = worker._ici_redist_bytes >> 20
    worker.ici_redist_usec += usec
    gbps = round(stripe_bytes * 8 / (usec * 1000), 3)
    worker.ici_gbps_hwm = max(worker.ici_gbps_hwm, gbps)
    worker.live_ops.num_entries_done += 1  # one stripe redistributed
    worker.entries_latency_histo.add_latency(usec)
    if worker._tracer is not None:
        # the redistribution's own sub-span lane (chart: tpu_ici lane)
        worker._tracer.record(
            "tpu_ici", "tpu_ici", handle["t_submit_ns"], usec,
            rank=worker.rank, sampled=True,
            stripe=handle["stripe_idx"], bytes=stripe_bytes,
            spec=runner.redist_spec)
